"""Framework CLI: ``python -m cassmantle_tpu <command>``.

The reference has no CLI layer at all — it launches as ``uvicorn
main:app`` (reference requirements.txt:2, main.py:18) and its one tool is
a bare script (download_model.py). A standalone framework needs a front
door; this one wraps every runnable surface:

- ``serve``            game server (presets: sd15 / sdxl / fast; --fake)
- ``bench``            the BASELINE.md workload ladder (repo-root bench.py)
- ``fetch-weights``    checkpoint/tokenizer bootstrap (tools/fetch_weights.py)
- ``quantize-weights`` offline int8 LM checkpoints (tools/quantize_weights.py)
- ``clip-report``      CLIP-sim quality gate across presets (tools/clip_report.py)
- ``build-wordlist``   regenerate the spellcheck lexicon (tools/build_wordlist.py)
- ``build-embed-table`` emit the int8 wordlist scoring table
                       (tools/build_embed_table.py --emit)
- ``lm-int8-ab``       fp-vs-int8 LM decode A/B (tools/lm_int8_ab.py)
- ``weights-drill``    fetch -> quantize -> CLIP gate -> LM A/B -> one
                       LM-decoded game round, fail-fast (the whole
                       weights-provisioned drill as one verb)
- ``train-diffusion``  dp×tp×sp UNet fine-tuning loop (synthetic or .npy data)
- ``train-lm``         LM fine-tuning loop (GPT-2 by default)
- ``version``

Training commands are thin loops over parallel/train.py and
parallel/lm_train.py with orbax checkpointing — the same step functions
the multi-chip dryrun compiles.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _exit_code(e: SystemExit) -> int:
    """sys.exit accepts any object; non-int codes print to stderr."""
    if e.code is None:
        return 0
    if isinstance(e.code, int):
        return e.code
    print(e.code, file=sys.stderr)
    return 1


def cmd_serve(argv) -> int:
    from cassmantle_tpu.server.app import main as serve_main

    saved = sys.argv
    sys.argv = ["cassmantle-tpu serve"] + list(argv)
    try:
        serve_main()
    except SystemExit as e:
        return _exit_code(e)
    finally:
        sys.argv = saved
    return 0


def _run_script(relpath: str, argv) -> int:
    """Exec a repo-root script (bench.py, tools/*) in-process.

    cwd is left alone — user-supplied relative paths keep meaning what
    they mean in the shell. The scripts themselves resolve their
    *defaults* (data/wordlist.txt, BENCH_SUITE.json, weights/) against
    the repo root so a module-CLI invocation from anywhere still reads
    and writes where the package expects."""
    import runpy

    path = os.path.join(_repo_root(), relpath)
    if not os.path.exists(path):
        print(f"{relpath} not found (not a source checkout?)",
              file=sys.stderr)
        return 2
    saved = sys.argv
    sys.argv = [path] + list(argv)
    try:
        runpy.run_path(path, run_name="__main__")
    except SystemExit as e:
        return _exit_code(e)
    finally:
        sys.argv = saved
    return 0


def cmd_bench(argv) -> int:
    return _run_script("bench.py", argv)


def cmd_fetch_weights(argv) -> int:
    return _run_script(os.path.join("tools", "fetch_weights.py"), argv)


def cmd_quantize_weights(argv) -> int:
    return _run_script(os.path.join("tools", "quantize_weights.py"), argv)


def cmd_clip_report(argv) -> int:
    return _run_script(os.path.join("tools", "clip_report.py"), argv)


def cmd_build_wordlist(argv) -> int:
    return _run_script(os.path.join("tools", "build_wordlist.py"), argv)


def cmd_build_embed_table(argv) -> int:
    return _run_script(os.path.join("tools", "build_embed_table.py"),
                       argv)


def cmd_lm_int8_ab(argv) -> int:
    return _run_script(os.path.join("tools", "lm_int8_ab.py"), argv)


def cmd_weights_drill(argv) -> int:
    """The weights-provisioned drill, one verb (VERDICT r4 #3):
    fetch -> quantize -> CLIP quality gate -> LM int8 A/B -> one game
    round whose prompt text is genuinely LM-decoded (no template
    fallback). Fail-fast: the first failing leg fails the drill, and
    the CLIP leg enforces config.QualityGateConfig whenever the report
    is a real measurement."""
    p = argparse.ArgumentParser(
        description="weights-provisioned drill: fetch -> quantize -> "
                    "clip gate -> lm A/B -> LM-decoded round")
    p.add_argument("--weights", default=os.path.join(_repo_root(),
                                                     "weights"))
    p.add_argument("--seeds", type=int, default=2,
                   help="image batches per preset for the CLIP leg")
    p.add_argument("--tokens", type=int, default=64,
                   help="decode length for the LM int8 A/B leg")
    p.add_argument("--platform", default="auto", choices=("auto", "cpu"))
    p.add_argument("--tiny", action="store_true",
                   help="tiny configs end to end (plumbing smoke on "
                        "CPU; numbers are not measurements)")
    for leg in ("fetch", "quantize", "clip", "lm-ab", "round"):
        p.add_argument(f"--skip-{leg}", action="store_true")
    args = p.parse_args(argv)

    if args.tiny:
        # tiny is a plumbing smoke of the measurement legs; it must
        # never download checkpoints or leave random-init artifacts in
        # the real weights directory
        args.skip_fetch = args.skip_quantize = True

    def leg(name: str, fn) -> int:
        if getattr(args, f"skip_{name.replace('-', '_')}"):
            print(f"[drill] {name}: skipped")
            return 0
        print(f"[drill] {name}: running")
        rc = fn()
        print(f"[drill] {name}: {'ok' if rc == 0 else f'FAILED ({rc})'}")
        return rc

    plat = ["--platform", "cpu"] if args.platform == "cpu" else []
    tiny = ["--tiny"] if args.tiny else []
    steps = [
        ("fetch", lambda: cmd_fetch_weights(
            ["--out", args.weights])),
        ("quantize", lambda: cmd_quantize_weights(
            ["--weights", args.weights] + plat)),
        ("clip", lambda: cmd_clip_report(
            ["--weights", args.weights, "--seeds", str(args.seeds)]
            + plat + tiny)),
        ("lm-ab", lambda: cmd_lm_int8_ab(
            ["--weights", args.weights, "--tokens", str(args.tokens)]
            + plat + tiny)),
        ("round", lambda: _lm_decoded_round(args)),
    ]
    for name, fn in steps:
        rc = leg(name, fn)
        if rc != 0:
            return rc
    print("[drill] all legs passed")
    return 0


def _lm_decoded_round(args) -> int:
    """One full game round whose prompt text came from the LM — the
    seam the virtual-mesh dryrun only ever exercised via the template
    fallback (VERDICT r4 weak #5). Fails when the decode degenerates
    into the fallback (pipeline.text_fallbacks increments), so a
    weights-provisioned host proves LM text flows through masking ->
    round -> store."""
    import asyncio
    import dataclasses
    import glob

    if not args.tiny:
        # cheap provisioning check BEFORE any model init: the fail
        # path must not pay a full-size random-init stack just to say
        # "needs a provisioned host"
        has_lm = any(
            glob.glob(os.path.join(args.weights, pat))
            for pat in ("gpt2.safetensors", "gpt2-*.safetensors",
                        "mistral.safetensors", "mistral-*.safetensors"))
        if not has_lm:
            print("[drill] round: no LM checkpoint under "
                  f"{args.weights} — this leg needs a provisioned "
                  f"host (or --tiny for plumbing)", file=sys.stderr)
            return 5

    if args.platform == "cpu":
        from cassmantle_tpu.utils.xla_flags import pin_cpu_platform

        pin_cpu_platform(virtual_devices=False)

    from cassmantle_tpu.config import FrameworkConfig, test_config
    from cassmantle_tpu.engine.game import Game
    from cassmantle_tpu.engine.store import MemoryStore
    from cassmantle_tpu.serving.service import InferenceService
    from cassmantle_tpu.utils.logging import metrics

    cfg = test_config() if args.tiny else FrameworkConfig()
    cfg = cfg.replace(game=dataclasses.replace(
        cfg.game, time_per_prompt=30.0, lock_timeout=120.0))
    weights_dir = args.weights if os.path.isdir(args.weights) else None
    svc = InferenceService(cfg, weights_dir=None if args.tiny
                           else weights_dir)
    if not args.tiny and not svc.backend.prompt_gen.loaded_real_weights:
        print("[drill] round: LM weights are random init — this leg "
              "needs a provisioned host (or --tiny for plumbing)",
              file=sys.stderr)
        return 5

    game = Game(cfg, MemoryStore(), svc.content_backend, svc.embed,
                svc.similarity)

    async def play() -> int:
        fallbacks0 = metrics.snapshot()["counters"].get(
            "pipeline.text_fallbacks", 0)
        await game.startup()
        prompt = await game.fetch_prompt_json("drill-player")
        masks = await game.rounds.current_masks()
        scores = await game.compute_client_scores(
            "drill-player", {str(masks[0]): "stormy"})
        await game.shutdown()
        await svc.stop()
        fallbacks = metrics.snapshot()["counters"].get(
            "pipeline.text_fallbacks", 0) - fallbacks0
        assert prompt and masks and "won" in scores, (prompt, scores)
        if fallbacks and not args.tiny:
            print(f"[drill] round: {fallbacks} template fallback(s) — "
                  f"prompt text did NOT come from the LM",
                  file=sys.stderr)
            return 6
        print(f"[drill] round ok: {len(masks)} masks from "
              f"{'template (tiny)' if fallbacks else 'LM-decoded'} text, "
              f"guess scored")
        return 0

    return asyncio.run(play())


def _train_parser(desc: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=desc)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--dp", type=int, default=-1)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--sp", type=int, default=1)
    p.add_argument("--remat", action="store_true",
                   help="rematerialize the forward in backward (fits "
                        "bigger batches per chip)")
    p.add_argument("--checkpoint-dir", default=None,
                   help="orbax checkpoint directory (resumes if present)")
    p.add_argument("--checkpoint-every", type=int, default=100)
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--config", default="default",
                   choices=("default", "test"),
                   help="'test' = the tiny-model config (smoke runs on "
                        "CPU devices)")
    p.add_argument("--platform", default="auto", choices=("auto", "cpu"),
                   help="'cpu' pins jax to host devices (with the "
                        "8-virtual-device flag) — smoke-test sharded "
                        "training without touching an accelerator")
    return p


def _apply_platform(args) -> None:
    if args.platform == "cpu":
        from cassmantle_tpu.utils.xla_flags import pin_cpu_platform

        pin_cpu_platform(virtual_devices=True)


def _framework_config(args):
    if args.config == "test":
        from cassmantle_tpu.config import test_config

        return test_config()
    from cassmantle_tpu.config import FrameworkConfig

    return FrameworkConfig()


def _checkpointer(args):
    if not args.checkpoint_dir:
        return None
    from cassmantle_tpu.utils.checkpoint import TrainCheckpointer

    return TrainCheckpointer(args.checkpoint_dir)


def _train_loop(name, args, trainer, params, opt_state, next_batch):
    """Shared driver: step/log/checkpoint. ``next_batch(step)`` returns a
    sharded batch dict."""
    import jax

    ckpt = _checkpointer(args)
    start = 0
    if ckpt is not None and ckpt.latest_step() is not None:
        start = ckpt.latest_step()
        restored = ckpt.restore(
            template={"params": params, "opt_state": opt_state})
        params, opt_state = restored["params"], restored["opt_state"]
        print(f"resumed from step {start}")
    root_rng = jax.random.PRNGKey(args.seed)
    for step in range(start, args.steps):
        # fold the step index in (not a split chain): a resumed run at
        # step N draws the same subkey an uninterrupted run would
        sub = jax.random.fold_in(root_rng, step)
        params, opt_state, loss = trainer.step(
            params, opt_state, next_batch(step), sub)
        if step % args.log_every == 0 or step == args.steps - 1:
            print(f"[{name}] step {step} loss {float(loss):.5f}")
        if ckpt is not None and (step + 1) % args.checkpoint_every == 0:
            ckpt.save(step + 1, params, opt_state)
    if ckpt is not None:
        ckpt.save(args.steps, params, opt_state)
        ckpt.close()
    return 0


def cmd_train_diffusion(argv) -> int:
    p = _train_parser("UNet denoising fine-tune (dp × tp × sp)")
    p.add_argument("--latents", default=None,
                   help=".npy of clean latents (N, H, W, 4); synthetic "
                        "data when omitted")
    p.add_argument("--context", default=None,
                   help=".npy of text states (N, S, context_dim)")
    p.add_argument("--image-size", type=int, default=512)
    args = p.parse_args(argv)
    if bool(args.latents) != bool(args.context):
        p.error("--latents and --context must be given together")
    _apply_platform(args)

    import jax.numpy as jnp

    from cassmantle_tpu.config import MeshConfig
    from cassmantle_tpu.parallel.mesh import make_mesh
    from cassmantle_tpu.parallel.train import DiffusionTrainer

    cfg = _framework_config(args)
    mesh = make_mesh(MeshConfig(dp=args.dp, tp=args.tp, sp=args.sp))
    trainer = DiffusionTrainer(cfg, mesh, lr=args.lr, remat=args.remat)

    hw = args.image_size // 8
    ctx_dim = cfg.models.unet.context_dim
    if args.latents:
        lat_all = np.load(args.latents).astype(np.float32)
        ctx_all = np.load(args.context).astype(np.float32)
    else:
        rng = np.random.default_rng(args.seed)
        lat_all = rng.standard_normal((args.batch * 4, hw, hw, 4),
                                      dtype=np.float32)
        ctx_all = rng.standard_normal((args.batch * 4, 77, ctx_dim),
                                      dtype=np.float32)

    sample = trainer.shard_batch({
        "latents": jnp.asarray(lat_all[: args.batch]),
        "context": jnp.asarray(ctx_all[: args.batch]),
    })
    params, opt_state = trainer.init_state(sample, seed=args.seed)

    n = lat_all.shape[0]

    def next_batch(step):
        idx = np.arange(step * args.batch, (step + 1) * args.batch) % n
        return trainer.shard_batch({
            "latents": jnp.asarray(lat_all[idx]),
            "context": jnp.asarray(ctx_all[idx]),
        })

    return _train_loop("diffusion", args, trainer, params, opt_state,
                       next_batch)


def cmd_train_lm(argv) -> int:
    p = _train_parser("LM next-token fine-tune (GPT-2 family)")
    p.add_argument("--tokens", default=None,
                   help=".npy int32 token stream; synthetic when omitted")
    p.add_argument("--seq-len", type=int, default=256)
    args = p.parse_args(argv)
    _apply_platform(args)

    import jax.numpy as jnp

    from cassmantle_tpu.config import MeshConfig
    from cassmantle_tpu.models.gpt2 import GPT2LM
    from cassmantle_tpu.parallel.mesh import make_mesh
    from cassmantle_tpu.parallel.lm_train import LMTrainer

    cfg = _framework_config(args)
    mesh = make_mesh(MeshConfig(dp=args.dp, tp=args.tp, sp=args.sp))
    model = GPT2LM(cfg.models.gpt2)
    trainer = LMTrainer(model, mesh, lr=args.lr, remat=args.remat)

    if args.tokens:
        stream = np.load(args.tokens).astype(np.int32)
    else:
        rng = np.random.default_rng(args.seed)
        stream = rng.integers(
            0, cfg.models.gpt2.vocab_size,
            size=args.batch * args.seq_len * 4, dtype=np.int32)
    rows = len(stream) // args.seq_len
    ids = stream[: rows * args.seq_len].reshape(rows, args.seq_len)
    mask = np.ones_like(ids)
    n = ids.shape[0]

    sample = trainer.shard_batch({
        "input_ids": jnp.asarray(ids[: args.batch]),
        "loss_mask": jnp.asarray(mask[: args.batch]),
    })
    params, opt_state = trainer.init_state(sample["input_ids"],
                                           seed=args.seed)

    def next_batch(step):
        idx = np.arange(step * args.batch, (step + 1) * args.batch) % n
        return trainer.shard_batch({
            "input_ids": jnp.asarray(ids[idx]),
            "loss_mask": jnp.asarray(mask[idx]),
        })

    return _train_loop("lm", args, trainer, params, opt_state, next_batch)


COMMANDS = {
    "serve": cmd_serve,
    "bench": cmd_bench,
    "fetch-weights": cmd_fetch_weights,
    "quantize-weights": cmd_quantize_weights,
    "clip-report": cmd_clip_report,
    "build-wordlist": cmd_build_wordlist,
    "build-embed-table": cmd_build_embed_table,
    "lm-int8-ab": cmd_lm_int8_ab,
    "weights-drill": cmd_weights_drill,
    "train-diffusion": cmd_train_diffusion,
    "train-lm": cmd_train_lm,
}


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "version":
        from cassmantle_tpu import __version__

        print(__version__)
        return 0
    if not argv or argv[0] in ("-h", "--help") or argv[0] not in COMMANDS:
        names = " | ".join(list(COMMANDS) + ["version"])
        print(f"usage: python -m cassmantle_tpu {{{names}}} [args]",
              file=sys.stderr)
        return 0 if argv and argv[0] in ("-h", "--help") else 2
    return COMMANDS[argv[0]](argv[1:])


if __name__ == "__main__":
    sys.exit(main())
