"""Framework CLI: ``python -m cassmantle_tpu <command>``.

The reference has no CLI layer at all — it launches as ``uvicorn
main:app`` (reference requirements.txt:2, main.py:18) and its one tool is
a bare script (download_model.py). A standalone framework needs a front
door; this one wraps every runnable surface:

- ``serve``            game server (presets: sd15 / sdxl / fast; --fake)
- ``bench``            the BASELINE.md workload ladder (repo-root bench.py)
- ``fetch-weights``    checkpoint/tokenizer bootstrap (tools/fetch_weights.py)
- ``quantize-weights`` offline int8 LM checkpoints (tools/quantize_weights.py)
- ``clip-report``      CLIP-sim quality gate across presets (tools/clip_report.py)
- ``build-wordlist``   regenerate the spellcheck lexicon (tools/build_wordlist.py)
- ``lm-int8-ab``       fp-vs-int8 LM decode A/B (tools/lm_int8_ab.py)
- ``train-diffusion``  dp×tp×sp UNet fine-tuning loop (synthetic or .npy data)
- ``train-lm``         LM fine-tuning loop (GPT-2 by default)
- ``version``

Training commands are thin loops over parallel/train.py and
parallel/lm_train.py with orbax checkpointing — the same step functions
the multi-chip dryrun compiles.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _exit_code(e: SystemExit) -> int:
    """sys.exit accepts any object; non-int codes print to stderr."""
    if e.code is None:
        return 0
    if isinstance(e.code, int):
        return e.code
    print(e.code, file=sys.stderr)
    return 1


def cmd_serve(argv) -> int:
    from cassmantle_tpu.server.app import main as serve_main

    saved = sys.argv
    sys.argv = ["cassmantle-tpu serve"] + list(argv)
    try:
        serve_main()
    except SystemExit as e:
        return _exit_code(e)
    finally:
        sys.argv = saved
    return 0


def _run_script(relpath: str, argv) -> int:
    """Exec a repo-root script (bench.py, tools/*) in-process.

    cwd is left alone — user-supplied relative paths keep meaning what
    they mean in the shell. The scripts themselves resolve their
    *defaults* (data/wordlist.txt, BENCH_SUITE.json, weights/) against
    the repo root so a module-CLI invocation from anywhere still reads
    and writes where the package expects."""
    import runpy

    path = os.path.join(_repo_root(), relpath)
    if not os.path.exists(path):
        print(f"{relpath} not found (not a source checkout?)",
              file=sys.stderr)
        return 2
    saved = sys.argv
    sys.argv = [path] + list(argv)
    try:
        runpy.run_path(path, run_name="__main__")
    except SystemExit as e:
        return _exit_code(e)
    finally:
        sys.argv = saved
    return 0


def cmd_bench(argv) -> int:
    return _run_script("bench.py", argv)


def cmd_fetch_weights(argv) -> int:
    return _run_script(os.path.join("tools", "fetch_weights.py"), argv)


def cmd_quantize_weights(argv) -> int:
    return _run_script(os.path.join("tools", "quantize_weights.py"), argv)


def cmd_clip_report(argv) -> int:
    return _run_script(os.path.join("tools", "clip_report.py"), argv)


def cmd_build_wordlist(argv) -> int:
    return _run_script(os.path.join("tools", "build_wordlist.py"), argv)


def cmd_lm_int8_ab(argv) -> int:
    return _run_script(os.path.join("tools", "lm_int8_ab.py"), argv)


def _train_parser(desc: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=desc)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--dp", type=int, default=-1)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--sp", type=int, default=1)
    p.add_argument("--remat", action="store_true",
                   help="rematerialize the forward in backward (fits "
                        "bigger batches per chip)")
    p.add_argument("--checkpoint-dir", default=None,
                   help="orbax checkpoint directory (resumes if present)")
    p.add_argument("--checkpoint-every", type=int, default=100)
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--config", default="default",
                   choices=("default", "test"),
                   help="'test' = the tiny-model config (smoke runs on "
                        "CPU devices)")
    p.add_argument("--platform", default="auto", choices=("auto", "cpu"),
                   help="'cpu' pins jax to host devices (with the "
                        "8-virtual-device flag) — smoke-test sharded "
                        "training without touching an accelerator")
    return p


def _apply_platform(args) -> None:
    if args.platform == "cpu":
        from cassmantle_tpu.utils.xla_flags import pin_cpu_platform

        pin_cpu_platform(virtual_devices=True)


def _framework_config(args):
    if args.config == "test":
        from cassmantle_tpu.config import test_config

        return test_config()
    from cassmantle_tpu.config import FrameworkConfig

    return FrameworkConfig()


def _checkpointer(args):
    if not args.checkpoint_dir:
        return None
    from cassmantle_tpu.utils.checkpoint import TrainCheckpointer

    return TrainCheckpointer(args.checkpoint_dir)


def _train_loop(name, args, trainer, params, opt_state, next_batch):
    """Shared driver: step/log/checkpoint. ``next_batch(step)`` returns a
    sharded batch dict."""
    import jax

    ckpt = _checkpointer(args)
    start = 0
    if ckpt is not None and ckpt.latest_step() is not None:
        start = ckpt.latest_step()
        restored = ckpt.restore(
            template={"params": params, "opt_state": opt_state})
        params, opt_state = restored["params"], restored["opt_state"]
        print(f"resumed from step {start}")
    root_rng = jax.random.PRNGKey(args.seed)
    for step in range(start, args.steps):
        # fold the step index in (not a split chain): a resumed run at
        # step N draws the same subkey an uninterrupted run would
        sub = jax.random.fold_in(root_rng, step)
        params, opt_state, loss = trainer.step(
            params, opt_state, next_batch(step), sub)
        if step % args.log_every == 0 or step == args.steps - 1:
            print(f"[{name}] step {step} loss {float(loss):.5f}")
        if ckpt is not None and (step + 1) % args.checkpoint_every == 0:
            ckpt.save(step + 1, params, opt_state)
    if ckpt is not None:
        ckpt.save(args.steps, params, opt_state)
        ckpt.close()
    return 0


def cmd_train_diffusion(argv) -> int:
    p = _train_parser("UNet denoising fine-tune (dp × tp × sp)")
    p.add_argument("--latents", default=None,
                   help=".npy of clean latents (N, H, W, 4); synthetic "
                        "data when omitted")
    p.add_argument("--context", default=None,
                   help=".npy of text states (N, S, context_dim)")
    p.add_argument("--image-size", type=int, default=512)
    args = p.parse_args(argv)
    if bool(args.latents) != bool(args.context):
        p.error("--latents and --context must be given together")
    _apply_platform(args)

    import jax.numpy as jnp

    from cassmantle_tpu.config import MeshConfig
    from cassmantle_tpu.parallel.mesh import make_mesh
    from cassmantle_tpu.parallel.train import DiffusionTrainer

    cfg = _framework_config(args)
    mesh = make_mesh(MeshConfig(dp=args.dp, tp=args.tp, sp=args.sp))
    trainer = DiffusionTrainer(cfg, mesh, lr=args.lr, remat=args.remat)

    hw = args.image_size // 8
    ctx_dim = cfg.models.unet.context_dim
    if args.latents:
        lat_all = np.load(args.latents).astype(np.float32)
        ctx_all = np.load(args.context).astype(np.float32)
    else:
        rng = np.random.default_rng(args.seed)
        lat_all = rng.standard_normal((args.batch * 4, hw, hw, 4),
                                      dtype=np.float32)
        ctx_all = rng.standard_normal((args.batch * 4, 77, ctx_dim),
                                      dtype=np.float32)

    sample = trainer.shard_batch({
        "latents": jnp.asarray(lat_all[: args.batch]),
        "context": jnp.asarray(ctx_all[: args.batch]),
    })
    params, opt_state = trainer.init_state(sample, seed=args.seed)

    n = lat_all.shape[0]

    def next_batch(step):
        idx = np.arange(step * args.batch, (step + 1) * args.batch) % n
        return trainer.shard_batch({
            "latents": jnp.asarray(lat_all[idx]),
            "context": jnp.asarray(ctx_all[idx]),
        })

    return _train_loop("diffusion", args, trainer, params, opt_state,
                       next_batch)


def cmd_train_lm(argv) -> int:
    p = _train_parser("LM next-token fine-tune (GPT-2 family)")
    p.add_argument("--tokens", default=None,
                   help=".npy int32 token stream; synthetic when omitted")
    p.add_argument("--seq-len", type=int, default=256)
    args = p.parse_args(argv)
    _apply_platform(args)

    import jax.numpy as jnp

    from cassmantle_tpu.config import MeshConfig
    from cassmantle_tpu.models.gpt2 import GPT2LM
    from cassmantle_tpu.parallel.mesh import make_mesh
    from cassmantle_tpu.parallel.lm_train import LMTrainer

    cfg = _framework_config(args)
    mesh = make_mesh(MeshConfig(dp=args.dp, tp=args.tp, sp=args.sp))
    model = GPT2LM(cfg.models.gpt2)
    trainer = LMTrainer(model, mesh, lr=args.lr, remat=args.remat)

    if args.tokens:
        stream = np.load(args.tokens).astype(np.int32)
    else:
        rng = np.random.default_rng(args.seed)
        stream = rng.integers(
            0, cfg.models.gpt2.vocab_size,
            size=args.batch * args.seq_len * 4, dtype=np.int32)
    rows = len(stream) // args.seq_len
    ids = stream[: rows * args.seq_len].reshape(rows, args.seq_len)
    mask = np.ones_like(ids)
    n = ids.shape[0]

    sample = trainer.shard_batch({
        "input_ids": jnp.asarray(ids[: args.batch]),
        "loss_mask": jnp.asarray(mask[: args.batch]),
    })
    params, opt_state = trainer.init_state(sample["input_ids"],
                                           seed=args.seed)

    def next_batch(step):
        idx = np.arange(step * args.batch, (step + 1) * args.batch) % n
        return trainer.shard_batch({
            "input_ids": jnp.asarray(ids[idx]),
            "loss_mask": jnp.asarray(mask[idx]),
        })

    return _train_loop("lm", args, trainer, params, opt_state, next_batch)


COMMANDS = {
    "serve": cmd_serve,
    "bench": cmd_bench,
    "fetch-weights": cmd_fetch_weights,
    "quantize-weights": cmd_quantize_weights,
    "clip-report": cmd_clip_report,
    "build-wordlist": cmd_build_wordlist,
    "lm-int8-ab": cmd_lm_int8_ab,
    "train-diffusion": cmd_train_diffusion,
    "train-lm": cmd_train_lm,
}


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "version":
        from cassmantle_tpu import __version__

        print(__version__)
        return 0
    if not argv or argv[0] in ("-h", "--help") or argv[0] not in COMMANDS:
        names = " | ".join(list(COMMANDS) + ["version"])
        print(f"usage: python -m cassmantle_tpu {{{names}}} [args]",
              file=sys.stderr)
        return 0 if argv and argv[0] in ("-h", "--help") else 2
    return COMMANDS[argv[0]](argv[1:])


if __name__ == "__main__":
    sys.exit(main())
