"""cassmantle_tpu — a TPU-native real-time generative guessing-game framework.

A ground-up JAX/Flax/Pallas re-design of the capability surface of
SnowCheetos/CassMantle (see SURVEY.md): a multiplayer web game whose content
loop — LLM prompt generation, diffusion image generation, descriptive-word
masking, guess-similarity scoring, progressive image reveal — is served
entirely from TPU VMs, with no GPU and no external inference API.

Where the reference (``/root/reference``) delegates model compute to the
HuggingFace hosted Inference API (backend.py:24-25, 240-295) and scores
guesses with a CPU word2vec model (backend.py:45, 303-317), this framework
runs everything locally as jit/shard_map'd XLA graphs:

- ``models/``   Flax model zoo: CLIP text encoder, SD UNet, VAE, GPT-2, MiniLM.
- ``ops/``      TPU compute ops: Pallas flash attention, DDIM scan sampler,
                KV-cached greedy decode, batched cosine scorer, device blur.
- ``parallel/`` Mesh construction, shardings, ring attention, collectives,
                distributed train/serve steps.
- ``engine/``   Game engine: state store, sessions, rounds, scoring, masking.
- ``serving/``  Continuous-batching queue + async device dispatch.
- ``server/``   HTTP/WS API surface (aiohttp) + static frontend.
- ``utils/``    Codec, text, logging/metrics, profiling.
"""

__version__ = "0.1.0"

from cassmantle_tpu.config import (  # noqa: F401
    FrameworkConfig,
    GameConfig,
    MeshConfig,
    ModelZooConfig,
    SamplerConfig,
    ServingConfig,
)
