"""Per-worker device telemetry: HBM occupancy, per-pipeline highwater,
compile-cost summary (ISSUE 14).

The accelerator is the scarcest resource in the fleet and, until this
module, the only one `/metrics` said nothing about: a worker could sit
one allocation from an OOM, or burn minutes in recompiles, and the
federation view showed healthy queues. The sampler exports, per local
device:

- ``device.hbm_bytes_in_use`` / ``device.hbm_bytes_limit`` /
  ``device.hbm_peak_bytes`` gauges (labeled ``device=``), read from
  ``device.memory_stats()`` — refreshed on every `/metrics` scrape and
  by a background loop (same cadence knob as ``obs/process.py``,
  ``ObsConfig.process_sample_interval_s``);
- ``device.hbm_available`` — an EXPLICIT availability marker: a CPU
  host (``memory_stats()`` returns None) or an older runtime (method
  absent) exports ``0`` and **no** ``hbm_*`` gauges at all, never
  zeros. A dashboard must distinguish "no HBM telemetry here" from
  "this chip is empty" — an all-zero worker would read as free
  capacity and attract load (tests/test_obs_device.py pins this);
- ``device.hbm_highwater_bytes`` (labeled ``pipeline=``): the highest
  ``bytes_in_use`` observed at that pipeline's dispatch boundaries
  (``utils/profiling.block_timer`` calls :func:`note_dispatch` right
  after the device sync, while the dispatch's buffers are still
  resident) — which pipeline's working set actually crowds the chip.

`/readyz` embeds :func:`device_block`: the same numbers plus the jit
sentinel's compile summary (count / wall seconds / slowest functions,
``utils/jit_sentinel.py``), so the page that says a worker is degraded
also says whether HBM pressure or a compile storm explains it.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Dict, Optional

from cassmantle_tpu.utils.logging import get_logger, metrics

log = get_logger("obs.device")

#: memory_stats() key -> exported gauge suffix; only present keys
#: export (a partial stats dict exports what it has, marks available)
_STAT_GAUGES = (
    ("bytes_in_use", "device.hbm_bytes_in_use"),
    ("bytes_limit", "device.hbm_bytes_limit"),
    ("peak_bytes_in_use", "device.hbm_peak_bytes"),
)


def _memory_stats(device) -> Optional[Dict[str, float]]:
    """``device.memory_stats()`` with every degradation mode folded to
    None: method absent (old runtime), returns None (CPU backend),
    raises, or returns a dict with no byte fields."""
    stats_fn = getattr(device, "memory_stats", None)
    if stats_fn is None:
        return None
    try:
        stats = stats_fn()
    except Exception:
        return None
    if not isinstance(stats, dict):
        return None
    if not any(k in stats for k, _ in _STAT_GAUGES):
        return None
    return stats


def _device_label(device) -> str:
    return f"{getattr(device, 'platform', 'dev')}:" \
           f"{getattr(device, 'id', 0)}"


class DeviceMetrics:
    """HBM gauges + per-pipeline dispatch-boundary highwater."""

    def __init__(self, registry=None, devices_fn=None) -> None:
        self._registry = registry if registry is not None else metrics
        # injectable device list (tests fake memory_stats shapes
        # without a backend); default reads jax lazily — importing this
        # module must never initialize a backend
        self._devices_fn = devices_fn
        self._lock = threading.Lock()
        self._highwater: Dict[str, float] = {}
        self._last: Dict[str, Optional[Dict[str, float]]] = {}

    def _devices(self):
        if self._devices_fn is not None:
            return self._devices_fn()
        import sys

        # a telemetry read must never be the thing that imports jax or
        # INITIALIZES a backend: --fake drill workers are deliberately
        # accelerator-free (serving/fake_scorer.py), and on a TPU host
        # an auxiliary worker grabbing the single-client runtime would
        # contend with the real serving process. No backend = no
        # devices to report, honestly — the serving pipelines
        # initialize it long before any scrape that matters.
        if "jax" not in sys.modules:
            return []
        try:
            from jax._src import xla_bridge

            if not getattr(xla_bridge, "_backends", None):
                return []
        except Exception:  # probe unavailable on a future jax: accept
            pass           # the import-only signal above
        import jax

        return jax.local_devices()

    def sample(self) -> Dict[str, Optional[Dict[str, float]]]:
        """Refresh the per-device gauges; returns {label: stats|None}
        (None = telemetry unavailable on that device). Cheap — one
        runtime call per device — so it runs on every scrape."""
        seen: Dict[str, Optional[Dict[str, float]]] = {}
        try:
            devices = self._devices()
        except Exception:  # backend dead/uninitializable: mark nothing
            log.exception("device list unavailable; hbm gauges not "
                          "refreshed")
            return {}
        for dev in devices:
            label = _device_label(dev)
            stats = _memory_stats(dev)
            seen[label] = stats
            labels = {"device": label}
            if stats is None:
                # explicit unavailability — never zeros (zeros read as
                # an empty chip and attract load). Byte gauges this
                # device exported BEFORE going dark are retracted: a
                # frozen last reading would serve as current occupancy
                # to every later scrape, the exact misleading state
                # the marker exists to prevent
                self._registry.gauge("device.hbm_available", 0.0,
                                     labels=labels)
                for _, gauge in _STAT_GAUGES:
                    self._registry.remove_gauge(gauge, labels=labels)
                continue
            self._registry.gauge("device.hbm_available", 1.0,
                                 labels=labels)
            for key, gauge in _STAT_GAUGES:
                if key in stats:
                    self._registry.gauge(gauge, float(stats[key]),
                                         labels=labels)
                else:
                    self._registry.remove_gauge(gauge, labels=labels)
        with self._lock:
            self._last = seen
        return seen

    def note_dispatch(self, pipeline: str) -> None:
        """Dispatch-boundary highwater hook (block_timer exit, right
        after the device sync): record the worst ``bytes_in_use``
        across devices against this pipeline. Silently a no-op where
        HBM telemetry is unavailable — the gauge simply never exists
        (the availability marker already says why)."""
        try:
            worst = 0.0
            seen_any = False
            for dev in self._devices():
                stats = _memory_stats(dev)
                if stats is None or "bytes_in_use" not in stats:
                    continue
                seen_any = True
                worst = max(worst, float(stats["bytes_in_use"]))
            if not seen_any:
                return
            with self._lock:
                prev = self._highwater.get(pipeline, 0.0)
                if worst <= prev:
                    return
                self._highwater[pipeline] = worst
                # gauge emitted INSIDE the lock: map-update and export
                # must be atomic, or a preempted smaller sample's late
                # gauge write would shadow a larger one forever (the
                # `worst <= prev` early-out never re-emits). The
                # registry lock is a leaf — same nesting every
                # metrics-under-dispatch-lock site already does.
                self._registry.gauge("device.hbm_highwater_bytes",
                                     worst,
                                     labels={"pipeline": pipeline})
        except Exception:  # telemetry must never break a dispatch
            log.exception("hbm highwater sample failed")

    def highwater(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._highwater)

    def device_block(self) -> Dict[str, object]:
        """The `/readyz`-adjacent ``device_telemetry`` block: last
        sampled per-device HBM numbers (or the explicit
        ``"unavailable"`` marker), per-pipeline dispatch highwater, and
        the jit sentinel's compile-cost summary."""
        from cassmantle_tpu.utils import jit_sentinel

        seen = self.sample()
        devices: Dict[str, object] = {}
        for label, stats in seen.items():
            if stats is None:
                devices[label] = "unavailable"
            else:
                devices[label] = {
                    key: int(stats[key])
                    for key, _ in _STAT_GAUGES if key in stats
                }
        compile_s = jit_sentinel.compile_time_snapshot()
        slowest = sorted(compile_s.items(), key=lambda kv: -kv[1])[:5]
        return {
            "devices": devices,
            "hbm_highwater_bytes": {
                k: int(v) for k, v in self.highwater().items()},
            "compile": {
                "functions": len(compile_s),
                "compiles": jit_sentinel.compiles(),
                "total_s": round(sum(compile_s.values()), 3),
                "slowest": [{"fn": name, "seconds": round(sec, 3)}
                            for name, sec in slowest],
            },
        }

    async def run(self, interval_s: float = 5.0) -> None:
        """Background sampler (started beside the process-metrics loop,
        server/app.py): scrapes also refresh opportunistically, but a
        worker nobody scrapes must still carry fresh HBM gauges into
        its membership-driven federation view."""
        self.sample()
        while True:
            await asyncio.sleep(interval_s)
            self.sample()


#: process-global instance — block_timer's dispatch hook and the server
#: share one highwater map, like the tracer/flight-recorder singletons
device_metrics = DeviceMetrics()


def note_dispatch(pipeline: str) -> None:
    device_metrics.note_dispatch(pipeline)
