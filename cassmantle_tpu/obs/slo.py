"""SLO burn-rate engine: declarative objectives over the metrics registry.

`bench.py rooms_load` measures the p99 once and forgets; production
needs somebody *watching* it. This module evaluates a small set of
declarative objectives against the existing cumulative
counter/histogram registry (utils/logging.py) — no second measurement
pipeline — over **multi-window burn rates**:

- the **fast window** (~5 min) answers "are we burning error budget
  RIGHT NOW" — an objective trips to ``burning`` when its fast-window
  burn rate exceeds 1.0 (budget spent faster than the SLO allows);
- the **slow window** (~1 h) answers "has the incident actually
  drained" — a burning objective recovers only once the slow window is
  back under budget (and the fast window agrees), so a flapping burst
  can't flap the verdict with it.

Burn rate is the standard SRE quantity: ``bad_fraction / error_budget``
— 1.0 means exactly on-SLO spend, 10 means the budget burns 10x too
fast. Windowed deltas come from periodic samples of the cumulative
series (the engine keeps a bounded ring; windows older than the ring
use its oldest sample — a partial window, never a fabricated one).

Three objective kinds:

- ``latency``: a histogram name + threshold — the SLO is "fraction of
  observations ≤ threshold ≥ objective_ratio" (p99 ≤ target ==
  ratio 0.99). Good counts come from the cumulative buckets at the
  smallest bound ≥ the threshold, so the verdict is exact with respect
  to the bucket ladder.
- ``ratio``: good/bad counter name tuples (summed across label sets —
  per-room labels aggregate to worker truth).
- ``gauge``: a gauge name + bound; burn is the instantaneous
  ``value / bound`` (replication lag has no meaningful window delta).

Outputs: ``slo.burn_rate_fast`` / ``slo.burn_rate_slow`` /
``slo.burning`` gauges (labeled ``objective=``), ``slo.burn`` /
``slo.recovered`` flight-recorder events, the ``/sloz`` page, and a
**non-gating advisory block** in ``/readyz`` — an SLO verdict tells the
operator where the budget goes; it must never drain a worker by itself
(that is the supervisor's job, on direct evidence).

Everything is injectable (clock, registry, recorder) so the state
machine is unit-testable without wall time (tests/test_obs_cluster.py).
"""

from __future__ import annotations

import bisect
import dataclasses
import os
import time
from collections import deque
from typing import Callable, Deque, Dict, Optional, Sequence, Tuple

from cassmantle_tpu.obs.recorder import flight_recorder
from cassmantle_tpu.utils.logging import get_logger, metrics

log = get_logger("slo")


@dataclasses.dataclass(frozen=True)
class Objective:
    """One declarative objective. ``kind`` selects which fields apply:
    latency → metric (histogram) + threshold_s + objective_ratio;
    ratio → good/bad counter tuples + objective_ratio;
    gauge → metric (gauge) + bound."""

    name: str
    kind: str                       # "latency" | "ratio" | "gauge"
    description: str = ""
    metric: str = ""
    threshold_s: float = 0.0
    objective_ratio: float = 0.99
    good: Tuple[str, ...] = ()
    bad: Tuple[str, ...] = ()
    bound: float = 0.0

    def target(self) -> Dict[str, object]:
        if self.kind == "latency":
            return {"quantile": self.objective_ratio,
                    "le_s": self.threshold_s}
        if self.kind == "ratio":
            return {"success_ratio": self.objective_ratio}
        return {"max": self.bound}


def default_objectives(cfg) -> Tuple[Objective, ...]:
    """The worker's default SLO set, thresholds from ``ObsConfig``:
    the guess-path latency SLO `bench.py rooms_load` measures, the
    round-generation success ratio the supervisor degrades on, and the
    replication-lag bound DEPLOY.md §3a tells operators to alert on."""
    obs = cfg.obs
    return (
        Objective(
            name="score_latency", kind="latency",
            metric="http.compute_score_s",
            threshold_s=obs.slo_score_p99_s, objective_ratio=0.99,
            description="p99 of /compute_score end-to-end latency"),
        Objective(
            name="round_generation", kind="ratio",
            good=("rounds.generated", "rounds.buffered"),
            bad=("rounds.buffer_failures",),
            objective_ratio=obs.slo_generation_ratio,
            description="round content generation success ratio"),
        Objective(
            name="replication_lag", kind="gauge", metric="repl.lag",
            bound=obs.slo_repl_lag_max,
            description="worst follower lag in shipped log commands"),
    ) + _probe_objectives(obs)


def _probe_objectives(obs) -> Tuple[Objective, ...]:
    """Black-box canary objectives (ISSUE 18): the probe plays the
    real game surface, so its verdicts are the closest thing to a
    player's experience the SLO set has. Absent entirely under
    CASSMANTLE_NO_PROBER — a disabled prober must leave zero probe
    artifacts, including the slo.burning{objective=probe_*} gauges
    evaluate() would otherwise mint with no traffic."""
    if os.environ.get("CASSMANTLE_NO_PROBER", "").lower() in (
            "1", "true", "yes", "on"):
        return ()
    return (
        Objective(
            name="probe_success", kind="ratio",
            good=("probe.ok",), bad=("probe.failures",),
            objective_ratio=obs.probe_success_ratio,
            description="synthetic canary probe success ratio"),
        Objective(
            name="probe_latency", kind="latency",
            metric="probe.e2e_s",
            threshold_s=obs.probe_p99_s, objective_ratio=0.99,
            description="p99 of canary end-to-end probe time"),
    )


def _latency_good(bounds: Sequence[float], counts: Sequence[int],
                  threshold: float) -> int:
    """Observations ≤ the smallest bucket bound ≥ ``threshold`` — exact
    w.r.t. the ladder; a threshold above every bound counts everything
    outside the +Inf overflow bucket as good."""
    idx = bisect.bisect_left(list(bounds), threshold)
    if idx >= len(bounds):
        return int(sum(counts[:-1]))
    return int(sum(counts[: idx + 1]))


class SloEngine:
    """Samples the registry, computes per-objective fast/slow burn
    rates, and runs the ok↔burning state machine."""

    def __init__(
        self,
        objectives: Sequence[Objective],
        *,
        registry=None,
        recorder=None,
        fast_window_s: float = 300.0,
        slow_window_s: float = 3600.0,
        clock: Callable[[], float] = time.monotonic,
        min_eval_gap_s: Optional[float] = None,
        max_samples: int = 4096,
    ) -> None:
        self.objectives = tuple(objectives)
        self._registry = registry if registry is not None else metrics
        self._recorder = recorder if recorder is not None \
            else flight_recorder
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = max(float(slow_window_s),
                                 self.fast_window_s)
        self._clock = clock
        # scrape-driven evaluation (/sloz calls evaluate per hit) must
        # not grow the sample ring per request: below the gap the last
        # verdict is served verbatim
        self.min_eval_gap_s = (min(1.0, self.fast_window_s / 10.0)
                               if min_eval_gap_s is None
                               else float(min_eval_gap_s))
        # (t, {objective: raw}) — newest last; bounded both by time
        # (pruned past the slow window) and by count (scrape floods)
        self._samples: Deque[Tuple[float, Dict[str, object]]] = \
            deque(maxlen=max_samples)
        self._state: Dict[str, str] = {o.name: "ok"
                                       for o in self.objectives}
        self._last_eval: Optional[float] = None
        self._last: Dict[str, dict] = {}
        # consumers called after each evaluation pass with the verdict
        # dict (e.g. the brownout ladder, serving/overload.py); an
        # actuation bug must never break the evaluation loop
        self._listeners: list = []
        # the baseline: deltas measure from engine start, not from the
        # process's whole cumulative history
        self._samples.append((self._clock(), self._raw()))

    # -- raw sampling ------------------------------------------------------
    def _raw(self) -> Dict[str, object]:
        raw: Dict[str, object] = {}
        for obj in self.objectives:
            if obj.kind == "latency":
                ht = self._registry.hist_totals(obj.metric)
                if ht is None:
                    raw[obj.name] = (0, 0)
                else:
                    bounds, counts, total = ht
                    raw[obj.name] = (
                        _latency_good(bounds, counts, obj.threshold_s),
                        total)
            elif obj.kind == "ratio":
                good = sum(self._registry.counter_total(n)
                           for n in obj.good)
                bad = sum(self._registry.counter_total(n)
                          for n in obj.bad)
                raw[obj.name] = (good, good + bad)
            else:  # gauge
                values = self._registry.gauge_values(obj.metric)
                raw[obj.name] = max(values) if values else None
        return raw

    def _sample_at(self, t_cut: float) -> Optional[Dict[str, object]]:
        """The newest sample taken at or before ``t_cut``; the oldest
        resident sample when the ring doesn't reach that far back (a
        partial window — honest, never fabricated)."""
        best = None
        for t, raw in self._samples:
            if t <= t_cut:
                best = raw
            else:
                break
        if best is None and self._samples:
            best = self._samples[0][1]
        return best

    def _burn(self, obj: Objective, now_raw, now: float,
              window_s: float) -> float:
        if obj.kind == "gauge":
            if now_raw is None or obj.bound <= 0:
                return 0.0
            return float(now_raw) / obj.bound
        base = self._sample_at(now - window_s)
        g0, t0 = base.get(obj.name, (0, 0)) if base else (0, 0)
        g1, t1 = now_raw
        d_total = float(t1) - float(t0)
        if d_total <= 0:
            return 0.0          # no traffic in the window = no burn
        d_bad = max(0.0, d_total - (float(g1) - float(g0)))
        budget = max(1e-9, 1.0 - obj.objective_ratio)
        return (d_bad / d_total) / budget

    # -- evaluation --------------------------------------------------------
    def evaluate(self) -> Dict[str, dict]:
        """One evaluation pass: burn rates, state transitions, gauges,
        recorder events. Returns the per-objective verdicts (also kept
        for :meth:`status`). Rate-limited by ``min_eval_gap_s``."""
        now = self._clock()
        if self._last_eval is not None and \
                now - self._last_eval < self.min_eval_gap_s:
            return self._last
        self._last_eval = now
        raws = self._raw()
        out: Dict[str, dict] = {}
        for obj in self.objectives:
            fast = self._burn(obj, raws.get(obj.name), now,
                              self.fast_window_s)
            slow = self._burn(obj, raws.get(obj.name), now,
                              self.slow_window_s)
            state = self._state[obj.name]
            if state == "ok" and fast > 1.0:
                state = "burning"
                self._recorder.record(
                    "slo.burn", objective=obj.name,
                    fast_burn=round(fast, 3), slow_burn=round(slow, 3))
                log.warning("SLO %s burning: fast burn %.2f "
                            "(slow %.2f)", obj.name, fast, slow)
            elif state == "burning" and slow <= 1.0 and fast <= 1.0:
                state = "ok"
                self._recorder.record(
                    "slo.recovered", objective=obj.name,
                    fast_burn=round(fast, 3), slow_burn=round(slow, 3))
                log.info("SLO %s recovered", obj.name)
            self._state[obj.name] = state
            labels = {"objective": obj.name}
            self._registry.gauge("slo.burn_rate_fast", fast,
                                 labels=labels)
            self._registry.gauge("slo.burn_rate_slow", slow,
                                 labels=labels)
            self._registry.gauge(
                "slo.burning", 1.0 if state == "burning" else 0.0,
                labels=labels)
            out[obj.name] = {
                "kind": obj.kind,
                "state": state,
                "fast_burn": round(fast, 4),
                "slow_burn": round(slow, 4),
                "target": obj.target(),
                "description": obj.description,
            }
        self._registry.inc("slo.evals")
        self._samples.append((now, raws))
        # keep ONE sample at-or-before the slow-window start as the
        # boundary baseline; everything older is unreachable
        cut = now - self.slow_window_s
        while len(self._samples) > 1 and self._samples[1][0] <= cut:
            self._samples.popleft()
        self._last = out
        for listener in self._listeners:
            try:
                listener(out)
            except Exception:
                log.exception("slo listener failed; continuing")
        return out

    def add_listener(self, fn: Callable[[Dict[str, dict]], None]) -> None:
        """Subscribe a consumer to every evaluation pass (the brownout
        ladder). Listeners run inside evaluate(), on whichever thread
        called it — they must be fast and lock-light."""
        self._listeners.append(fn)

    def status(self) -> Dict[str, object]:
        """The `/sloz` body and the `/readyz` advisory block (callers
        wanting freshness call :meth:`evaluate` first)."""
        if not self._last:
            self.evaluate()
        return {
            "objectives": self._last,
            "burning": sorted(n for n, s in self._state.items()
                              if s == "burning"),
            "windows": {"fast_s": self.fast_window_s,
                        "slow_s": self.slow_window_s},
        }
