"""Analytic roofline cost model: FLOPs + HBM bytes per serving dispatch.

PERF_NOTES has carried analytic per-image TF figures since round 6
(510.6 vs 686.6 TF/image, "58% of ceiling") — but only as doc prose.
This module makes the analytic model a *runtime* object (ISSUE 14):

- :func:`trace_cost` derives FLOPs and an HBM-traffic proxy from a
  function's jaxpr (dot/conv only, scan trip counts multiplied) —
  shape-only, so it runs on any backend, against ``ShapeDtypeStruct``
  params, without executing anything. ``tools/profile_unet.py`` shares
  the same per-eqn math (:func:`eqn_flops`), so the profiler tables and
  the live attribution can never disagree.
- ``data/cost_model.json`` (written by ``tools/profile_unet.py
  --emit-cost-model``, drift-gated by tests/test_obs_device.py) is the
  committed artifact: per pipeline/stage/bucket analytic FLOPs + HBM
  bytes for the production configs, keyed by a config-digest signature.
- :func:`flops_per_item` is what the serving pipelines call per
  dispatch variant: committed entry when the runtime signature matches
  the artifact (production configs — no tracing at startup), else a
  trace-once of the pipeline's OWN jitted impl (exact for any config:
  tiers, encprop, deepcache — the jaxpr is the truth), cached
  process-wide. The result feeds ``block_timer(flops_est=...)``
  (utils/profiling.py): stage spans gain ``flops_est`` attrs and
  ``pipeline.mxu_utilization`` / ``request.device_flops`` report
  measured-vs-ceiling live (docs/PERF_NOTES.md "Reading the roofline
  live").

The HBM-bytes figure is a roofline *proxy* — operand + result buffer
bytes of every counted op, ignoring XLA fusion (which keeps most
intermediates out of HBM). It upper-bounds true traffic and is emitted
for the artifact's roofline arithmetic, not for live attribution.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import threading
from typing import Callable, Dict, Optional, Tuple

from cassmantle_tpu.utils.logging import get_logger

log = get_logger("costmodel")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
COST_MODEL_PATH = os.path.join(_REPO_ROOT, "data", "cost_model.json")

#: default per-chip peak (bf16 TFLOP/s): the v5e figure every PERF_NOTES
#: ceiling uses; override per fleet via CASSMANTLE_CHIP_TFLOPS (§6).
DEFAULT_CHIP_TFLOPS = 197.0


def chip_peak_flops() -> float:
    """Peak device FLOP/s the ``pipeline.mxu_utilization`` gauge divides
    by. On a non-TPU backend the ratio still renders (a tiny honest
    number) so the CPU smoke path exercises the same code."""
    raw = os.environ.get("CASSMANTLE_CHIP_TFLOPS", "")
    try:
        tflops = float(raw) if raw else DEFAULT_CHIP_TFLOPS
    except ValueError:
        tflops = DEFAULT_CHIP_TFLOPS
    return tflops * 1e12


# -- per-eqn analytic math (shared with tools/profile_unet.py) -------------

def eqn_flops(eqn) -> float:
    """Analytic FLOPs of one jaxpr eqn: 2·M·N·K for ``dot_general``,
    2·out·C_in·prod(kernel) for ``conv_general_dilated``, 0 otherwise.
    Shape-derived — backend-independent."""
    name = eqn.primitive.name
    if name == "dot_general":
        dims = eqn.params["dimension_numbers"]
        (lc, _), _ = dims
        a = eqn.invars[0].aval.shape
        out = eqn.outvars[0].aval.shape
        k = math.prod(a[i] for i in lc) or 1
        return 2.0 * math.prod(out) * k
    if name == "conv_general_dilated":
        out = eqn.outvars[0].aval.shape
        rhs = eqn.invars[1].aval.shape
        dn = eqn.params["dimension_numbers"]
        rhs_spec = dn.rhs_spec  # (out_c, in_c, *spatial)
        cin = rhs[rhs_spec[1]]
        spatial = [rhs[i] for i in rhs_spec[2:]]
        return 2.0 * math.prod(out) * cin * math.prod(spatial)
    return 0.0


def _aval_bytes(aval) -> float:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0.0
    try:
        itemsize = dtype.itemsize
    except AttributeError:  # pragma: no cover - exotic avals
        return 0.0
    return float(math.prod(shape) * itemsize)


def eqn_hbm_bytes(eqn) -> float:
    """HBM-traffic proxy for a counted eqn: operand + result buffer
    bytes (reads + the write). Ignores fusion — an upper bound."""
    if eqn.primitive.name not in ("dot_general", "conv_general_dilated"):
        return 0.0
    total = sum(_aval_bytes(v.aval) for v in eqn.invars)
    total += sum(_aval_bytes(v.aval) for v in eqn.outvars)
    return total


def trace_cost(fn, *args) -> Tuple[float, float]:
    """(FLOPs, HBM-bytes proxy) of ``fn(*args)`` from its jaxpr.

    Scan bodies multiply by their trip count; pjit/cond/other
    sub-jaxprs recurse at the ambient multiplier (a ``while_loop`` body
    counts once — unknown trip count, documented undercount; none of
    the costed serving graphs contain one). Args may be concrete arrays
    or ``ShapeDtypeStruct``s — nothing executes."""
    import jax

    jaxpr = jax.make_jaxpr(fn)(*args)
    totals = [0.0, 0.0]

    def visit(jx, mult: float = 1.0) -> None:
        for eqn in jx.eqns:
            inner = mult
            if eqn.primitive.name == "scan":
                inner = mult * float(eqn.params.get("length", 1))
            for sub in eqn.params.values():
                if hasattr(sub, "jaxpr"):
                    visit(sub.jaxpr, inner)
                elif isinstance(sub, (list, tuple)):
                    for s in sub:
                        if hasattr(s, "jaxpr"):
                            visit(s.jaxpr, inner)
            totals[0] += eqn_flops(eqn) * mult
            totals[1] += eqn_hbm_bytes(eqn) * mult

    visit(jaxpr.jaxpr)
    return totals[0], totals[1]


def params_count(tree) -> int:
    """Total element count of a param pytree (host metadata only —
    works on device arrays, numpy, and ShapeDtypeStructs alike). The
    LM/scorer analytic model: dense decode costs 2·N FLOPs per token."""
    import jax

    return int(sum(
        math.prod(getattr(leaf, "shape", ()) or ())
        for leaf in jax.tree_util.tree_leaves(tree)
        if hasattr(leaf, "shape")))


def params_bytes(tree) -> int:
    """Total byte size of a param pytree — the per-token weight-read
    floor of an LM decode step (PERF_NOTES "LM decode accounting")."""
    import jax

    return int(sum(
        _aval_bytes(leaf)
        for leaf in jax.tree_util.tree_leaves(tree)
        if hasattr(leaf, "shape")))


# -- config signatures ------------------------------------------------------
# The committed artifact and the runtime pipeline must derive the SAME
# signature from the same config, or the match silently never fires —
# one definition here, used by --emit-cost-model and the pipelines.

def _digest(*parts) -> str:
    return hashlib.sha256("|".join(repr(p) for p in parts)
                          .encode()).hexdigest()[:16]


def _w8a8_effective(flag: bool) -> bool:
    """The ARMED w8a8 state for signature purposes: under the
    CASSMANTLE_NO_W8A8 kill switch a w8a8 config serves the fp path,
    and its dispatches must resolve the fp cost entry — same rationale
    as effective_sampler_cfg for the consistency kill switch."""
    if not flag:
        return False
    from cassmantle_tpu.ops.quant_matmul import w8a8_disabled

    return not w8a8_disabled()


def t2i_signature(cfg, sampler_cfg=None) -> str:
    """SD1.5 text→image dispatch signature: everything the analytic
    per-image FLOPs depend on (model archs + the sampler geometry —
    ``consistency`` included, since the few-step path runs num_steps
    direct forwards of the same UNet; the ARMED w8a8 state included,
    since quantized serving halves weight-side HBM bytes and the
    committed w8a8 variant carries its own roofline entry)."""
    s = sampler_cfg if sampler_cfg is not None else cfg.sampler
    m = cfg.models
    return _digest("t2i", m.unet.arch(), m.vae.arch(), m.clip_text,
                   s.image_size, s.num_steps, s.kind, s.deepcache,
                   s.encprop, s.encprop_stride, s.encprop_dense_steps,
                   s.consistency, _w8a8_effective(m.unet_w8a8))


def sdxl_signature(cfg, sampler_cfg=None) -> str:
    s = sampler_cfg if sampler_cfg is not None else cfg.sampler
    m = cfg.models
    return _digest("sdxl", m.unet.arch(), m.vae.arch(), m.clip_text,
                   m.clip_text_2, s.image_size, s.num_steps, s.kind,
                   s.deepcache, s.encprop, s.encprop_stride,
                   s.encprop_dense_steps, s.consistency,
                   _w8a8_effective(m.unet_w8a8))


def lm_signature(mcfg, w8a8: bool = False) -> str:
    """Prompt-LM signature: the model config alone — decode FLOPs are
    2·N(params)·tokens regardless of sampler knobs. ``w8a8``: the
    ARMED lm_w8a8 state (the caller owns the ModelZooConfig; pass
    ``_w8a8_effective(models.lm_w8a8)``) — the quantized tree streams
    half the weight bytes per token, a separate committed entry."""
    return _digest("lm", mcfg, _w8a8_effective(w8a8))


def scorer_signature(mcfg, seq_len: int) -> str:
    return _digest("scorer", mcfg, seq_len)


# -- the committed artifact -------------------------------------------------

_model_lock = threading.Lock()
_model_cache: Optional[Dict] = None
_runtime_cache: Dict[Tuple[str, str], Optional[float]] = {}


def load_cost_model(path: Optional[str] = None) -> Dict:
    """The committed cost-model JSON ({} when absent/unreadable —
    attribution then falls back to trace-once, never crashes serving)."""
    global _model_cache
    if path is not None:  # explicit path: no process cache (tests)
        try:
            with open(path) as f:
                return json.load(f)
        except Exception:
            return {}
    with _model_lock:
        if _model_cache is None:
            try:
                with open(COST_MODEL_PATH) as f:
                    _model_cache = json.load(f)
            except Exception:
                _model_cache = {}
        return _model_cache


def committed_entry(kind: str, signature: str) -> Optional[Dict]:
    """The artifact's entry for this pipeline kind IF its signature
    matches the runtime config (production presets); None otherwise.
    Preset VARIANT entries (e.g. ``t2i_lcm`` — the same pipeline kind
    at a different committed sampler geometry) are found by signature
    scan, so the lcm preset resolves without tracing too: signatures
    are digests over the kind prefix + full config, so a cross-kind
    collision cannot occur."""
    pipelines = load_cost_model().get("pipelines", {})
    entry = pipelines.get(kind)
    if isinstance(entry, dict) and entry.get("signature") == signature:
        return entry
    for other in pipelines.values():
        if isinstance(other, dict) and \
                other.get("signature") == signature:
            return other
    return None


def flops_per_item(kind: str, signature: str,
                   tracer: Optional[Callable[[], float]] = None,
                   ) -> Optional[float]:
    """Per-item (image / token-batch row / encode row) analytic FLOPs
    for a dispatch variant:

    1. the committed ``data/cost_model.json`` entry when the runtime
       signature matches (production configs — zero tracing cost);
    2. else ``tracer()`` — the caller traces its OWN jitted impl
       (exact for tiers/encprop/deepcache), cached process-wide by
       ``(kind, signature)``;
    3. else None — the dispatch simply carries no cost attribution
       (attribution must never break serving).
    """
    key = (kind, signature)
    with _model_lock:
        if key in _runtime_cache:
            return _runtime_cache[key]
    entry = committed_entry(kind, signature)
    value: Optional[float] = None
    if entry is not None:
        try:
            value = float(entry["flops_per_item"])
        except (KeyError, TypeError, ValueError):
            value = None
    if value is None and tracer is not None:
        try:
            value = float(tracer())
        except Exception:
            log.exception("cost-model trace failed for %s; dispatches "
                          "carry no FLOPs attribution", kind)
            value = None
    with _model_lock:
        _runtime_cache[key] = value
    return value


def reset_runtime_cache() -> None:
    """Test seam: drop trace-once results (and the artifact cache)."""
    global _model_cache
    with _model_lock:
        _runtime_cache.clear()
        _model_cache = None
