"""Process self-metrics: the per-worker health floor of federation.

A cluster `/metrics` view is only as useful as each worker's baseline:
before any serving-specific signal, an operator needs to see that every
process is up (``process.uptime_s``), how much resident memory it holds
(``process.rss_bytes`` — the param/compile caches dominate), how much
CPU it has burned (``process.cpu_s``, user+system, cumulative), and
whether its asyncio event loop is keeping up (``server.loop_lag_s`` —
the 1 Hz WS clock and every handler share that loop, so sustained lag
IS user-visible latency).

All four are gauges refreshed by a background sampler task
(``ObsConfig.process_sample_interval_s``) and, for the three process
gauges, opportunistically on every `/metrics` scrape — a scrape always
sees fresh values without waiting out the sampler interval. Loop lag is
measured only by the sampler (sleep-overshoot of its own interval: the
probe needs the loop to actually schedule it).

RSS comes from ``/proc/self/statm`` (current resident set); on hosts
without procfs it falls back to ``resource.getrusage`` peak RSS —
documented as a ceiling, not a current value, but monotone enough to
alert on.
"""

from __future__ import annotations

import asyncio
import os
import time
from typing import Callable

from cassmantle_tpu.utils.logging import get_logger, metrics

log = get_logger("obs.process")

try:
    _PAGE_SIZE = os.sysconf("SC_PAGE_SIZE")
except (ValueError, OSError, AttributeError):
    _PAGE_SIZE = 4096


class ProcessMetrics:
    def __init__(self, registry=None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self._registry = registry if registry is not None else metrics
        self._clock = clock
        self._start = clock()

    def rss_bytes(self) -> float:
        try:
            with open("/proc/self/statm") as f:
                return float(f.read().split()[1]) * _PAGE_SIZE
        except Exception:
            import resource

            # ru_maxrss is PEAK rss in KiB on linux — a ceiling, used
            # only where procfs is absent
            return float(
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            ) * 1024.0

    def cpu_seconds(self) -> float:
        t = os.times()
        return float(t.user + t.system)

    def sample(self) -> None:
        """Refresh the three process gauges (cheap: two syscalls and a
        procfs read — safe on every scrape)."""
        self._registry.gauge("process.uptime_s",
                             self._clock() - self._start)
        self._registry.gauge("process.rss_bytes", self.rss_bytes())
        self._registry.gauge("process.cpu_s", self.cpu_seconds())

    async def run(self, interval_s: float = 5.0) -> None:
        """Background sampler: process gauges plus the event-loop lag
        probe — the overshoot of our own sleep is exactly how long a
        ready callback waited behind whatever clogged the loop."""
        loop = asyncio.get_running_loop()
        self._registry.gauge("server.loop_lag_s", 0.0)
        self.sample()
        while True:
            t0 = loop.time()
            await asyncio.sleep(interval_s)
            lag = max(0.0, (loop.time() - t0) - interval_s)
            self._registry.gauge("server.loop_lag_s", lag)
            self.sample()
