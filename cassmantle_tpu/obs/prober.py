"""Synthetic canary prober (ISSUE 18): play the real game, constantly.

White-box health (breakers, watchdogs, device probes) answers "do the
parts report healthy"; the canary answers the only question a player
cares about — "can someone actually PLAY right now". Every worker runs
a background loop that exercises the full serving surface end-to-end
over real HTTP: ``/init`` → one ``/clock`` WebSocket tick →
``/fetch/contents`` (JPEG decode + mask-shape verification) →
``/compute_score`` on a known-answer probe room. One guess is the
exact answer (the deterministic 1.0 path); one is deliberately
non-exact, forcing the batched similarity rung — the int8 embed table
when armed, the device queue otherwise — so the probe covers the same
scoring ladder players ride.

The probe room (``engine/game.py PROBE_ROOM``) is isolated on every
axis: its store keys live under ``probe:<worker_id>:`` (no collision
with any room prefix), its Game emits no engine metrics (NULL_METRICS
— game.guesses, cache ratios, and the latency histograms feeding
admission capacity estimates never see probe traffic), it is absent
from the room directory/placement/heartbeats, and the HTTP layer
admits it only to cluster peers (``?room=__probe__`` answers 404 to
outsiders). Cross-worker probes walk the membership table with the
cluster token, so every worker also validates its peers' serving paths
— a black-box mesh check the white-box supervisor cannot fake.

Every probe runs under a traced root span marked for tail retention
("probe"), so a failed probe's full trace is always retrievable at
``/debugz?trace=<id>`` — and the ``probe.e2e_s`` histogram's bucket
exemplars link straight to it. Verdicts feed ``probe.ok`` /
``probe.failures`` / ``probe.e2e_s``, ``probe.fail`` flight-recorder
events, the ``canary`` block in ``/readyz``, and the two black-box SLO
objectives (obs/slo.py probe_success / probe_latency).

Kill switch: ``CASSMANTLE_NO_PROBER=1`` (checked at startup AND per
tick) leaves zero probe artifacts — no metrics, no store keys, no
background task. ``CASSMANTLE_PROBE_INTERVAL_S`` overrides the cadence
(docs/DEPLOY.md §6).
"""

from __future__ import annotations

import asyncio
import base64
import json
import os
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from cassmantle_tpu.engine.masking import build_prompt_state
from cassmantle_tpu.engine.rounds import (
    COUNTDOWN_KEY,
    IMAGE_KEY,
    PROMPT_KEY,
    STORY_KEY,
)
from cassmantle_tpu.obs.recorder import flight_recorder
from cassmantle_tpu.obs.trace import format_traceparent, tracer
from cassmantle_tpu.utils.logging import get_logger, metrics

log = get_logger("prober")

# Fixed probe content: build_prompt_state is deterministic (no RNG), so
# every worker derives the SAME masks and answers from this sentence —
# a cross-worker probe knows the remote probe room's answers without
# reading the remote store.
PROBE_SENTENCE = (
    "a violet lighthouse hums beside the glass harbor while copper "
    "gulls drift over the quiet evening tide"
)
PROBE_IMAGE_SIZE = 64
# countdown TTL refreshed whenever it runs low: the probe room's clock
# must always read a live round, but never runs a round timer
PROBE_COUNTDOWN_S = 3600.0
# deliberately-wrong guess for one mask: the exact-match shortcut in
# GuessScorer must NOT fire, so the batched similarity path (table or
# device) is exercised on every probe (the word is not in the sentence)
PROBE_NEAR_GUESS = "harbinger"


class ProbeFailure(AssertionError):
    """One probe leg's verification failed (carries the leg name in
    the message; the verdict records which leg via span attrs)."""


def probe_image() -> np.ndarray:
    """Deterministic synthetic round image: a diagonal gradient the
    fetch leg can verify by exact shape after the decode+blur+encode
    round-trip."""
    g = np.arange(PROBE_IMAGE_SIZE, dtype=np.int32)
    grad = (np.add.outer(g, g) * 2 % 256).astype(np.uint8)
    return np.stack([grad, grad.T, 255 - grad], axis=-1)


def probe_state(game) -> Dict:
    """The probe round's prompt state, derived (and memoized) from the
    probe game's own embed fn — identical on every worker running the
    same model config."""
    state = getattr(game, "_probe_state", None)
    if state is None:
        state = build_prompt_state(
            PROBE_SENTENCE, game.rounds.embed, game.rounds.num_masked)
        game._probe_state = state
    return state


def probe_answers(state: Dict) -> Dict[str, str]:
    tokens = state["tokens"]
    return {str(m): str(tokens[int(m)]) for m in state["masks"]}


async def ensure_probe_round(game) -> Dict:
    """Seed the probe room's store with the known-answer round if it is
    missing (first probe on this worker, or a cross-worker probe
    landing on a cold peer), and keep its countdown alive. Idempotent
    and cheap once seeded (one hget + one ttl)."""
    from cassmantle_tpu.utils.codec import encode_jpeg

    state = probe_state(game)
    store = game.store
    if await store.hget(PROMPT_KEY, "current") is None:
        await store.hset(PROMPT_KEY, "seed", PROBE_SENTENCE)
        await store.hset(PROMPT_KEY, "current", json.dumps(state))
        await store.hset(IMAGE_KEY, "current",
                         encode_jpeg(probe_image()))
        await store.hset(IMAGE_KEY, "version", "1")
        await store.hset(STORY_KEY, mapping={
            "title": "canary", "content": PROBE_SENTENCE})
        # pin the probe answers into the int8 embed table when one is
        # armed — the near-guess then rides the table-served rung, the
        # same rung 0 players hit (ops/embed_table.py)
        await game.rounds._notify_answers(state)
    if await store.ttl(COUNTDOWN_KEY) < 60.0:
        await store.setex(COUNTDOWN_KEY, PROBE_COUNTDOWN_S, "active")
    return state


def prober_disabled() -> bool:
    """CASSMANTLE_NO_PROBER truthy = no probes, no artifacts."""
    return os.environ.get("CASSMANTLE_NO_PROBER", "").lower() in (
        "1", "true", "yes", "on")


class CanaryProber:
    """The per-worker probe loop. ``self_addr`` is this worker's own
    HTTP address (loopback in production — the probe must traverse the
    real listener, middlewares included); cross-worker targets come
    from the membership table with the cluster token."""

    def __init__(self, fabric, cfg, self_addr: Optional[str] = None):
        self.fabric = fabric
        self.cfg = cfg
        self.self_addr = self_addr
        self._http = None
        # worker -> last verdict dict (the /readyz canary block)
        self._last: Dict[str, dict] = {}
        self._consecutive_failures = 0

    # -- config ------------------------------------------------------------
    def interval_s(self) -> float:
        raw = os.environ.get("CASSMANTLE_PROBE_INTERVAL_S", "")
        if raw:
            try:
                return max(0.5, float(raw))
            except ValueError:
                log.warning("bad CASSMANTLE_PROBE_INTERVAL_S=%r; using "
                            "config cadence", raw)
        return float(self.cfg.obs.probe_interval_s)

    # -- http --------------------------------------------------------------
    def _session(self):
        import aiohttp

        if self._http is None or self._http.closed:
            self._http = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(
                    total=float(self.cfg.obs.probe_timeout_s)))
        return self._http

    async def close(self) -> None:
        if self._http is not None and not self._http.closed:
            await self._http.close()
        self._http = None

    # -- one probe ---------------------------------------------------------
    async def probe_once(self, worker: Optional[str] = None,
                         addr: Optional[str] = None) -> dict:
        """Play the full game surface against one target worker and
        record the verdict. Returns the verdict dict (also kept for
        the /readyz canary block)."""
        if worker is None:
            worker = self.fabric.worker_id
        if addr is None:
            addr = self.self_addr or self.fabric.membership.addr
        verdict: Dict[str, object] = {
            "target": worker, "ok": False, "leg": None, "error": None,
            "e2e_s": None, "trace": None, "t": time.time(),
        }
        with tracer.span("probe.run", root=True,
                         attrs={"target": worker,
                                "worker": self.fabric.worker_id}) as span:
            # probes are always tail-retained: a failed probe's trace
            # must be retrievable, and a slow-but-passing one is the
            # earliest latency-regression evidence there is
            tracer.mark_retain("probe", span.ctx)
            verdict["trace"] = span.trace_id
            t0 = time.perf_counter()
            try:
                if not addr:
                    raise ProbeFailure("no probe target address")
                await self._play(worker, addr, span)
                verdict["ok"] = True
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                verdict["leg"] = span.attrs.get("leg", "connect")
                verdict["error"] = f"{type(exc).__name__}: {exc}"
                span.attrs["error"] = verdict["error"]
            dt = time.perf_counter() - t0
            verdict["e2e_s"] = round(dt, 6)
            # observed INSIDE the span: the ambient trace context tags
            # this observation's histogram bucket with an exemplar
            # pointing at exactly this probe's trace
            metrics.observe("probe.e2e_s", dt)
            if verdict["ok"]:
                metrics.inc("probe.ok")
                self._consecutive_failures = 0
            else:
                metrics.inc("probe.failures")
                self._consecutive_failures += 1
                flight_recorder.record(
                    "probe.fail", target=worker,
                    leg=verdict["leg"], error=verdict["error"],
                    trace=span.trace_id)
                log.warning("canary probe failed (target=%s leg=%s): %s",
                            worker, verdict["leg"], verdict["error"])
        self._last[worker] = verdict
        return verdict

    async def _play(self, worker: str, addr: str, span) -> None:
        """The four legs, in player order. Raises ProbeFailure (or any
        transport error) on the first leg that misbehaves; span.attrs
        ['leg'] names the leg in flight."""
        http = self._session()
        base = addr.rstrip("/")
        from cassmantle_tpu.engine.game import PROBE_ROOM
        from cassmantle_tpu.utils.codec import decode_jpeg

        state = probe_state(self.fabric.probe_game())
        answers = probe_answers(state)
        session_id = f"canary-{self.fabric.worker_id}"
        params = {"room": PROBE_ROOM, "session": session_id}
        headers = {"traceparent": format_traceparent(span.ctx)}
        token = self.fabric.cluster_token()
        if token:
            headers["X-Cluster-Auth"] = token

        span.attrs["leg"] = "init"
        async with http.get(base + "/init", params=params,
                            headers=headers) as res:
            if res.status != 200:
                raise ProbeFailure(f"init answered {res.status}")
            data = await res.json()
            if data.get("session_id") != session_id:
                raise ProbeFailure("init echoed a foreign session id")

        span.attrs["leg"] = "clock"
        timeout = float(self.cfg.obs.probe_timeout_s)
        async with http.ws_connect(base + "/clock", params=params,
                                   headers=headers) as ws:
            tick = await ws.receive_json(timeout=timeout)
            missing = [k for k in ("time", "reset", "conns")
                       if k not in tick]
            if missing:
                raise ProbeFailure(f"clock tick missing {missing}")

        span.attrs["leg"] = "fetch"
        async with http.get(base + "/fetch/contents", params=params,
                            headers=headers) as res:
            if res.status != 200:
                raise ProbeFailure(f"fetch/contents answered {res.status}")
            data = await res.json()
        image = decode_jpeg(base64.b64decode(data["image"]))
        if image.shape != (PROBE_IMAGE_SIZE, PROBE_IMAGE_SIZE, 3):
            raise ProbeFailure(
                f"image decoded to shape {image.shape}, expected "
                f"({PROBE_IMAGE_SIZE}, {PROBE_IMAGE_SIZE}, 3)")
        prompt = data.get("prompt", {})
        if list(prompt.get("masks", [])) != list(state["masks"]):
            raise ProbeFailure(
                f"masks {prompt.get('masks')} != seeded "
                f"{state['masks']}")
        for m in state["masks"]:
            if prompt["tokens"][int(m)] != "*":
                raise ProbeFailure(f"mask {m} not redacted in prompt")
        if not data.get("story"):
            raise ProbeFailure("story block missing")

        span.attrs["leg"] = "score"
        inputs = dict(answers)
        near_mask: Optional[str] = None
        if len(inputs) > 1:
            # last mask gets the non-exact guess: the exact-match
            # shortcut must not fire, so this rides the batched
            # similarity path (table rung or device queue)
            near_mask = str(state["masks"][-1])
            inputs[near_mask] = PROBE_NEAR_GUESS
        async with http.post(base + "/compute_score", params=params,
                             json={"inputs": inputs},
                             headers=headers) as res:
            if res.status != 200:
                raise ProbeFailure(f"compute_score answered {res.status}")
            scores = await res.json()
        for m in answers:
            raw = scores.get(m)
            if raw is None:
                raise ProbeFailure(f"mask {m} missing from scores")
            val = float(raw)
            if m == near_mask:
                # similarity-path score: GuessScorer clamps into
                # [min_score, 0.999]. 1.0 would mean the exact-match
                # shortcut fired (device path unexercised); a score AT
                # the floor is the serving stack's degraded fallback
                # (breaker open, dispatch deadline, invalid device
                # output — all floor to min_score) — exactly the
                # player-visible degradation the canary exists to catch
                floor = float(self.cfg.game.min_score)
                if val <= floor:
                    raise ProbeFailure(
                        f"near-guess scored the {floor} floor — "
                        f"degraded (breaker/deadline/invalid-output) "
                        f"similarity serving")
                if val > 0.999:
                    raise ProbeFailure(
                        f"near-guess score {val} > 0.999: the "
                        f"similarity path was not exercised")
            elif val != 1.0:
                raise ProbeFailure(
                    f"exact answer for mask {m} scored {val}, not 1.0")

    # -- the loop ----------------------------------------------------------
    def _targets(self) -> List[Tuple[str, Optional[str]]]:
        targets: List[Tuple[str, Optional[str]]] = [
            (self.fabric.worker_id,
             self.self_addr or self.fabric.membership.addr or None)]
        for worker, info in sorted(
                self.fabric.membership.live_workers().items()):
            if worker == self.fabric.worker_id:
                continue
            peer_addr = info.get("addr")
            if peer_addr:
                targets.append((worker, peer_addr))
        return targets

    async def probe_all(self) -> None:
        """One probe pass: self first, then every live peer with an
        advertised address. A worker with no self address (no loopback
        known, nothing advertised) simply has no self-probe — peers
        still probe it from outside."""
        for worker, addr in self._targets():
            if not addr:
                continue
            await self.probe_once(worker, addr)

    async def run(self) -> None:
        """Background loop for create_app's on_startup. The kill switch
        is re-read every tick, so CASSMANTLE_NO_PROBER flipped on a
        live worker quiesces probing within one interval (and a boot
        with it set never creates this task at all)."""
        try:
            while True:
                await asyncio.sleep(self.interval_s())
                if prober_disabled():
                    continue
                try:
                    await self.probe_all()
                except asyncio.CancelledError:
                    raise
                except Exception:
                    # the prober observes the system; it must never
                    # take it down
                    log.exception("canary probe pass failed; continuing")
        finally:
            await self.close()

    # -- status ------------------------------------------------------------
    def status_block(self) -> Dict[str, object]:
        """The /readyz ``canary`` block: last verdict per target plus
        the consecutive-failure streak. Advisory (like the SLO block):
        a failing canary explains a drain, it does not cause one."""
        last = {w: dict(v) for w, v in self._last.items()}
        ok: Optional[bool] = None
        if last:
            ok = all(bool(v.get("ok")) for v in last.values())
        return {
            "enabled": not prober_disabled(),
            "interval_s": self.interval_s(),
            "ok": ok,
            "consecutive_failures": self._consecutive_failures,
            "targets": last,
        }
