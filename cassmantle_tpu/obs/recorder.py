"""Serving flight recorder: a bounded ring of structured events.

Counters say *how often* the supervision machinery fired; they cannot
say *in what order* — and "what happened in the 30 s before the breaker
tripped" is exactly the question a degraded `/readyz` page raises. The
flight recorder keeps the last N structured events in process memory:

- breaker transitions (``utils/circuit.py`` — kind ``breaker``),
- dispatch watchdog fires and deadline expiries (``serving/queue.py`` —
  kinds ``queue.dispatch_hang`` / ``queue.deadline_expired``),
- supervisor overrun holds (``serving/supervisor.py`` —
  ``supervisor.overrun``),
- round promotions / replays / reserve rotations
  (``engine/rounds.py`` — ``round.*``) and reserve archive/pick traffic
  (``engine/reserve.py`` — ``reserve.*``).

Every event carries a monotonic sequence number and a wall timestamp,
so `/debugz` replays the causal story (trip -> reserve rotation ->
recovery) in order, and a degraded supervisor verdict embeds its recent
tail. Thread-safe; ``record`` is a deque append under a lock — cheap
enough for every transition path that emits one.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from cassmantle_tpu.utils.logging import metrics


class FlightRecorder:
    def __init__(self, capacity: int = 512) -> None:
        assert capacity > 0, "recorder capacity must be positive"
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=capacity)
        self._seq = 0
        self._dropped = 0

    def set_capacity(self, capacity: int) -> None:
        """Resize in place, keeping the newest events on shrink."""
        capacity = max(1, int(capacity))
        with self._lock:
            if capacity == self._events.maxlen:
                return
            kept = list(self._events)[-capacity:]
            self._dropped += len(self._events) - len(kept)
            self._events = deque(kept, maxlen=capacity)

    def record(self, kind: str, **fields) -> None:
        """Append one event. ``fields`` must be JSON-serializable —
        these bytes go straight out on `/debugz` and `/readyz`."""
        with self._lock:
            self._seq += 1
            if len(self._events) == self._events.maxlen:
                self._dropped += 1
            self._events.append({
                "seq": self._seq,
                "ts": time.time(),
                "kind": kind,
                **fields,
            })
        metrics.inc("obs.events")

    def tail(self, n: Optional[int] = None,
             kind: Optional[str] = None) -> List[dict]:
        """The newest events, oldest-first (replay order). ``kind``
        filters by exact kind or a ``prefix.`` (trailing dot)."""
        with self._lock:
            events = list(self._events)
        if kind is not None:
            if kind.endswith("."):
                events = [e for e in events if e["kind"].startswith(kind)]
            else:
                events = [e for e in events if e["kind"] == kind]
        if n is not None:
            n = int(n)
            events = events[-n:] if n > 0 else []
        return events

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "events": len(self._events),
                "capacity": self._events.maxlen or 0,
                "total_recorded": self._seq,
                "dropped": self._dropped,
            }


flight_recorder = FlightRecorder()
