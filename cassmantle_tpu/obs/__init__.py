"""Observability subsystem: request tracing, flight recorder, wiring.

The per-worker pieces (ISSUE 3):

- :mod:`cassmantle_tpu.obs.trace` — contextvar-propagated per-request
  trace/span IDs with a bounded in-process span sink. The HTTP layer
  opens a root span per request (returned as ``X-Trace-Id``), the
  batching queue splits queue-wait from batch-service per member, and
  device stages record synchronized spans via
  ``utils.profiling.block_timer``.
- :mod:`cassmantle_tpu.obs.recorder` — a bounded ring of structured
  events (breaker transitions, watchdog fires, deadline expiries,
  reserve rotations, round promotions) surfaced at ``/debugz`` and
  embedded in a degraded ``/readyz`` verdict.
- The metrics registry itself stays in :mod:`cassmantle_tpu.utils.logging`
  (histograms + Prometheus exposition + the federation state
  dump/merge) so the low-level layers keep their one import; this
  package depends on utils, never the reverse.

And the cluster-wide pieces (ISSUE 9):

- cross-worker trace propagation — ``traceparent`` format/parse in
  :mod:`cassmantle_tpu.obs.trace`, the HTTP acceptance/peer gate and
  the cluster-merged ``/debugz?trace=`` view in ``server/app.py``;
- :mod:`cassmantle_tpu.obs.slo` — the SLO burn-rate engine
  (declarative objectives, fast/slow windows, ``/sloz``, the
  non-gating ``/readyz`` advisory block);
- :mod:`cassmantle_tpu.obs.process` — process self-metrics
  (uptime/rss/cpu + event-loop lag), every worker's federation floor.

``configure_observability(cfg.obs)`` applies the config knobs to the
process-global instances; server startup calls it (server/app.py).
"""

from __future__ import annotations

from cassmantle_tpu.obs.recorder import flight_recorder
from cassmantle_tpu.obs.trace import tracer

__all__ = ["tracer", "flight_recorder", "configure_observability"]


def configure_observability(obs_cfg) -> None:
    """Apply an ``ObsConfig`` to the process-global tracer, flight
    recorder, and metrics histogram defaults. Idempotent; existing
    recorded data is kept (capacity shrink drops oldest entries)."""
    from cassmantle_tpu.utils.logging import metrics

    tracer.configure(
        capacity=obs_cfg.trace_capacity,
        sample_rate=obs_cfg.trace_sample_rate,
        max_spans_per_trace=obs_cfg.trace_max_spans,
        pending_capacity=obs_cfg.trace_pending_capacity,
        pending_ttl_s=obs_cfg.trace_pending_ttl_s,
        tail_slow_default_s=obs_cfg.tail_slow_default_s,
        tail_slow_routes=dict(obs_cfg.tail_slow_routes),
    )
    flight_recorder.set_capacity(obs_cfg.recorder_capacity)
    metrics.set_default_buckets(obs_cfg.latency_buckets_s)
