"""Request-scoped tracing: contextvar propagation + bounded span sink.

The serving stack's latency question — "where did this one slow request
spend its time" — needs per-request attribution, not aggregate
percentiles (the SwiftDiffusion/LegoDiffusion per-stage argument,
PAPERS.md). This module is the minimal native tracer that answers it:

- every HTTP request gets a **trace ID** (returned as ``X-Trace-Id``)
  and a root span (server/app.py middleware);
- the ambient span context rides a :mod:`contextvars` variable, so it
  survives ``await`` chains for free and crosses executor/dispatch
  threads explicitly (``run_with_ctx`` / ``contextvars.copy_context``);
- the batching queue records per-member **queue-wait** and
  **batch-service** spans and links the shared batch span
  (serving/queue.py);
- device stages record **device-synchronized** spans through
  ``utils.profiling.block_timer`` (the timing blocks on the stage's
  result arrays, so spans measure device work, not dispatch).

Finished spans land in a bounded per-trace ring (LRU eviction at
``capacity`` traces) queryable at ``/debugz?trace=<id>``.

**Sampling is tail-based with a head floor (ISSUE 18).** The root span
still draws once against ``sample_rate``, but the coin now decides
*certainty*, not *existence*: a head-sampled trace records straight
into the durable ring exactly as before (the healthy-baseline floor),
while a non-head trace buffers its spans in a bounded **pending ring**
until its root span completes. At completion a retention policy
promotes the traces worth keeping — errored, slower than the per-route
threshold (``ObsConfig.tail_slow_routes`` / ``tail_slow_default_s``),
or explicitly marked via :meth:`Tracer.mark_retain` (shed, brownout-
degraded, chaos-injected, canary probes) — into the durable ring;
everything else is dropped and its pending occupancy reclaimed. Traces
whose root never completes (client disconnect, watchdog kill) age out
of the pending ring under a TTL sweep, counted ``obs.traces_abandoned``.
``CASSMANTLE_NO_TAIL_SAMPLING=1`` (read per root-context mint) reverts
to the exact pre-tail head-sampling behavior: the coin IS the sampling
decision and nothing ever buffers.

Each root context also carries a small mutable ``marks`` dict shared by
the whole request: the queue writes ``queue_wait_s`` / ``service_s``
into it so the HTTP layer can return ``X-Queue-Wait`` /
``X-Service-Time`` headers without re-walking the trace.

**Cross-worker propagation (ISSUE 9):** a trace crosses the fabric's
worker boundary as a W3C-style ``traceparent`` token
(``00-<trace_id>-<span_id>-<flags>``): the HTTP layer accepts it as a
header (service mesh / peer fan-out) or a query parameter (the one
channel a 307 ``Location`` can carry through the redirecting client),
peer-gated to cluster members and loopback (server/app.py). A span
opened with ``tracer.span(..., parent=remote_ctx)`` continues the
remote trace — same trace id, the remote span as parent — so the
redirect hop, the owner worker's handling, and its device-stage spans
all land in ONE trace, merged across workers by
``/debugz?trace=<id>&scope=cluster``.
"""

from __future__ import annotations

import contextvars
import os
import random
import re
import threading
import time
import uuid
from collections import OrderedDict
from contextlib import contextmanager
from typing import Dict, List, Optional

from cassmantle_tpu.utils.logging import metrics


def _no_tail_sampling() -> bool:
    """Kill switch, read per use (flipping the env mid-flight takes
    effect on the next root context / observation, no restart)."""
    return os.environ.get(
        "CASSMANTLE_NO_TAIL_SAMPLING", "").lower() in \
        ("1", "true", "yes", "on")


class SpanContext:
    """Immutable-by-convention propagation record: who the ambient span
    is. ``marks`` is the one deliberately shared mutable field — the
    per-request blackboard (see module docstring). ``head`` says whether
    the trace is already durably retained (head-sampled, or continued
    from a remote hop): head spans record directly; non-head spans
    buffer pending the root's retention verdict."""

    __slots__ = ("trace_id", "span_id", "sampled", "marks", "head")

    def __init__(self, trace_id: str, span_id: str, sampled: bool,
                 marks: Optional[dict] = None, head: bool = True) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled
        self.marks = marks if marks is not None else {}
        self.head = head


_current: contextvars.ContextVar[Optional[SpanContext]] = \
    contextvars.ContextVar("cassmantle_span", default=None)


def current_ctx() -> Optional[SpanContext]:
    return _current.get()


def current_trace_id() -> Optional[str]:
    ctx = _current.get()
    return ctx.trace_id if ctx is not None else None


def current_marks() -> Optional[dict]:
    ctx = _current.get()
    return ctx.marks if ctx is not None else None


def run_with_ctx(ctx: Optional[SpanContext], fn, *args):
    """Run ``fn(*args)`` with ``ctx`` as the ambient span — the explicit
    cross-thread hop (dispatch thread, executors): contextvars don't
    follow plain threads."""
    token = _current.set(ctx)
    try:
        return fn(*args)
    finally:
        _current.reset(token)


def _new_id(nbytes: int) -> str:
    return uuid.uuid4().hex[: 2 * nbytes]


# W3C trace-context shape, version 00: 16-byte trace id, 8-byte span id
# (exactly the widths this tracer already mints), 1 flag byte whose low
# bit is "sampled".
_TRACEPARENT = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


def format_traceparent(ctx: SpanContext) -> str:
    """The outbound wire form of a context — what the fabric pins onto
    a cross-worker 307 ``Location`` (query param) and what a peer
    fan-out sends as a header."""
    return f"00-{ctx.trace_id}-{ctx.span_id}-" \
           f"{'01' if ctx.sampled else '00'}"


def parse_traceparent(value: Optional[str]) -> Optional[SpanContext]:
    """A :class:`SpanContext` from an inbound ``traceparent`` token, or
    None for anything malformed (malformed input is DROPPED, never a
    fresh trace — the caller decides what an absent context means). The
    marks blackboard is fresh: it is per-request local state, never
    shared across the worker boundary."""
    if not value:
        return None
    m = _TRACEPARENT.match(value.strip().lower())
    if not m:
        return None
    return SpanContext(m.group(1), m.group(2), m.group(3) != "00",
                       marks={})


class _SpanHandle:
    """What ``tracer.span`` yields: the live ids plus mutable attrs."""

    __slots__ = ("ctx", "attrs")

    def __init__(self, ctx: SpanContext, attrs: dict) -> None:
        self.ctx = ctx
        self.attrs = attrs

    @property
    def trace_id(self) -> str:
        return self.ctx.trace_id

    @property
    def span_id(self) -> str:
        return self.ctx.span_id


class Tracer:
    """Span factory + bounded per-trace sink. One global per process
    (``tracer``); instantiable standalone for tests."""

    def __init__(self, capacity: int = 256, sample_rate: float = 1.0,
                 max_spans_per_trace: int = 512,
                 rng: Optional[random.Random] = None) -> None:
        self._lock = threading.Lock()
        # trace_id -> list of finished span dicts, LRU-ordered (a new
        # span refreshes its trace's position, so long-running traces
        # survive bursts of short ones); eviction drops a whole trace
        self._traces: "OrderedDict[str, List[dict]]" = OrderedDict()
        # ids of evicted traces (bounded memory): a late span from an
        # evicted trace must be DROPPED, not resurrect a torn partial
        # trace that /debugz would serve with no hint its head is gone
        self._evicted: "OrderedDict[str, None]" = OrderedDict()
        # trace_id -> {"spans": [...], "t": creation wall time} for
        # non-head traces awaiting their root's retention verdict;
        # insertion-ordered so the TTL sweep walks oldest-first
        self._pending: "OrderedDict[str, dict]" = OrderedDict()
        self.capacity = capacity
        self.sample_rate = sample_rate
        self.max_spans_per_trace = max_spans_per_trace
        self.pending_capacity = 512
        self.pending_ttl_s = 120.0
        self.tail_slow_default_s = 1.0
        # root-span name ("http.post /compute_score") -> seconds
        self.tail_slow_routes: Dict[str, float] = {}
        self._rng = rng or random.Random()

    def configure(self, *, capacity: Optional[int] = None,
                  sample_rate: Optional[float] = None,
                  max_spans_per_trace: Optional[int] = None,
                  pending_capacity: Optional[int] = None,
                  pending_ttl_s: Optional[float] = None,
                  tail_slow_default_s: Optional[float] = None,
                  tail_slow_routes: Optional[dict] = None) -> None:
        with self._lock:
            if capacity is not None:
                self.capacity = max(1, int(capacity))
                while len(self._traces) > self.capacity:
                    evicted_id, _ = self._traces.popitem(last=False)
                    self._remember_evicted(evicted_id)
            if sample_rate is not None:
                self.sample_rate = min(1.0, max(0.0, float(sample_rate)))
            if max_spans_per_trace is not None:
                self.max_spans_per_trace = max(1, int(max_spans_per_trace))
            if pending_capacity is not None:
                self.pending_capacity = max(1, int(pending_capacity))
                while len(self._pending) > self.pending_capacity:
                    tid, _ = self._pending.popitem(last=False)
                    self._remember_evicted(tid)
                    metrics.inc("obs.traces_abandoned")
            if pending_ttl_s is not None:
                self.pending_ttl_s = max(0.0, float(pending_ttl_s))
            if tail_slow_default_s is not None:
                self.tail_slow_default_s = max(0.0,
                                               float(tail_slow_default_s))
            if tail_slow_routes is not None:
                self.tail_slow_routes = {
                    str(k): float(v) for k, v in
                    (tail_slow_routes.items()
                     if isinstance(tail_slow_routes, dict)
                     else tail_slow_routes)}

    # -- context derivation ----------------------------------------------
    def new_root_ctx(self) -> SpanContext:
        """Fresh trace. The sampling coin is drawn here; under tail
        sampling it decides head-certainty (the healthy-baseline floor)
        and every trace starts sampled pending its retention verdict.
        With ``CASSMANTLE_NO_TAIL_SAMPLING`` set the coin IS the
        sampling decision — the exact pre-tail behavior."""
        coin = (self.sample_rate >= 1.0
                or self._rng.random() < self.sample_rate)
        if _no_tail_sampling():
            return SpanContext(_new_id(16), _new_id(8), coin, marks={})
        return SpanContext(_new_id(16), _new_id(8), True, marks={},
                           head=coin)

    def child_ctx(self, parent: Optional[SpanContext]) -> SpanContext:
        """A child of ``parent`` (same trace, same marks blackboard);
        a new root when there is no parent."""
        if parent is None:
            return self.new_root_ctx()
        return SpanContext(parent.trace_id, _new_id(8), parent.sampled,
                           marks=parent.marks, head=parent.head)

    def detached_ctx(self) -> SpanContext:
        """An always-unsampled context: lets shared infrastructure (a
        batch with no traced members) run span-producing code paths
        without recording anything or minting ring-occupying traces."""
        return SpanContext(_new_id(16), _new_id(8), False, marks={})

    # -- recording --------------------------------------------------------
    def record_span(self, name: str, ctx: SpanContext, *,
                    parent_id: Optional[str] = None,
                    start_wall: float, duration_s: float,
                    status: str = "ok",
                    attrs: Optional[dict] = None) -> None:
        """Sink an already-timed span (the queue's wait/service spans are
        measured outside any ``with`` block). No-op when unsampled."""
        if not ctx.sampled:
            return
        span = {
            "trace_id": ctx.trace_id,
            "span_id": ctx.span_id,
            "parent_id": parent_id,
            "name": name,
            "start_ts": start_wall,
            "duration_s": duration_s,
            "status": status,
        }
        if attrs:
            span["attrs"] = dict(attrs)
        with self._lock:
            spans = self._traces.get(ctx.trace_id)
            if spans is None:
                if ctx.trace_id in self._evicted:
                    metrics.inc("obs.spans_dropped")
                    return
                if not ctx.head:
                    # tail-pending: buffer until the root's retention
                    # verdict (_finish_root). obs.spans counts only on
                    # promotion — a dropped pending trace recorded
                    # nothing, exactly like a pre-tail unsampled one.
                    self._record_pending_locked(span, ctx.trace_id)
                    return
                while len(self._traces) >= self.capacity:
                    evicted_id, _ = self._traces.popitem(last=False)
                    self._remember_evicted(evicted_id)
                    metrics.inc("obs.trace_evictions")
                spans = []
                self._traces[ctx.trace_id] = spans
            else:
                self._traces.move_to_end(ctx.trace_id)
            if len(spans) >= self.max_spans_per_trace:
                # cap hit: drop honestly — count it and mark the last
                # resident span so /debugz shows the trace is truncated
                metrics.inc("obs.spans_dropped")
                spans[-1].setdefault("attrs", {})["truncated"] = True
                return
            spans.append(span)
        metrics.inc("obs.spans")

    def _record_pending_locked(self, span: dict, trace_id: str) -> None:
        pend = self._pending.get(trace_id)
        if pend is None:
            self._sweep_pending_locked(time.time())
            while len(self._pending) >= self.pending_capacity:
                # capacity pressure evicts the oldest pending trace —
                # its root will find nothing to promote, same as a TTL
                # abandonment, and late spans drop via _evicted
                tid, _ = self._pending.popitem(last=False)
                self._remember_evicted(tid)
                metrics.inc("obs.traces_abandoned")
            pend = {"spans": [], "t": time.time()}
            self._pending[trace_id] = pend
        spans = pend["spans"]
        if len(spans) >= self.max_spans_per_trace:
            metrics.inc("obs.spans_dropped")
            spans[-1].setdefault("attrs", {})["truncated"] = True
            return
        spans.append(span)

    def _sweep_pending_locked(self, now: float) -> None:
        """Age out pending traces whose root never completed (client
        disconnect, watchdog kill): oldest-first, stopping at the first
        young entry — bounded work per sweep by construction."""
        while self._pending:
            tid, pend = next(iter(self._pending.items()))
            if now - pend["t"] <= self.pending_ttl_s:
                break
            del self._pending[tid]
            self._remember_evicted(tid)
            metrics.inc("obs.traces_abandoned")

    def mark_retain(self, reason: str,
                    ctx: Optional[SpanContext] = None) -> None:
        """Flag the (ambient) trace for tail retention regardless of its
        latency — the hook the HTTP layer uses for shed/degraded
        responses, chaos for injections, and the prober for its probes.
        First reason wins (the earliest cause is the interesting one).
        Harmless on head traces (they are already durable)."""
        c = ctx if ctx is not None else _current.get()
        if c is not None:
            c.marks.setdefault("tail.retain", str(reason))

    def _finish_root(self, ctx: SpanContext, name: str,
                     duration_s: float, status: str) -> None:
        """The tail-retention verdict, at root-span completion of a
        non-head trace: promote (error / marked / slow) or drop —
        either way the pending occupancy is reclaimed."""
        slow = duration_s >= self.tail_slow_routes.get(
            name, self.tail_slow_default_s)
        mark = ctx.marks.get("tail.retain")
        reason = None
        if mark == "baseline":
            # explicit demotion (the HTTP layer's routine-non-2xx
            # verdict: 307 ownership hops, 4xx): slow still retains,
            # the error status alone does not
            reason = "slow" if slow else None
        elif mark:
            reason = mark
        elif status != "ok":
            reason = "error"
        elif slow:
            reason = "slow"
        promoted = 0
        with self._lock:
            pend = self._pending.pop(ctx.trace_id, None)
            if reason is not None and pend is not None:
                while len(self._traces) >= self.capacity:
                    evicted_id, _ = self._traces.popitem(last=False)
                    self._remember_evicted(evicted_id)
                    metrics.inc("obs.trace_evictions")
                self._traces[ctx.trace_id] = pend["spans"]
                promoted = len(pend["spans"])
            else:
                # completed-but-unretained (or already swept): the id
                # must never re-enter pending via a straggler span
                self._remember_evicted(ctx.trace_id)
        if promoted:
            metrics.inc("obs.spans", promoted)
            metrics.inc("obs.tail_retained")
            metrics.retain_exemplars(ctx.trace_id)
            from cassmantle_tpu.obs.recorder import flight_recorder
            flight_recorder.record(
                "trace.tail_retained", trace=ctx.trace_id, route=name,
                reason=reason, duration_s=round(duration_s, 6))
        else:
            metrics.discard_exemplars(ctx.trace_id)

    def _remember_evicted(self, trace_id: str) -> None:
        """Bounded (4x capacity) eviction memory; oldest ids age out —
        by then their in-flight spans have long since finished."""
        self._evicted[trace_id] = None
        while len(self._evicted) > 4 * self.capacity:
            self._evicted.popitem(last=False)

    @contextmanager
    def span(self, name: str, *, root: bool = False,
             parent: Optional[SpanContext] = None,
             attrs: Optional[dict] = None):
        """Open a span as the new ambient context, child of the ambient
        parent. ``root=True`` forces a fresh trace; ``parent=`` CONTINUES
        an explicit (typically remote, traceparent-parsed) context
        instead — same trace id, that span as parent — which is how a
        cross-worker hop stays one trace. The body may mutate
        ``handle.attrs``; exceptions mark status=error and propagate.
        (Spans with an explicit non-ambient parent — the queue's batch
        split — go through :meth:`record_span` directly.)"""
        if parent is not None:
            ctx = self.child_ctx(parent)
            parent_id = parent.span_id
        elif root:
            ctx = self.new_root_ctx()
            parent_id = None
        else:
            pctx = _current.get()
            ctx = self.child_ctx(pctx)
            parent_id = pctx.span_id if pctx is not None else None
        handle = _SpanHandle(ctx, dict(attrs) if attrs else {})
        token = _current.set(ctx)
        start_wall = time.time()
        start = time.perf_counter()
        status = "ok"
        try:
            yield handle
        except BaseException:
            status = "error"
            raise
        finally:
            _current.reset(token)
            duration_s = time.perf_counter() - start
            self.record_span(
                name, ctx, parent_id=parent_id, start_wall=start_wall,
                duration_s=duration_s, status=status,
                attrs=handle.attrs)
            if root and ctx.sampled and not ctx.head:
                # the trace's root just completed: issue the tail
                # retention verdict (promote or reclaim). Spans with an
                # explicit parent= continue someone else's trace — the
                # verdict belongs to THAT root, never the hop.
                self._finish_root(ctx, name, duration_s, status)

    # -- query ------------------------------------------------------------
    def get_trace(self, trace_id: str) -> Optional[List[dict]]:
        """Durable ring first; a still-pending trace answers too (an
        operator chasing a live request must not see a 404 that flips
        to data one second later)."""
        with self._lock:
            spans = self._traces.get(trace_id)
            if spans is None:
                pend = self._pending.get(trace_id)
                if pend is not None:
                    spans = pend["spans"]
            return [dict(s) for s in spans] if spans is not None else None

    def trace_ids(self) -> List[str]:
        """Oldest-first resident trace ids (the ``/debugz`` listing) —
        durable (retained) traces only; pending ones are in flight."""
        with self._lock:
            return list(self._traces.keys())

    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {
                "traces": len(self._traces),
                "capacity": self.capacity,
                "sample_rate": self.sample_rate,
                "pending": len(self._pending),
                "pending_capacity": self.pending_capacity,
            }


tracer = Tracer()


def _exemplar_probe():
    """Metrics→trace linkage (utils.logging exemplars): every histogram
    observation asks which trace it belongs to. Head traces are already
    durable (certain → bucket exemplar written immediately); pending
    tail traces park as candidates until their retention verdict. The
    tail-sampling kill switch disables the linkage entirely — the
    pre-tail exposition had no exemplars."""
    if _no_tail_sampling():
        return None
    ctx = _current.get()
    if ctx is None or not ctx.sampled:
        return None
    return ctx.trace_id, ctx.head


metrics.set_exemplar_source(_exemplar_probe)
