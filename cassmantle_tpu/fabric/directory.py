"""Room directory: session→room→worker placement.

Two layers of stable hashing:

- **session → room** is a plain stable hash over the fixed room list:
  a session lands in the same room on every request, from any worker,
  with no coordination (the room count only changes by config rollout).
- **room → worker** is a consistent-hash ring (``vnodes`` virtual
  nodes per worker, md5 positions): when a worker joins or leaves, only
  the rooms whose arc it owned move — the property that keeps a scale
  event from resetting every room in the fleet
  (tests/test_fabric.py::test_ring_moves_are_minimal).

Hashes are md5-based, NOT Python ``hash()``: placement must agree
across worker processes (PYTHONHASHSEED would otherwise split-brain
the routing).

Concurrency contract (docs/STATIC_ANALYSIS.md lock hierarchy): the
``fabric.directory`` OrderedLock (rank 4) guards only the in-process
ring and room list. It is never held across an await or a store call —
lookups are pure in-memory math; membership refresh computes the new
worker set *outside* the lock and swaps it in under it (the
store-failover golden fixture in tests/test_check_concurrency.py pins
the violating shape).
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

from cassmantle_tpu.utils.locks import OrderedLock


def stable_hash(key: str) -> int:
    """64-bit process-independent hash."""
    return int.from_bytes(hashlib.md5(key.encode()).digest()[:8], "big")


class RoomDirectory:
    def __init__(self, rooms: Sequence[str], workers: Sequence[str] = (),
                 vnodes: int = 64) -> None:
        assert rooms, "a directory needs at least one room"
        self.vnodes = vnodes
        self._lock = OrderedLock("fabric.directory", rank=4)
        self._rooms: List[str] = list(rooms)
        self._workers: List[str] = []
        self._ring: List[Tuple[int, str]] = []
        if workers:
            self.set_workers(workers)

    # -- ring maintenance --------------------------------------------------
    def _build_ring(self, workers: Sequence[str]) -> List[Tuple[int, str]]:
        ring = [
            (stable_hash(f"worker:{worker}#{v}"), worker)
            for worker in workers
            for v in range(self.vnodes)
        ]
        ring.sort()
        return ring

    def set_workers(self, workers: Sequence[str]) -> Dict[str, Tuple[Optional[str], str]]:
        """Replace the live worker set; returns ``{room: (old_owner,
        new_owner)}`` for every room whose placement moved (old_owner is
        None on the first build)."""
        new_workers = sorted(set(workers))
        new_ring = self._build_ring(new_workers)
        with self._lock:
            if new_workers == self._workers:
                return {}
            old_ring = self._ring
            old_empty = not old_ring
            self._workers = new_workers
            self._ring = new_ring
        moves: Dict[str, Tuple[Optional[str], str]] = {}
        for room in self.rooms():
            old = None if old_empty else self._owner(old_ring, room)
            new = self._owner(new_ring, room)
            if old != new:
                moves[room] = (old, new)
        return moves

    @staticmethod
    def _owner(ring: List[Tuple[int, str]], room: str) -> Optional[str]:
        if not ring:
            return None
        point = stable_hash(f"room:{room}")
        idx = bisect.bisect_right(ring, (point, "￿")) % len(ring)
        return ring[idx][1]

    # -- lookups -----------------------------------------------------------
    def rooms(self) -> List[str]:
        with self._lock:
            return list(self._rooms)

    def workers(self) -> List[str]:
        with self._lock:
            return list(self._workers)

    def has_room(self, room: str) -> bool:
        with self._lock:
            return room in self._rooms

    def room_for_session(self, session: str) -> str:
        """The room a session belongs to — stable across requests and
        across workers (acceptance-pinned, tests/test_fabric.py)."""
        with self._lock:
            rooms = self._rooms
        return rooms[stable_hash(f"session:{session}") % len(rooms)]

    def worker_for_room(self, room: str) -> Optional[str]:
        """The owning worker, or None when no workers registered."""
        with self._lock:
            ring = self._ring
        return self._owner(ring, room)

    def rooms_owned_by(self, worker: str) -> List[str]:
        return [room for room in self.rooms()
                if self.worker_for_room(room) == worker]

    def placement(self) -> Dict[str, Optional[str]]:
        """room -> owner snapshot (the `/readyz` fabric block)."""
        return {room: self.worker_for_room(room) for room in self.rooms()}
