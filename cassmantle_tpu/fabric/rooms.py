"""Room fabric: per-room game engines over namespaced store views.

A **room** is a full game — its own round clock, prompt/image content,
sessions, and score state — living under a per-room key prefix in the
shared (replicated) store. :class:`RoomFabric` owns the set of rooms a
worker serves: it lazily builds one :class:`~cassmantle_tpu.engine.game.Game`
per owned room (all rooms share the worker's serving backend, so many
rooms' round generation funnels into the same batched device through
the round reserve and the staged serving path), heartbeats membership,
and drains/adopts rooms when the consistent-hash ring moves.

The **default room** maps to the *empty* prefix: legacy un-roomed
requests, pre-fabric stores, and the unchanged frontend all keep
working — a one-worker one-room fabric is byte-for-byte the old game.

Concurrency contract: the fabric's own mutable state (the room→game
map, startup tasks) is touched only from the serving event loop and
holds no thread locks by design; the thread-locked pieces are the
directory ring (rank 4), the replication status snapshot (rank 5), and
the membership cache (rank 6) — see docs/STATIC_ANALYSIS.md.
"""

from __future__ import annotations

import asyncio
import contextlib
import random
from typing import AsyncIterator, Callable, Dict, List, Optional

from cassmantle_tpu.config import FrameworkConfig
from cassmantle_tpu.engine.game import Game
from cassmantle_tpu.engine.store import StateStore
from cassmantle_tpu.fabric.directory import RoomDirectory
from cassmantle_tpu.fabric.membership import ClusterMembership
from cassmantle_tpu.obs.recorder import flight_recorder
from cassmantle_tpu.utils.logging import get_logger, metrics

log = get_logger("fabric.rooms")


class NamespacedStore(StateStore):
    """A per-room view of a shared store: every key (and lock name)
    carries the room prefix, so N rooms coexist in one store without
    the engine knowing. ``close`` is a no-op — the underlying store is
    shared and the fabric closes it exactly once at shutdown."""

    def __init__(self, store: StateStore, prefix: str) -> None:
        self._store = store
        self.prefix = prefix

    def _k(self, key: str) -> str:
        return self.prefix + key

    async def set(self, key, value):
        return await self._store.set(self._k(key), value)

    async def get(self, key):
        return await self._store.get(self._k(key))

    async def setex(self, key, ttl, value):
        return await self._store.setex(self._k(key), ttl, value)

    async def delete(self, *keys):
        return await self._store.delete(*[self._k(k) for k in keys])

    async def exists(self, key):
        return await self._store.exists(self._k(key))

    async def expire(self, key, ttl):
        return await self._store.expire(self._k(key), ttl)

    async def ttl(self, key):
        return await self._store.ttl(self._k(key))

    async def hset(self, key, field=None, value=None, mapping=None):
        return await self._store.hset(self._k(key), field=field,
                                      value=value, mapping=mapping)

    async def hget(self, key, field):
        return await self._store.hget(self._k(key), field)

    async def hgetall(self, key):
        return await self._store.hgetall(self._k(key))

    async def hdel(self, key, *fields):
        return await self._store.hdel(self._k(key), *fields)

    async def hincrby(self, key, field, amount: int = 1):
        return await self._store.hincrby(self._k(key), field, amount)

    async def sadd(self, key, *members):
        return await self._store.sadd(self._k(key), *members)

    async def srem(self, key, *members):
        return await self._store.srem(self._k(key), *members)

    async def smembers(self, key):
        return await self._store.smembers(self._k(key))

    async def sismember(self, key, member):
        return await self._store.sismember(self._k(key), member)

    def lock(self, name: str, timeout: float = 120.0,
             blocking_timeout: float = 2.0):
        # room-scoped locks: each room's startup/buffer/promotion
        # lifecycle excludes per room, not globally
        return self._store.lock(self._k(name), timeout=timeout,
                                blocking_timeout=blocking_timeout)

    async def close(self) -> None:
        pass


def room_prefix(room: str, default_room: str) -> str:
    """Store key prefix for a room ('' = the legacy un-roomed keys)."""
    return "" if room == default_room else f"room:{room}:"


def room_ids(cfg: FrameworkConfig) -> List[str]:
    fabric = cfg.fabric
    return [fabric.default_room] + [
        f"room-{i}" for i in range(1, max(1, fabric.num_rooms))
    ]


class RoomFabric:
    """The per-worker fabric runtime: room→game map, membership
    heartbeats, ownership-change draining."""

    def __init__(
        self,
        cfg: FrameworkConfig,
        store: StateStore,
        game_factory: Callable[[str, StateStore], Game],
        *,
        worker_id: str = "worker-0",
        advertise_addr: str = "",
        start_timers: bool = True,
        heartbeat: bool = True,
        supervisor=None,
    ) -> None:
        self.cfg = cfg
        self.store = store
        self.game_factory = game_factory
        self.worker_id = worker_id
        self.start_timers = start_timers
        # ONE supervisor per worker, shared by every room's game (and
        # by the inference service behind them): /readyz fuses a single
        # worker-level verdict, not a per-room one
        if supervisor is None:
            from cassmantle_tpu.serving.supervisor import ServingSupervisor

            supervisor = ServingSupervisor()
        self.supervisor = supervisor
        self.supervisor.fabric_status = self.status
        self.default_room = cfg.fabric.default_room
        self.directory = RoomDirectory(
            room_ids(cfg), workers=[worker_id], vnodes=cfg.fabric.vnodes)
        self.membership = ClusterMembership(
            store, worker_id, addr=advertise_addr,
            ttl_s=cfg.fabric.membership_ttl_s)
        self._heartbeat_enabled = heartbeat
        self._cluster_key: Optional[bytes] = None
        self._games: Dict[str, Game] = {}
        self._startups: Dict[str, asyncio.Task] = {}
        self._hb_task: Optional[asyncio.Task] = None
        self._draining = False
        # canary probe engine (ISSUE 18): built lazily, NEVER in
        # _games — invisible to the directory ring, placement answers,
        # heartbeat room counts, and fabric.rooms_created
        self._probe_game: Optional[Game] = None
        self._legacy_game: Optional[Game] = None

    # -- legacy wrap -------------------------------------------------------
    @classmethod
    def for_game(cls, game: Game, cfg: FrameworkConfig,
                 start_timers: bool = True) -> "RoomFabric":
        """Wrap one pre-built Game as a single-room fabric — the shim
        that keeps ``create_app(game, cfg)`` and every existing caller
        working unchanged (the game IS the default room). The wrap is
        pinned to ONE room regardless of ``cfg.fabric.num_rooms``:
        multi-room serving must come through a per-room game factory
        (build_fabric) — routing a second room id onto the one shared
        Game would re-run its startup and stack a second round clock."""
        import dataclasses

        cfg = cfg.replace(fabric=dataclasses.replace(
            cfg.fabric, num_rooms=1))
        fabric = cls(cfg, game.store, lambda room, store: game,
                     start_timers=start_timers, heartbeat=False,
                     supervisor=game.supervisor)
        fabric._games[fabric.default_room] = game
        # the wrap's factory ignores its store argument (it returns the
        # one pre-built game), so probe_game() must derive a separate
        # probe engine from this game's parts instead
        fabric._legacy_game = game
        return fabric

    # -- ownership ---------------------------------------------------------
    def is_local(self, room: str) -> bool:
        owner = self.directory.worker_for_room(room)
        return owner is None or owner == self.worker_id

    def owner_addr(self, room: str) -> Optional[str]:
        """Advertised address of the room's owner (None when unknown or
        local — callers redirect only on a real remote address)."""
        owner = self.directory.worker_for_room(room)
        if owner is None or owner == self.worker_id:
            return None
        return self.membership.addr_of(owner)

    def owned_rooms(self) -> List[str]:
        return self.directory.rooms_owned_by(self.worker_id)

    def peer_hosts(self) -> set:
        """Hostnames of every live member's advertised address (plus
        our own advertise) — one leg of the trust set for inbound
        cross-worker observability (server/app.py ``_is_cluster_peer``;
        exact-match only, so fleets advertising DNS names or NATed
        egress rely on the cluster-secret leg below instead).
        Membership rows come from the shared store, which cluster
        workers already trust for round state itself."""
        from urllib.parse import urlsplit

        addrs = [info.get("addr")
                 for info in self.membership.live_workers().values()]
        addrs.append(self.membership.addr)
        hosts = set()
        for addr in addrs:
            if not addr:
                continue
            try:
                host = urlsplit(addr).hostname
            except ValueError:
                continue
            if host:
                hosts.add(host)
        return hosts

    # -- cluster secret (cross-worker observability trust) -----------------
    # The store distributes one random secret per cluster: a cross-
    # worker 307 pins tracesig=HMAC(secret, traceparent) next to the
    # trace context, so the owner worker can honor a context carried
    # BACK by an untrusted client (the redirect channel — the bearer's
    # IP proves nothing), and peer fan-outs authenticate with a
    # secret-derived bearer token instead of IP matching (which breaks
    # under DNS-advertised addresses or NATed egress). Trust anchor =
    # the shared store, exactly the thing cluster workers already
    # trust for round state.
    CLUSTER_KEY_STORE_KEY = "fabric:cluster_key"

    async def _ensure_cluster_key(self) -> None:
        import secrets

        try:
            raw = await self.store.get(self.CLUSTER_KEY_STORE_KEY)
            if raw is None:
                await self.store.set(self.CLUSTER_KEY_STORE_KEY,
                                     secrets.token_hex(32))
                # re-read: two workers racing the first boot both keep
                # whichever write won (last-write store semantics)
                raw = await self.store.get(self.CLUSTER_KEY_STORE_KEY)
            self._cluster_key = raw
        except Exception:
            # READONLY follower mid-election / store hiccup: no key
            # means signature trust is simply unavailable this beat
            # (loopback/host legs still work); the next heartbeat
            # retries. Counted: a worker stuck without signature trust
            # for many beats is a real degradation a log line can't
            # alert on
            metrics.inc("fabric.cluster_key_failures")
            log.exception("cluster key fetch failed; retrying next beat")
            self._cluster_key = None

    def _hmac(self, payload: str) -> Optional[str]:
        import hashlib
        import hmac

        key = getattr(self, "_cluster_key", None)
        if not key:
            return None
        return hmac.new(key, payload.encode(), hashlib.sha256) \
            .hexdigest()[:32]

    def sign_trace(self, traceparent: str) -> Optional[str]:
        """The ``tracesig`` a redirect pins next to ``traceparent``
        (None while the key is unavailable)."""
        return self._hmac("trace:" + traceparent)

    def verify_trace_sig(self, traceparent: str, sig: str) -> bool:
        import hmac

        want = self.sign_trace(traceparent)
        return want is not None and hmac.compare_digest(want, sig)

    def cluster_token(self) -> Optional[str]:
        """The bearer token peer fan-outs send as ``X-Cluster-Auth``
        (a fixed derivation, NOT the key itself)."""
        return self._hmac("peer-auth")

    def verify_cluster_token(self, token: str) -> bool:
        import hmac

        want = self.cluster_token()
        return want is not None and hmac.compare_digest(want, token)

    # -- room lifecycle ----------------------------------------------------
    async def game_for(self, room: str) -> Game:
        """The room's engine, created + started on first use. Unknown
        rooms raise KeyError (the HTTP layer answers 404)."""
        if not self.directory.has_room(room):
            raise KeyError(room)
        game = self._games.get(room)
        if game is None:
            game = self._build_game(room)
        startup = self._startups.get(room)
        if startup is not None:
            # single-flight startup: concurrent first requests share one
            # content generation; shield keeps a canceled waiter (client
            # disconnect) from killing the shared startup
            await asyncio.shield(startup)
        return game

    def _build_game(self, room: str) -> Game:
        view = NamespacedStore(
            self.store, room_prefix(room, self.default_room))
        game = self.game_factory(room, view)
        # per-room deterministic seed stream: two rooms on one worker
        # must hold DIFFERENT prompts (acceptance, tests/test_fabric.py),
        # which starts with them picking different story seeds
        game.rounds.rng = random.Random(f"{room}:{self.cfg.seed}")
        self._games[room] = game
        metrics.inc("fabric.rooms_created")
        flight_recorder.record("fabric.room_created", room=room)

        async def _start() -> None:
            try:
                await game.startup()
                if self.start_timers:
                    game.start_timer()
            except BaseException:
                # failed startup must not cache a half-built room: drop
                # it so the next request retries from the store
                self._games.pop(room, None)
                raise
            finally:
                self._startups.pop(room, None)

        self._startups[room] = asyncio.get_running_loop().create_task(
            _start())
        return game

    def probe_game(self) -> Game:
        """The canary probe engine (ISSUE 18): a full Game over a
        ``probe:<worker_id>:``-prefixed store view, playing the exact
        serving surface players hit — but isolated on every axis that
        matters: its store keys never collide with any room prefix
        (rooms use ``room:<id>:`` or ''), it is absent from ``_games``
        (so the directory, placement answers, heartbeat room counts,
        and drain/handoff never see it), it runs no round clock or
        startup generation (the prober seeds known-answer content
        directly), and ``room=PROBE_ROOM`` swaps its engine metrics for
        the null sink. Lazily built once per worker."""
        from cassmantle_tpu.engine.game import PROBE_ROOM

        if self._probe_game is not None:
            return self._probe_game
        view = NamespacedStore(self.store, f"probe:{self.worker_id}:")
        legacy = self._legacy_game
        if legacy is not None:
            # for_game wrap: its factory returns the ONE shared game
            # regardless of arguments, so derive the probe engine from
            # the wrapped game's serving parts
            game = Game(self.cfg, view, legacy.rounds.backend,
                        embed=legacy.rounds.embed,
                        similarity=legacy.scorer._similarity,
                        blur_fn=legacy.blur_fn,
                        supervisor=legacy.supervisor,
                        room=PROBE_ROOM)
        else:
            game = self.game_factory(PROBE_ROOM, view)
        game.rounds.rng = random.Random(f"{PROBE_ROOM}:{self.cfg.seed}")
        self._probe_game = game
        return game

    async def rotate_room(self, room: str) -> None:
        """Force the room onto fresh content now (promote + reset +
        clock restart) — the operator lever behind room lifecycle."""
        game = await self.game_for(room)
        await game.rounds.rollover()
        metrics.inc("fabric.room_rotations")
        flight_recorder.record("fabric.room_rotated", room=room)

    async def drain_room(self, room: str) -> None:
        """Stop serving a room locally (ownership moved / shutdown):
        its clock and buffer tasks stop, its state stays in the store
        for the adopting worker to resume."""
        game = self._games.pop(room, None)
        startup = self._startups.pop(room, None)
        if startup is not None:
            startup.cancel()
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await startup
        if game is not None:
            await game.rounds.stop()
            metrics.inc("fabric.rooms_drained")
            flight_recorder.record("fabric.room_drained", room=room)

    # -- graceful handoff (ISSUE 12) ---------------------------------------
    @property
    def draining(self) -> bool:
        return self._draining

    async def handoff(self, grace_s: Optional[float] = None) -> None:
        """Graceful SIGTERM departure: make peers adopt this worker's
        rooms BEFORE the process dies, instead of after the membership
        staleness TTL notices the silence.

        Sequence: stop the heartbeat (it would re-announce us), leave
        the membership table, rebuild the LOCAL ring without ourselves
        (any request still answered for an ex-room 307s to its new
        owner — the operator-initiated drain case, where the listener
        is still up; under SIGTERM aiohttp has already closed it),
        drain the room engines (clocks stop; round/session state stays
        in the shared store for the adopters to resume), then wait —
        bounded by ``FabricConfig.handoff_grace_s`` — until every live
        peer has heartbeated PAST our departure (its beat re-reads
        membership and rebuilds its ring = adoption). /readyz reports
        ``draining`` for as long as this worker still answers probes,
        so load balancers stop admitting while in-flight requests
        finish under their deadlines. Idempotent; the server's SIGTERM
        hook (create_app on_shutdown) runs it before cleanup."""
        if self._draining:
            return
        self._draining = True
        t0 = asyncio.get_running_loop().time()
        rooms_held = len(self._games)
        metrics.inc("fabric.handoffs")
        flight_recorder.record("fabric.handoff_started",
                               worker=self.worker_id, rooms=rooms_held)
        if self._hb_task is not None:
            self._hb_task.cancel()
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await self._hb_task
            self._hb_task = None
        if self._heartbeat_enabled:
            with contextlib.suppress(Exception):
                await self.membership.leave()
        # baseline each live peer's CURRENT stamp, read AFTER the leave
        # landed: a peer beat stamps itself BEFORE its membership
        # refresh, so a stamp that ADVANCES past this baseline implies
        # the refresh following it read a table without us — the ring
        # rebuild that adopts our rooms. Comparing a peer's stamp to
        # its OWN earlier stamp keeps this correct across hosts: an
        # absolute our-clock-vs-their-clock compare would let skew
        # either confirm adoption off a pre-leave beat or stall every
        # deploy for the full grace.
        baseline: Dict[str, float] = {}
        if self._heartbeat_enabled:
            try:
                table = await self.membership.table()
                baseline = {
                    w: float(row["info"].get("t", 0.0))
                    for w, row in table.items()
                    if w != self.worker_id and not row["stale"]
                }
            # lint: ignore[swallowed-error] — handoff baseline is best-effort: no snapshot degrades adoption-wait to its bounded timeout, and this worker is shutting down
            except Exception:
                baseline = {}
        # move the ring NOW: ownership answers flip to the survivors
        # while this worker can still serve the redirects
        peers = [w for w in self.directory.workers()
                 if w != self.worker_id]
        if peers:
            moves = self.directory.set_workers(peers)
            for room, (old, new) in moves.items():
                metrics.inc("fabric.room_moves")
                flight_recorder.record("fabric.room_move", room=room,
                                       src=old, dst=new)
        for room in list(self._games):
            await self.drain_room(room)
        if peers and self._heartbeat_enabled:
            await self._await_adoption(baseline, grace_s)
        duration = asyncio.get_running_loop().time() - t0
        metrics.observe("fabric.handoff_s", duration)
        flight_recorder.record("fabric.handoff_complete",
                               worker=self.worker_id, rooms=rooms_held,
                               duration_s=round(duration, 3))
        log.info("graceful handoff complete: %d room(s) released in "
                 "%.2fs", rooms_held, duration)

    async def _await_adoption(self, baseline: Dict[str, float],
                              grace_s: Optional[float]) -> None:
        """Block (bounded) until every live peer's heartbeat stamp has
        ADVANCED past its post-leave baseline — that beat rebuilt the
        peer's ring, i.e. our rooms are adopted. Each peer's stamp is
        compared only to its own earlier stamp (skew-safe across
        hosts); a peer with no baseline joined after we left and
        already holds the new ring. A peer that also left (its row is
        gone) or a store outage stops the wait: dying is the job here,
        waiting forever is not."""
        grace = (grace_s if grace_s is not None
                 else self.cfg.fabric.handoff_grace_s)
        deadline = asyncio.get_running_loop().time() + grace
        poll = min(0.1, max(0.02, self.cfg.fabric.heartbeat_s / 4.0))
        while asyncio.get_running_loop().time() < deadline:
            try:
                table = await self.membership.table()
            # lint: ignore[swallowed-error] — store unreachable during shutdown: nothing left to confirm, returning ends the bounded adoption wait
            except Exception:
                return  # store unreachable: nothing left to confirm
            live = {w: row for w, row in table.items()
                    if w != self.worker_id and not row["stale"]}
            if not live:
                return  # peers left too (fleet-wide shutdown)
            if all(w not in baseline
                   or float(row["info"].get("t", 0.0)) > baseline[w]
                   for w, row in live.items()):
                return
            await asyncio.sleep(poll)
        log.warning("handoff grace (%.1fs) expired before every peer "
                    "re-heartbeated; exiting anyway", grace)

    # -- lifecycle ---------------------------------------------------------
    async def startup(self) -> None:
        """Announce membership, adopt owned rooms (the default room
        eagerly — legacy clients expect content at boot), start the
        heartbeat loop."""
        starter = getattr(self.store, "start", None)
        if callable(starter):
            # ReplicatedStore: find/elect the leader and start the
            # log-shipping pump on this worker's event loop
            await starter()
        if self._heartbeat_enabled:
            await self._ensure_cluster_key()
            try:
                live = await self.membership.heartbeat(len(self._games))
                self._apply_membership(live)
            except Exception:
                # best-effort like every later beat: a store hiccup (or
                # an injected heartbeat fault) on the FIRST beat must
                # not fail worker boot — the loop below re-announces
                # within one heartbeat_s
                log.exception("startup heartbeat failed; continuing")
                metrics.inc("fabric.heartbeat_failures")
        # preinstalled games (the for_game legacy wrap) start the way
        # create_app always started its one game
        for room, game in list(self._games.items()):
            if room not in self._startups:
                await game.startup()
                if self.start_timers:
                    game.start_timer()
        if self.is_local(self.default_room) \
                and self.default_room not in self._games:
            await self.game_for(self.default_room)
        if self._heartbeat_enabled:
            self._hb_task = asyncio.get_running_loop().create_task(
                self._heartbeat_loop())

    async def shutdown(self) -> None:
        if self._hb_task is not None:
            self._hb_task.cancel()
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await self._hb_task
            self._hb_task = None
        if self._heartbeat_enabled:
            with contextlib.suppress(Exception):
                await self.membership.leave()
        for room in list(self._games):
            await self.drain_room(room)
        await self.store.close()

    async def _heartbeat_loop(self) -> None:
        interval = self.cfg.fabric.heartbeat_s
        while True:
            await asyncio.sleep(interval)
            try:
                # EVERY beat re-reads the store key: a worker that lost
                # the first-boot set race (or cached a key the store
                # later replaced) must converge on the winning value,
                # not hold its loser forever and mint signatures no
                # peer verifies
                await self._ensure_cluster_key()
                # overload advertisement (serving/overload.py): peers
                # read shed/btier from our heartbeat before hedging
                # scorer work here (score.hedge_skipped_overloaded)
                from cassmantle_tpu.serving.overload import peer_advert

                live = await self.membership.heartbeat(
                    len(self._games), extra=peer_advert())
                await self._handle_moves(self._apply_membership(live))
            except asyncio.CancelledError:
                raise
            except Exception:
                # membership is best-effort per tick: a store hiccup
                # must not kill the loop (the next beat retries)
                log.exception("membership heartbeat failed; continuing")
                metrics.inc("fabric.heartbeat_failures")

    def _apply_membership(self, live: Dict[str, dict]) -> Dict[str, tuple]:
        workers = set(live) | {self.worker_id}
        moves = self.directory.set_workers(sorted(workers))
        for room, (old, new) in moves.items():
            metrics.inc("fabric.room_moves")
            flight_recorder.record("fabric.room_move", room=room,
                                   src=old, dst=new)
        metrics.gauge("fabric.rooms_owned", float(len(self.owned_rooms())))
        return moves

    async def _handle_moves(self, moves: Dict[str, tuple]) -> None:
        for room, (old, new) in moves.items():
            if old == self.worker_id and new != self.worker_id \
                    and room in self._games:
                await self.drain_room(room)

    # -- status ------------------------------------------------------------
    def status(self) -> Dict[str, object]:
        """The `/readyz` fabric block: identity, placement, membership,
        replication. Sync by contract — reads only cached snapshots."""
        status: Dict[str, object] = {
            "worker": self.worker_id,
            "rooms": self.directory.placement(),
            "owned": self.owned_rooms(),
            "active": sorted(self._games),
            "workers": self.membership.live_workers(),
            "draining": self._draining,
        }
        repl_status = getattr(self.store, "status", None)
        if callable(repl_status):
            status["replication"] = repl_status()
        return status
