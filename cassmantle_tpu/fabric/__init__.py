"""Room fabric: the sharded multi-room game layer (ROADMAP item 2).

Scales the *game* the way serving/stages.py scales the *models*: many
concurrent rooms — each with its own round clock, prompt/image content,
and score state — consistent-hash-placed across workers over one
replicated store, surviving worker death.

- :mod:`fabric.directory` — session→room→worker placement (stable
  hashing + a consistent-hash worker ring with minimal movement).
- :mod:`fabric.membership` — store-backed worker heartbeats: the live
  worker set the ring is built from, per-worker room counts for
  `/readyz`.
- :mod:`fabric.rooms` — :class:`RoomFabric`: per-room ``Game`` engines
  over namespaced store views; room lifecycle (create / rotate /
  drain) and ownership-change draining.

Store replication itself lives one layer down
(``engine/store.ReplicatedStore`` over ``native/mantlestore.cc``'s
REPL verbs); the fabric consumes it like any other ``StateStore``.
"""

from cassmantle_tpu.fabric.directory import RoomDirectory
from cassmantle_tpu.fabric.membership import ClusterMembership
from cassmantle_tpu.fabric.rooms import NamespacedStore, RoomFabric

__all__ = [
    "ClusterMembership",
    "NamespacedStore",
    "RoomDirectory",
    "RoomFabric",
]
