"""Cluster membership: store-backed worker heartbeats.

Each worker periodically writes one field of the ``fabric:workers``
hash: ``{addr, rooms, t}`` with a wall-clock stamp. Liveness is
stamp-based (a field older than the membership TTL is a dead worker)
rather than per-field TTL because the store contract has no per-field
expiry — and a dead worker's stale field costs a few bytes until its
next overwrite, not correctness.

The cached live-worker view feeds two consumers: the
:class:`~cassmantle_tpu.fabric.directory.RoomDirectory` ring rebuild
(room placement follows membership) and the `/readyz` ``fabric`` block
(per-worker room counts, addresses — the operator's cluster map).

Concurrency contract: the ``fabric.membership`` OrderedLock (rank 6)
guards only the cached snapshot; store I/O happens outside it
(refresh reads the hash first, then swaps the parsed view in under the
lock) so a slow store round trip can never be held under a thread lock.
"""

from __future__ import annotations

import json
import time
from typing import Callable, Dict, Optional

from cassmantle_tpu.chaos import afault_point
from cassmantle_tpu.engine.store import StateStore
from cassmantle_tpu.utils.locks import OrderedLock
from cassmantle_tpu.utils.logging import get_logger, metrics

log = get_logger("fabric.membership")

WORKERS_KEY = "fabric:workers"


class ClusterMembership:
    def __init__(self, store: StateStore, worker_id: str, *,
                 addr: str = "", ttl_s: float = 6.0,
                 clock: Optional[Callable[[], float]] = None) -> None:
        self.store = store
        self.worker_id = worker_id
        self.addr = addr
        self.ttl_s = ttl_s
        # wall clock: stamps are compared ACROSS processes, so monotonic
        # (per-process epoch) would read every peer as dead
        self._clock = clock or time.time
        self._lock = OrderedLock("fabric.membership", rank=6)
        self._live: Dict[str, dict] = {}

    async def heartbeat(self, room_count: int = 0,
                        extra: Optional[Dict[str, object]] = None
                        ) -> Dict[str, dict]:
        """Announce this worker and refresh the live view. ``extra``
        merges additional advertisement fields into the payload — the
        fabric passes the worker's overload state (``shed``/``btier``,
        serving/overload.py peer_advert) so peers stop hedging scorer
        work into an already-shedding worker (ISSUE 13 satellite)."""
        # heartbeat fault point: a flake here ages this worker toward
        # the staleness TTL (peers see it leave and adopt its rooms) —
        # the membership-churn drill (docs/CHAOS.md)
        await afault_point("fabric.heartbeat")
        info: Dict[str, object] = {
            "addr": self.addr,
            "rooms": int(room_count),
        }
        if extra:
            info.update(extra)
        info["t"] = self._clock()
        payload = json.dumps(info)
        await self.store.hset(WORKERS_KEY, self.worker_id, payload)
        return await self.refresh()

    async def refresh(self) -> Dict[str, dict]:
        """Re-read the membership table; caches and returns live
        workers only (one parser — :meth:`table` — decides liveness)."""
        table = await self.table()
        live = {worker: row["info"] for worker, row in table.items()
                if not row["stale"]}
        with self._lock:
            self._live = live
        metrics.gauge("fabric.workers_live", float(len(live)))
        return live

    async def table(self) -> Dict[str, dict]:
        """The FULL membership table with staleness marked per entry:
        ``{worker: {"info", "stale", "age_s"}}`` — the ONE place the
        hash is parsed and liveness judged (``refresh`` derives from
        it). The cluster observability fan-outs
        (`/metrics?scope=cluster`, `/debugz?trace=&scope=cluster`)
        read this instead of the live view so a dead/stale peer is
        *marked* in the merged output rather than silently vanishing
        from it."""
        raw = await self.store.hgetall(WORKERS_KEY)
        now = self._clock()
        table: Dict[str, dict] = {}
        for field, value in raw.items():
            worker = field if isinstance(field, str) else field.decode()
            try:
                info = json.loads(value.decode())
            # lint: ignore[swallowed-error] — torn/foreign row skip is the documented merge rule; the row simply isn't membership data
            except Exception:
                continue  # torn/foreign field, same rule as refresh()
            age = now - float(info.get("t", 0.0))
            table[worker] = {
                "info": info,
                "stale": age > self.ttl_s,
                "age_s": round(age, 3),
            }
        return table

    async def leave(self) -> None:
        """Graceful departure: peers re-place our rooms on their next
        refresh instead of waiting a full staleness TTL."""
        await self.store.hdel(WORKERS_KEY, self.worker_id)

    # -- sync snapshot (status reporting) ----------------------------------
    def live_workers(self) -> Dict[str, dict]:
        with self._lock:
            return dict(self._live)

    def addr_of(self, worker: str) -> Optional[str]:
        info = self.live_workers().get(worker)
        return (info or {}).get("addr") or None
