from cassmantle_tpu.parallel.mesh import make_mesh  # noqa: F401
from cassmantle_tpu.parallel.ring import ring_attention  # noqa: F401
