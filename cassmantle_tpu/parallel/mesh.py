"""Device mesh construction + multi-host initialization.

The reference has NO distributed backend — Redis locks are its only
cross-process coordination (SURVEY.md §2 #16, §5.8). The TPU-native
equivalent: a logical `jax.sharding.Mesh` over the slice with named axes

- ``dp``  data parallel (batch sharding; gradients psum over ICI),
- ``tp``  tensor parallel (attention heads / MLP columns),
- ``sp``  sequence/context parallel (ring attention over tokens),

XLA GSPMD inserts the collectives; shardings are chosen so they ride ICI
within a slice. Multi-host (v5e-16 style) joins via
``jax.distributed.initialize`` before mesh construction, with host 0 alone
talking to the game-state store — mirroring how only the reference's lock
winner generates content.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cassmantle_tpu.config import MeshConfig
from cassmantle_tpu.utils.logging import get_logger

# jax promoted shard_map out of jax.experimental across releases (and
# renamed its replication-check kwarg check_rep -> check_vma); every
# parallel module imports the resolved symbol from here so the whole
# package works on either side of the move.
try:
    shard_map = jax.shard_map
except AttributeError:  # older jax: experimental namespace + old kwarg
    from jax.experimental.shard_map import shard_map as _shard_map_compat

    def shard_map(*args, check_vma=None, **kw):
        if check_vma is not None:
            kw.setdefault("check_rep", check_vma)
        return _shard_map_compat(*args, **kw)


def pcast_varying(x, axis_name: str):
    """``jax.lax.pcast(x, axis, to="varying")`` where available — newer
    jax's explicit constant->device-varying cast, needed to keep scan
    carry types consistent under check_vma. Older jax has no pcast and
    no varying-type tracking, so the cast is a no-op there."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is None:
        return x
    return pcast(x, (axis_name,), to="varying")

log = get_logger("mesh")


def maybe_init_distributed() -> bool:
    """Join a multi-host run if coordinator env vars are present."""
    if os.environ.get("CASSMANTLE_COORDINATOR"):
        jax.distributed.initialize(
            coordinator_address=os.environ["CASSMANTLE_COORDINATOR"],
            num_processes=int(os.environ.get("CASSMANTLE_NUM_PROCS", "1")),
            process_id=int(os.environ.get("CASSMANTLE_PROC_ID", "0")),
        )
        log.info("joined multi-host run: process %d/%d",
                 jax.process_index(), jax.process_count())
        return True
    return False


def resolve_axis_sizes(cfg: MeshConfig, n_devices: int) -> Sequence[int]:
    """Fill -1 axes with the remaining device count (row-major).

    Order matches ``cfg.axis_names``: (dp, pp, tp, sp, ep).
    """
    sizes = [cfg.dp, cfg.pp, cfg.tp, cfg.sp, cfg.ep]
    fixed = 1
    for s in sizes:
        if s > 0:
            fixed *= s
    assert n_devices % fixed == 0, (
        f"{n_devices} devices not divisible by fixed axes {fixed}"
    )
    remaining = n_devices // fixed
    out = []
    for s in sizes:
        if s > 0:
            out.append(s)
        else:
            out.append(remaining)
            remaining = 1
    assert int(np.prod(out)) == n_devices, (out, n_devices)
    return out


def make_mesh(cfg: Optional[MeshConfig] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    cfg = cfg or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    sizes = resolve_axis_sizes(cfg, len(devices))
    arr = np.asarray(devices).reshape(sizes)
    mesh = Mesh(arr, cfg.axis_names)
    log.info("mesh: %s", dict(zip(cfg.axis_names, sizes)))
    return mesh


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Activations: batch over dp, replicated elsewhere."""
    return NamedSharding(mesh, P("dp"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
