"""Activation-scale calibration for W8A8 serving (ISSUE 20).

The int8 W8A8 kernels (ops/quant_matmul.py) scale activations before
quantizing. Dynamic mode computes absmax in-graph per dispatch — always
correct, but it reads the fp activation twice (max, then quantize) and
its scale wobbles with batch content. STATIC mode folds a calibrated
per-site scale into the quantized param tree at load time: one read,
content-independent numerics, and the scale constant-folds into the
epilogue. This module is where static scales come from.

The pass runs N real seed prompts (data/seeds.txt — the same titles the
game serves) through the UNMODIFIED fp pipeline EAGERLY and collects
per-site activation absmax through the thread-local recorder
(ops/quant.py collect_act_stats; the recorder skips tracers by design,
so a jitted forward records nothing — calibration must stay eager).
Site keys are flax module paths, the exact keys the tree transform
(w8a8_tree_host) folds scales back into.

Artifact discipline (the cost-model/embed-table contract): the emitted
``data/act_scales.json`` is signature-gated — a digest over the model
config and the calibration prompt set. Serving loads scales ONLY when
an entry's signature matches the runtime config; anything else (config
drift, edited seeds, missing file) falls back to dynamic scales and
logs the rebuild command. The committed artifact is emitted from
``calibration_config()`` (reduced test geometry, random-init weights —
honest about what a CPU container can run; tier-1 then exercises the
static-scale path end to end). A production fleet re-emits against its
own config + real weights and commits that entry alongside.

Rebuild + commit:

    python -m cassmantle_tpu.parallel.calibrate --emit
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Dict, Optional, Sequence

from cassmantle_tpu.utils.logging import get_logger

log = get_logger("calibrate")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
ACT_SCALES_PATH = os.path.join(_REPO_ROOT, "data", "act_scales.json")

#: prompts per calibration pass: enough to spread content styles, small
#: enough that the eager fp forwards stay a one-minute offline job
NUM_CALIBRATION_PROMPTS = 8

#: denoise timesteps sampled per prompt — a spread across the schedule
#: (activation ranges drift from pure-noise t≈1000 to near-image t≈0)
CALIBRATION_TIMESTEPS = (981, 661, 341, 21)


def calibration_prompts(n: int = NUM_CALIBRATION_PROMPTS) -> list:
    """The first ``n`` seed titles — real serving content, versioned
    with the repo so the calibration set digests deterministically."""
    from cassmantle_tpu.server.assets import load_seeds

    return list(load_seeds())[:n]


def prompts_digest(prompts: Sequence[str]) -> str:
    return hashlib.sha256("\n".join(prompts).encode()).hexdigest()[:16]


def calibration_signature(models_cfg, prompts_dig: str) -> str:
    """What gates an artifact entry to a runtime config: the UNet arch
    + text-encoder config (the modules whose activations were recorded)
    and the calibration-set digest. One definition, used by --emit and
    by serving's loader — drift on either side un-matches the entry."""
    from cassmantle_tpu.obs.costmodel import _digest

    return _digest("act_scales", models_cfg.unet.arch(),
                   models_cfg.clip_text, prompts_dig)


def calibration_config():
    """The config the COMMITTED artifact is emitted from: the tiny CPU
    test geometry with the fused-conv path on (the w8a8 serving
    contract requires it, serving/pipeline.py w8a8_unet_tools) and the
    site floor dropped so every kernel site records. Production fleets
    emit with their own config instead."""
    from cassmantle_tpu.config import test_config

    base = test_config()
    m = base.models
    return dataclasses.replace(base, models=dataclasses.replace(
        m,
        unet=dataclasses.replace(m.unet, fused_conv=True),
        w8a8_min_size=0,
    ))


def collect_unet_stats(cfg, weights_dir: Optional[str] = None,
                       prompts: Optional[Sequence[str]] = None,
                       timesteps: Sequence[int] = CALIBRATION_TIMESTEPS,
                       ) -> Dict[str, float]:
    """Per-site activation absmax for the image UNet: eager fp forwards
    over the calibration prompts at a spread of denoise timesteps.
    Deterministic for a fixed (config, weights, prompt set): latents
    come from fixed PRNG keys and the recorder keeps a running max."""
    import jax
    import jax.numpy as jnp

    from cassmantle_tpu.ops import quant
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    m = cfg.models
    assert not (m.unet_w8a8 or m.lm_w8a8), (
        "calibration runs the UNMODIFIED fp path; strip the w8a8 flags "
        "from the config first (they would quantize the very "
        "activations being measured)")
    prompts = list(prompts if prompts is not None
                   else calibration_prompts())
    pipe = Text2ImagePipeline(cfg, weights_dir)
    ids = jnp.asarray(pipe._tokenize(prompts))
    # context OUTSIDE the recorder: CLIP's own attention/MLP sites must
    # not pollute the UNet entry (separate trees, separate paths)
    ctx = pipe.clip.apply(pipe.clip_params, ids)["hidden"]
    lat_hw = cfg.sampler.image_size // pipe.vae_scale
    with quant.collect_act_stats() as stats:
        for i, t in enumerate(timesteps):
            lat = jax.random.normal(
                jax.random.PRNGKey(i),
                (len(prompts), lat_hw, lat_hw, 4), jnp.float32)
            tvec = jnp.full((len(prompts),), int(t), jnp.int32)
            pipe.unet.apply(pipe.unet_params, lat, tvec, ctx)
    return dict(stats)


def emit(path: str = ACT_SCALES_PATH, cfg=None,
         weights_dir: Optional[str] = None) -> dict:
    """Run the calibration pass and write the signed artifact."""
    cfg = cfg or calibration_config()
    prompts = calibration_prompts()
    dig = prompts_digest(prompts)
    stats = collect_unet_stats(cfg, weights_dir, prompts)
    artifact = {
        "version": 1,
        "generated_by": "python -m cassmantle_tpu.parallel.calibrate "
                        "--emit",
        "note": "per-site activation absmax from EAGER fp forwards over "
                "the calibration prompt set (module docstring); scales "
                "derive as absmax/qmax at load (ops/quant.py "
                "act_scale_from_absmax). Committed entry: reduced test "
                "geometry, random-init weights — re-emit per fleet "
                "against production config + real checkpoints.",
        "entries": {
            "unet": {
                "signature": calibration_signature(cfg.models, dig),
                "prompts_digest": dig,
                "num_prompts": len(prompts),
                "timesteps": list(CALIBRATION_TIMESTEPS),
                "scales": {k: float(v) for k, v in sorted(stats.items())},
            },
        },
    }
    with open(path, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")
    log.info("wrote %s: %d sites, signature %s", path, len(stats),
             artifact["entries"]["unet"]["signature"])
    return artifact


def load_act_scales(models_cfg, path: str = ACT_SCALES_PATH,
                    ) -> Optional[Dict[str, float]]:
    """The committed entry's site→absmax map IF its signature matches
    this runtime config; None otherwise (serving then quantizes with
    dynamic in-graph scales — correct, just not constant-folded). Never
    raises: a missing/corrupt artifact must not break serving."""
    try:
        with open(path) as f:
            artifact = json.load(f)
    except Exception:
        log.warning(
            "w8a8: no calibration artifact at %s — dynamic activation "
            "scales; rebuild with `python -m "
            "cassmantle_tpu.parallel.calibrate --emit`", path)
        return None
    for name, entry in artifact.get("entries", {}).items():
        if not isinstance(entry, dict):
            continue
        expect = calibration_signature(
            models_cfg, str(entry.get("prompts_digest")))
        if entry.get("signature") == expect:
            scales = entry.get("scales") or {}
            return {str(k): float(v) for k, v in scales.items()}
    log.warning(
        "w8a8: no calibration entry in %s matches this model config — "
        "dynamic activation scales; rebuild with `python -m "
        "cassmantle_tpu.parallel.calibrate --emit` and commit the "
        "artifact", path)
    return None


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--emit", action="store_true",
                    help="run the calibration pass and write the "
                         "signed artifact")
    ap.add_argument("--out", default=ACT_SCALES_PATH)
    ap.add_argument("--weights-dir", default=None,
                    help="checkpoint dir (random init when absent — "
                         "the emitted note says which)")
    args = ap.parse_args(argv)
    if not args.emit:
        ap.print_help()
        return 2
    emit(args.out, weights_dir=args.weights_dir)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
