"""Ring attention: sequence-parallel attention over a mesh axis.

Long-context support (SURVEY.md §5.7): the UNet's image-token axis (16k+
tokens at SDXL-1024 and beyond) and any long text sequence shard over the
``sp`` mesh axis. Each device holds a sequence slice of Q/K/V; K/V blocks
rotate around the ring via ``ppermute`` (one ICI hop per step) while the
online-softmax running max/denominator merge partial results — the
shard_map/XLA-collective formulation of the same math the Pallas flash
kernel does within a chip. Memory per device stays O(S/n), and the K/V
transfer for step i+1 overlaps with the compute of step i (XLA schedules
the ppermute async on ICI).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _ring_attention_local(q, k, v, axis_name: str, scale: float):
    """Per-shard body (runs under shard_map). q/k/v: (B, S_l, H, D)."""
    n = jax.lax.psum(1, axis_name)

    def step(carry, _):
        k_cur, v_cur, m, l, acc = carry
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k_cur,
            preferred_element_type=jnp.float32,
        ) * scale
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum(
            "bhqk,bkhd->bhqd", p.astype(v_cur.dtype), v_cur,
            preferred_element_type=jnp.float32,
        )
        acc_new = acc * alpha + pv
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, m_new, l_new, acc_new), None

    b, s_l, h, d = q.shape
    # initial carries are constants -> mark them device-varying over the
    # ring axis so the scan carry type stays consistent
    vary = lambda x: jax.lax.pcast(x, (axis_name,), to="varying")  # noqa: E731
    m0 = vary(jnp.full((b, h, s_l, 1), _NEG_INF, dtype=jnp.float32))
    l0 = vary(jnp.zeros((b, h, s_l, 1), dtype=jnp.float32))
    acc0 = vary(jnp.zeros((b, h, s_l, d), dtype=jnp.float32))
    (_, _, _, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), None, length=n
    )
    out = acc / l
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "sp",
    scale: Optional[float] = None,
) -> jax.Array:
    """Sequence-parallel attention. Global shapes (B, S, H, D); S shards
    over ``axis_name``; every other dim is replicated across that axis."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    body = functools.partial(
        _ring_attention_local, axis_name=axis_name, scale=float(scale)
    )
    spec = P(None, axis_name, None, None)
    return jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )(q, k, v)
