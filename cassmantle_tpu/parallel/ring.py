"""Ring attention: sequence-parallel attention over a mesh axis.

Long-context support (SURVEY.md §5.7): the UNet's image-token axis (16k+
tokens at SDXL-1024 and beyond) and any long text sequence shard over the
``sp`` mesh axis. Each device holds a sequence slice of Q/K/V; K/V blocks
rotate around the ring via ``ppermute`` (one ICI hop per step) while the
online-softmax running max/denominator merge partial results — the
shard_map/XLA-collective formulation of the same math the Pallas flash
kernel does within a chip. Memory per device stays O(S/n), and the K/V
transfer for step i+1 overlaps with the compute of step i (XLA schedules
the ppermute async on ICI).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax

from cassmantle_tpu.parallel.mesh import shard_map
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _ring_attention_local(q, k, v, axis_name: str, scale: float,
                          causal: bool):
    """Per-shard body (runs under shard_map). q/k/v: (B, S_l, H, D).

    Causal mode (the LM long-context path): with the sequence sharded
    contiguously, at ring step ``i`` this device holds the K/V block that
    ORIGINATED on device ``(j - i) mod n``; masking compares global
    positions. Step 0 is the local (diagonal) block, where every query
    sees at least itself — so the running max is finite from the first
    step and fully-masked later blocks contribute exp(-1e30 - m) = 0,
    keeping the online softmax NaN-free with additive finite masking.

    Known trade-off: fully-masked blocks still compute their QK^T in
    SPMD lockstep (wall-time neutral — at every ring step some device
    computes a live block, so the critical path is one block either
    way — but ~2x the attention FLOPs/energy of the load-balanced
    zigzag layout, where each device holds two symmetric sequence
    slices). ``ring_attention(causal=True)`` therefore dispatches to
    the zigzag schedule whenever 2n divides S; this contiguous
    formulation remains for schedule="contiguous" (the fallback for
    S % 2n != 0 and the oracle the zigzag tests compare against)."""
    n = jax.lax.psum(1, axis_name)
    j = jax.lax.axis_index(axis_name)
    s_l = q.shape[1]
    q_pos = j * s_l + jnp.arange(s_l)                      # global q idx

    def step(carry, i):
        k_cur, v_cur, m, l, acc = carry
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k_cur,
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            origin = (j - i) % n                           # block owner
            k_pos = origin * s_l + jnp.arange(s_l)
            visible = q_pos[:, None] >= k_pos[None, :]     # (S_l, S_l)
            s = jnp.where(visible[None, None], s, _NEG_INF)
        m_new, l_new, acc_new = _merge((m, l, acc), s, v_cur)
        perm = [(r, (r + 1) % n) for r in range(n)]
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, m_new, l_new, acc_new), None

    b, s_l, h, d = q.shape
    # initial carries are constants -> mark them device-varying over the
    # ring axis so the scan carry type stays consistent
    from cassmantle_tpu.parallel.mesh import pcast_varying

    vary = lambda x: pcast_varying(x, axis_name)  # noqa: E731
    m0 = vary(jnp.full((b, h, s_l, 1), _NEG_INF, dtype=jnp.float32))
    l0 = vary(jnp.zeros((b, h, s_l, 1), dtype=jnp.float32))
    acc0 = vary(jnp.zeros((b, h, s_l, d), dtype=jnp.float32))
    (_, _, _, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(n)
    )
    out = acc / l
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def zigzag_permute(x: jax.Array, n: int, axis: int = 1) -> jax.Array:
    """Reorder the sequence axis into the zigzag layout: split into 2n
    chunks c_0..c_{2n-1} and lay them out as [c_0, c_{2n-1}, c_1,
    c_{2n-2}, ...] so that a contiguous n-way shard gives device j the
    pair (c_j, c_{2n-1-j}). This balances causal-attention work: device
    j's low chunk is early (few keys visible) exactly when its high
    chunk is late (many keys visible)."""
    s = x.shape[axis]
    assert s % (2 * n) == 0, f"seq {s} not divisible by 2n={2 * n}"
    chunks = jnp.split(x, 2 * n, axis=axis)
    order = [c for j in range(n) for c in (chunks[j], chunks[2 * n - 1 - j])]
    return jnp.concatenate(order, axis=axis)


def zigzag_unpermute(x: jax.Array, n: int, axis: int = 1) -> jax.Array:
    """Inverse of :func:`zigzag_permute`."""
    chunks = jnp.split(x, 2 * n, axis=axis)
    out: list = [None] * (2 * n)
    for j in range(n):
        out[j] = chunks[2 * j]
        out[2 * n - 1 - j] = chunks[2 * j + 1]
    return jnp.concatenate(out, axis=axis)


def _merge(stats, logits, v_blk):
    """Online-softmax merge of one (BQ, BK) logits block into carried
    (m, l, acc); logits fp32 (B, H, S_q, S_k), v (B, S_k, H, D)."""
    m, l, acc = stats
    m_cur = jnp.max(logits, axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_cur)
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(logits - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum(
        "bhqk,bkhd->bhqd", p.astype(v_blk.dtype), v_blk,
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, acc * alpha + pv


def _zigzag_local(q, k, v, axis_name: str, scale: float, n: int):
    """Per-shard body: local sequence is the pair [c_j, c_{2n-1-j}],
    each of length S_c. Prologue handles the device's own (diagonal)
    blocks with triangular masks; every scanned ring step then computes
    exactly TWO fully-visible (S_c x S_c) blocks — no masking, no wasted
    QK^T — which is the zigzag schedule's whole point:

      at step i the received K/V pair originated on o = (j - i) mod n;
      for j > o both local q chunks fully see k_low = c_o (and never
      k_high = c_{2n-1-o}); for j < o only q_high = c_{2n-1-j} is live,
      and it fully sees BOTH received chunks. Either way: two full
      blocks, every device, every step.
    """
    j = jax.lax.axis_index(axis_name)
    s2 = q.shape[1]
    s_c = s2 // 2
    ql, qh = q[:, :s_c], q[:, s_c:]

    def logits(qb, kb):
        return jnp.einsum(
            "bqhd,bkhd->bhqk", qb, kb,
            preferred_element_type=jnp.float32,
        ) * scale

    # -- prologue: the device's own diagonal blocks --------------------
    tri = jnp.tril(jnp.ones((s_c, s_c), bool))[None, None]
    b, _, h, d = q.shape
    zeros = lambda: (  # noqa: E731
        jnp.full((b, h, s_c, 1), _NEG_INF, jnp.float32),
        jnp.zeros((b, h, s_c, 1), jnp.float32),
        jnp.zeros((b, h, s_c, d), jnp.float32),
    )
    kl, kh, vl, vh = k[:, :s_c], k[:, s_c:], v[:, :s_c], v[:, s_c:]
    low = _merge(zeros(), jnp.where(tri, logits(ql, kl), _NEG_INF), vl)
    high = _merge(zeros(), jnp.where(tri, logits(qh, kh), _NEG_INF), vh)
    high = _merge(high, logits(qh, kl), vl)   # c_{2n-1-j} fully sees c_j

    # (carries derive from q/k/v, so they are already device-varying —
    # no pcast needed, unlike _ring_attention_local's constant inits)

    # -- ring: two full blocks per step --------------------------------
    def step(carry, i):
        kv, low, high = carry
        k_cur, v_cur = kv
        o = (j - i) % n
        from_lower = j > o                     # scalar, device-varying
        k_lo, k_hi = k_cur[:, :s_c], k_cur[:, s_c:]
        v_lo, v_hi = v_cur[:, :s_c], v_cur[:, s_c:]

        # block A: q = (j>o ? q_low : q_high), k = received low chunk.
        # Select the DESTINATION stats first and merge once (one PV
        # einsum), then scatter back — not merge-into-both-and-select,
        # which would execute a third, discarded merge per step.
        aq = jnp.where(from_lower, ql, qh)
        sel = tuple(jnp.where(from_lower, lo, hi)
                    for lo, hi in zip(low, high))
        merged = _merge(sel, logits(aq, k_lo), v_lo)
        low = tuple(jnp.where(from_lower, m, lo)
                    for m, lo in zip(merged, low))
        high = tuple(jnp.where(from_lower, hi, m)
                     for m, hi in zip(merged, high))

        # block B: q = q_high, k = (j>o ? received low : received high)
        bk = jnp.where(from_lower, k_lo, k_hi)
        bv = jnp.where(from_lower, v_lo, v_hi)
        high = _merge(high, logits(qh, bk), bv)

        perm = [(r, (r + 1) % n) for r in range(n)]
        kv = (jax.lax.ppermute(k_cur, axis_name, perm),
              jax.lax.ppermute(v_cur, axis_name, perm))
        return (kv, low, high), None

    if n == 1:
        out_low, out_high = low, high
    else:
        perm = [(r, (r + 1) % n) for r in range(n)]
        kv0 = (jax.lax.ppermute(k, axis_name, perm),
               jax.lax.ppermute(v, axis_name, perm))
        (_, out_low, out_high), _ = jax.lax.scan(
            step, (kv0, low, high), jnp.arange(1, n)
        )

    def finish(stats):
        m, l, acc = stats
        return jnp.einsum("bhqd->bqhd", acc / l)

    out = jnp.concatenate([finish(out_low), finish(out_high)], axis=1)
    return out.astype(q.dtype)


def zigzag_sharded_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "sp",
    scale: Optional[float] = None,
    batch_axis: Optional[str] = None,
) -> jax.Array:
    """Causal zigzag attention over ALREADY-zigzag-permuted sequences.

    The model-integration entry point: a long-context training step
    permutes its data once on input (parallel/lm_train.py) and keeps
    every layer's activations in zigzag order, so attention needs no
    per-layer permute collectives. ``batch_axis`` lets the batch dim
    ride an outer data-parallel axis (activations (B/dp, S/sp, H, D)
    per device)."""
    n = int(mesh.shape[axis_name])
    assert q.shape[1] % (2 * n) == 0, (q.shape, n)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    body = functools.partial(
        _zigzag_local, axis_name=axis_name, scale=float(scale), n=n
    )
    spec = P(batch_axis, axis_name, None, None)
    return shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )(q, k, v)


def zigzag_ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "sp",
    scale: Optional[float] = None,
) -> jax.Array:
    """Load-balanced CAUSAL ring attention (zigzag schedule).

    Takes/returns tensors in NATURAL sequence order, (B, S, H, D) with
    S % 2n == 0; the zigzag permutation is applied and undone inside.
    Halves critical-path attention compute vs contiguous causal ring:
    every ring step computes two fully-live (S/2n)^2 blocks on every
    device instead of one half-masked (S/n)^2 block on some of them.
    """
    n = int(mesh.shape[axis_name])
    qz = zigzag_permute(q, n)
    kz = zigzag_permute(k, n)
    vz = zigzag_permute(v, n)
    out = zigzag_sharded_attention(
        qz, kz, vz, mesh, axis_name=axis_name, scale=scale
    )
    return zigzag_unpermute(out, n)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "sp",
    scale: Optional[float] = None,
    causal: bool = False,
    schedule: str = "auto",
) -> jax.Array:
    """Sequence-parallel attention. Global shapes (B, S, H, D); S shards
    over ``axis_name``; every other dim is replicated across that axis.
    ``causal=True`` applies the LM triangular mask on global positions.

    ``schedule`` (causal only): ``"auto"`` — the default — routes to the
    load-balanced zigzag ring whenever ``S % (2n) == 0``, which computes
    two fully-live blocks per device per step instead of half-masked
    ones (~2x fewer attention FLOPs on the critical path);
    ``"contiguous"`` forces the plain contiguous-shard schedule (the
    reference formulation kept as a fallback for sequences that divide
    n but not 2n, and as the independent oracle the zigzag tests check
    against)."""
    if schedule not in ("auto", "contiguous"):
        raise ValueError(f"schedule must be 'auto' or 'contiguous', "
                         f"got {schedule!r}")
    n = int(mesh.shape[axis_name])
    if causal and schedule == "auto" and q.shape[1] % (2 * n) == 0:
        return zigzag_ring_attention(
            q, k, v, mesh, axis_name=axis_name, scale=scale
        )
    if scale is None:
        scale = q.shape[-1] ** -0.5
    body = functools.partial(
        _ring_attention_local, axis_name=axis_name, scale=float(scale),
        causal=causal,
    )
    spec = P(None, axis_name, None, None)
    return shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )(q, k, v)
