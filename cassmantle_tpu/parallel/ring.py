"""Ring attention: sequence-parallel attention over a mesh axis.

Long-context support (SURVEY.md §5.7): the UNet's image-token axis (16k+
tokens at SDXL-1024 and beyond) and any long text sequence shard over the
``sp`` mesh axis. Each device holds a sequence slice of Q/K/V; K/V blocks
rotate around the ring via ``ppermute`` (one ICI hop per step) while the
online-softmax running max/denominator merge partial results — the
shard_map/XLA-collective formulation of the same math the Pallas flash
kernel does within a chip. Memory per device stays O(S/n), and the K/V
transfer for step i+1 overlaps with the compute of step i (XLA schedules
the ppermute async on ICI).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _ring_attention_local(q, k, v, axis_name: str, scale: float,
                          causal: bool):
    """Per-shard body (runs under shard_map). q/k/v: (B, S_l, H, D).

    Causal mode (the LM long-context path): with the sequence sharded
    contiguously, at ring step ``i`` this device holds the K/V block that
    ORIGINATED on device ``(j - i) mod n``; masking compares global
    positions. Step 0 is the local (diagonal) block, where every query
    sees at least itself — so the running max is finite from the first
    step and fully-masked later blocks contribute exp(-1e30 - m) = 0,
    keeping the online softmax NaN-free with additive finite masking.

    Known trade-off: fully-masked blocks still compute their QK^T in
    SPMD lockstep (wall-time neutral — at every ring step some device
    computes a live block, so the critical path is one block either
    way — but ~2x the attention FLOPs/energy of a load-balanced
    zigzag layout, where each device holds two symmetric sequence
    slices; that schedule is the planned upgrade for 16k+ training)."""
    n = jax.lax.psum(1, axis_name)
    j = jax.lax.axis_index(axis_name)
    s_l = q.shape[1]
    q_pos = j * s_l + jnp.arange(s_l)                      # global q idx

    def step(carry, i):
        k_cur, v_cur, m, l, acc = carry
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k_cur,
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            origin = (j - i) % n                           # block owner
            k_pos = origin * s_l + jnp.arange(s_l)
            visible = q_pos[:, None] >= k_pos[None, :]     # (S_l, S_l)
            s = jnp.where(visible[None, None], s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum(
            "bhqk,bkhd->bhqd", p.astype(v_cur.dtype), v_cur,
            preferred_element_type=jnp.float32,
        )
        acc_new = acc * alpha + pv
        perm = [(r, (r + 1) % n) for r in range(n)]
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, m_new, l_new, acc_new), None

    b, s_l, h, d = q.shape
    # initial carries are constants -> mark them device-varying over the
    # ring axis so the scan carry type stays consistent
    vary = lambda x: jax.lax.pcast(x, (axis_name,), to="varying")  # noqa: E731
    m0 = vary(jnp.full((b, h, s_l, 1), _NEG_INF, dtype=jnp.float32))
    l0 = vary(jnp.zeros((b, h, s_l, 1), dtype=jnp.float32))
    acc0 = vary(jnp.zeros((b, h, s_l, d), dtype=jnp.float32))
    (_, _, _, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(n)
    )
    out = acc / l
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "sp",
    scale: Optional[float] = None,
    causal: bool = False,
) -> jax.Array:
    """Sequence-parallel attention. Global shapes (B, S, H, D); S shards
    over ``axis_name``; every other dim is replicated across that axis.
    ``causal=True`` applies the LM triangular mask on global positions
    (sequence shards must be contiguous slices, which is how GSPMD
    shards a P(None, 'sp', ...) spec)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    body = functools.partial(
        _ring_attention_local, axis_name=axis_name, scale=float(scale),
        causal=causal,
    )
    spec = P(None, axis_name, None, None)
    return jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )(q, k, v)
