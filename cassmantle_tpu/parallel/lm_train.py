"""Distributed causal-LM training step (dp × tp over one mesh).

The reference never trains its LLM — it rents Mistral-7B through the HF
Inference API (reference backend.py:25, 240-268). A complete framework
owns the other half of that model's lifecycle: fine-tuning the prompt LM
(GPT-2 or the Mistral family — both expose the same ``__call__``) on
story text. Design mirrors DiffusionTrainer (parallel/train.py):

- **loss**: next-token cross-entropy, pad positions masked out; logits
  computed fp32 by the models' LM heads for a stable softmax.
- **dp**: batch sharded; GSPMD inserts the gradient all-reduce (ICI).
- **tp**: attention q/k/v columns and MLP (fc/SwiGLU) kernels sharded
  per parallel/sharding.py — the same rule table serves both families.
- remat option recomputes the forward in backward (HBM for FLOPs);
  ``donate_argnums`` updates params/opt state in place.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cassmantle_tpu.parallel.sharding import shard_params
from cassmantle_tpu.parallel.train import make_optimizer


def next_token_loss(logits: jax.Array, input_ids: jax.Array,
                    loss_mask: jax.Array) -> jax.Array:
    """Mean masked cross-entropy of logits[:, :-1] against ids[:, 1:]."""
    targets = input_ids[:, 1:]
    mask = loss_mask[:, 1:].astype(jnp.float32)
    losses = optax.softmax_cross_entropy_with_integer_labels(
        logits[:, :-1].astype(jnp.float32), targets
    )
    return jnp.sum(losses * mask) / jnp.maximum(jnp.sum(mask), 1.0)


class LMTrainer:
    """Owns sharded params/opt state and the compiled LM train step.

    ``model`` is any module with ``__call__(input_ids, valid) -> logits``
    — GPT2LM and MistralLM both qualify (models/gpt2.py, models/mistral.py).
    """

    def __init__(self, model, mesh: Mesh, lr: float = 3e-4,
                 remat: bool = False) -> None:
        self.model = model
        self.mesh = mesh
        self._apply = (jax.checkpoint(model.apply) if remat
                       else model.apply)
        self.optimizer = make_optimizer(lr)
        self._step = jax.jit(self._train_step_impl, donate_argnums=(0, 1))

    # -- state ------------------------------------------------------------
    def init_state(self, sample_ids: jax.Array, seed: int = 0
                   ) -> Tuple[Any, Any]:
        params = self.model.init(jax.random.PRNGKey(seed), sample_ids)
        params = shard_params(params, self.mesh)
        opt_state = self.optimizer.init(params)
        return params, opt_state

    def batch_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P("dp"))

    def shard_batch(self, batch: Dict[str, jax.Array]
                    ) -> Dict[str, jax.Array]:
        sh = self.batch_sharding()
        return {k: jax.device_put(v, sh) for k, v in batch.items()}

    # -- step -------------------------------------------------------------
    def _train_step_impl(self, params, opt_state, batch, rng):
        del rng  # deterministic forward; kept for API parity with
        # DiffusionTrainer.step so drivers treat both uniformly

        def loss_fn(p):
            logits = self._apply(
                p, batch["input_ids"], batch["loss_mask"].astype(bool)
            )
            return next_token_loss(
                logits, batch["input_ids"], batch["loss_mask"]
            )

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, new_opt = self.optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_opt, loss

    def step(self, params, opt_state, batch, rng):
        return self._step(params, opt_state, batch, rng)
