"""Distributed causal-LM training step (dp × tp over one mesh).

The reference never trains its LLM — it rents Mistral-7B through the HF
Inference API (reference backend.py:25, 240-268). A complete framework
owns the other half of that model's lifecycle: fine-tuning the prompt LM
(GPT-2 or the Mistral family — both expose the same ``__call__``) on
story text. Design mirrors DiffusionTrainer (parallel/train.py):

- **loss**: next-token cross-entropy, pad positions masked out; logits
  computed fp32 by the models' LM heads for a stable softmax.
- **dp**: batch sharded; GSPMD inserts the gradient all-reduce (ICI).
- **tp**: attention q/k/v columns and MLP (fc/SwiGLU) kernels sharded
  per parallel/sharding.py — the same rule table serves both families.
- remat option recomputes the forward in backward (HBM for FLOPs);
  ``donate_argnums`` updates params/opt state in place.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cassmantle_tpu.parallel.sharding import shard_params
from cassmantle_tpu.parallel.train import make_optimizer


def masked_ce(logits: jax.Array, targets: jax.Array,
              mask: jax.Array) -> jax.Array:
    """Mean cross-entropy over positions where ``mask`` is nonzero."""
    losses = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), targets
    )
    maskf = mask.astype(jnp.float32)
    return jnp.sum(losses * maskf) / jnp.maximum(jnp.sum(maskf), 1.0)


def next_token_loss(logits: jax.Array, input_ids: jax.Array,
                    loss_mask: jax.Array) -> jax.Array:
    """Mean masked cross-entropy of logits[:, :-1] against ids[:, 1:]."""
    return masked_ce(logits[:, :-1], input_ids[:, 1:], loss_mask[:, 1:])


def prepare_long_context_batch(
    input_ids, loss_mask, n_sp: int
) -> Dict[str, Any]:
    """Natural-order (B, S) rows -> the zigzag-permuted batch a
    context-parallel train step consumes.

    Targets are shifted in NATURAL order first (position t predicts
    t+1), THEN permuted — a shift applied after permutation would cross
    zigzag chunk boundaries into the wrong neighbor. Positions ride
    along so the positional embedding sees each token's true index.

    ``loss_mask`` must be tail-pad form (once 0, stays 0): the
    context-parallel forward attends over ALL positions (the zigzag
    kernel carries no validity mask), which is provably equivalent to
    the plain path for tail pads — under causality a pad key is only
    visible to later (pad, loss-masked) queries — but NOT for interior
    zeros (e.g. instruction-tuning prompt masking), where the two modes
    would silently train different models. Interior zeros raise."""
    import numpy as np

    from cassmantle_tpu.parallel.ring import zigzag_permute

    mask_np = np.asarray(loss_mask)
    # tail-pad check: the mask may only step 1 -> 0 (no 0 -> 1 rises)
    if (mask_np[:, 1:] > mask_np[:, :-1]).any():
        raise ValueError(
            "context-parallel training requires a tail-pad loss_mask "
            "(no interior zeros): the sequence-parallel attention "
            "attends over all positions, which diverges from the plain "
            "trainer's key-masking for interior-masked tokens"
        )

    ids = jnp.asarray(input_ids)
    mask = jnp.asarray(loss_mask)
    b, s = ids.shape
    zeros = jnp.zeros((b, 1), ids.dtype)
    targets = jnp.concatenate([ids[:, 1:], zeros], axis=1)
    tmask = jnp.concatenate(
        [mask[:, 1:], jnp.zeros((b, 1), mask.dtype)], axis=1)
    positions = jnp.broadcast_to(
        jnp.arange(s, dtype=jnp.int32)[None, :], (b, s))
    perm = lambda t: zigzag_permute(t, n_sp, axis=1)  # noqa: E731
    return {
        "input_ids": perm(ids),
        "targets": perm(targets),
        "loss_mask": perm(tmask),
        "positions": perm(positions),
    }


class LMTrainer:
    """Owns sharded params/opt state and the compiled LM train step.

    ``model`` is any module with ``__call__(input_ids, valid) -> logits``
    — GPT2LM and MistralLM both qualify (models/gpt2.py, models/mistral.py).
    ``context_parallel=True`` (long-context: sequence sharded over the
    ``sp`` axis, zigzag ring attention) additionally requires explicit
    ``positions`` support and plain causal attention — GPT2LM, and
    MistralLM for sequences within its sliding window (the band mask
    degenerates to causal there); the constructor rejects models that
    don't qualify.
    """

    def __init__(self, model, mesh: Mesh, lr: float = 3e-4,
                 remat: bool = False,
                 context_parallel: bool = False,
                 sp_axis: str = "sp") -> None:
        self.model = model
        self.mesh = mesh
        self._apply = (jax.checkpoint(model.apply) if remat
                       else model.apply)
        self.optimizer = make_optimizer(lr)
        self.context_parallel = context_parallel
        self.sp_axis = sp_axis
        self.n_sp = int(mesh.shape[sp_axis]) if context_parallel else 1
        if context_parallel:
            import inspect

            sig = inspect.signature(type(model).__call__)
            if "positions" not in sig.parameters:
                raise TypeError(
                    f"context_parallel needs a model whose __call__ "
                    f"takes explicit `positions` (zigzag-permuted "
                    f"data); {type(model).__name__} does not — GPT2LM "
                    f"and MistralLM (sequences within the sliding "
                    f"window) qualify"
                )
        impl = (self._cp_step_impl if context_parallel
                else self._train_step_impl)
        self._step = jax.jit(impl, donate_argnums=(0, 1))

    # -- state ------------------------------------------------------------
    def init_state(self, sample_ids: jax.Array, seed: int = 0
                   ) -> Tuple[Any, Any]:
        params = self.model.init(jax.random.PRNGKey(seed), sample_ids)
        params = shard_params(params, self.mesh)
        opt_state = self.optimizer.init(params)
        return params, opt_state

    def batch_sharding(self) -> NamedSharding:
        if self.context_parallel:
            # batch over dp AND sequence over sp: each device holds a
            # (B/dp, S/sp) activation tile end to end
            return NamedSharding(self.mesh, P("dp", self.sp_axis))
        return NamedSharding(self.mesh, P("dp"))

    def shard_batch(self, batch: Dict[str, jax.Array]
                    ) -> Dict[str, jax.Array]:
        sh = self.batch_sharding()
        return {k: jax.device_put(v, sh) for k, v in batch.items()}

    def prepare_batch(self, input_ids, loss_mask) -> Dict[str, jax.Array]:
        """Data prep + sharding for either mode: plain rows in, the
        step's batch dict out (context-parallel mode zigzag-permutes and
        adds targets/positions)."""
        if not self.context_parallel:
            return self.shard_batch(
                {"input_ids": jnp.asarray(input_ids),
                 "loss_mask": jnp.asarray(loss_mask)})
        return self.shard_batch(
            prepare_long_context_batch(input_ids, loss_mask, self.n_sp))

    # -- step -------------------------------------------------------------
    def _train_step_impl(self, params, opt_state, batch, rng):
        del rng  # deterministic forward; kept for API parity with
        # DiffusionTrainer.step so drivers treat both uniformly

        def loss_fn(p):
            logits = self._apply(
                p, batch["input_ids"], batch["loss_mask"].astype(bool)
            )
            return next_token_loss(
                logits, batch["input_ids"], batch["loss_mask"]
            )

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, new_opt = self.optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_opt, loss

    def _cp_step_impl(self, params, opt_state, batch, rng):
        """Context-parallel step: activations stay zigzag-permuted and
        sequence-sharded through the whole forward; attention runs the
        sharded zigzag ring via the ops.attention context. Targets were
        shifted in natural order before permutation, so the loss is
        positionally exact."""
        del rng
        from cassmantle_tpu.ops.attention import context_parallel

        def loss_fn(p):
            with context_parallel(self.mesh, self.sp_axis,
                                  batch_axis="dp"):
                logits = self._apply(
                    p, batch["input_ids"], None, batch["positions"]
                )
            return masked_ce(logits, batch["targets"], batch["loss_mask"])

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, new_opt = self.optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_opt, loss

    def step(self, params, opt_state, batch, rng):
        return self._step(params, opt_state, batch, rng)
