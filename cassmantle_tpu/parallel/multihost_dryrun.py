"""Multi-host (DCN-leg) dryrun: 2 real processes, one global mesh.

SURVEY.md §5.8 commits this framework to ``jax.distributed.initialize``
for v5e-16-style multi-host serving; :func:`mesh.maybe_init_distributed`
implements the join. VERDICT r2 called it the one SURVEY-promised leg
with zero executions — nothing anywhere ran a second process. This
module closes that: the parent spawns ``n_procs`` real OS processes,
each pinned to CPU with ``local_devices`` virtual devices, that

1. join one coordinator via ``maybe_init_distributed`` (the exact
   production code path, driven by the CASSMANTLE_* env contract),
2. build ONE cross-process ``Mesh`` over all ``n_procs*local_devices``
   devices (``make_mesh`` sees the global device list),
3. run an explicit shard_map psum across the cross-process dp axis, and
4. run a jit'd dp train step (value_and_grad with dp-sharded batch,
   replicated params) whose gradient psum XLA lowers onto the
   cross-process channel — asserting loss and gradient equal the
   single-host reference computed locally from the same seed.

On real v5e-16 the same join runs with the TPU backend and the psum
rides ICI/DCN instead of the CPU channel; everything above the backend
is identical. Run standalone: ``python -m
cassmantle_tpu.parallel.multihost_dryrun`` (parent mode — spawns and
checks the children; the children re-enter this module with
CASSMANTLE_COORDINATOR set).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_OK_MARKER = "MULTIHOST-DRYRUN-OK"


def _child() -> None:
    # Pin BEFORE any jax backend use: the parent strips its own
    # XLA_FLAGS from our env so the device count here is authoritative.
    from cassmantle_tpu.utils.xla_flags import pin_cpu_platform

    pin_cpu_platform(
        virtual_devices=True,
        device_count=int(os.environ["CASSMANTLE_DRYRUN_LOCAL_DEVICES"]))

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from cassmantle_tpu.config import MeshConfig
    from cassmantle_tpu.parallel.mesh import (
        batch_sharding,
        make_mesh,
        maybe_init_distributed,
        replicated,
        shard_map,
    )

    assert maybe_init_distributed(), "coordinator env vars missing"
    pid = jax.process_index()
    n_procs = jax.process_count()
    assert n_procs == int(os.environ["CASSMANTLE_NUM_PROCS"]), n_procs
    local = jax.local_device_count()
    n_dev = len(jax.devices())
    assert n_dev == n_procs * local, (n_dev, n_procs, local)

    mesh = make_mesh(MeshConfig(dp=-1, pp=1, tp=1, sp=1, ep=1))

    # 1) explicit collective across the cross-process dp axis
    ones = jax.make_array_from_process_local_data(
        batch_sharding(mesh), np.ones((local, 1), np.float32))
    total = jax.jit(shard_map(
        lambda x: jax.lax.psum(jnp.sum(x), "dp"),
        mesh=mesh, in_specs=P("dp"), out_specs=P()))(ones)
    assert float(total) == float(n_dev), float(total)

    # 2) dp train step: dp-sharded batch, replicated params; GSPMD
    #    inserts the cross-process gradient psum
    dim, batch = 16, n_dev * 2
    rng = np.random.default_rng(0)  # same seed everywhere
    x_full = rng.standard_normal((batch, dim)).astype(np.float32)
    y_full = rng.standard_normal((batch,)).astype(np.float32)
    w0 = np.linspace(-1.0, 1.0, dim).astype(np.float32)
    shard = batch // n_procs
    sl = slice(pid * shard, (pid + 1) * shard)
    dp = NamedSharding(mesh, P("dp"))
    x_g = jax.make_array_from_process_local_data(dp, x_full[sl])
    y_g = jax.make_array_from_process_local_data(dp, y_full[sl])
    w_g = jax.device_put(jnp.asarray(w0), replicated(mesh))

    def loss_fn(w, x, y):
        return jnp.mean((x @ w - y) ** 2)

    step = jax.jit(
        jax.value_and_grad(loss_fn),
        in_shardings=(replicated(mesh), dp, dp),
        out_shardings=(replicated(mesh), replicated(mesh)))
    loss, grad = step(w_g, x_g, y_g)
    w1 = w_g - 0.1 * grad  # the actual SGD update, on-mesh

    resid = x_full @ w0 - y_full
    ref_loss = float(np.mean(resid ** 2))
    ref_grad = (2.0 / batch) * x_full.T @ resid
    np.testing.assert_allclose(float(loss), ref_loss, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grad), ref_grad,
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(w1), w0 - 0.1 * ref_grad,
                               rtol=1e-4, atol=1e-5)

    print(f"[multihost] proc {pid}/{n_procs}: {n_dev} global devices, "
          f"psum={float(total):.0f}, loss={float(loss):.6f} ok",
          flush=True)
    if pid == 0:
        print(_OK_MARKER, flush=True)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def run_multihost_dryrun(n_procs: int = 2, local_devices: int = 4,
                         timeout_s: float = 420.0) -> str:
    """Spawn the children, wait, raise on any failure; returns proc-0
    output (contains the OK marker)."""
    from cassmantle_tpu.utils.xla_flags import (
        COLLECTIVE_TIMEOUT_FLAGS,
        _supported_optional_flags,
        virtual_device_flag,
    )

    port = _free_port()
    # children must NOT inherit the parent's XLA_FLAGS: a pre-existing
    # --xla_force_host_platform_device_count (e.g. conftest's 8) would
    # win over ours by append_xla_flags' first-wins rule. The timeout
    # flags go through the same supported-by-this-jaxlib probe as
    # pin_cpu_platform — an unknown flag is FATAL in the children.
    base = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    flags = " ".join(
        [virtual_device_flag(local_devices)]
        + _supported_optional_flags(COLLECTIVE_TIMEOUT_FLAGS))
    procs = []
    for pid in range(n_procs):
        env = dict(
            base, XLA_FLAGS=flags, JAX_PLATFORMS="cpu",
            CASSMANTLE_COORDINATOR=f"localhost:{port}",
            CASSMANTLE_NUM_PROCS=str(n_procs),
            CASSMANTLE_PROC_ID=str(pid),
            CASSMANTLE_DRYRUN_LOCAL_DEVICES=str(local_devices),
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "cassmantle_tpu.parallel.multihost_dryrun"],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    import time

    deadline = time.monotonic() + timeout_s  # shared, not per-process
    outs = [None] * n_procs
    timed_out = False
    for i, p in enumerate(procs):
        try:
            outs[i], _ = p.communicate(
                timeout=max(1.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            timed_out = True
            break
    if timed_out:
        for p in procs:
            if p.poll() is None:
                p.kill()
        # reap + drain pipes so the hung child's own output (the only
        # diagnostic of WHERE it hung) makes it into the error
        for i, p in enumerate(procs):
            if outs[i] is None:
                try:
                    outs[i], _ = p.communicate(timeout=10)
                except Exception:
                    outs[i] = ""
        raise RuntimeError(
            f"multihost dryrun timed out after {timeout_s:.0f}s; "
            "children said:\n"
            + "\n---\n".join((o or "")[-2000:] for o in outs))
    bad = [i for i, p in enumerate(procs) if p.returncode != 0]
    if bad:
        raise RuntimeError(
            f"multihost dryrun failed in process(es) {bad}:\n"
            + "\n---\n".join(outs[i][-2000:] for i in bad))
    if _OK_MARKER not in outs[0]:
        raise RuntimeError(f"marker missing from proc 0:\n{outs[0][-2000:]}")
    return outs[0]


def main() -> None:
    if os.environ.get("CASSMANTLE_COORDINATOR"):
        _child()
    else:
        out = run_multihost_dryrun()
        sys.stdout.write(out)


if __name__ == "__main__":
    main()
