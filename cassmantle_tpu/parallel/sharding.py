"""Parameter/activation sharding rules (GSPMD partition specs).

Megatron-style tensor parallelism for every transformer in the zoo, using
the param-path conventions of our modules (models/layers.py):

- attention ``q/k/v`` Dense kernels: shard the output (head) dim over
  ``tp``; the ``out`` projection shards its input dim — the pair needs one
  psum per attention block, inserted automatically by GSPMD.
- MLP/GEGLU: first Dense shards output dim, second shards input dim.
- conv kernels, norms, embeddings: replicated (convs are the UNet's
  majority FLOPs but shard naturally over ``dp``/``sp`` instead).

Everything is expressed as regex -> PartitionSpec rules on flattened param
paths, so the same table serves UNet, CLIP, GPT-2 and MiniLM.
"""

from __future__ import annotations

import re
from typing import List, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# (path regex, spec) — first match wins. Kernel layouts: Dense (in, out),
# Conv (H, W, in, out), Embed (vocab, dim).
TP_RULES: List[Tuple[str, P]] = [
    # attention projections
    (r".*/(self_attn|cross_attn|attn)/(q|k|v|qkv|kv)/kernel$", P(None, "tp")),
    (r".*/(self_attn|cross_attn|attn)/(q|k|v|qkv|kv)/bias$", P("tp")),
    (r".*/(self_attn|cross_attn|attn)/out/kernel$", P("tp", None)),
    # MLP / GEGLU / SwiGLU (Mistral gate+up shard columns, down rows)
    (r".*/(mlp|ff)/(fc1|proj|gate|up)/kernel$", P(None, "tp")),
    (r".*/(mlp|ff)/(fc1|proj|gate|up)/bias$", P("tp")),
    (r".*/(mlp|ff)/(fc2|out|down)/kernel$", P("tp", None)),
    # everything else replicated
    (r".*", P()),
]


def _path_str(path) -> str:
    return "/".join(str(getattr(p, "key", p)) for p in path)


def param_specs(params) -> "jax.tree_util.PyTreeDef":
    """Param tree -> tree of PartitionSpec following TP_RULES."""

    def spec_for(path, leaf):
        s = _path_str(path)
        for pattern, spec in TP_RULES:
            if re.match(pattern, s):
                # never shard a dim that doesn't divide; GSPMD requires
                # divisibility — fall back to replication if mismatched.
                return spec
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, params)


def shard_params(params, mesh: Mesh):
    """Place a param tree on the mesh per TP_RULES (validating divisibility
    and falling back to replication where a dim doesn't divide)."""
    tp = mesh.shape.get("tp", 1)

    def place(path, leaf):
        spec = None
        s = _path_str(path)
        for pattern, candidate in TP_RULES:
            if re.match(pattern, s):
                spec = candidate
                break
        if spec is None:
            spec = P()
        # validate divisibility of each sharded dim
        for dim, axis in enumerate(spec):
            if axis is None:
                continue
            if dim >= leaf.ndim or leaf.shape[dim] % tp != 0:
                spec = P()
                break
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, params)
