"""Pipeline parallelism: GPipe-style microbatched stage execution.

The reference has no parallelism at all (SURVEY.md §2 #15); this is the
``pp`` rung of the TPU build's mesh. Idiomatic TPU pipelining is NOT a
scheduler thread per stage (the GPU/NCCL pattern) — it is a single SPMD
program over the ``pp`` mesh axis:

- every device holds ONE stage's parameters (the stage-stacked param tree
  is sharded on its leading axis with ``P("pp")``);
- a ``lax.scan`` runs ``M + S - 1`` ticks; on each tick every device
  applies its stage to the activation it holds, then the activations
  rotate one hop around the ring with ``lax.ppermute`` (one ICI hop —
  exactly the collective the hardware is built for);
- stage 0 feeds a fresh microbatch into tick ``t < M``; stage ``S-1``
  banks its output for microbatch ``t - (S-1)``. The bubble is the
  classic ``(S-1) / (M + S - 1)`` fraction.

``pipeline_apply`` is generic over any per-stage function; ``stack_stage
_params`` builds the stage-stacked tree from per-layer trees (e.g. GPT-2
blocks, models/gpt2.py). Composes with ``dp`` (shard the microbatch dim)
and ``tp`` (shard the stage weights) on the same mesh.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax

from cassmantle_tpu.parallel.mesh import shard_map
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def stack_stage_params(per_stage_params: Sequence):
    """List of S identically-shaped param trees -> one tree with a leading
    stage axis, ready to shard with ``P("pp")``."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves, axis=0), *per_stage_params
    )


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    x: jax.Array,
    mesh: Mesh,
    num_microbatches: int = 0,
    axis: str = "pp",
) -> jax.Array:
    """Run ``x`` through ``S`` pipeline stages on the mesh's ``pp`` axis.

    ``stage_fn(params_s, h) -> h`` applies one stage; ``stage_params`` has
    a leading stage axis of size ``S = mesh.shape[axis]``; ``x`` is
    ``(B, ...)`` with ``B`` divisible by ``num_microbatches`` (defaults to
    ``S``). Returns the same-shaped output of the full stage stack.
    """
    S = int(mesh.shape[axis])
    M = num_microbatches or S
    b = x.shape[0]
    assert b % M == 0, f"batch {b} not divisible by {M} microbatches"
    mb = b // M
    xs = x.reshape(M, mb, *x.shape[1:])
    perm = [(j, (j + 1) % S) for j in range(S)]

    def per_device(params, xs):
        # shard_map leaves the sharded leading axis as size 1: strip it.
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        idx = jax.lax.axis_index(axis)

        def tick(carry, t):
            buf, ys = carry
            # stage 0 ingests microbatch t while t < M
            inp = xs[jnp.minimum(t, M - 1)]
            buf = jnp.where(jnp.logical_and(idx == 0, t < M), inp, buf)
            out = stage_fn(params, buf)
            # last stage banks microbatch m = t - (S-1) once it's real
            m = t - (S - 1)
            banked = jax.lax.dynamic_update_index_in_dim(
                ys, out, jnp.maximum(m, 0), 0
            )
            ys = jnp.where(jnp.logical_and(idx == S - 1, m >= 0), banked, ys)
            buf = jax.lax.ppermute(out, axis, perm)
            return (buf, ys), None

        init = (jnp.zeros_like(xs[0]), jnp.zeros_like(xs))
        (_, ys), _ = jax.lax.scan(tick, init, jnp.arange(M + S - 1))
        return ys[None]  # (1, M, mb, ...): stacked over pp outside

    stacked = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(axis), P(*(None,) * xs.ndim)),
        out_specs=P(axis),
        check_vma=False,
    )(stage_params, xs)
    # stage S-1 holds the real outputs; earlier stages hold zeros/garbage
    return stacked[S - 1].reshape(b, *x.shape[1:])


def gpt2_stage_fn(block_apply: Callable, mask: jax.Array) -> Callable:
    """Adapt a GPT2Block apply to the pipeline's ``(params, h) -> h``.

    ``block_apply({"params": p}, h, mask=mask)`` returns ``(h, kv)``; the
    pipeline carries hidden states only.
    """

    def fn(params, h):
        out, _ = block_apply({"params": params}, h, mask=mask)
        return out

    return fn


def pipelined_lm_forward(
    model,
    params,
    input_ids: jax.Array,
    mesh: Mesh,
    num_microbatches: int = 0,
) -> jax.Array:
    """GPT-2 forward with the block stack pipelined over ``pp``.

    Embedding/LM-head run replicated (they are a tiny fraction of FLOPs);
    the ``num_layers`` blocks split into ``pp`` equal stages of stacked
    layers. Numerically identical to ``model.apply`` up to reduction
    order — tests/test_pipeline_parallel.py asserts parity.
    """
    from cassmantle_tpu.models.gpt2 import GPT2Block

    S = int(mesh.shape["pp"])
    cfg = model.cfg
    L = cfg.num_layers
    assert L % S == 0, f"{L} layers not divisible into {S} stages"
    per_stage = L // S

    p = params["params"]
    block_params = [p[f"block_{i}"] for i in range(L)]
    # leading axes: (S stages, per_stage layers within the stage)
    stage_trees = [
        stack_stage_params(block_params[s * per_stage:(s + 1) * per_stage])
        for s in range(S)
    ]
    stacked = stack_stage_params(stage_trees)

    b, s_len = input_ids.shape
    positions = jnp.arange(s_len)[None, :]
    dtype = jnp.dtype(cfg.dtype)
    wte = p["wte"]["embedding"]
    wpe = p["wpe"]["embedding"]
    x = wte[input_ids].astype(dtype) + wpe[positions].astype(dtype)
    mask = jnp.tril(jnp.ones((s_len, s_len), dtype=bool))[None, None]

    block = GPT2Block(cfg, dtype)

    def stage_fn(stage_params, h):
        # sequentially apply this stage's stacked layers via lax.scan
        def layer(h, lp):
            out, _ = block.apply({"params": lp}, h, mask=mask)
            return out, None

        h, _ = jax.lax.scan(layer, h, stage_params)
        return h

    x = pipeline_apply(stage_fn, stacked, x, mesh,
                       num_microbatches=num_microbatches)

    # final LN + tied LM head, replicated (fp32, as in GPT2LM._logits)
    ln = p["ln_f"]
    x = x.astype(jnp.float32)
    mean = x.mean(-1, keepdims=True)
    var = ((x - mean) ** 2).mean(-1, keepdims=True)
    xn = (x - mean) / jnp.sqrt(var + 1e-6)
    xn = xn * ln["scale"] + ln["bias"]
    return xn.astype(jnp.float32) @ wte.astype(jnp.float32).T
