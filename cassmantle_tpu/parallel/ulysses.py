"""Ulysses-style sequence parallelism: all-to-all head sharding.

The second sequence/context-parallel flavor next to ring attention
(parallel/ring.py): instead of rotating K/V blocks around the ring, ONE
``all_to_all`` re-shards the activations from sequence-sharded to
head-sharded, every device computes FULL-sequence attention for its
subset of heads, and a second ``all_to_all`` shards back by sequence.

Trade-offs vs ring (both ride ICI):
- Ulysses: 2 collective hops total, local attention sees the whole
  sequence (exact softmax in one pass — no online-softmax merging), but
  needs ``num_heads % sp == 0`` and moves Q, K, and V once each.
- Ring: n-1 hops of K/V only with compute/comm overlap; works for any
  head count; memory per device stays O(S/n) even inside attention.

Per SURVEY.md §5.7 this is the head-sharded scale-up path for 1024²+
image-token attention and long text sequences.
"""

from __future__ import annotations

import functools

import jax

from cassmantle_tpu.parallel.mesh import shard_map
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from cassmantle_tpu.ops.attention import xla_attention


def _ulysses_local(q, k, v, axis_name: str, scale: float, causal: bool):
    """Per-shard body. q/k/v: (B, S_l, H, D) — sequence-sharded in."""

    def seq_to_heads(t):
        # (B, S_l, H, D) -> (B, S, H/n, D): gather sequence, scatter heads
        return jax.lax.all_to_all(
            t, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def heads_to_seq(t):
        return jax.lax.all_to_all(
            t, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    mask = None
    if causal:
        # after the all-to-all, each device sees the FULL sequence for
        # its heads, so causal is the plain triangular mask
        s = qh.shape[-3]
        mask = jnp.tril(jnp.ones((s, s), bool))
    out = xla_attention(qh, kh, vh, mask=mask, scale=scale)
    return heads_to_seq(out)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "sp",
    scale=None,
    causal: bool = False,
) -> jax.Array:
    """Sequence-parallel attention via head sharding.

    Global shapes (B, S, H, D); S shards over ``axis_name``; requires
    ``H % mesh.shape[axis_name] == 0``. ``causal=True`` applies the LM
    triangular mask.
    """
    n = int(mesh.shape[axis_name])
    h = q.shape[-2]
    assert h % n == 0, f"{h} heads not divisible by {axis_name}={n}"
    if scale is None:
        scale = q.shape[-1] ** -0.5
    body = functools.partial(
        _ulysses_local, axis_name=axis_name, scale=float(scale),
        causal=causal,
    )
    spec = P(None, axis_name, None, None)
    return shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )(q, k, v)
