"""Distributed diffusion training step (dp × tp × sp over one mesh).

The reference never trains anything — but a complete framework must
(fine-tuning the UNet on new styles is the natural extension of the game's
content loop), and the driver's multi-chip dryrun compiles exactly this
step. Design:

- **loss**: standard denoising-score-matching: sample t ~ U, noise the
  clean latents with the DDIM schedule's ᾱ, MSE between predicted and true
  noise.
- **dp**: batch dim sharded; gradient all-reduce inserted by GSPMD from
  the sharding constraints (rides ICI).
- **tp**: attention/MLP kernels sharded per parallel/sharding.py rules.
- **sp**: inside the UNet the image-token axis can further shard via ring
  attention (parallel/ring.py); at train-step level the latent height dim
  shards over ``sp`` for the conv stack (halo-free 1x1/3x3 convs handled
  by GSPMD's spatial partitioning).
- bf16 activations, fp32 params/optimizer state, optax adamw with
  gradient clipping; ``donate_argnums`` so params/opt state update
  in place in HBM.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cassmantle_tpu.config import FrameworkConfig
from cassmantle_tpu.models.unet import UNet
from cassmantle_tpu.models.weights import init_params
from cassmantle_tpu.parallel.sharding import shard_params


def make_optimizer(lr: float = 1e-4) -> optax.GradientTransformation:
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(lr, b1=0.9, b2=0.999, weight_decay=0.01),
    )


class DiffusionTrainer:
    """Owns sharded params/opt state and the compiled train step."""

    def __init__(
        self,
        cfg: FrameworkConfig,
        mesh: Mesh,
        lr: float = 1e-4,
        num_train_steps: int = 1000,
        remat: bool = False,
    ) -> None:
        self.cfg = cfg
        self.mesh = mesh
        self.unet = UNet(cfg.models.unet)
        # Rematerialization trades FLOPs for HBM: the backward pass
        # recomputes the UNet forward instead of keeping every
        # activation live — the standard lever for fitting bigger
        # batches/resolutions per chip.
        self._apply = (jax.checkpoint(self.unet.apply) if remat
                       else self.unet.apply)
        self.optimizer = make_optimizer(lr)

        betas = (
            jnp.linspace(0.00085**0.5, 0.012**0.5, num_train_steps) ** 2
        )
        self.alpha_bars = jnp.cumprod(1.0 - betas)
        self.num_train_steps = num_train_steps

        self._step = jax.jit(
            self._train_step_impl, donate_argnums=(0, 1)
        )

    # -- state ------------------------------------------------------------
    def init_state(self, sample_batch: Dict[str, jax.Array], seed: int = 0
                   ) -> Tuple[Any, Any]:
        params = init_params(
            self.unet, seed,
            sample_batch["latents"],
            jnp.zeros((sample_batch["latents"].shape[0],), jnp.int32),
            sample_batch["context"],
        )
        params = shard_params(params, self.mesh)
        opt_state = self.optimizer.init(params)
        # optimizer moments inherit param shardings naturally via init
        return params, opt_state

    def batch_sharding(self) -> NamedSharding:
        # batch over dp; latent height over sp (spatial partitioning)
        return NamedSharding(self.mesh, P("dp", "sp"))

    def shard_batch(self, batch: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        lat_sh = self.batch_sharding()
        ctx_sh = NamedSharding(self.mesh, P("dp"))
        return {
            "latents": jax.device_put(batch["latents"], lat_sh),
            "context": jax.device_put(batch["context"], ctx_sh),
        }

    # -- step -------------------------------------------------------------
    def _train_step_impl(self, params, opt_state, batch, rng):
        latents = batch["latents"]
        context = batch["context"]
        b = latents.shape[0]
        rng_t, rng_n = jax.random.split(rng)
        t = jax.random.randint(rng_t, (b,), 0, self.num_train_steps)
        noise = jax.random.normal(rng_n, latents.shape, latents.dtype)
        a = self.alpha_bars[t][:, None, None, None]
        noisy = jnp.sqrt(a) * latents + jnp.sqrt(1.0 - a) * noise

        def loss_fn(p):
            pred = self._apply(p, noisy, t, context)
            return jnp.mean((pred - noise) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, new_opt = self.optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_opt, loss

    def step(self, params, opt_state, batch, rng):
        return self._step(params, opt_state, batch, rng)
