"""Distributed diffusion training step (dp × tp × sp over one mesh).

The reference never trains anything — but a complete framework must
(fine-tuning the UNet on new styles is the natural extension of the game's
content loop), and the driver's multi-chip dryrun compiles exactly this
step. Design:

- **loss**: standard denoising-score-matching: sample t ~ U, noise the
  clean latents with the DDIM schedule's ᾱ, MSE between predicted and true
  noise.
- **dp**: batch dim sharded; gradient all-reduce inserted by GSPMD from
  the sharding constraints (rides ICI).
- **tp**: attention/MLP kernels sharded per parallel/sharding.py rules.
- **sp**: inside the UNet the image-token axis can further shard via ring
  attention (parallel/ring.py); at train-step level the latent height dim
  shards over ``sp`` for the conv stack (halo-free 1x1/3x3 convs handled
  by GSPMD's spatial partitioning).
- bf16 activations, fp32 params/optimizer state, optax adamw with
  gradient clipping; ``donate_argnums`` so params/opt state update
  in place in HBM.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cassmantle_tpu.config import FrameworkConfig
from cassmantle_tpu.models.unet import UNet
from cassmantle_tpu.models.weights import init_params
from cassmantle_tpu.parallel.sharding import shard_params


def make_optimizer(lr: float = 1e-4) -> optax.GradientTransformation:
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(lr, b1=0.9, b2=0.999, weight_decay=0.01),
    )


class DiffusionTrainer:
    """Owns sharded params/opt state and the compiled train step."""

    def __init__(
        self,
        cfg: FrameworkConfig,
        mesh: Mesh,
        lr: float = 1e-4,
        num_train_steps: int = 1000,
        remat: bool = False,
    ) -> None:
        self.cfg = cfg
        self.mesh = mesh
        self.unet = UNet(cfg.models.unet)
        # Rematerialization trades FLOPs for HBM: the backward pass
        # recomputes the UNet forward instead of keeping every
        # activation live — the standard lever for fitting bigger
        # batches/resolutions per chip.
        self._apply = (jax.checkpoint(self.unet.apply) if remat
                       else self.unet.apply)
        self.optimizer = make_optimizer(lr)

        betas = (
            jnp.linspace(0.00085**0.5, 0.012**0.5, num_train_steps) ** 2
        )
        self.alpha_bars = jnp.cumprod(1.0 - betas)
        self.num_train_steps = num_train_steps

        self._step = jax.jit(
            self._train_step_impl, donate_argnums=(0, 1)
        )

    # -- state ------------------------------------------------------------
    def init_state(self, sample_batch: Dict[str, jax.Array], seed: int = 0
                   ) -> Tuple[Any, Any]:
        params = init_params(
            self.unet, seed,
            sample_batch["latents"],
            jnp.zeros((sample_batch["latents"].shape[0],), jnp.int32),
            sample_batch["context"],
        )
        params = shard_params(params, self.mesh)
        opt_state = self.optimizer.init(params)
        # optimizer moments inherit param shardings naturally via init
        return params, opt_state

    def batch_sharding(self) -> NamedSharding:
        # batch over dp; latent height over sp (spatial partitioning)
        return NamedSharding(self.mesh, P("dp", "sp"))

    def shard_batch(self, batch: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        lat_sh = self.batch_sharding()
        ctx_sh = NamedSharding(self.mesh, P("dp"))
        return {
            "latents": jax.device_put(batch["latents"], lat_sh),
            "context": jax.device_put(batch["context"], ctx_sh),
        }

    # -- step -------------------------------------------------------------
    def _train_step_impl(self, params, opt_state, batch, rng):
        latents = batch["latents"]
        context = batch["context"]
        b = latents.shape[0]
        rng_t, rng_n = jax.random.split(rng)
        t = jax.random.randint(rng_t, (b,), 0, self.num_train_steps)
        noise = jax.random.normal(rng_n, latents.shape, latents.dtype)
        a = self.alpha_bars[t][:, None, None, None]
        noisy = jnp.sqrt(a) * latents + jnp.sqrt(1.0 - a) * noise

        def loss_fn(p):
            pred = self._apply(p, noisy, t, context)
            return jnp.mean((pred - noise) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, new_opt = self.optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_opt, loss

    def step(self, params, opt_state, batch, rng):
        return self._step(params, opt_state, batch, rng)


class ConsistencyDistillTrainer:
    """Consistency/LCM distillation of a zoo UNet into a few-step
    student (ROADMAP item 3a, ISSUE 15) on the same train infrastructure
    as :class:`DiffusionTrainer`.

    - **teacher**: the frozen zoo UNet plus ONE deterministic DDIM
      solver step (:func:`~cassmantle_tpu.ops.ddim.ddim_update`) over a
      ``solver_steps``-point discretization as the ODE-step oracle —
      ``skip`` > 1 strides the oracle step over several schedule
      positions (LCM's skip-step trick: one teacher forward covers a
      wider λ interval, so the student sees larger consistency hops for
      the same compute).
    - **student**: the SAME ``UNetConfig`` architecture, initialized
      from the teacher tree — identical param pytree, so
      ``utils/checkpoint.py`` and ``share_compatible`` work unchanged
      and a distilled checkpoint drops into the serving weights path
      as-is (tests/test_distill.py pins the layout).
    - **EMA target network**: the consistency target is evaluated by an
      exponential moving average of the student (``ema_decay``), the
      stabilizer from the consistency-models recipe; its update rides
      inside the jitted step.
    - **loss**: skip-step consistency loss — noise clean latents to a
      random schedule position n, run the teacher oracle one (strided)
      step down the ODE, and pull the student's boundary-parameterized
      x0 estimate at n toward the EMA target's estimate at n+skip
      (``consistency_boundary`` c_skip/c_out, the same parameterization
      the serving sampler applies).

    ``max_serve_steps`` declares the largest ``num_steps`` the student
    will be served at — the constructor rejects skip/solver
    combinations whose trained query range does not cover every
    ``ConsistencySchedule`` up to it (the serving-coverage contract;
    the schedule only ever queries the teacher discretization, and
    training must have visited those points).

    With ``mesh`` the batch shards over dp/sp and params shard per
    sharding rules (exactly DiffusionTrainer's layout); ``mesh=None``
    runs a plain jit — the CPU toy-geometry path tier-1 exercises.
    ``donate_argnums`` updates student/EMA/optimizer state in place;
    the teacher tree is a plain (non-donated) argument and is never
    written.
    """

    def __init__(
        self,
        cfg: FrameworkConfig,
        mesh: "Mesh | None" = None,
        lr: float = 1e-4,
        solver_steps: Optional[int] = None,
        skip: int = 1,
        ema_decay: float = 0.95,
        sigma_data: float = 0.5,
        num_train_steps: int = 1000,
        remat: bool = False,
        max_serve_steps: int = 8,
    ) -> None:
        import numpy as np

        from cassmantle_tpu.ops.ddim import (
            DDIMSchedule,
            alpha_bars_full,
        )

        solver_steps = (solver_steps if solver_steps is not None
                        else cfg.sampler.consistency_teacher_steps)
        assert 1 <= skip < solver_steps, (
            f"skip {skip} outside [1, {solver_steps})")
        # Serving-coverage contract: ConsistencySchedule queries grid
        # indices (L//m)·j, j < m, over the t>0 grid (L = solver_steps−1
        # points, ops/samplers.py), while training only queries student
        # positions n ≤ solver_steps−1−skip (the randint below) — large
        # skip narrows the trained range. Every schedule this student
        # may be served at (num_steps ≤ max_serve_steps) must stay
        # inside it; reject the combination at TRAIN time instead of
        # silently serving untrained noise levels.
        grid_len = solver_steps - 1
        worst = max((grid_len // m) * (m - 1)
                    for m in range(1, min(max_serve_steps, grid_len) + 1))
        assert worst <= solver_steps - 1 - skip, (
            f"skip {skip} leaves serving schedules uncovered: a "
            f"num_steps<={max_serve_steps} ConsistencySchedule queries "
            f"grid index {worst} but training only queries up to "
            f"{solver_steps - 1 - skip}; lower skip or max_serve_steps")
        self.cfg = cfg
        self.mesh = mesh
        self.unet = UNet(cfg.models.unet)
        self._apply = (jax.checkpoint(self.unet.apply) if remat
                       else self.unet.apply)
        self.optimizer = make_optimizer(lr)
        self.solver_steps = solver_steps
        self.skip = skip
        self.ema_decay = float(ema_decay)
        self.sigma_data = float(sigma_data)
        sched = DDIMSchedule.create(solver_steps, num_train_steps)
        self.timesteps = sched.timesteps        # (T,) int32 descending
        self.alpha_bars = sched.alpha_bars      # (T,) float32
        ab_full = alpha_bars_full(num_train_steps)
        self.sigma_min = float(np.sqrt((1.0 - ab_full[0]) / ab_full[0]))
        self._step = jax.jit(
            self._distill_step_impl, donate_argnums=(0, 1, 2)
        )

    # -- state ------------------------------------------------------------
    def init_state(self, teacher_params) -> Tuple[Any, Any, Any]:
        """(student, ema, opt_state) from a frozen teacher tree. Student
        and EMA start as COPIES (standard distillation init — and the
        donated buffers must not alias the teacher's)."""
        def copy_tree(tree):
            return jax.tree_util.tree_map(jnp.array, tree)

        student = copy_tree(teacher_params)
        ema = copy_tree(teacher_params)
        if self.mesh is not None:
            student = shard_params(student, self.mesh)
            ema = shard_params(ema, self.mesh)
        opt_state = self.optimizer.init(student)
        return student, ema, opt_state

    def batch_sharding(self) -> Optional[NamedSharding]:
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, P("dp", "sp"))

    def shard_batch(self, batch: Dict[str, jax.Array]
                    ) -> Dict[str, jax.Array]:
        if self.mesh is None:
            return batch
        lat_sh = self.batch_sharding()
        ctx_sh = NamedSharding(self.mesh, P("dp"))
        return {
            "latents": jax.device_put(batch["latents"], lat_sh),
            "context": jax.device_put(batch["context"], ctx_sh),
        }

    # -- step -------------------------------------------------------------
    def _consistency_f(self, params, x, t, ab, context):
        """The boundary-parameterized consistency function f(x, t):
        c_skip·x + c_out·x0_pred, the exact form the serving sampler
        evaluates (ops/samplers.py::consistency_sample)."""
        from cassmantle_tpu.ops.samplers import consistency_boundary

        eps = self._apply(params, x, t, context)
        x0 = (x - jnp.sqrt(1.0 - ab) * eps) / jnp.sqrt(ab)
        sigma = jnp.sqrt((1.0 - ab) / ab)
        c_skip, c_out = consistency_boundary(
            sigma, self.sigma_min, self.sigma_data)
        return c_skip * x + c_out * x0

    def _distill_step_impl(self, student, ema, opt_state, teacher,
                           batch, rng):
        from cassmantle_tpu.ops.ddim import ddim_update

        latents = batch["latents"]
        context = batch["context"]
        b = latents.shape[0]
        rng_n, rng_eps = jax.random.split(rng)
        # per-sample schedule position n; the oracle maps n -> n+skip
        n = jax.random.randint(
            rng_n, (b,), 0, self.timesteps.shape[0] - self.skip)
        t_n = self.timesteps[n]
        ab_n = self.alpha_bars[n][:, None, None, None]
        t_k = self.timesteps[n + self.skip]
        ab_k = self.alpha_bars[n + self.skip][:, None, None, None]
        noise = jax.random.normal(rng_eps, latents.shape, latents.dtype)
        x_n = jnp.sqrt(ab_n) * latents + jnp.sqrt(1.0 - ab_n) * noise
        # the ODE-step oracle: one teacher forward + one deterministic
        # DDIM transition down the schedule (eta=0 — the same update
        # the serving sampler's scan body applies)
        eps_teacher = self._apply(teacher, x_n, t_n, context)
        x_k = ddim_update(x_n, eps_teacher, ab_n, ab_k)
        target = jax.lax.stop_gradient(
            self._consistency_f(ema, x_k, t_k, ab_k, context))

        def loss_fn(p):
            pred = self._consistency_f(p, x_n, t_n, ab_n, context)
            return jnp.mean((pred - target) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(student)
        updates, new_opt = self.optimizer.update(grads, opt_state, student)
        new_student = optax.apply_updates(student, updates)
        d = self.ema_decay
        new_ema = jax.tree_util.tree_map(
            lambda e, s: d * e + (1.0 - d) * s, ema, new_student)
        return new_student, new_ema, new_opt, loss

    def step(self, student, ema, opt_state, teacher, batch, rng):
        """One distillation step; returns (student, ema, opt_state,
        loss) with loss still on device — callers accumulating a loss
        curve should collect device scalars and transfer ONCE at the
        end, never per step (the host-sync lint's train-loop shape,
        tests/test_check_jax.py)."""
        return self._step(student, ema, opt_state, teacher, batch, rng)
