"""Circuit breaker for the device-facing dispatch paths (SURVEY.md §5.3).

The reference's failure handling stops at per-call retry + skip-don't-crash
(utils.py:43-61, backend.py:211-215): a dead backend is re-dialed at full
cost every round, forever, and nothing upstream ever learns the device is
dark. A breaker turns that into an explicit state machine:

- **closed** — normal operation; failures are counted in a sliding window.
- **open** — too many recent failures; calls fail fast (no device dial, no
  retry backoff burn) until ``reset_timeout_s`` passes.
- **half_open** — one trial call is let through; success closes the
  breaker, failure re-opens it.

Every transition is counted (``circuit.<name>.opened`` / ``.closed`` /
``.half_open``) and the current state is a gauge, so `/metrics` and the
serving supervisor can see a dark device the moment it trips. The clock is
injectable so round-lifecycle tests run the whole trip/probe/recover cycle
in milliseconds. Thread-safe: the content path records from the event loop
while the scorer path records from request handlers.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Deque, Dict

from cassmantle_tpu.utils.locks import OrderedLock
from cassmantle_tpu.utils.logging import get_logger, metrics

log = get_logger("circuit")

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_GAUGE = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}


class CircuitOpen(Exception):
    """Raised (or returned as a fast-fail) when the breaker rejects a call."""


class CircuitBreaker:
    """Closed/open/half-open breaker with a sliding failure window.

    ``allow()`` must be called before the guarded operation;
    ``record_success()`` / ``record_failure()`` after it. ``allow()`` is
    where the open -> half_open transition happens (lazily, on the first
    call after the cooldown), so an idle breaker needs no timer task.
    """

    def __init__(
        self,
        name: str,
        *,
        failure_threshold: int = 5,
        window_s: float = 120.0,
        reset_timeout_s: float = 45.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.name = name
        self.failure_threshold = max(1, failure_threshold)
        self.window_s = window_s
        self.reset_timeout_s = reset_timeout_s
        self.clock = clock
        # innermost tier of the docs/STATIC_ANALYSIS.md lock hierarchy:
        # breaker state may be read under the supervisor lock, never the
        # other way around
        self._lock = OrderedLock(f"circuit.{name}", rank=40)
        self._state = CLOSED
        self._failures: Deque[float] = deque()
        self._opened_at = 0.0
        # half-open lets ONE probe through at a time; a probe that never
        # reports (hung device call) expires after reset_timeout_s so the
        # breaker cannot wedge in half_open forever
        self._probe_at: float = -1.0

    # -- state ------------------------------------------------------------
    def _set_state(self, state: str) -> None:
        if state == self._state:
            return
        prev, self._state = self._state, state
        event = {CLOSED: "closed", OPEN: "opened", HALF_OPEN: "half_open"}[state]
        metrics.inc(f"circuit.{self.name}.{event}")
        metrics.gauge(f"circuit.{self.name}.state", _STATE_GAUGE[state])
        # the flight recorder keeps the ORDER of transitions — /debugz
        # replays trip -> reserve rotation -> recovery causally. Lazy
        # import: utils never depends on obs at module scope (the same
        # rule logging/profiling follow)
        from cassmantle_tpu.obs.recorder import flight_recorder

        flight_recorder.record("breaker", name=self.name,
                               state=state, prev=prev,
                               recent_failures=len(self._failures))
        log.warning("breaker %r -> %s", self.name, state)

    def _tick(self, now: float) -> None:
        """Lazy transitions: open -> half_open after the cooldown."""
        if self._state == OPEN and now - self._opened_at >= self.reset_timeout_s:
            self._set_state(HALF_OPEN)
            self._probe_at = -1.0

    @property
    def state(self) -> str:
        with self._lock:
            self._tick(self.clock())
            return self._state

    def seconds_until_half_open(self) -> float:
        """0 unless open; how long callers should wait before retrying."""
        with self._lock:
            now = self.clock()
            self._tick(now)
            if self._state != OPEN:
                return 0.0
            return max(0.0, self.reset_timeout_s - (now - self._opened_at))

    # -- the guard --------------------------------------------------------
    def allow(self) -> bool:
        """True if a call may proceed. open: fast-fail. half_open: one
        probe at a time (an unreported probe expires after the cooldown)."""
        with self._lock:
            now = self.clock()
            self._tick(now)
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN:
                if self._probe_at < 0 or \
                        now - self._probe_at >= self.reset_timeout_s:
                    self._probe_at = now
                    return True
                metrics.inc(f"circuit.{self.name}.rejected")
                return False
            metrics.inc(f"circuit.{self.name}.rejected")
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures.clear()
            self._probe_at = -1.0
            self._set_state(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            now = self.clock()
            self._tick(now)
            metrics.inc(f"circuit.{self.name}.failures")
            if self._state == HALF_OPEN:
                # the probe failed: straight back to open, fresh cooldown
                self._probe_at = -1.0
                self._opened_at = now
                self._set_state(OPEN)
                return
            self._failures.append(now)
            while self._failures and now - self._failures[0] > self.window_s:
                self._failures.popleft()
            if self._state == CLOSED and \
                    len(self._failures) >= self.failure_threshold:
                self._opened_at = now
                self._set_state(OPEN)

    # -- introspection ----------------------------------------------------
    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            now = self.clock()
            self._tick(now)
            return {
                "state": self._state,
                "recent_failures": len(self._failures),
                "failure_threshold": self.failure_threshold,
                "retry_after_s": (
                    max(0.0, self.reset_timeout_s - (now - self._opened_at))
                    if self._state == OPEN else 0.0
                ),
            }
