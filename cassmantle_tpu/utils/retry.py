"""Generic async retry with backoff.

The reference's only fault-handling primitive is ``api_call``'s retry-on-503
with linear backoff (utils.py:32-72, ≤5 tries, (k+1)·10 s). The framework
keeps the same envelope but generalizes it: any async operation (content
generation, store I/O) can be wrapped, with injectable sleep for tests and
a backoff schedule matching the reference's default.
"""

from __future__ import annotations

import asyncio
import random
import threading
import time
from typing import Awaitable, Callable, Optional, Tuple, Type, TypeVar

from cassmantle_tpu.utils.logging import get_logger, metrics

T = TypeVar("T")
log = get_logger("retry")


class RetryBudget:
    """Token-bucket cap on the RATE of retries (ISSUE 17).

    Per-call retry loops are individually bounded but collectively
    unbounded: under a persistent fault, every caller spends its full
    ``max_retries`` re-dialing the same dead thing, and the retry
    traffic itself becomes load (checkpoint re-reads in device
    recovery, device dials behind a flaky tunnel). A shared budget
    makes the AGGREGATE bounded: each retry attempt spends a token,
    tokens refill at a fixed rate, and an empty bucket turns further
    retries into immediate give-ups (``retry.budget_exhausted``).

    Breaker fast-fails are exempt by construction — a ``give_up_on``
    abort in :func:`retry_async` raises before any token is consumed,
    and CircuitOpen paths never reach a retry loop at all; the budget
    meters real re-dials only, never the cheap refusals.

    Thread-safe; ``clock`` is injectable for tests and drills.
    """

    def __init__(self, name: str, capacity: float = 10.0,
                 refill_per_s: float = 0.5,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.name = name
        self.capacity = float(capacity)
        self.refill_per_s = float(refill_per_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._tokens = float(capacity)
        self._at = clock()

    def tokens(self) -> float:
        """Current token balance (after refill), for status surfaces."""
        with self._lock:
            self._refill_locked()
            return self._tokens

    def _refill_locked(self) -> None:
        now = self.clock()
        self._tokens = min(self.capacity,
                           self._tokens
                           + (now - self._at) * self.refill_per_s)
        self._at = now

    def acquire(self, n: float = 1.0) -> bool:
        """Spend ``n`` tokens if available. False = budget exhausted:
        the caller must give up this retry (counted, logged)."""
        with self._lock:
            self._refill_locked()
            if self._tokens >= n:
                self._tokens -= n
                return True
        metrics.inc("retry.budget_exhausted",
                    labels={"budget": self.name})
        log.warning("retry budget %r exhausted; giving up retry",
                    self.name)
        return False

# Default jitter source. Module-level (not per-call) so the stream is
# one process-wide sequence; seed_jitter() pins it for drills/tests —
# a seeded chaos run replays the same retry spacing too.
_jitter_rng = random.Random()


def seed_jitter(seed: int) -> None:
    """Re-seed the default jitter stream (deterministic drills)."""
    global _jitter_rng
    _jitter_rng = random.Random(seed)


def linear_backoff(base_s: float = 10.0):
    """Reference schedule: (attempt+1) * base seconds (utils.py:61)."""

    def schedule(attempt: int) -> float:
        return (attempt + 1) * base_s

    return schedule


async def retry_async(
    op: Callable[[], Awaitable[T]],
    *,
    max_retries: int = 5,
    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
    backoff: Optional[Callable[[int], float]] = None,
    sleep: Callable[[float], Awaitable[None]] = asyncio.sleep,
    name: str = "op",
    deadline_s: Optional[float] = None,
    give_up_on: Tuple[Type[BaseException], ...] = (),
    jitter: bool = True,
    rng: Optional[random.Random] = None,
    budget: Optional[RetryBudget] = None,
) -> T:
    """Run ``op`` with up to ``max_retries`` attempts; re-raises the last
    failure (callers keep skip-don't-crash semantics at their level).

    Backoff is FULL-JITTERED by default: each pause is drawn uniformly
    from (0, schedule(attempt)] — N callers tripped by one store blip
    (every worker's round clock hitting the same dead leader) spread
    their re-dials across the window instead of retrying in lockstep
    and re-spiking the thing that just fell over. ``rng`` injects the
    jitter source (deterministic under drill seeds; see
    :func:`seed_jitter` for the module default); ``jitter=False`` keeps
    the exact reference schedule.

    ``deadline_s`` bounds total wall time: no further attempt starts once
    elapsed + the next backoff would pass it. Callers that retry while
    holding an expiring lock set this below the lock timeout, so the lock
    cannot lapse mid-retry and admit a second worker (a started attempt
    can still overrun — an in-flight device call is not preemptible).

    ``give_up_on`` exceptions abort immediately with no further attempts —
    e.g. a CircuitOpen fast-fail, where backing off and re-dialing an
    open breaker would just burn the caller's lock budget.

    ``budget``: a shared :class:`RetryBudget` each RE-dial must acquire
    from (the first attempt is free — it is not a retry). Exhaustion
    re-raises the last failure immediately; give_up_on fast-fails never
    touch the budget."""
    backoff = backoff or linear_backoff()
    loop = asyncio.get_running_loop()
    start = loop.time()
    last: Optional[BaseException] = None
    for attempt in range(max_retries):
        try:
            return await op()
        except give_up_on:
            raise
        except retry_on as exc:  # noqa: PERF203
            last = exc
            metrics.inc(f"retry.{name}.failures")
            log.warning("%s attempt %d/%d failed: %s",
                        name, attempt + 1, max_retries, exc)
            if attempt + 1 < max_retries:
                if budget is not None and not budget.acquire():
                    log.warning("%s: retry budget exhausted after %d "
                                "attempt(s)", name, attempt + 1)
                    break
                pause = backoff(attempt)
                if jitter and pause > 0:
                    # full jitter (uniform over (0, schedule]): the
                    # spread that actually decorrelates a thundering
                    # herd; attempts stay bounded by max_retries and
                    # the deadline check below, so a small draw cannot
                    # turn backoff into an unbounded hot loop
                    pause *= (rng or _jitter_rng).random()
                if deadline_s is not None and \
                        loop.time() - start + pause >= deadline_s:
                    log.warning("%s: deadline %.0fs reached after %d "
                                "attempts", name, deadline_s, attempt + 1)
                    break
                await sleep(pause)
    assert last is not None
    raise last
