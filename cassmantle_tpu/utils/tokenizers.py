"""Self-contained tokenizers: byte-level BPE (GPT-2/CLIP), WordPiece (BERT),
and a dependency-free byte fallback.

The reference never tokenizes for models — its tokenization is
nltk.word_tokenize for mask selection only (utils.py:83); model-side
tokenization happened inside the HF Inference API. Running models locally
needs real tokenizers, and this environment has no network egress, so:

- If vocab artifacts exist in ``weights_dir`` (``vocab.json``+``merges.txt``
  for GPT-2/CLIP, ``vocab.txt`` for MiniLM), full BPE/WordPiece encode and
  decode are implemented here from scratch (no `tokenizers` wheel needed).
- Otherwise :class:`ByteTokenizer` maps UTF-8 bytes to ids — lossless,
  vocabulary-free, and enough to exercise every model path end to end with
  random weights.
"""

from __future__ import annotations

import json
import os
from functools import lru_cache
from typing import Dict, List, Optional, Sequence, Tuple


class Tokenizer:
    vocab_size: int
    eos_id: int
    pad_id: int

    def encode(self, text: str) -> List[int]:
        raise NotImplementedError

    def decode(self, ids: Sequence[int]) -> str:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Byte fallback
# ---------------------------------------------------------------------------

class ByteTokenizer(Tokenizer):
    """ids 0..255 = bytes; 256 = BOS, 257 = EOS, 258 = PAD."""

    BOS, EOS, PAD = 256, 257, 258

    def __init__(self, vocab_size: int = 259) -> None:
        assert vocab_size >= 259
        self.vocab_size = vocab_size
        self.eos_id = self.EOS
        self.pad_id = self.PAD

    def encode(self, text: str) -> List[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="ignore")


# ---------------------------------------------------------------------------
# Byte-level BPE (GPT-2) and word-level BPE with </w> (CLIP)
# ---------------------------------------------------------------------------

@lru_cache()
def _bytes_to_unicode() -> Dict[int, str]:
    """GPT-2's reversible byte<->printable-unicode table."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("¡"), ord("¬") + 1))
        + list(range(ord("®"), ord("ÿ") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, map(chr, cs)))


def _bpe_merge(word: Tuple[str, ...], ranks: Dict[Tuple[str, str], int]
               ) -> Tuple[str, ...]:
    """Apply BPE merges to a symbol tuple until no ranked pair remains."""
    word = list(word)
    while len(word) > 1:
        pairs = [(word[i], word[i + 1]) for i in range(len(word) - 1)]
        best = min(pairs, key=lambda p: ranks.get(p, 1 << 30))
        if best not in ranks:
            break
        merged, i = [], 0
        while i < len(word):
            if (
                i < len(word) - 1
                and (word[i], word[i + 1]) == best
            ):
                merged.append(word[i] + word[i + 1])
                i += 2
            else:
                merged.append(word[i])
                i += 1
        word = merged
    return tuple(word)


class BPETokenizer(Tokenizer):
    """GPT-2-style byte-level BPE (``style='gpt2'``) or CLIP-style
    lowercased word BPE with ``</w>`` end-of-word markers
    (``style='clip'``)."""

    def __init__(self, vocab: Dict[str, int], merges: List[Tuple[str, str]],
                 style: str = "gpt2") -> None:
        import re

        self.vocab = vocab
        self.inv_vocab = {v: k for k, v in vocab.items()}
        self.ranks = {tuple(m): i for i, m in enumerate(merges)}
        self.style = style
        self.byte_enc = _bytes_to_unicode()
        self.byte_dec = {v: k for k, v in self.byte_enc.items()}
        self.vocab_size = max(vocab.values()) + 1
        if style == "clip":
            self.bos_id = vocab.get("<|startoftext|>", 0)
            self.eos_id = vocab.get("<|endoftext|>", self.vocab_size - 1)
            self.pad_id = self.eos_id
        else:
            self.eos_id = vocab.get("<|endoftext|>", self.vocab_size - 1)
            self.pad_id = self.eos_id
        # Authentic split patterns (stdlib-re renderings of the published
        # ones; [^\W\d_] = unicode letter). GPT-2: contractions, space-
        # prefixed letter/digit/punct runs, then whitespace — with the
        # trailing-whitespace lookahead, and NO newline collapsing (the
        # real vocab carries Ġ/Ċ whitespace symbols). CLIP: punctuation
        # splits off words and every digit stands alone — real CLIP
        # tokenizes "depicting:" as depicting</w> :</w>, which the
        # checkpoint's merge table expects; whitespace-splitting would
        # mis-tokenize any word adjacent to punctuation under real
        # weights.
        # the published punct class is [^\s\p{L}\p{N}]+ which INCLUDES
        # '_' ('\w' would exclude it — an unmatched '_' silently
        # vanishes in finditer)
        self._word_re = re.compile(
            r"'s|'t|'re|'ve|'m|'ll|'d| ?[^\W\d_]+| ?\d+"
            r"| ?(?:[^\s\w]|_)+|\s+(?!\S)|\s+"
        )
        self._clip_re = re.compile(
            r"'s|'t|'re|'ve|'m|'ll|'d|[^\W\d_]+|\d|(?:[^\s\w]|_)+")
        self._cache: Dict[str, Tuple[str, ...]] = {}

    @staticmethod
    def from_files(vocab_path: str, merges_path: str,
                   style: str = "gpt2") -> "BPETokenizer":
        with open(vocab_path) as f:
            vocab = json.load(f)
        merges = []
        with open(merges_path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split()
                if len(parts) == 2:
                    merges.append((parts[0], parts[1]))
        return BPETokenizer(vocab, merges, style=style)

    def _encode_word(self, chunk: str) -> List[int]:
        if chunk in self._cache:
            symbols = self._cache[chunk]
        else:
            sym = tuple(self.byte_enc[b] for b in chunk.encode("utf-8"))
            if self.style == "clip":
                # real CLIP byte-encodes the chunk too, then marks the
                # last symbol as word-final before merging
                sym = sym[:-1] + (sym[-1] + "</w>",)
            symbols = _bpe_merge(sym, self.ranks)
            self._cache[chunk] = symbols
        unk = self.vocab.get("<|unk|>", self.eos_id)
        return [self.vocab.get(s, unk) for s in symbols]

    def encode(self, text: str) -> List[int]:
        import re

        if self.style == "clip":
            # whitespace-clean + lowercase, as the published tokenizer
            text = re.sub(r"\s+", " ", text.strip()).lower()
            ids = [self.bos_id]
            for m in self._clip_re.finditer(text):
                ids.extend(self._encode_word(m.group(0)))
            return ids
        ids: List[int] = []
        for m in self._word_re.finditer(text):
            ids.extend(self._encode_word(m.group(0)))
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        parts = [self.inv_vocab.get(int(i), "") for i in ids]
        text = "".join(parts)
        if self.style == "clip":
            for tok in ("<|startoftext|>", "<|endoftext|>"):
                text = text.replace(tok, "")
            # symbols are byte-encoded (mirror of encode), so strip the
            # word marks first, then byte-decode
            text = text.replace("</w>", " ")
        data = bytes(self.byte_dec.get(c, 32) for c in text)
        out = data.decode("utf-8", errors="ignore")
        return out.strip() if self.style == "clip" else out


# ---------------------------------------------------------------------------
# SentencePiece-BPE (Llama/Mistral family)
# ---------------------------------------------------------------------------

class SentencePieceBPETokenizer(Tokenizer):
    """SentencePiece-style BPE: ▁ word-boundary markers + <0xXX> byte
    fallback, loaded from an HF ``tokenizer.json`` (plain JSON — no
    sentencepiece/tokenizers wheel needed). Covers the Mistral/Llama vocab
    format for models/mistral.py."""

    WORD_MARK = "▁"  # ▁

    def __init__(self, vocab: Dict[str, int],
                 merges: List[Tuple[str, str]]) -> None:
        self.vocab = vocab
        self.inv_vocab = {v: k for k, v in vocab.items()}
        self.ranks = {tuple(m): i for i, m in enumerate(merges)}
        self.vocab_size = max(vocab.values()) + 1
        self.unk_id = vocab.get("<unk>", 0)
        self.bos_id = vocab.get("<s>", 1)
        self.eos_id = vocab.get("</s>", 2)
        self.pad_id = self.eos_id
        self._byte_ids = {
            b: vocab[f"<0x{b:02X}>"]
            for b in range(256) if f"<0x{b:02X}>" in vocab
        }
        self._cache: Dict[str, Tuple[str, ...]] = {}

    @staticmethod
    def from_file(tokenizer_json: str) -> "SentencePieceBPETokenizer":
        with open(tokenizer_json) as f:
            spec = json.load(f)
        model = spec["model"]
        vocab = dict(model["vocab"])
        merges = []
        for m in model.get("merges", []):
            pair = tuple(m.split(" ")) if isinstance(m, str) else tuple(m)
            if len(pair) == 2:
                merges.append(pair)
        for tok in spec.get("added_tokens", []):
            vocab.setdefault(tok["content"], tok["id"])
        return SentencePieceBPETokenizer(vocab, merges)

    def _encode_word(self, word: str) -> List[int]:
        """word (already ▁-prefixed) -> ids with byte fallback."""
        if word in self._cache:
            symbols = self._cache[word]
        else:
            symbols = _bpe_merge(tuple(word), self.ranks)
            self._cache[word] = symbols
        ids: List[int] = []
        for s in symbols:
            if s in self.vocab:
                ids.append(self.vocab[s])
            elif self._byte_ids:
                ids.extend(
                    self._byte_ids.get(b, self.unk_id)
                    for b in s.encode("utf-8")
                )
            else:
                ids.append(self.unk_id)
        return ids

    def _byte_fallback(self, s: str) -> List[int]:
        return [self._byte_ids.get(b, self.unk_id)
                for b in s.encode("utf-8")] if self._byte_ids \
            else [self.unk_id]

    def encode(self, text: str) -> List[int]:
        import re

        ids = [self.bos_id]
        # words get a ▁ mark when preceded by a space (or start-of-text,
        # SentencePiece's add_dummy_prefix); non-space whitespace
        # (\n, \t, ...) is structure the model saw in training — encode it
        # via byte fallback rather than silently dropping it
        prev_end, prev_char = 0, " "
        for m in re.finditer(r"[^\s]+|[^\S ]", text):
            if m.start() > prev_end:
                prev_char = text[m.start() - 1]
            chunk = m.group(0)
            if chunk.strip():
                marked = prev_char == " " or m.start() == 0
                ids.extend(self._encode_word(
                    (self.WORD_MARK if marked else "") + chunk
                ))
            else:
                ids.extend(self._byte_fallback(chunk))
            prev_end, prev_char = m.end(), chunk[-1]
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        out: List[str] = []
        pending: List[int] = []  # byte-fallback run

        def flush():
            if pending:
                out.append(bytes(pending).decode("utf-8", errors="ignore"))
                pending.clear()

        for i in ids:
            tok = self.inv_vocab.get(int(i), "")
            if tok.startswith("<0x") and tok.endswith(">") and len(tok) == 6:
                pending.append(int(tok[3:5], 16))
                continue
            flush()
            if tok in ("<s>", "</s>", "<unk>", "<pad>"):
                continue
            out.append(tok.replace(self.WORD_MARK, " "))
        flush()
        return "".join(out).strip()


# ---------------------------------------------------------------------------
# WordPiece (BERT / MiniLM)
# ---------------------------------------------------------------------------

class WordPieceTokenizer(Tokenizer):
    def __init__(self, vocab: Dict[str, int]) -> None:
        self.vocab = vocab
        self.inv_vocab = {v: k for k, v in vocab.items()}
        self.vocab_size = max(vocab.values()) + 1
        self.cls_id = vocab.get("[CLS]", 0)
        self.sep_id = vocab.get("[SEP]", 0)
        self.unk_id = vocab.get("[UNK]", 0)
        self.pad_id = vocab.get("[PAD]", 0)
        self.eos_id = self.sep_id

    @staticmethod
    def from_file(vocab_path: str) -> "WordPieceTokenizer":
        vocab = {}
        with open(vocab_path) as f:
            for i, line in enumerate(f):
                vocab[line.rstrip("\n")] = i
        return WordPieceTokenizer(vocab)

    def _split_word(self, word: str) -> List[int]:
        ids, start = [], 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                piece = word[start:end]
                if start > 0:
                    piece = "##" + piece
                if piece in self.vocab:
                    cur = self.vocab[piece]
                    break
                end -= 1
            if cur is None:
                return [self.unk_id]
            ids.append(cur)
            start = end
        return ids

    def encode(self, text: str) -> List[int]:
        import re

        words = re.findall(r"[a-z0-9]+|[^\sa-z0-9]", text.lower())
        ids = [self.cls_id]
        for w in words:
            ids.extend(self._split_word(w))
        ids.append(self.sep_id)
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        parts = []
        for i in ids:
            tok = self.inv_vocab.get(int(i), "")
            if tok in ("[CLS]", "[SEP]", "[PAD]"):
                continue
            if tok.startswith("##") and parts:
                parts[-1] += tok[2:]
            else:
                parts.append(tok)
        return " ".join(parts)


# ---------------------------------------------------------------------------
# Loading
# ---------------------------------------------------------------------------

def load_tokenizer(
    weights_dir: Optional[str], kind: str, vocab_size: int
) -> Tokenizer:
    """kind in {'gpt2', 'clip', 'minilm', 'mistral'}; byte fallback when
    artifacts are missing (always the case under zero egress with no baked
    checkpoints)."""
    if weights_dir:
        if kind in ("gpt2", "clip"):
            vocab = os.path.join(weights_dir, f"{kind}_vocab.json")
            merges = os.path.join(weights_dir, f"{kind}_merges.txt")
            if os.path.exists(vocab) and os.path.exists(merges):
                return BPETokenizer.from_files(vocab, merges, style=kind)
        if kind == "minilm":
            vocab_txt = os.path.join(weights_dir, "minilm_vocab.txt")
            if os.path.exists(vocab_txt):
                return WordPieceTokenizer.from_file(vocab_txt)
        if kind == "mistral":
            tok_json = os.path.join(weights_dir, "mistral_tokenizer.json")
            if os.path.exists(tok_json):
                return SentencePieceBPETokenizer.from_file(tok_json)
    return ByteTokenizer(max(vocab_size, 259))
