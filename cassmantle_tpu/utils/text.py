"""Word-level tokenization, detokenization, and clock formatting.

The reference tokenizes prompts with nltk.word_tokenize for mask selection
(utils.py:83) and ships a (buggy, unused) detokenizer (utils.py:18-26 — its
article-skip condition is always true; see SURVEY.md §2.4). We implement a
self-contained regex tokenizer with a correct inverse so the framework has no
runtime NLTK-download dependency and prompt round-tripping is testable.
"""

from __future__ import annotations

import re
import string
from typing import List

_TOKEN_RE = re.compile(
    r"[A-Za-z]+(?:['’-][A-Za-z]+)*"  # words incl. contractions/hyphens
    r"|\d+(?:\.\d+)?"                      # numbers
    r"|[^\sA-Za-z\d]"                      # single punctuation marks
)

_NO_SPACE_BEFORE = set(".,!?;:)]}%") | {"'", "’", '"'}
_NO_SPACE_AFTER = set("([{$#") | {'"'}


def tokenize_words(text: str) -> List[str]:
    """Split text into word/punctuation tokens (word indices are stable)."""
    return _TOKEN_RE.findall(text)


def detokenize(tokens: List[str]) -> str:
    """Inverse of :func:`tokenize_words`, with sane punctuation spacing."""
    out: List[str] = []
    no_space_next = False
    for tok in tokens:
        if not out:
            out.append(tok)
        elif no_space_next or tok in _NO_SPACE_BEFORE or (
            len(tok) > 1 and tok[0] in {"'", "’"}
        ):
            out.append(tok)
        else:
            out.append(" " + tok)
        no_space_next = tok in _NO_SPACE_AFTER
    return "".join(out)


def format_clock(seconds: float) -> str:
    """Seconds -> mm:ss, clamped at zero (reference utils.py:28-30)."""
    seconds = max(0, int(seconds))
    minutes, rem = divmod(seconds, 60)
    return f"{minutes:02d}:{rem:02d}"


def is_wordlike(token: str) -> bool:
    return bool(token) and token[0] not in string.punctuation and any(
        c.isalpha() for c in token
    )
