from cassmantle_tpu.utils.codec import (  # noqa: F401
    decode_jpeg,
    encode_jpeg,
    image_to_base64,
)
from cassmantle_tpu.utils.text import (  # noqa: F401
    detokenize,
    format_clock,
    tokenize_words,
)
