"""Training data pipeline: token packing + background device prefetch.

The reference has no dataset machinery at all (its only data files are
17 seed titles and 7 styles, reference data/seeds.txt, data/styles.txt);
training a prompt LM on story text needs one. TPU-first shape:

- **pack_tokens**: corpus -> fixed-length rows. Documents are tokenized,
  joined with EOS separators into one stream, and reshaped to
  (rows, seq_len) — every row is fully dense (no padding waste on the
  MXU), the standard LM packing layout. A ``loss_mask`` marks real
  tokens (everything but the tail pad of the final partial row).
- **PrefetchLoader**: wraps any host-batch iterator; a daemon thread
  stages the NEXT batch onto device (with the trainer's sharding) while
  the current step runs — host tokenization/IO overlaps device compute,
  so the scan never waits on the loader. Depth-bounded queue gives
  backpressure.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, Iterable, Iterator, Optional, Sequence

import numpy as np


def pack_tokens(
    texts: Sequence[str],
    encode: Callable[[str], Sequence[int]],
    seq_len: int,
    eos_id: int,
) -> Dict[str, np.ndarray]:
    """Documents -> dense packed LM rows.

    Returns ``{"input_ids": (N, seq_len) int32, "loss_mask": (N, seq_len)
    int32}``; the stream is ``doc0 EOS doc1 EOS ...`` padded with EOS to a
    row boundary, mask 0 only on that tail pad.
    """
    stream: list = []
    for text in texts:
        stream.extend(int(t) for t in encode(text))
        stream.append(eos_id)
    if not stream:
        return {
            "input_ids": np.zeros((0, seq_len), np.int32),
            "loss_mask": np.zeros((0, seq_len), np.int32),
        }
    n_rows = (len(stream) + seq_len - 1) // seq_len
    pad = n_rows * seq_len - len(stream)
    ids = np.asarray(stream + [eos_id] * pad, dtype=np.int32)
    mask = np.ones(len(stream), dtype=np.int32)
    mask = np.concatenate([mask, np.zeros(pad, dtype=np.int32)])
    return {
        "input_ids": ids.reshape(n_rows, seq_len),
        "loss_mask": mask.reshape(n_rows, seq_len),
    }


def batches_from(
    packed: Dict[str, np.ndarray],
    batch_size: int,
    *,
    shuffle: bool = True,
    seed: int = 0,
    epochs: Optional[int] = None,
) -> Iterator[Dict[str, np.ndarray]]:
    """Packed rows -> host batch dicts; drops the trailing partial batch.

    ``epochs=None`` streams forever (reshuffling each epoch).
    """
    n = packed["input_ids"].shape[0]
    if n < batch_size:
        raise ValueError(
            f"corpus packs to {n} rows < batch_size {batch_size}; "
            "no batch can ever be yielded"
        )
    rng = np.random.default_rng(seed)
    epoch = 0
    while epochs is None or epoch < epochs:
        order = rng.permutation(n) if shuffle else np.arange(n)
        for start in range(0, n - batch_size + 1, batch_size):
            sel = order[start : start + batch_size]
            yield {k: v[sel] for k, v in packed.items()}
        epoch += 1


class PrefetchLoader:
    """Stage host batches onto device ahead of consumption.

    ``place`` is typically ``trainer.shard_batch`` — it runs on the
    prefetch thread, so the device transfer (and any sharded
    device_put collateral) overlaps the previous train step.
    """

    _DONE = object()

    def __init__(
        self,
        batches: Iterable[Dict[str, np.ndarray]],
        place: Optional[Callable] = None,
        depth: int = 2,
    ) -> None:
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._place = place or (lambda b: b)
        self._err: Optional[BaseException] = None
        self._exhausted = False
        self._thread = threading.Thread(
            target=self._run, args=(iter(batches),), daemon=True,
            name="data-prefetch",
        )
        self._thread.start()

    def _run(self, it: Iterator) -> None:
        try:
            for batch in it:
                self._queue.put(self._place(batch))
        except BaseException as exc:  # surfaced on the consumer thread
            self._err = exc
        finally:
            self._queue.put(self._DONE)

    def __iter__(self) -> "PrefetchLoader":
        return self

    def __next__(self):
        if self._exhausted:
            # the _DONE sentinel is consumed exactly once; without this
            # flag a second next() would block forever on the empty queue
            raise StopIteration
        item = self._queue.get()
        if item is self._DONE:
            self._exhausted = True
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item
