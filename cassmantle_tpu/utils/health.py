"""Device liveness / health checks (SURVEY.md §5.3).

The reference's failure handling is per-call retry + skip-don't-crash
(utils.py:43-61, backend.py:123-129); it has no health surface at all.
Here the serving layer gets one: a tiny jitted probe computation runs on
the default device with a wall-clock deadline (a wedged TPU tunnel or a
dying chip makes device calls hang rather than raise — exactly the
failure this detects), and the result is cached briefly so `/healthz`
polling can't pile probes onto the device.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from cassmantle_tpu.utils.locks import OrderedLock
from cassmantle_tpu.utils.logging import get_logger, metrics

log = get_logger("health")


_probe_jit = None


def _probe_once() -> bool:
    import jax
    import jax.numpy as jnp

    # One process-wide jitted probe: a fresh lambda per call would miss
    # the jit cache (identity-keyed) and re-trace/compile every probe.
    global _probe_jit
    if _probe_jit is None:
        _probe_jit = jax.jit(lambda v: (v * 2.0).sum())
    x = jnp.arange(8, dtype=jnp.float32)
    y = _probe_jit(x)
    return float(jax.block_until_ready(y)) == 56.0


class _Probe:
    """One probe on a DAEMON thread: a stuck XLA call can't be cancelled,
    only disowned — daemon threads never pin process exit."""

    def __init__(self) -> None:
        self.done = threading.Event()
        self.ok = False
        # the exception when the probe RAISED (vs hung/miscomputed):
        # a raise carries the runtime's own error, which the recovery
        # manager can classify as device loss (a timeout cannot — a
        # wedge is the watchdog's department)
        self.exc: Optional[BaseException] = None
        self.started_at = time.monotonic()
        threading.Thread(
            target=self._run, daemon=True, name="device-probe"
        ).start()

    def _run(self) -> None:
        try:
            self.ok = bool(_probe_once())
        except Exception as exc:
            log.warning("device probe failed: %s", exc)
            self.ok = False
            self.exc = exc
        self.done.set()


class DeviceHealth:
    """Cached device-liveness prober.

    ``check()`` returns (healthy, age_s). A probe that exceeds
    ``timeout_s`` marks the device unhealthy WITHOUT blocking the caller
    beyond the timeout; the hung probe thread is left behind (daemon)
    and reused if it ever completes.
    """

    def __init__(self, timeout_s: float = 10.0, cache_s: float = 15.0):
        self.timeout_s = timeout_s
        self.cache_s = cache_s
        # leaf tier of the docs/STATIC_ANALYSIS.md lock hierarchy: the
        # probe cache nests inside anything, holds nothing else
        self._lock = OrderedLock("health.device", rank=50)
        self._healthy: Optional[bool] = None
        self._checked_at = 0.0
        self._inflight: Optional[_Probe] = None
        # failure CLASS behind a cached False verdict: "timeout" (the
        # probe hung — a wedge, the watchdog's department), or
        # "raise:<ExcType>" (the runtime itself errored — candidate
        # device loss). None while healthy/unknown.
        self._failure: Optional[str] = None
        # wired by the serving layer (DeviceRecoveryManager
        # .note_probe_exception): called OUTSIDE the lock with the
        # probe's exception when a probe completes by raising, so a
        # dispatch-quiet worker still detects runtime loss
        self.on_probe_error = None  # type: Optional[callable]

    def last_verdict(self):
        """The cached verdict (True/False/None-unknown) with NO probe
        dial — the request-path read (scorer hedging) where blocking up
        to ``timeout_s`` on a wedged device is not an option."""
        with self._lock:
            return self._healthy

    def last_failure(self) -> Optional[str]:
        """Failure class behind the cached verdict ("timeout" /
        "raise:<ExcType>"), None while healthy or unknown. Surfaced so
        a /readyz reader (and the recovery manager) can tell a wedged
        device from a dead runtime."""
        with self._lock:
            return self._failure

    def invalidate(self) -> None:
        """Drop the cached verdict (device-loss recovery: a freshly
        rebuilt runtime must be re-probed, not vouched for by the dead
        one's verdict)."""
        with self._lock:
            self._healthy = None
            self._failure = None
            self._checked_at = 0.0

    def check(self) -> tuple:
        with self._lock:
            age = time.monotonic() - self._checked_at
            if self._healthy is not None and age < self.cache_s:
                return self._healthy, age
            stale = (
                self._inflight is not None
                and not self._inflight.done.is_set()
                and time.monotonic() - self._inflight.started_at
                > 2 * self.timeout_s
            )
            if self._inflight is None or stale:
                # a probe hung past its deadline is disowned (daemon
                # thread) and replaced, so a device that RECOVERS is
                # re-detected instead of being pinned unhealthy forever
                self._inflight = _Probe()
            probe = self._inflight
        if probe.done.wait(timeout=self.timeout_s):
            ok = probe.ok
            failure = (None if ok else
                       f"raise:{type(probe.exc).__name__}"
                       if probe.exc is not None else "miscompute")
        else:
            ok = False
            failure = "timeout"
            log.warning("device probe exceeded %.1fs (device hung?)",
                        self.timeout_s)
        with self._lock:
            if probe.done.is_set():
                self._inflight = None
            self._healthy = ok
            self._failure = failure
            self._checked_at = time.monotonic()
        metrics.gauge("health.device_ok", 1.0 if ok else 0.0)
        hook = self.on_probe_error
        if probe.exc is not None and hook is not None:
            # outside the lock: the hook may start a recovery thread
            # that flips supervisor state
            try:
                hook(probe.exc)
            except Exception:
                log.exception("probe-error hook failed")
        return ok, 0.0
