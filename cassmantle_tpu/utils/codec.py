"""Host-side image codec: device arrays <-> JPEG bytes <-> base64.

The reference stores round images as JPEG bytes in Redis and re-encodes per
request (utils.py:12-16, main.py:100-107). We keep JPEG-in-store for the same
resume-on-restart property, but the blur happens on device (ops/blur.py), so
the codec boundary is uint8 HWC arrays.
"""

from __future__ import annotations

import base64
import io

import numpy as np
from PIL import Image


def encode_jpeg(image: np.ndarray, quality: int = 90) -> bytes:
    """uint8 HWC RGB array -> JPEG bytes."""
    arr = np.asarray(image)
    if arr.dtype != np.uint8:
        arr = np.clip(arr, 0, 255).astype(np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG", quality=quality)
    return buf.getvalue()


def decode_jpeg(data: bytes) -> np.ndarray:
    """JPEG bytes -> uint8 HWC RGB array."""
    return np.asarray(Image.open(io.BytesIO(data)).convert("RGB"))


def image_to_base64(image: np.ndarray, quality: int = 90) -> str:
    return base64.b64encode(encode_jpeg(image, quality)).decode()
