"""OrderedLock: the runtime half of the lock-discipline defense.

The static pass (``cassmantle_tpu/analysis/lockorder.py``) proves what
it can see — same-module, ``with``-statement nesting. This wrapper
covers the rest at runtime: every acquisition is checked against the
documented lock hierarchy (``docs/STATIC_ANALYSIS.md``) and against the
acquisition orders actually observed so far, so an inversion that only
materializes across modules, threads, or dynamic call paths raises (or
logs) *at the acquisition that would deadlock*, with both stacks —
instead of wedging a serving fleet the way the PR 1 dispatch deadlock
did.

Checks, in order, when the sentinel is enabled:

1. **re-acquire** — acquiring a non-reentrant lock this thread already
   holds (guaranteed self-deadlock);
2. **rank** — each OrderedLock carries an optional ``rank``; a thread
   may only acquire a lock with rank *strictly greater* than any ranked
   lock it holds (the hierarchy table is the single source of ranks);
3. **observed inversion** — for rank-less locks: acquiring B while
   holding A after B→A has been observed anywhere records a cycle.

The sentinel is **off by default in production** (acquisitions then cost
one extra list append); ``CASSMANTLE_LOCK_SENTINEL=1`` arms it
log-only, and the test suite arms it in raising mode via an autouse
conftest fixture — the fast tier doubles as a deadlock sentinel.
Violations always count ``locks.order_violations`` and land in the
flight recorder.
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Dict, List, Optional, Tuple

from cassmantle_tpu.utils.logging import get_logger, metrics

log = get_logger("locks")


class LockOrderViolation(RuntimeError):
    """An acquisition that breaks the lock hierarchy (would deadlock)."""


_tls = threading.local()

# (first_name, then_name) -> where that order was first observed
_graph_lock = threading.Lock()
_edges: Dict[Tuple[str, str], str] = {}

_enabled = os.environ.get("CASSMANTLE_LOCK_SENTINEL", "") not in ("", "0")
_raise_on_violation = False


def enable_sentinel(raise_on_violation: bool = True) -> None:
    global _enabled, _raise_on_violation
    _enabled = True
    _raise_on_violation = raise_on_violation


def disable_sentinel() -> None:
    global _enabled, _raise_on_violation
    _enabled = False
    _raise_on_violation = False


def sentinel_active() -> bool:
    return _enabled


def reset_observations() -> None:
    """Drop the observed-order graph (tests: one graph per test, so
    unrelated tests' acquisition orders can't cross-contaminate)."""
    with _graph_lock:
        _edges.clear()


def _held() -> List["OrderedLock"]:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _site() -> str:
    # the innermost non-locks.py frame — where the caller acquired
    for frame in reversed(traceback.extract_stack(limit=8)):
        if not frame.filename.endswith("locks.py"):
            return f"{frame.filename}:{frame.lineno} ({frame.name})"
    return "<unknown>"


class OrderedLock:
    """Drop-in ``threading.Lock`` with hierarchy/order instrumentation.

    ``name`` identifies the lock in violations and the observed-order
    graph (instances sharing a name share an ordering identity);
    ``rank`` places it in the documented hierarchy — None means "order
    learned from observation only".
    """

    __slots__ = ("name", "rank", "_inner")

    def __init__(self, name: str, rank: Optional[int] = None) -> None:
        self.name = name
        self.rank = rank
        self._inner = threading.Lock()

    def __repr__(self) -> str:
        return f"OrderedLock({self.name!r}, rank={self.rank})"

    # -- sentinel ---------------------------------------------------------

    def _violation(self, message: str) -> None:
        metrics.inc("locks.order_violations")
        # lazy import: utils never depends on obs at module scope (the
        # circuit-breaker rule)
        from cassmantle_tpu.obs.recorder import flight_recorder

        flight_recorder.record("locks.violation", lock=self.name,
                               message=message)
        if _raise_on_violation:
            raise LockOrderViolation(message)
        log.error("lock-order violation: %s", message)

    def _check(self, held: List["OrderedLock"]) -> None:
        if not held:
            return  # the common case: no stack extraction on the fast path
        site = _site()
        for h in held:
            if h is self:
                self._violation(
                    f"re-acquire of non-reentrant {self.name!r} already "
                    f"held by this thread at {site} (self-deadlock)")
                return
        for h in held:
            if self.rank is not None and h.rank is not None \
                    and h.rank >= self.rank:
                self._violation(
                    f"acquiring {self.name!r} (rank {self.rank}) while "
                    f"holding {h.name!r} (rank {h.rank}) at {site}: the "
                    f"hierarchy (docs/STATIC_ANALYSIS.md) requires "
                    f"strictly increasing ranks")
                return
        with _graph_lock:
            for h in held:
                if h.name == self.name:
                    continue
                reverse = _edges.get((self.name, h.name))
                if reverse is not None:
                    self._violation(
                        f"acquisition-order inversion: {h.name!r} -> "
                        f"{self.name!r} at {site}, but {self.name!r} -> "
                        f"{h.name!r} was acquired at {reverse} — these "
                        f"two paths deadlock under concurrency")
                    return
                _edges.setdefault((h.name, self.name), site)

    # -- threading.Lock surface -------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if _enabled:
            # check BEFORE blocking on the inner lock: the violation
            # must raise instead of deadlocking the test that seeds it
            self._check(_held())
        acquired = self._inner.acquire(blocking, timeout)
        if acquired:
            _held().append(self)
        return acquired

    def release(self) -> None:
        self._inner.release()
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "OrderedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False
