"""JAX profiler helpers: trace capture + per-stage device timing.

The reference has no tracing at all (SURVEY.md §5.1). We wrap
``jax.profiler`` so any serving stage can be captured to a TensorBoard trace
directory, and provide a ``block_timer`` that synchronizes on device results
so timings measure device work, not dispatch.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

import jax

from cassmantle_tpu.utils.logging import get_logger, metrics

log = get_logger("profiling")


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Capture a jax.profiler trace if log_dir is set; no-op otherwise."""
    if not log_dir:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield
    log.info("profiler trace written to %s", log_dir)


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Name a region in the device trace (shows up in TensorBoard)."""
    with jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def block_timer(name: str, *results) -> Iterator[list]:
    """Time a region to metrics, blocking on listed device arrays at exit.

    Also records a **device-synchronized stage span** into the active
    trace (obs/trace.py) when one is ambient: the block-until-ready at
    exit means the span's duration covers the device work, not just
    dispatch — these are the per-stage spans a request trace shows for
    scorer encodes, prompt decodes, and image generations."""
    from cassmantle_tpu.obs.trace import current_ctx, tracer

    sink: list = []
    start_wall = time.time()
    start = time.perf_counter()
    try:
        yield sink
    finally:
        for r in list(results) + sink:
            jax.block_until_ready(r)
        elapsed = time.perf_counter() - start
        metrics.observe(name, elapsed)
        ctx = current_ctx()
        if ctx is not None and ctx.sampled:
            tracer.record_span(
                name, tracer.child_ctx(ctx), parent_id=ctx.span_id,
                start_wall=start_wall, duration_s=elapsed,
                attrs={"device_synced": True})
