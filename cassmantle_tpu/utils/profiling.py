"""JAX profiler helpers: trace capture + per-stage device timing.

The reference has no tracing at all (SURVEY.md §5.1). We wrap
``jax.profiler`` so any serving stage can be captured to a TensorBoard trace
directory, and provide a ``block_timer`` that synchronizes on device results
so timings measure device work, not dispatch.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

import jax

from cassmantle_tpu.utils.logging import get_logger, metrics

log = get_logger("profiling")


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Capture a jax.profiler trace if log_dir is set; no-op otherwise."""
    if not log_dir:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield
    log.info("profiler trace written to %s", log_dir)


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Name a region in the device trace (shows up in TensorBoard)."""
    with jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def block_timer(name: str, *results, flops_est=None,
                pipeline: Optional[str] = None) -> Iterator[list]:
    """Time a region to metrics, blocking on listed device arrays at exit.

    Also records a **device-synchronized stage span** into the active
    trace (obs/trace.py) when one is ambient: the block-until-ready at
    exit means the span's duration covers the device work, not just
    dispatch — these are the per-stage spans a request trace shows for
    scorer encodes, prompt decodes, and image generations.

    Roofline attribution (ISSUE 14): callers that know their dispatch's
    analytic FLOPs (obs/costmodel.py) pass ``flops_est`` (a float, or a
    zero-arg callable evaluated at exit for costs only known after
    dispatch — the prompt path's bucket grouping) plus a ``pipeline``
    label. The span then carries ``flops_est``/``mxu_utilization``
    attrs, ``request.device_flops`` accumulates the attributed FLOPs,
    and ``pipeline.mxu_utilization{pipeline=}`` reports achieved-vs-
    peak (flops / device-synchronized seconds / chip peak,
    ``costmodel.chip_peak_flops``) — the "58% of ceiling" number, live
    per dispatch. ``pipeline`` alone also marks a dispatch boundary for
    the HBM highwater tracker (obs/device.py)."""
    from cassmantle_tpu.obs.trace import current_ctx, tracer

    sink: list = []
    start_wall = time.time()
    start = time.perf_counter()
    ok = False
    try:
        yield sink
        ok = True
    finally:
        for r in list(results) + sink:
            jax.block_until_ready(r)
        elapsed = time.perf_counter() - start
        metrics.observe(name, elapsed)
        attrs = {"device_synced": True}
        flops = None
        # attribution only for dispatches that COMPLETED: a body that
        # raised (OOM, chaos injection) did not do its analytic FLOPs,
        # and dividing them by the short elapsed-at-failure would spike
        # mxu_utilization above 1.0 exactly while an operator triages
        if ok and flops_est is not None:
            try:
                flops = float(flops_est() if callable(flops_est)
                              else flops_est)
            except Exception:  # attribution must never fail a dispatch
                flops = None
        if flops is not None and flops > 0:
            from cassmantle_tpu.obs.costmodel import chip_peak_flops

            labels = {"pipeline": pipeline} if pipeline else None
            metrics.inc("request.device_flops", flops, labels=labels)
            attrs["flops_est"] = flops
            if elapsed > 0:
                mxu = flops / elapsed / chip_peak_flops()
                attrs["mxu_utilization"] = round(mxu, 6)
                metrics.gauge("pipeline.mxu_utilization", mxu,
                              labels=labels)
        if pipeline:
            # HBM highwater at the dispatch boundary: the sync above
            # means this pipeline's buffers are still resident
            from cassmantle_tpu.obs.device import note_dispatch

            note_dispatch(pipeline)
        ctx = current_ctx()
        if ctx is not None and ctx.sampled:
            tracer.record_span(
                name, tracer.child_ctx(ctx), parent_id=ctx.span_id,
                start_wall=start_wall, duration_s=elapsed,
                attrs=attrs)
