"""Structured logging + metrics counters.

The reference's only observability is print statements with [INFO]/[ERROR]
prefixes (SURVEY.md §5.1/§5.5). Here: stdlib logging with a single namespaced
logger tree, plus a tiny in-process metrics registry (counters/gauges/latency
histograms) surfaced by the server's /metrics route — the north-star metric is
images/sec/chip, so the serving path increments these at every stage.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, List


def get_logger(name: str) -> logging.Logger:
    logger = logging.getLogger(f"cassmantle.{name}")
    if not logging.getLogger("cassmantle").handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(
            logging.Formatter(
                "%(asctime)s %(levelname)s %(name)s %(message)s"
            )
        )
        root = logging.getLogger("cassmantle")
        root.addHandler(handler)
        root.setLevel(logging.INFO)
        root.propagate = False
    return logger


class Metrics:
    """Thread-safe counters/gauges/timers. One global registry per process."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = defaultdict(float)
        self._gauges: Dict[str, float] = {}
        self._timings: Dict[str, List[float]] = defaultdict(list)

    def inc(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += value

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            samples = self._timings[name]
            samples.append(seconds)
            if len(samples) > 1024:  # bounded memory
                del samples[: len(samples) - 1024]

    @contextmanager
    def timer(self, name: str):
        start = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - start)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            timings = {}
            for name, samples in self._timings.items():
                if not samples:
                    continue
                ordered = sorted(samples)
                timings[name] = {
                    "count": len(ordered),
                    "mean_s": sum(ordered) / len(ordered),
                    "p50_s": ordered[len(ordered) // 2],
                    "p99_s": ordered[min(len(ordered) - 1,
                                         int(len(ordered) * 0.99))],
                }
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "timings": timings,
            }


metrics = Metrics()
