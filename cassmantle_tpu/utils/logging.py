"""Structured logging + metrics registry (counters/gauges/histograms).

The reference's only observability is print statements with [INFO]/[ERROR]
prefixes (SURVEY.md §5.1/§5.5). Here: stdlib logging with a single
namespaced logger tree (opt-in JSON lines carrying the active trace ID via
``CASSMANTLE_LOG_FORMAT=json``), plus an in-process metrics registry
surfaced by the server's /metrics route — JSON snapshot by default,
Prometheus text exposition under ``Accept: text/plain``.

Timings are **fixed-bucket cumulative histograms**, not sample lists: the
old keep-last-1024 trim silently turned p50/p99 into sliding-window stats
(and indexed p99 off-by-one at small n); buckets make memory constant per
series, percentiles all-time, and the exposition Prometheus-native
(``_bucket{le=...}/_sum/_count``). The JSON snapshot keeps the historical
``count/mean_s/p50_s/p99_s`` shape, with percentiles now interpolated
from the cumulative bucket counts.

Metric names are dotted lowercase (``subsystem.metric``), with dynamic
segments (queue/breaker names) interpolated in the middle; timing
histograms end ``_s`` (seconds) and size histograms ``_size``.
``tools/check_metrics.py`` lints every literal emission site against this
convention and the catalog in ``docs/OBSERVABILITY.md``.
"""

from __future__ import annotations

import bisect
import json
import logging
import os
import threading
import time
from collections import OrderedDict
from contextlib import contextmanager
from typing import Dict, List, Optional, Sequence, Tuple

# Latency-shaped default bounds: sub-ms host work through cold-compile
# minutes. Overridable per-process via ObsConfig.latency_buckets_s
# (set_default_buckets) and per-series via observe(buckets=...).
DEFAULT_BUCKETS_S: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)

_LOGGER_LOCK = threading.Lock()


class JsonLogFormatter(logging.Formatter):
    """One JSON object per line, carrying the active trace ID so a
    request's log lines and its `/debugz` trace join on one key."""

    def format(self, record: logging.LogRecord) -> str:
        payload = {
            "ts": self.formatTime(record),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        try:
            # lazy: utils.logging must stay importable before (and
            # without) the obs package — never a module-level cycle
            from cassmantle_tpu.obs.trace import current_trace_id

            trace_id = current_trace_id()
        except Exception:
            trace_id = None
        if trace_id:
            payload["trace_id"] = trace_id
        if record.exc_info:
            payload["exc"] = self.formatException(record.exc_info)
        return json.dumps(payload, ensure_ascii=False)


def _make_formatter() -> logging.Formatter:
    if os.environ.get("CASSMANTLE_LOG_FORMAT", "").lower() == "json":
        return JsonLogFormatter()
    return logging.Formatter(
        "%(asctime)s %(levelname)s %(name)s %(message)s"
    )


def get_logger(name: str) -> logging.Logger:
    logger = logging.getLogger(f"cassmantle.{name}")
    root = logging.getLogger("cassmantle")
    if not root.handlers:
        # double-checked under a lock: two threads racing the bare
        # check above would each attach a handler and duplicate every
        # log line for the life of the process
        with _LOGGER_LOCK:
            if not root.handlers:
                handler = logging.StreamHandler()
                handler.setFormatter(_make_formatter())
                root.addHandler(handler)
                root.setLevel(logging.INFO)
                root.propagate = False
    return logger


LabelsKey = Tuple[Tuple[str, str], ...]
SeriesKey = Tuple[str, LabelsKey]


def _series_key(name: str, labels: Optional[Dict[str, str]]) -> SeriesKey:
    if not labels:
        return name, ()
    return name, tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _flat_name(key: SeriesKey) -> str:
    """JSON-snapshot key: plain name, or name{k="v",...} when labeled —
    unlabeled series (every pre-existing name) keep their exact keys."""
    name, labels = key
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return f"{name}{{{inner}}}"


class Histogram:
    """Cumulative fixed-bucket histogram: constant memory per series,
    all-time percentile estimates via in-bucket linear interpolation.

    ``exemplars`` maps a bucket index to the LAST retained trace that
    landed in that bucket — ``(trace_id, value, unix_ts)`` — so a p99
    spike in any dashboard dereferences in one hop to a full waterfall
    at ``/debugz?trace=``. Bounded by construction (one slot per
    bucket); only rendered by the OpenMetrics exposition and the
    ``?exemplars=1`` JSON form, never by :meth:`Metrics.prometheus`."""

    __slots__ = ("bounds", "counts", "total", "sum", "exemplars")

    def __init__(self, bounds: Sequence[float]) -> None:
        self.bounds = tuple(sorted(float(b) for b in bounds))
        assert self.bounds, "histogram needs at least one bucket bound"
        self.counts = [0] * (len(self.bounds) + 1)  # last = +Inf overflow
        self.total = 0
        self.sum = 0.0
        self.exemplars: Dict[int, Tuple[str, float, float]] = {}

    def observe(self, value: float) -> None:
        # Prometheus buckets are le= (inclusive upper bounds)
        self.counts[bisect.bisect_left(self.bounds, value)] += 1
        self.total += 1
        self.sum += float(value)

    def quantile(self, q: float) -> float:
        """Estimate the q-quantile (0..1). Values in the +Inf overflow
        bucket report the top finite bound — a lower bound on the true
        quantile (size your buckets to cover the tail you care about)."""
        if self.total == 0:
            return 0.0
        rank = q * self.total
        cum = 0
        for i, count in enumerate(self.counts):
            if count and cum + count >= rank:
                if i >= len(self.bounds):      # overflow bucket
                    return self.bounds[-1]
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i]
                return lo + (hi - lo) * ((rank - cum) / count)
            cum += count
        return self.bounds[-1]

    def mean(self) -> float:
        return self.sum / self.total if self.total else 0.0


def _prom_name(name: str, labels: LabelsKey) -> Tuple[str, str]:
    """(metric_name, label_suffix) in Prometheus grammar: dots/dashes to
    underscores, ``cassmantle_`` namespace prefix, the ``_s`` seconds
    suffix expanded to ``_seconds`` per convention."""
    base = name.replace(".", "_").replace("-", "_")
    if base.endswith("_s"):
        base = base[:-2] + "_seconds"
    suffix = ""
    if labels:
        inner = ",".join(
            '{}="{}"'.format(k, v.replace("\\", "\\\\").replace('"', '\\"'))
            for k, v in labels)
        suffix = "{" + inner + "}"
    return "cassmantle_" + base, suffix


class Metrics:
    """Thread-safe counters/gauges/histograms. One global registry per
    process; instantiable standalone (golden tests use fresh instances)."""

    def __init__(self,
                 default_buckets: Sequence[float] = DEFAULT_BUCKETS_S
                 ) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[SeriesKey, float] = {}
        self._gauges: Dict[SeriesKey, float] = {}
        self._hists: Dict[SeriesKey, Histogram] = {}
        self._default_buckets = tuple(default_buckets)
        # exemplar machinery (ISSUE 18): an injected source answers
        # "which trace is this observation from, and is that trace
        # already durably retained?" — (trace_id, certain). Certain
        # observations write their bucket exemplar immediately;
        # uncertain ones (a pending tail-sampled trace whose retention
        # verdict lands at root completion) park as candidates until
        # retain_exemplars/discard_exemplars resolves them. A fresh
        # Metrics() has no source, so exemplars are strictly opt-in.
        self._exemplar_source = None
        self._exemplar_pending: \
            "OrderedDict[str, List[Tuple[Histogram, int, float, float]]]" \
            = OrderedDict()
        self._exemplar_pending_cap = 256

    def set_default_buckets(self, bounds: Sequence[float]) -> None:
        """Default bounds for histograms created AFTER this call;
        existing series keep their buckets (cumulative counts cannot be
        re-binned)."""
        with self._lock:
            self._default_buckets = tuple(bounds)

    def inc(self, name: str, value: float = 1.0,
            labels: Optional[Dict[str, str]] = None) -> None:
        key = _series_key(name, labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + value

    def gauge(self, name: str, value: float,
              labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._gauges[_series_key(name, labels)] = value

    def remove_gauge(self, name: str,
                     labels: Optional[Dict[str, str]] = None) -> None:
        """Retract a gauge series. Gauges are point-in-time readings:
        when their source disappears (a device whose memory_stats went
        dark mid-flight, obs/device.py) the honest export is ABSENCE —
        a frozen last value would be read as current truth by every
        later scrape. No-op when the series never existed."""
        with self._lock:
            self._gauges.pop(_series_key(name, labels), None)

    def observe(self, name: str, value: float,
                labels: Optional[Dict[str, str]] = None,
                buckets: Optional[Sequence[float]] = None) -> None:
        """Record into the series' histogram. ``buckets`` applies only
        on first observation of a series (fixing its bounds for life)."""
        key = _series_key(name, labels)
        source = self._exemplar_source
        tagged = source() if source is not None else None
        with self._lock:
            hist = self._hists.get(key)
            if hist is None:
                hist = Histogram(buckets or self._default_buckets)
                self._hists[key] = hist
            hist.observe(value)
            if tagged is not None:
                trace_id, certain = tagged
                idx = bisect.bisect_left(hist.bounds, value)
                if certain:
                    hist.exemplars[idx] = (trace_id, float(value),
                                           time.time())
                else:
                    slots = self._exemplar_pending.get(trace_id)
                    if slots is None:
                        slots = []
                        self._exemplar_pending[trace_id] = slots
                        while len(self._exemplar_pending) > \
                                self._exemplar_pending_cap:
                            self._exemplar_pending.popitem(last=False)
                    slots.append((hist, idx, float(value), time.time()))

    # -- exemplars (ISSUE 18) ---------------------------------------------
    def set_exemplar_source(self, fn) -> None:
        """Install the trace-association callback ``fn() -> None |
        (trace_id, certain)`` called on every histogram observation.
        The obs layer owns the policy (ambient span context, kill
        switch); this registry only stores the linkage."""
        self._exemplar_source = fn

    def retain_exemplars(self, trace_id: str) -> None:
        """A pending trace was tail-retained: promote its parked
        candidate observations into their buckets' exemplar slots
        (last-writer-wins = last retained trace per bucket)."""
        with self._lock:
            for hist, idx, value, ts in \
                    self._exemplar_pending.pop(trace_id, ()):
                hist.exemplars[idx] = (trace_id, value, ts)

    def discard_exemplars(self, trace_id: str) -> None:
        """A pending trace was dropped at root completion: its parked
        candidates must never surface as exemplars."""
        with self._lock:
            self._exemplar_pending.pop(trace_id, None)

    @contextmanager
    def timer(self, name: str, labels: Optional[Dict[str, str]] = None):
        start = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - start, labels=labels)

    # -- registry reads (SLO engine, obs/slo.py) ---------------------------
    def counter_total(self, name: str) -> float:
        """Sum of a counter across ALL its label sets (per-room labels
        must aggregate to worker truth for SLO ratios)."""
        with self._lock:
            return sum(v for (n, _), v in self._counters.items()
                       if n == name)

    def gauge_values(self, name: str) -> List[float]:
        """Every label set's current value for a gauge (callers pick
        max/min as the conservative aggregate)."""
        with self._lock:
            return [v for (n, _), v in self._gauges.items() if n == name]

    def hist_totals(self, name: str
                    ) -> Optional[Tuple[Tuple[float, ...],
                                        Tuple[int, ...], int]]:
        """(bounds, bucket counts, total) for a histogram, summed across
        label sets sharing the first-seen bounds (one process = one
        bucket ladder per name by construction); None when the series
        has never been observed."""
        with self._lock:
            bounds = None
            counts: List[int] = []
            total = 0
            for (n, _), h in self._hists.items():
                if n != name:
                    continue
                if bounds is None:
                    bounds = h.bounds
                    counts = list(h.counts)
                    total = h.total
                elif h.bounds == bounds:
                    counts = [a + b for a, b in zip(counts, h.counts)]
                    total += h.total
            if bounds is None:
                return None
            return bounds, tuple(counts), total

    # -- federation (cluster /metrics, server/app.py) ----------------------
    def dump_state(self) -> Dict[str, list]:
        """Full-fidelity JSON-serializable registry state — what a peer
        ships for cluster federation. Unlike :meth:`snapshot`, histogram
        BUCKETS survive, so a merge is exact, not re-estimated."""
        with self._lock:
            return {
                "counters": [[k[0], [list(p) for p in k[1]], v]
                             for k, v in self._counters.items()],
                "gauges": [[k[0], [list(p) for p in k[1]], v]
                           for k, v in self._gauges.items()],
                "hists": [[k[0], [list(p) for p in k[1]],
                           list(h.bounds), list(h.counts), h.sum, h.total]
                          for k, h in self._hists.items()],
            }

    def merge_hist_state(self, name: str, labels: Optional[Dict[str, str]],
                         bounds: Sequence[float], counts: Sequence[int],
                         total_sum: float, total: int) -> bool:
        """Fold one shipped histogram into this registry. Same bounds →
        bucket counts add elementwise (the EXACT merge — every worker
        runs the same fixed ladders by construction); returns False on a
        bounds mismatch so the caller can fall back to a per-worker
        labeled series instead of silently mis-binning."""
        bounds = tuple(float(b) for b in bounds)
        key = _series_key(name, labels)
        with self._lock:
            hist = self._hists.get(key)
            if hist is None:
                hist = Histogram(bounds)
                self._hists[key] = hist
            if hist.bounds != bounds:
                return False
            hist.counts = [a + int(b)
                           for a, b in zip(hist.counts, counts)]
            hist.total += int(total)
            hist.sum += float(total_sum)
            return True

    # -- exposition -------------------------------------------------------
    def snapshot(self, exemplars: bool = False) -> Dict[str, object]:
        """The backward-compatible JSON shape: flat counters/gauges plus
        ``timings`` entries of ``{count, mean_s, p50_s, p99_s}`` (the
        ``_s`` keys are historical; non-seconds histograms like
        ``*.batch_size`` report their native unit under them).
        ``exemplars=True`` (the ``/metrics?exemplars=1`` form) adds a
        top-level ``exemplars`` map — per histogram, per bucket upper
        bound, the last retained trace — WITHOUT touching the default
        key set (pinned backward-compatible)."""
        with self._lock:
            timings = {
                _flat_name(key): {
                    "count": h.total,
                    "mean_s": h.mean(),
                    "p50_s": h.quantile(0.5),
                    "p99_s": h.quantile(0.99),
                }
                for key, h in self._hists.items() if h.total
            }
            out: Dict[str, object] = {
                "counters": {_flat_name(k): v
                             for k, v in self._counters.items()},
                "gauges": {_flat_name(k): v
                           for k, v in self._gauges.items()},
                "timings": timings,
            }
            if exemplars:
                ex: Dict[str, dict] = {}
                for key, h in self._hists.items():
                    if not h.exemplars:
                        continue
                    per = {}
                    for idx, (tid, value, ts) in \
                            sorted(h.exemplars.items()):
                        le = ("+Inf" if idx >= len(h.bounds)
                              else repr(float(h.bounds[idx])))
                        per[le] = {"trace_id": tid, "value": value,
                                   "ts": ts}
                    ex[_flat_name(key)] = per
                out["exemplars"] = ex
            return out

    def prometheus(self) -> str:
        """Text exposition (format version 0.0.4): counters as
        ``*_total``, gauges plain, histograms as cumulative
        ``_bucket{le=...}`` + ``_sum`` + ``_count``. Deterministically
        sorted so scrapes (and golden tests) are stable."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {k: (h.bounds, tuple(h.counts), h.sum, h.total)
                     for k, h in self._hists.items()}
        lines = []
        typed = set()

        def _emit_type(pname: str, kind: str) -> None:
            if pname not in typed:
                typed.add(pname)
                lines.append(f"# TYPE {pname} {kind}")

        def _fmt(v: float) -> str:
            return repr(v) if isinstance(v, float) and not v.is_integer() \
                else str(int(v))

        for key in sorted(counters):
            pname, suffix = _prom_name(key[0], key[1])
            _emit_type(pname + "_total", "counter")
            lines.append(f"{pname}_total{suffix} {_fmt(counters[key])}")
        for key in sorted(gauges):
            pname, suffix = _prom_name(key[0], key[1])
            _emit_type(pname, "gauge")
            lines.append(f"{pname}{suffix} {_fmt(gauges[key])}")
        for key in sorted(hists):
            bounds, counts, total_sum, total = hists[key]
            pname, suffix = _prom_name(key[0], key[1])
            _emit_type(pname, "histogram")
            label_body = suffix[1:-1] + "," if suffix else ""
            cum = 0
            for bound, count in zip(bounds, counts):
                cum += count
                lines.append(
                    f'{pname}_bucket{{{label_body}le="{_fmt(bound)}"}} '
                    f"{cum}")
            cum += counts[-1]
            lines.append(f'{pname}_bucket{{{label_body}le="+Inf"}} {cum}')
            lines.append(f"{pname}_sum{suffix} {repr(float(total_sum))}")
            lines.append(f"{pname}_count{suffix} {total}")
        return "\n".join(lines) + "\n"

    def openmetrics(self) -> str:
        """OpenMetrics 1.0 text exposition (the
        ``application/openmetrics-text`` negotiation): same series as
        :meth:`prometheus` — counters declared on their BASE name with
        ``_total`` samples per the OpenMetrics grammar — plus
        ``# {trace_id="..."} value ts`` exemplar annotations on
        histogram ``_bucket`` lines and the mandatory ``# EOF``
        terminator. The plain Prometheus exposition stays byte-identical
        (exemplars render ONLY here and in ``snapshot(exemplars=True)``)."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {k: (h.bounds, tuple(h.counts), h.sum, h.total,
                         dict(h.exemplars))
                     for k, h in self._hists.items()}
        lines = []
        typed = set()

        def _emit_type(pname: str, kind: str) -> None:
            if pname not in typed:
                typed.add(pname)
                lines.append(f"# TYPE {pname} {kind}")

        def _fmt(v: float) -> str:
            return repr(v) if isinstance(v, float) and not v.is_integer() \
                else str(int(v))

        def _exemplar(ex) -> str:
            if ex is None:
                return ""
            trace_id, value, ts = ex
            return (f' # {{trace_id="{trace_id}"}} '
                    f"{repr(float(value))} {repr(float(ts))}")

        for key in sorted(counters):
            pname, suffix = _prom_name(key[0], key[1])
            _emit_type(pname, "counter")
            lines.append(f"{pname}_total{suffix} {_fmt(counters[key])}")
        for key in sorted(gauges):
            pname, suffix = _prom_name(key[0], key[1])
            _emit_type(pname, "gauge")
            lines.append(f"{pname}{suffix} {_fmt(gauges[key])}")
        for key in sorted(hists):
            bounds, counts, total_sum, total, exemplars = hists[key]
            pname, suffix = _prom_name(key[0], key[1])
            _emit_type(pname, "histogram")
            label_body = suffix[1:-1] + "," if suffix else ""
            cum = 0
            for i, (bound, count) in enumerate(zip(bounds, counts)):
                cum += count
                lines.append(
                    f'{pname}_bucket{{{label_body}le="{_fmt(bound)}"}} '
                    f"{cum}{_exemplar(exemplars.get(i))}")
            cum += counts[-1]
            lines.append(
                f'{pname}_bucket{{{label_body}le="+Inf"}} {cum}'
                f"{_exemplar(exemplars.get(len(bounds)))}")
            lines.append(f"{pname}_sum{suffix} {repr(float(total_sum))}")
            lines.append(f"{pname}_count{suffix} {total}")
        lines.append("# EOF")
        return "\n".join(lines) + "\n"


def _parse_labels(raw) -> Optional[Dict[str, str]]:
    if not raw:
        return None
    return {str(k): str(v) for k, v in raw}


def merge_states(states: Sequence[Tuple[str, Dict[str, list]]]
                 ) -> "Metrics":
    """Fold per-worker :meth:`Metrics.dump_state` payloads into one
    registry — the cluster view (`/metrics?scope=cluster`):

    - **counters sum** exactly (they are deltas of the same events);
    - **gauges get a ``worker`` label** — a point-in-time value per
      process has no meaningful sum, but the per-worker spread is
      exactly what an operator reads (which worker's loop is lagging);
    - **histograms merge exactly**: every worker runs the same fixed
      bucket ladders by construction, so bucket counts add elementwise;
      a bounds mismatch (a mid-rollout config skew) falls back to a
      per-worker labeled series rather than mis-binning.
    """
    merged = Metrics()
    for worker, state in states:
        for name, labels, value in state.get("counters", []):
            merged.inc(name, value, labels=_parse_labels(labels))
        for name, labels, value in state.get("gauges", []):
            lbl = dict(_parse_labels(labels) or {})
            lbl["worker"] = worker
            merged.gauge(name, value, labels=lbl)
        for name, labels, bounds, counts, hsum, total in \
                state.get("hists", []):
            if not merged.merge_hist_state(name, _parse_labels(labels),
                                           bounds, counts, hsum, total):
                lbl = dict(_parse_labels(labels) or {})
                lbl["worker"] = worker
                merged.merge_hist_state(name, lbl, bounds, counts,
                                        hsum, total)
    return merged


class _NullMetrics:
    """A no-op registry with the Metrics emission surface. The canary
    probe Game (obs/prober.py) runs the REAL engine code paths but must
    leave zero marks on player-facing series (``game.guesses`` feeds
    leaderboard dashboards; cache counters feed capacity planning), so
    it swaps this in for its instance-level emissions. Reads are not
    supported on purpose — nothing should aggregate from a null sink."""

    def inc(self, name, value=1.0, labels=None):
        pass

    def gauge(self, name, value, labels=None):
        pass

    def remove_gauge(self, name, labels=None):
        pass

    def observe(self, name, value, labels=None, buckets=None):
        pass

    @contextmanager
    def timer(self, name, labels=None):
        yield


NULL_METRICS = _NullMetrics()

metrics = Metrics()
