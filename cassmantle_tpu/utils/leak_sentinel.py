"""LeakSentinel: the runtime half of the resource-lifecycle defense.

The static passes (``cassmantle_tpu/analysis/lifecycle.py`` and
friends) prove what they can see — fire-and-forget tasks, threads no
``stop()`` joins, resources with no close path. This sentinel covers
the rest at runtime: a per-test snapshot/diff of live threads, asyncio
tasks, and open fds, armed for EVERY test by an autouse conftest
fixture — the same static-pass + runtime-sentinel pairing as
``lockorder.py``/``utils/locks.py`` and ``recompile.py``/
``utils/jit_sentinel.py``.

How it listens: while armed, ``threading.Thread.start`` and
``BaseEventLoop.create_task`` (the choke point under both
``asyncio.create_task`` and ``ensure_future``) are wrapped to stamp
each new thread/task with a monotonic sequence number and its
**creation site** (the first stack frame outside threading/asyncio/
this module), registered in ``WeakSet``s. :func:`verify` then reports
every tracked thread still alive / task still pending that was created
after the snapshot — with the origin site, so the failure message says
*who leaked*, not just "a thread leaked". Objects created before
arming (pytest's own machinery, jax's compilation pools) are invisible
by construction: the wrapper wasn't installed when they started.

Fd accounting is diff-only (``/proc/self/fd`` where available): no
per-fd origin, and lazy module-level caches (the mmap'd embedding
table, a jax backend initializing mid-suite) legitimately open
process-lifetime fds — so the conftest fixture runs fds in LOG-ONLY
mode by default (``fd_policy="log"``) while threads/tasks raise. Tests
that seed a deliberate fd leak assert with ``fd_policy="raise"``.

Known limits, by design:

- anonymous daemon threads on the static pass's allowlist (the health
  prober's ``device-probe``, the process-global queue dispatcher) are
  mirrored here by the thread-name allowlist — process-lifetime
  singletons by contract, not per-test leaks; tasks CREATED on an
  allowlisted worker's loop (stamped with the creating thread's name)
  are that worker's working set — the staged server's queue-getter
  tasks between batches — and exempt the same way;
- a task that finishes (or is cancelled by ``asyncio.run``'s exit
  sweep) before the diff runs is NOT a leak — the sentinel measures
  what outlives the test, which is exactly the flaky-teardown shape.

Usage (tests — the autouse conftest fixture arms + verifies per test):

    snap = leak_sentinel.snapshot()
    ... test body ...
    leak_sentinel.verify(snap)        # raises LeakError, with origins

Production: ``CASSMANTLE_LEAK_SENTINEL=1`` arms log-only tracking at
server boot; :func:`scan` (called from the server's watchdog cadence)
counts ``leaks.threads``/``leaks.tasks``/``leaks.fds`` gauges and
flight-records ``leak.detected`` with the oldest origins whenever the
tracked-live census GROWS past its high-water mark — steady growth is
the leak signal; a stable census is just the working set
(docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import fnmatch
import os
import sys
import threading
import weakref
from typing import Dict, List, Optional, Set

from cassmantle_tpu.utils.logging import get_logger, metrics

log = get_logger("leak_sentinel")

#: process-lifetime singletons, by contract (mirrors the static pass's
#: anonymous-daemon exemption): the shared dispatch worker survives
#: across tests on purpose; the device probe is fire-and-forget with a
#: bounded life of its own
_THREAD_ALLOWLIST = (
    "cassmantle-queue.dispatch_worker",
    "device-probe",
    # the staged image server (loop/denoise/stage-dispatch threads) is
    # shared MODULE-scoped across tests for compile economics — its
    # threads are the module's working set, not a per-test leak. The
    # stop-retires-the-thread contract this could otherwise mask is
    # pinned directly by the _DispatchWorker.stop() unit in
    # tests/test_check_lifecycle.py.
    "cassmantle-stage*",
    # jax/XLA internals spin pools lazily on first dispatch mid-test
    "jax*", "ThreadPoolExecutor-*", "pjit*",
)


class LeakError(AssertionError):
    """A thread/task/fd created during the test outlived it. The
    message carries each leaked object's creation site."""


_lock = threading.Lock()
_seq = 0
_armed = False
_orig_thread_start = None
_orig_create_task = None
_tracked_threads: "weakref.WeakSet" = weakref.WeakSet()
_tracked_tasks: "weakref.WeakSet" = weakref.WeakSet()
#: prod scan() high-water marks (census sizes at the last scan)
_hiwater = {"threads": 0, "tasks": 0, "fds": 0}

_SKIP_FRAMES = (os.sep + "threading.py", os.sep + "asyncio" + os.sep,
                "leak_sentinel.py")


def _origin() -> str:
    """First stack frame outside threading/asyncio/this module — the
    site that actually asked for the thread/task. Raw-frame walk (no
    traceback.extract_stack: that builds FrameSummaries with source
    lookup for the WHOLE stack, and this runs on every spawn while the
    suite is armed)."""
    frame = sys._getframe(1)
    while frame is not None:
        fn = frame.f_code.co_filename
        if not any(s in fn for s in _SKIP_FRAMES):
            return f"{fn}:{frame.f_lineno} in {frame.f_code.co_name}"
        frame = frame.f_back
    return "<unknown>"


def _next_seq() -> int:
    global _seq
    with _lock:
        _seq += 1
        return _seq


def _wrapped_thread_start(self, *args, **kwargs):
    if not getattr(self, "_leak_seq", None):
        self._leak_seq = _next_seq()
        self._leak_origin = _origin()
        _tracked_threads.add(self)
    return _orig_thread_start(self, *args, **kwargs)


def _wrapped_create_task(loop, coro, **kwargs):
    task = _orig_create_task(loop, coro, **kwargs)
    try:
        task._leak_seq = _next_seq()
        task._leak_origin = _origin()
        # create_task runs ON the loop's thread: an allowlisted
        # process/module-lifetime worker's tasks (the staged server's
        # queue getters between batches) are its working set, exempt
        # the same way the worker thread itself is
        task._leak_thread = threading.current_thread().name
        _tracked_tasks.add(task)
    except Exception:  # pragma: no cover — a task subclass with slots
        pass
    return task


def enable_sentinel() -> None:
    """Install the Thread.start / loop.create_task wrappers
    (idempotent). Cheap: one sequence bump + one extract_stack per
    spawn, nothing on any hot dispatch path."""
    global _armed, _orig_thread_start, _orig_create_task
    with _lock:
        if _armed:
            return
        _armed = True
    import asyncio.base_events

    _orig_thread_start = threading.Thread.start
    threading.Thread.start = _wrapped_thread_start
    _orig_create_task = asyncio.base_events.BaseEventLoop.create_task
    asyncio.base_events.BaseEventLoop.create_task = _wrapped_create_task


def disable_sentinel() -> None:
    global _armed, _orig_thread_start, _orig_create_task
    with _lock:
        if not _armed:
            return
        _armed = False
    import asyncio.base_events

    threading.Thread.start = _orig_thread_start
    asyncio.base_events.BaseEventLoop.create_task = _orig_create_task
    _orig_thread_start = None
    _orig_create_task = None


def sentinel_active() -> bool:
    return _armed


def maybe_enable_from_env() -> None:
    """Production arming: CASSMANTLE_LEAK_SENTINEL=1 turns on log-only
    origin tracking (the server's watchdog cadence calls :func:`scan`).
    Called from server boot so deployments opt in with one env var."""
    if os.environ.get("CASSMANTLE_LEAK_SENTINEL", "") not in ("", "0"):
        enable_sentinel()


def _allowlisted_name(name: str) -> bool:
    return any(fnmatch.fnmatch(name or "", pat)
               for pat in _THREAD_ALLOWLIST)


def _allowlisted(thread: threading.Thread) -> bool:
    return _allowlisted_name(thread.name)


def _open_fds() -> Optional[Set[int]]:
    try:
        return {int(x) for x in os.listdir("/proc/self/fd")}
    except (OSError, ValueError):  # macOS/sandbox: fd diffing is off
        return None


def snapshot() -> Dict[str, object]:
    """The per-test baseline: the spawn-sequence high-water mark plus
    the open-fd set. Anything tracked with a LATER sequence number that
    is still alive at :func:`verify` time leaked."""
    return {"seq": _seq, "fds": _open_fds()}


def _live_after(snap_seq: int):
    threads = [t for t in list(_tracked_threads)
               if getattr(t, "_leak_seq", 0) > snap_seq
               and t.is_alive() and not _allowlisted(t)]
    tasks = [t for t in list(_tracked_tasks)
             if getattr(t, "_leak_seq", 0) > snap_seq and not t.done()
             and not _allowlisted_name(getattr(t, "_leak_thread", ""))]
    return threads, tasks


def verify(snap: Dict[str, object], *, raise_on_leak: bool = True,
           fd_policy: str = "log") -> List[str]:
    """Diff live threads/tasks/fds against ``snap``; returns the leak
    descriptions (empty = clean). ``raise_on_leak`` raises
    :class:`LeakError` on thread/task leaks — the test-mode contract.
    ``fd_policy``: ``"log"`` (default — fd growth logs + counts but
    never raises: lazy process-lifetime caches open fds mid-suite),
    ``"raise"`` (seeded-leak tests), or ``"off"``."""
    threads, tasks = _live_after(int(snap["seq"]))
    leaks = [
        f"thread {t.name!r} (daemon={t.daemon}) still alive, "
        f"created at {getattr(t, '_leak_origin', '<unknown>')}"
        for t in threads
    ] + [
        f"task {t.get_name()!r} still pending, "
        f"created at {getattr(t, '_leak_origin', '<unknown>')}"
        for t in tasks
    ]
    if threads:
        metrics.inc("leaks.threads", float(len(threads)))
    if tasks:
        metrics.inc("leaks.tasks", float(len(tasks)))
    fd_leaks: List[str] = []
    if fd_policy != "off" and snap.get("fds") is not None:
        now = _open_fds()
        if now is not None:
            grew = now - snap["fds"]  # type: ignore[operator]
            if grew:
                fd_leaks = [f"{len(grew)} fd(s) opened and not closed: "
                            f"{sorted(grew)[:8]}"]
                metrics.inc("leaks.fds", float(len(grew)))
    if leaks or fd_leaks:
        _record(leaks + fd_leaks)
    if raise_on_leak and (leaks or (fd_policy == "raise" and fd_leaks)):
        detail = "\n  ".join(leaks + fd_leaks)
        raise LeakError(
            f"{len(leaks) + len(fd_leaks)} leak(s) outlived the test:\n"
            f"  {detail}\nJoin the thread / await-or-cancel the task / "
            f"close the fd in teardown (or allowlist a documented "
            f"process-lifetime singleton in utils/leak_sentinel.py)")
    return leaks + fd_leaks


def _record(leaks: List[str]) -> None:
    # lazy import: utils never depends on obs at module scope (the
    # circuit-breaker rule, same as locks.py / jit_sentinel.py)
    from cassmantle_tpu.obs.recorder import flight_recorder

    flight_recorder.record("leak.detected", count=len(leaks),
                           leaks=leaks[:8])
    for line in leaks:
        log.warning("leak: %s", line)


def scan() -> Dict[str, int]:
    """Production sweep (log-only): census of tracked-live threads/
    tasks (+ open fds) vs the high-water marks. Growth counts the
    ``leaks.*`` metrics and flight-records ``leak.detected``; the
    returned census feeds whatever status block calls it. Never
    raises — prod mode observes, tests enforce."""
    threads, tasks = _live_after(0)
    fds = _open_fds()
    census = {"threads": len(threads), "tasks": len(tasks),
              "fds": len(fds) if fds is not None else 0}
    grew: List[str] = []
    for key in ("threads", "tasks"):
        if census[key] > _hiwater[key]:
            objs = threads if key == "threads" else tasks
            oldest = sorted(objs,
                            key=lambda o: getattr(o, "_leak_seq", 0))
            grew.append(f"{key} census {census[key]} > high-water "
                        f"{_hiwater[key]}; oldest from "
                        + "; ".join(
                            getattr(o, "_leak_origin", "<unknown>")
                            for o in oldest[:3]))
            metrics.inc(f"leaks.{key}",
                        float(census[key] - _hiwater[key]))
            _hiwater[key] = census[key]
    if fds is not None and census["fds"] > _hiwater["fds"]:
        if _hiwater["fds"]:  # first scan just sets the baseline
            metrics.inc("leaks.fds",
                        float(census["fds"] - _hiwater["fds"]))
            grew.append(f"fd census {census['fds']} > high-water "
                        f"{_hiwater['fds']}")
        _hiwater["fds"] = census["fds"]
    if grew:
        _record(grew)
    return census


def reset() -> None:
    """Drop tracking state (tests): the WeakSets, the sequence counter,
    and the prod high-water marks."""
    global _seq
    with _lock:
        _seq = 0
        _hiwater.update(threads=0, tasks=0, fds=0)
    _tracked_threads.clear()
    _tracked_tasks.clear()
