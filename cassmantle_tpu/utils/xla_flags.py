"""XLA_FLAGS management shared by every multi-device CPU entry point.

Must stay importable BEFORE jax (no jax imports here): XLA parses the env
var once at first backend initialization, so tests/conftest.py and
__graft_entry__.py both append these flags at module import time.
"""

from __future__ import annotations

import os

# On few-core hosts the virtual CPU devices' programs serialize, and XLA's
# default 40 s collective termination timeout kills the process while
# straggler devices are still computing. Harmless on real-TPU paths.
COLLECTIVE_TIMEOUT_FLAGS = (
    "--xla_cpu_collective_call_warn_stuck_timeout_seconds=300",
    "--xla_cpu_collective_call_terminate_timeout_seconds=3600",
)

VIRTUAL_8_DEVICE_FLAG = "--xla_force_host_platform_device_count=8"


def append_xla_flags(*flags: str) -> None:
    """Append each flag to XLA_FLAGS unless its name is already set."""
    current = os.environ.get("XLA_FLAGS", "")
    for flag in flags:
        name = flag.split("=")[0].lstrip("-")
        if name not in current:
            current = (current + " " + flag).strip()
    os.environ["XLA_FLAGS"] = current
