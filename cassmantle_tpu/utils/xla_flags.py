"""XLA_FLAGS management shared by every multi-device CPU entry point.

Must stay importable BEFORE jax (no jax imports here): XLA parses the env
var once at first backend initialization, so tests/conftest.py and
__graft_entry__.py both append these flags at module import time.
"""

from __future__ import annotations

import os

# On few-core hosts the virtual CPU devices' programs serialize, and XLA's
# default 40 s collective termination timeout kills the process while
# straggler devices are still computing. Harmless on real-TPU paths.
# OPTIONAL: these tuning flags are newer than some deployed jaxlib builds,
# and XLA aborts the process on any unknown flag name — so they only land
# after the probe below finds them registered in the installed binary.
COLLECTIVE_TIMEOUT_FLAGS = (
    "--xla_cpu_collective_call_warn_stuck_timeout_seconds=300",
    "--xla_cpu_collective_call_terminate_timeout_seconds=3600",
)

# Probe cache shared with child processes (test subprocesses, bench entry
# children): the scan of the jaxlib binary runs once per process tree.
_PROBE_ENV = "CASSMANTLE_XLA_FLAG_SUPPORT"


def _supported_optional_flags(flags) -> list:
    """Filter ``flags`` to the ones the installed jaxlib registers.

    XLA treats an unknown flag in XLA_FLAGS as FATAL (the whole process
    aborts at first backend init), so version-dependent tuning flags must
    be verified before they enter the env. There is no Python API listing
    registered flags; the reliable signal is the flag-name string compiled
    into the jaxlib extension binary. On any probe failure the optional
    flags are DROPPED — a missing timeout flag costs at worst a slow-host
    collective timeout, while an unknown flag costs the entire process.
    """
    names = [f.split("=")[0].lstrip("-") for f in flags]
    cached = os.environ.get(_PROBE_ENV)
    if cached is None:
        supported = set()
        try:
            import glob

            import jaxlib

            libdir = os.path.dirname(jaxlib.__file__)
            paths = (glob.glob(os.path.join(libdir, "xla_extension*"))
                     or glob.glob(os.path.join(libdir, "**", "xla_extension*"),
                                  recursive=True))
            if paths:
                with open(paths[0], "rb") as fh:
                    blob = fh.read()
                supported = {n for n in names if n.encode() in blob}
        except Exception:
            supported = set()
        os.environ[_PROBE_ENV] = ",".join(sorted(supported))
        cached = os.environ[_PROBE_ENV]
    ok = set(cached.split(","))
    return [f for f, n in zip(flags, names) if n in ok]


def virtual_device_flag(count: int) -> str:
    return f"--xla_force_host_platform_device_count={count}"


def append_xla_flags(*flags: str) -> None:
    """Append each flag to XLA_FLAGS unless its name is already set."""
    current = os.environ.get("XLA_FLAGS", "")
    for flag in flags:
        name = flag.split("=")[0].lstrip("-")
        if name not in current:
            current = (current + " " + flag).strip()
    os.environ["XLA_FLAGS"] = current


def pin_cpu_platform(
    virtual_devices: bool = True, device_count: int = 8
) -> None:
    """Force jax onto host CPU devices, robustly against plugin backends.

    The one place the subtle ordering rules live (used by
    tests/conftest.py, the CLI's ``--platform cpu``, and the dryrun):

    - XLA flags must land in the env before the first backend init;
    - the environment may pin JAX_PLATFORMS to an accelerator plugin
      (e.g. a tunneled device) and a sitecustomize may have imported jax
      already, so the env var alone is not enough;
    - ``jax_platforms`` (plural) must be forced through the config API —
      ``jax_platform_name`` only picks the *default*, while backend
      discovery still initializes every allowed platform, which blocks
      forever when the tunnel behind a plugin is down.
    """
    timeout_flags = _supported_optional_flags(COLLECTIVE_TIMEOUT_FLAGS)
    if virtual_devices:
        append_xla_flags(virtual_device_flag(device_count), *timeout_flags)
    else:
        append_xla_flags(*timeout_flags)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_platform_name", "cpu")
