"""Server-side spellchecker — the reference implementation of
``static/spell.js`` (same API surface as the reference's vendored
typo.js: check / suggest, reference static/typo.js:622,755).

KEEP IN LOCKSTEP WITH static/spell.js: same suffix rules, same
edit-distance-1 candidate generation order (deletion, transposition,
insertion, substitution at each position, left to right). The browser
runs the JS against GET /wordlist; tests (tests/test_spell.py) drive
THIS implementation against the same served wordlist, so suggest()
quality is pinned in CI without a JS runtime. The stem rules are
rule-based affix reduction (plural, past, progressive, agentive,
superlative, adverb), standing in for hunspell .aff expansion at a
fraction of the complexity.
"""

from __future__ import annotations

import re
from typing import Iterable, List

_WORD_RE = re.compile(r"^[a-zA-Z][a-zA-Z'-]*$")
_DOUBLED = re.compile(r"^(.+?)([bdgklmnprt])\2(ed|ing)$")
_ALPHABET = "abcdefghijklmnopqrstuvwxyz"
# KEEP IN LOCKSTEP with static/spell.js PREFIXES (test_spell_rule_parity)
_PREFIXES = ("un", "re", "dis", "mis", "pre", "non", "over", "under", "out", "semi", "anti")  # noqa: E501


class Spell:
    def __init__(self, words: Iterable[str]) -> None:
        # insertion order IS the frequency rank (the served wordlist is
        # most-common-first, tools/build_wordlist.py); suggestions sort
        # by it so common words beat obscure ones
        self.rank = {}
        for w in words or ():
            w = str(w).lower()
            if w not in self.rank:
                self.rank[w] = len(self.rank)
        self.words = set(self.rank)

    def _stems(self, word: str) -> List[str]:
        w = word.lower()
        out = [w]

        def add(s: str) -> None:
            if len(s) >= 2:
                out.append(s)

        if w.endswith("ies"):
            add(w[:-3] + "y")
        if w.endswith("es"):
            add(w[:-2])
        if w.endswith("s"):
            add(w[:-1])
        if w.endswith("ed"):
            add(w[:-2])
            add(w[:-1])
        if w.endswith("ing"):
            add(w[:-3])
            add(w[:-3] + "e")
        if w.endswith("ly"):
            add(w[:-2])
        if w.endswith("er"):
            add(w[:-2])
            add(w[:-1])
        if w.endswith("est"):
            add(w[:-3])
            add(w[:-2])
        # y-inflections (happier/happiest/happily -> happy)
        if w.endswith("ier"):
            add(w[:-3] + "y")
        if w.endswith("iest"):
            add(w[:-4] + "y")
        if w.endswith("ily"):
            add(w[:-3] + "y")
        # f/fe plurals (wolves -> wolf, knives -> knife)
        if w.endswith("ves"):
            add(w[:-3] + "f")
            add(w[:-3] + "fe")
        # derivational suffixes (brightness, hopeful, stormless,
        # greenish, movement, drinkable)
        if w.endswith("ness"):
            add(w[:-4])
        if w.endswith("ful"):
            add(w[:-3])
        if w.endswith("less"):
            add(w[:-4])
        if w.endswith("ish"):
            add(w[:-3])
        if w.endswith("ment"):
            add(w[:-4])
        if w.endswith("able"):
            add(w[:-4])
            add(w[:-4] + "e")
        m = _DOUBLED.match(w)
        if m:  # doubled final consonant before -ed/-ing (stopped -> stop)
            add(m.group(1) + m.group(2))
        # prefix stripping composes with every suffix stem above
        # (unfolded -> folded -> fold); one prefix layer, remainder >= 3
        for s in list(out):
            for p in _PREFIXES:
                if s.startswith(p) and len(s) - len(p) >= 3:
                    out.append(s[len(p):])
        return out

    def check(self, word: str) -> bool:
        # fullmatch: Python's '$' would accept a trailing newline that
        # the JS mirror's /^...$/ (no multiline) rejects
        if not word or not _WORD_RE.fullmatch(word):
            return False
        return any(s in self.words for s in self._stems(word))

    def suggest(self, word: str, limit: int = 5) -> List[str]:
        """Edit-distance-1 candidates that pass check(), ranked by
        corpus frequency (list position), generation order breaking
        ties — a typo of a common word surfaces the common word first
        (the role of hunspell's replacement tables in the reference's
        typo.js). Candidates accepted only via stemming carry their
        stem's rank."""
        w = str(word).lower()
        seen = set()
        out: List[str] = []

        def cand_rank(cand: str):
            # direct lexicon entries strictly beat stem-only matches:
            # the stemmer accepts constructions like "form"+"est" that
            # must never outrank a real word
            r = self.rank.get(cand)
            if r is not None:
                return (0, r)
            return (1, min((self.rank[s] for s in self._stems(cand)
                            if s in self.rank),
                           default=len(self.rank)))

        def consider(cand: str) -> None:
            if cand not in seen and cand != w and self.check(cand):
                seen.add(cand)
                out.append(cand)

        for i in range(len(w) + 1):
            head, tail = w[:i], w[i:]
            if tail:
                consider(head + tail[1:])                      # deletion
            if len(tail) > 1:                                  # transposition
                consider(head + tail[1] + tail[0] + tail[2:])
            for c in _ALPHABET:
                consider(head + c + tail)                      # insertion
                if tail:
                    consider(head + c + tail[1:])              # substitution
        out.sort(key=cand_rank)  # stable: generation order breaks ties
        return out[:limit]
