"""Checkpoint/resume (SURVEY.md §5.4).

Two planes, mirroring the reference's split:

- **Game state** resumes through the state store's durability
  (MemoryStore.snapshot/restore here; Redis persistence in the reference —
  a worker restart re-attaches to the in-flight round, backend.py:93-97).
- **Model/training state** checkpoints via orbax: params + optimizer state
  + step counter, with atomic versioned directories and resume-latest.
"""

from __future__ import annotations

import os
from typing import Any, Optional

from cassmantle_tpu.utils.logging import get_logger

log = get_logger("checkpoint")


class TrainCheckpointer:
    def __init__(self, directory: str, max_to_keep: int = 3) -> None:
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, params: Any, opt_state: Any,
             extra: Optional[dict] = None) -> None:
        import orbax.checkpoint as ocp

        payload = {"params": params, "opt_state": opt_state}
        if extra:
            payload["extra"] = extra
        self._mgr.save(step, args=ocp.args.StandardSave(payload))
        self._mgr.wait_until_finished()
        log.info("saved checkpoint step=%d to %s", step, self.directory)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, step: Optional[int] = None,
                template: Optional[dict] = None) -> Optional[dict]:
        import orbax.checkpoint as ocp

        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        if template is not None:
            restored = self._mgr.restore(
                step, args=ocp.args.StandardRestore(template)
            )
        else:
            restored = self._mgr.restore(step)
        log.info("restored checkpoint step=%d", step)
        return restored

    def close(self) -> None:
        self._mgr.close()
