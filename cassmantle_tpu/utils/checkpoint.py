"""Checkpoint/resume (SURVEY.md §5.4).

Two planes, mirroring the reference's split:

- **Game state** resumes through the state store's durability
  (MemoryStore.snapshot/restore here; Redis persistence in the reference —
  a worker restart re-attaches to the in-flight round, backend.py:93-97).
- **Model/training state** checkpoints via orbax: params + optimizer state
  + step counter, with atomic versioned directories and resume-latest.

Plus **load-time fingerprints** (ISSUE 17): two loaders read the same
multi-GB safetensors files — boot (models/weights.py maybe_load) and
the device-loss rebuild (serving/device_recovery.py), which re-uploads
them while an incident is already in progress. A file that changed (or
rotted) between those two reads would silently swap weights under a
live game. The first successful load records a sidecar
(``<file>.fingerprint``); every later load verifies against it and
fails FAST with :class:`CheckpointCorrupt` naming the path — distinct
from the absent-file case, which remains the documented random-init
fallback (a missing checkpoint is a configuration, a changed one is an
incident).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Optional

from cassmantle_tpu.utils.logging import get_logger, metrics

log = get_logger("checkpoint")


class TrainCheckpointer:
    def __init__(self, directory: str, max_to_keep: int = 3) -> None:
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, params: Any, opt_state: Any,
             extra: Optional[dict] = None) -> None:
        import orbax.checkpoint as ocp

        payload = {"params": params, "opt_state": opt_state}
        if extra:
            payload["extra"] = extra
        self._mgr.save(step, args=ocp.args.StandardSave(payload))
        self._mgr.wait_until_finished()
        log.info("saved checkpoint step=%d to %s", step, self.directory)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, step: Optional[int] = None,
                template: Optional[dict] = None) -> Optional[dict]:
        import orbax.checkpoint as ocp

        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        if template is not None:
            restored = self._mgr.restore(
                step, args=ocp.args.StandardRestore(template)
            )
        else:
            restored = self._mgr.restore(step)
        log.info("restored checkpoint step=%d", step)
        return restored

    def close(self) -> None:
        self._mgr.close()


# ---------------------------------------------------------------------------
# Checkpoint fingerprints (ISSUE 17): content-addressed load verification
# ---------------------------------------------------------------------------

SIDECAR_SUFFIX = ".fingerprint"
# The digest covers file size + the first and last MiB, not the full
# content: the safetensors header (the complete tensor inventory with
# offsets) lives at the head, so truncation, re-serialization, and
# tensor-level edits all move it, while a full-content hash would add
# seconds of re-read per multi-GB file on every boot for no extra
# detection in practice.
_CHUNK = 1 << 20


class CheckpointCorrupt(RuntimeError):
    """A checkpoint's bytes no longer match its recorded fingerprint.

    Raised by the load path (models/weights.py) and therefore by any
    recovery rebuild — callers must NOT degrade this to random init."""

    def __init__(self, path: str, expected: str, actual: str) -> None:
        super().__init__(
            f"checkpoint fingerprint mismatch at {path}: "
            f"expected {expected[:16]}..., got {actual[:16]}... — the "
            f"file changed since it was first loaded (re-fetch it, or "
            f"delete {path + SIDECAR_SUFFIX} to accept the new content)")
        self.path = path
        self.expected = expected
        self.actual = actual


def fingerprint_file(path: str) -> str:
    """sha256 over (size, head MiB, tail MiB) of ``path``."""
    size = os.path.getsize(path)
    h = hashlib.sha256()
    h.update(str(size).encode())
    with open(path, "rb") as f:
        h.update(f.read(_CHUNK))
        if size > _CHUNK:
            f.seek(max(_CHUNK, size - _CHUNK))
            h.update(f.read(_CHUNK))
    return h.hexdigest()


def read_fingerprint(path: str) -> Optional[str]:
    """The recorded digest for checkpoint ``path``, or None."""
    sidecar = path + SIDECAR_SUFFIX
    try:
        with open(sidecar, "r", encoding="utf-8") as f:
            return json.load(f).get("sha256") or None
    except FileNotFoundError:
        return None
    except Exception:
        # an unreadable sidecar cannot vouch for anything: treat as
        # unrecorded (the caller re-records), but say so
        log.warning("unreadable fingerprint sidecar %s; re-recording",
                    sidecar)
        return None


def record_fingerprint(path: str, digest: Optional[str] = None) -> None:
    """Write the sidecar. Best-effort: a read-only weights mount skips
    recording (loads of that file stay unverified) rather than failing
    the boot."""
    sidecar = path + SIDECAR_SUFFIX
    body = {"sha256": digest or fingerprint_file(path),
            "size": os.path.getsize(path)}
    try:
        with open(sidecar, "w", encoding="utf-8") as f:
            json.dump(body, f)
    except OSError as exc:
        log.info("cannot record fingerprint %s (%s); loads of this "
                 "file stay unverified", sidecar, exc)


def verify_or_record(path: str) -> None:
    """Verify ``path`` against its sidecar, recording one if absent.

    Raises :class:`CheckpointCorrupt` on mismatch; returns silently
    when verified or freshly recorded."""
    actual = fingerprint_file(path)
    expected = read_fingerprint(path)
    if expected is None:
        record_fingerprint(path, actual)
        return
    if actual != expected:
        metrics.inc("checkpoint.fingerprint_mismatch")
        log.error("checkpoint %s failed fingerprint verification", path)
        raise CheckpointCorrupt(path, expected, actual)
