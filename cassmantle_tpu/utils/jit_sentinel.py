"""JitCompileSentinel: the runtime half of the recompile defense.

The static pass (``cassmantle_tpu/analysis/recompile.py``) proves what
it can see — jit built in loops, per-call statics, mutable captures.
This sentinel covers the rest at runtime: it counts **actual XLA
compilations per jitted function**, so a recompile regression on a
steady-state serving path (a bucket key that quietly became per-call,
a shape that stopped being bucketed) fails a tier-1 test instead of
shipping as a silent 100x latency cliff — the same static-pass +
runtime-sentinel pairing as ``lockorder.py`` / ``utils/locks.py``.

How it listens: ``jax.monitoring`` fires a ``backend_compile`` event
per compile but carries **no function name**, so the sentinel instead
attaches a counting ``logging.Filter`` to jax's compile log
(``jax._src.interpreters.pxla`` emits one DEBUG record
``"Compiling <name> with global shapes and types ..."`` per cache-miss
compilation) and parses the name out — passing through, unchanged,
every record the operator's own logging config would have emitted.

Compile **wall time** (ISSUE 14) rides the same mechanism: jax wraps
every backend compile in ``dispatch.log_elapsed_time``, which emits
``"Finished XLA compilation of jit(<name>) in <secs> sec"`` on the
``jax._src.dispatch`` logger. A second filter parses name + seconds
into the ``jit.compile_s`` histogram (per-function ``fn=`` label), the
cumulative ``jit.compile_seconds`` counter (bench entries attach its
per-run delta — a 100 s SDXL recompile is *visible* in the trajectory,
not just countable), a per-name total (:func:`compile_time_snapshot`,
surfaced in the `/readyz` ``device_telemetry`` block), and — for
compiles ≥ 1 s, the same threshold the persistent cache uses — a
flight-recorder event (`/debugz` kind ``jit.compile``). Sub-second
compiles stay metric-only so warmup bursts cannot flush the event ring
of the supervision story an operator is actually triaging.

Known limit: the log line carries only the function's bare
``__name__``, so two distinct jitted functions sharing a name (e.g. a
jitted ``apply`` on two models) share one counter — the second
function's warmup compile registers as a "recompile" of the first.
Keep jitted entry-point names distinct where it matters, scope test
assertions with ``no_new_compiles(only=...)``, and read production
``jit.recompiles`` as a steady-state RATE signal, not per-event truth
(the per-name `/debugz` events say which name to go look at). That logger is jax's stable
compile-path narration; if a future jax renames it the sentinel
degrades to counting nothing — tests that assert a *seeded* recompile
raises (tests/test_check_jax.py) exist precisely to catch that
silently-disarmed state.

Usage (tests — an autouse conftest fixture arms + resets per test):

    warmup()                          # compile everything once
    with jit_sentinel.no_new_compiles():
        steady_state_traffic()        # raises JitRecompileError on ANY
                                      # new compilation, with names

Production: ``CASSMANTLE_JIT_SENTINEL=1`` arms log-only counting when
the pipelines boot (``enable_compile_cache`` arms it): every compile
counts ``jit.compiles``; a repeat compile of an already-compiled
function name counts ``jit.recompiles`` and lands in the flight
recorder (``/debugz`` kind ``jit.recompile``). Bucketed paths
legitimately re-compile once per bucket during warmup — the alert
signal is ``jit.recompiles`` *still climbing in steady state*, not its
absolute value (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import logging
import os
import threading
from contextlib import contextmanager
from typing import Dict, Iterable, Optional

from cassmantle_tpu.utils.logging import get_logger, metrics

log = get_logger("jit_sentinel")

#: jax's compile-path narration logger; one record per actual compile
_COMPILE_LOGGER = "jax._src.interpreters.pxla"
_PREFIX = "Compiling "
#: the elapsed-time record (dispatch.log_elapsed_time) — fires once per
#: backend compile with the wall seconds baked into the message
_FINISHED_LOGGER = "jax._src.dispatch"
_FINISHED_PREFIX = "Finished XLA compilation of "
#: flight-recorder threshold: compiles at/over this land in /debugz
#: (kind jit.compile); matches jax_persistent_cache_min_compile_time
_RECORDER_MIN_S = 1.0


class JitRecompileError(RuntimeError):
    """A post-warmup compilation happened inside a no_new_compiles
    window (the recompile the bucket discipline exists to prevent)."""


_lock = threading.Lock()
_counts: Dict[str, int] = {}
_compile_s: Dict[str, float] = {}
# (logger name, attached filter, pre-sentinel level) per listened logger
_filters: list = []


def _record_compile(name: str) -> None:
    with _lock:
        n = _counts.get(name, 0) + 1
        _counts[name] = n
    metrics.inc("jit.compiles")
    if n > 1:
        metrics.inc("jit.recompiles")
        # lazy import: utils never depends on obs at module scope (the
        # circuit-breaker rule, same as locks.py)
        from cassmantle_tpu.obs.recorder import flight_recorder

        flight_recorder.record("jit.recompile", fn=name, count=n)
        log.info("jit recompile #%d of %r", n, name)


def _normalize_fn_name(name: str) -> str:
    """The elapsed-time record wraps the name as ``jit(<name>)`` where
    the Compiling record uses the bare ``<name>`` — strip the wrapper
    so both feeds key one per-function identity."""
    if name.startswith("jit(") and name.endswith(")"):
        return name[4:-1]
    return name


def _record_compile_time(name: str, seconds: float) -> None:
    name = _normalize_fn_name(name)
    with _lock:
        _compile_s[name] = _compile_s.get(name, 0.0) + seconds
    metrics.observe("jit.compile_s", seconds, labels={"fn": name})
    metrics.inc("jit.compile_seconds", seconds)
    if seconds >= _RECORDER_MIN_S:
        from cassmantle_tpu.obs.recorder import flight_recorder

        flight_recorder.record("jit.compile", fn=name,
                               seconds=round(seconds, 3))
        log.info("jit compile of %r took %.1fs", name, seconds)


def _parse_compiling(msg: str) -> None:
    if msg.startswith(_PREFIX):
        _record_compile(msg[len(_PREFIX):].split(" ", 1)[0])


def _parse_finished(msg: str) -> None:
    if not msg.startswith(_FINISHED_PREFIX):
        return
    # "Finished XLA compilation of jit(fn) in 1.234 sec"
    body = msg[len(_FINISHED_PREFIX):]
    name, _, tail = body.rpartition(" in ")
    if not name:
        return
    try:
        seconds = float(tail.split()[0])
    except (ValueError, IndexError):
        return
    _record_compile_time(name, seconds)


class _CompileLogFilter(logging.Filter):
    """Feeds ``handle(message)`` from a logger-level filter (filters
    run before handlers AND propagation, so nothing needs to be
    attached downstream). The filter also keeps the sentinel's
    forced-DEBUG level from changing what operators see: records the
    PRE-sentinel effective level would have emitted pass through
    untouched (warnings/errors keep flowing — and if the operator
    configured DEBUG themselves, the compile narration still prints);
    only the records our level-forcing newly enabled are swallowed.
    Counting must never raise — a sentinel that can break compilation
    is worse than no sentinel."""

    def __init__(self, prior_effective: int, handle) -> None:
        super().__init__()
        self.prior_effective = prior_effective
        self._handle = handle

    def filter(self, record: logging.LogRecord) -> bool:
        try:
            self._handle(record.getMessage())
        except Exception:  # pragma: no cover - defensive
            pass
        return record.levelno >= self.prior_effective


#: (logger name, message handler) — the two compile-narration feeds
_LISTENERS = (
    (_COMPILE_LOGGER, _parse_compiling),
    (_FINISHED_LOGGER, _parse_finished),
)


def enable_sentinel() -> None:
    """Attach the compile-log listeners (idempotent): compile COUNTS
    from pxla's Compiling records, compile WALL TIME from dispatch's
    Finished records. Forces both loggers to DEBUG so the per-compile
    records actually fire; previous levels are restored by
    :func:`disable_sentinel`."""
    if _filters:
        return
    for logger_name, handle in _LISTENERS:
        logger = logging.getLogger(logger_name)
        filt = _CompileLogFilter(logger.getEffectiveLevel(), handle)
        _filters.append((logger_name, filt, logger.level))
        logger.addFilter(filt)
        logger.setLevel(logging.DEBUG)


def disable_sentinel() -> None:
    global _filters
    for logger_name, filt, prior_level in _filters:
        logger = logging.getLogger(logger_name)
        logger.removeFilter(filt)
        logger.setLevel(prior_level)
    _filters = []


def sentinel_active() -> bool:
    return bool(_filters)


def maybe_enable_from_env() -> None:
    """Production arming: CASSMANTLE_JIT_SENTINEL=1 turns on log-only
    compile counting. Called from ``enable_compile_cache`` so every
    pipeline/scorer boot arms it without its own wiring."""
    if os.environ.get("CASSMANTLE_JIT_SENTINEL", "") not in ("", "0"):
        enable_sentinel()


def reset_counts() -> None:
    with _lock:
        _counts.clear()
        _compile_s.clear()


def snapshot() -> Dict[str, int]:
    """Compile counts per jitted-function name since the last reset."""
    with _lock:
        return dict(_counts)


def compile_time_snapshot() -> Dict[str, float]:
    """Cumulative compile wall seconds per function since the last
    reset — the `/readyz` device_telemetry block's compile summary."""
    with _lock:
        return dict(_compile_s)


def compiles(name: Optional[str] = None) -> int:
    with _lock:
        if name is not None:
            return _counts.get(name, 0)
        return sum(_counts.values())


@contextmanager
def no_new_compiles(only: Optional[Iterable[str]] = None,
                    allow: Iterable[str] = ()):
    """Assert zero compilations happen inside the block — the
    "steady state after warmup" contract of every bucketed serving
    path. Raises :class:`JitRecompileError` naming each function that
    compiled and how many times.

    ``only`` restricts the assertion to specific jitted-function names
    (default: ANY compilation fails — the strongest form; jax-internal
    helper jits are cached by shape too, so steady-state traffic in
    warmed buckets compiles nothing at all). ``allow`` exempts names
    expected to compile (e.g. a bucket deliberately entered cold).

    No-op (with a warning) when the sentinel is not armed — the autouse
    test fixture arms it, so in-tree tests never hit that path."""
    if not sentinel_active():
        log.warning("no_new_compiles: sentinel not armed; assertion "
                    "is vacuous")
        yield
        return
    before = snapshot()
    yield
    after = snapshot()
    allow = set(allow)
    new = {k: n - before.get(k, 0) for k, n in after.items()
           if n > before.get(k, 0) and k not in allow}
    if only is not None:
        keep = set(only)
        new = {k: n for k, n in new.items() if k in keep}
    if new:
        detail = ", ".join(f"{k} x{n}" for k, n in sorted(new.items()))
        raise JitRecompileError(
            f"post-warmup compilation(s) inside a no_new_compiles "
            f"window: {detail} — a steady-state serving path "
            f"recompiled (bucket key regressed?)")
