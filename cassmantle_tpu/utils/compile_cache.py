"""Persistent XLA compile cache + big-model param cache locations.

First XLA compiles of the production models are expensive (tens of seconds
locally, minutes through a tunneled device); both the serving pipelines and
bench enable the on-disk compile cache so every later process reuses them.
"""

from __future__ import annotations

import hashlib
import os

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

COMPILE_CACHE_DIR = os.environ.get(
    "CASSMANTLE_COMPILE_CACHE", os.path.join(_REPO_ROOT, ".jax_cache")
)
PARAM_CACHE_DIR = os.environ.get(
    "CASSMANTLE_PARAM_CACHE", os.path.join(_REPO_ROOT, ".param_cache")
)

_enabled = False


def enable_compile_cache() -> None:
    global _enabled
    if _enabled:
        return
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", COMPILE_CACHE_DIR)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _enabled = True
    except Exception:  # older jax / unsupported backend: not fatal
        pass


def param_cache_path(name: str, cfg) -> str:
    """Stable cache file name for (model name, config)."""
    digest = hashlib.sha256(repr(cfg).encode()).hexdigest()[:16]
    return os.path.join(PARAM_CACHE_DIR, f"{name}-{digest}.safetensors")
