"""Persistent XLA compile cache + big-model param cache locations.

First XLA compiles of the production models are expensive (tens of seconds
locally, minutes through a tunneled device); both the serving pipelines and
bench enable the on-disk compile cache so every later process reuses them.
"""

from __future__ import annotations

import hashlib
import os

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

COMPILE_CACHE_DIR = os.environ.get(
    "CASSMANTLE_COMPILE_CACHE", os.path.join(_REPO_ROOT, ".jax_cache")
)
PARAM_CACHE_DIR = os.environ.get(
    "CASSMANTLE_PARAM_CACHE", os.path.join(_REPO_ROOT, ".param_cache")
)

_enabled = False


def enable_compile_cache() -> None:
    global _enabled
    # every pipeline/scorer boot passes through here: piggyback the
    # opt-in jit compile-count sentinel (utils/jit_sentinel.py) so
    # CASSMANTLE_JIT_SENTINEL=1 needs no per-pipeline wiring
    from cassmantle_tpu.utils.jit_sentinel import maybe_enable_from_env

    maybe_enable_from_env()
    if _enabled:
        return
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", COMPILE_CACHE_DIR)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _enabled = True
    except Exception:  # older jax / unsupported backend: not fatal
        pass


# Bump when a module's param STRUCTURE changes without a config change
# (the digest below only sees the config repr) — a stale cached init
# tree would otherwise load with missing/extra leaves and fail at apply.
# v2: UNet attention out-projections gained their published bias.
_PARAM_SCHEMA_VERSION = 4  # v4: fused qkv in UNet + CLIP/MiniLM


def param_cache_path(name: str, cfg) -> str:
    """Stable cache file name for (model name, config, schema)."""
    digest = hashlib.sha256(
        f"v{_PARAM_SCHEMA_VERSION}:{cfg!r}".encode()).hexdigest()[:16]
    return os.path.join(PARAM_CACHE_DIR, f"{name}-{digest}.safetensors")
