"""Persistent XLA compile cache + big-model param cache locations.

First XLA compiles of the production models are expensive (tens of seconds
locally, minutes through a tunneled device); both the serving pipelines and
bench enable the on-disk compile cache so every later process reuses them.

Cache EFFECTIVENESS is exported (ISSUE 14): jax announces
persistent-cache traffic via ``jax.monitoring`` events
(``/jax/compilation_cache/cache_hits`` / ``cache_misses``), and a
listener registered at :func:`enable_compile_cache` mirrors the
process-lifetime totals into the ``jit.cache_hits`` / ``jit.cache_misses``
gauges — so a worker whose cold start burned minutes recompiling
(cache volume lost, key churn from a config change) is attributable
from `/metrics` instead of from a hunch.

Semantics caveat (jax 0.4.37): the ``cache_misses`` event fires only
for misses whose compile was WRITTEN BACK to the cache — compiles
under ``jax_persistent_cache_min_compile_time_secs`` (1.0 s here) or
the min entry size never record a miss. So the pair counts *the
expensive traffic the cache exists for*: hits = expensive compiles it
absorbed, misses = expensive compiles it could not. A cold start made
of sub-second compiles legitimately shows 0/0 — read beside
``jit.compiles``/``jit.compile_s`` (utils/jit_sentinel.py), which
count every compile and what each cost, for the full picture.
"""

from __future__ import annotations

import hashlib
import os
import threading

from cassmantle_tpu.utils.logging import metrics

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

COMPILE_CACHE_DIR = os.environ.get(
    "CASSMANTLE_COMPILE_CACHE", os.path.join(_REPO_ROOT, ".jax_cache")
)
PARAM_CACHE_DIR = os.environ.get(
    "CASSMANTLE_PARAM_CACHE", os.path.join(_REPO_ROOT, ".param_cache")
)

_enabled = False
_listener_lock = threading.Lock()
_listener_armed = False
_cache_events = {"hits": 0, "misses": 0}

_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_MISS_EVENT = "/jax/compilation_cache/cache_misses"


def _on_cache_event(event: str, **_kw) -> None:
    """jax.monitoring listener: mirror persistent-cache traffic into
    gauges. Must never raise — it runs inside jax's compile path."""
    try:
        if event == _HIT_EVENT:
            _cache_events["hits"] += 1
            metrics.gauge("jit.cache_hits", float(_cache_events["hits"]))
        elif event == _MISS_EVENT:
            _cache_events["misses"] += 1
            metrics.gauge("jit.cache_misses",
                          float(_cache_events["misses"]))
    except Exception:  # pragma: no cover - defensive
        pass


def _arm_cache_listener() -> None:
    global _listener_armed
    with _listener_lock:
        if _listener_armed:
            return
        try:
            from jax import monitoring

            monitoring.register_event_listener(_on_cache_event)
            _listener_armed = True
        except Exception:  # older jax without monitoring: not fatal
            pass


def cache_event_counts() -> dict:
    """Process-lifetime persistent-cache hit/miss totals (what the
    gauges mirror) — test/debug seam."""
    return dict(_cache_events)


def enable_compile_cache() -> None:
    global _enabled
    # every pipeline/scorer boot passes through here: piggyback the
    # opt-in jit compile-count sentinel (utils/jit_sentinel.py) so
    # CASSMANTLE_JIT_SENTINEL=1 needs no per-pipeline wiring
    from cassmantle_tpu.utils.jit_sentinel import maybe_enable_from_env

    maybe_enable_from_env()
    # ...and the cache hit/miss listener, so cold-start compile cost is
    # attributable per worker without per-pipeline wiring either
    _arm_cache_listener()
    if _enabled:
        return
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", COMPILE_CACHE_DIR)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _enabled = True
    except Exception:  # older jax / unsupported backend: not fatal
        pass


# Bump when a module's param STRUCTURE changes without a config change
# (the digest below only sees the config repr) — a stale cached init
# tree would otherwise load with missing/extra leaves and fail at apply.
# v2: UNet attention out-projections gained their published bias.
_PARAM_SCHEMA_VERSION = 4  # v4: fused qkv in UNet + CLIP/MiniLM


def param_cache_path(name: str, cfg) -> str:
    """Stable cache file name for (model name, config, schema)."""
    digest = hashlib.sha256(
        f"v{_PARAM_SCHEMA_VERSION}:{cfg!r}".encode()).hexdigest()[:16]
    return os.path.join(PARAM_CACHE_DIR, f"{name}-{digest}.safetensors")
