"""Deterministic fault-injection subsystem (docs/CHAOS.md).

``fault_point`` / ``afault_point`` are the no-op-unless-armed hooks
compiled into every subsystem boundary; ``configure`` /
``configure_from_env`` arm a seeded plan from ``CASSMANTLE_CHAOS`` or
``config.ChaosConfig``; ``status()`` is the block `/readyz` and
`/healthz` carry whenever a drill is armed.
"""

from cassmantle_tpu.chaos.core import (
    CHAOS_ENV,
    FAULT_POINTS,
    KINDS,
    ChaosInjected,
    ChaosPartition,
    ChaosPlan,
    ChaosRule,
    afault_point,
    armed,
    configure,
    configure_from_env,
    disarm,
    fault_point,
    parse_spec,
    plan,
    release,
    status,
)

__all__ = [
    "CHAOS_ENV",
    "FAULT_POINTS",
    "KINDS",
    "ChaosInjected",
    "ChaosPartition",
    "ChaosPlan",
    "ChaosRule",
    "afault_point",
    "armed",
    "configure",
    "configure_from_env",
    "disarm",
    "fault_point",
    "parse_spec",
    "plan",
    "release",
    "status",
]
