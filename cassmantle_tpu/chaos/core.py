"""Deterministic fault injection: named fault points + a seeded plan.

Every failure drill the repo has run so far lived as ad-hoc
monkeypatching inside tests (FlakyBackend, FlakyStore, wedged handlers
in tests/test_fault_injection.py) — impossible to run against the real
multi-process cluster, and impossible to *replay*. This module makes
fault injection a first-class, production-safe subsystem:

- **Fault points** are named no-ops compiled into every boundary the
  system already crosses: store client ops, the replication pump and
  leader calls, batch dispatch, the staged denoise tick, content
  generation, membership heartbeats, cross-worker HTTP. Disarmed (the
  default, and the only state unless an operator sets
  ``CASSMANTLE_CHAOS``), a fault point is one module-global ``None``
  check — zero hot-path work, pinned by tests/test_chaos.py.
- **A seeded plan** (parsed from ``CASSMANTLE_CHAOS`` or
  ``config.ChaosConfig``) decides which hits fire. Each rule carries
  its own PRNG seeded from ``(plan seed, point, kind)`` and its own hit
  counter, so the fire/skip schedule at one point is a pure function of
  that point's hit sequence — the same seed replays the same fault
  schedule regardless of cross-point interleaving (acceptance-pinned).
- **Observability**: every injection counts ``chaos.injections``, lands
  in the flight recorder (kind ``chaos.injected``), and ``status()``
  rides `/readyz` + `/healthz` whenever armed, so a drill can never be
  mistaken for an incident (docs/CHAOS.md).

Fault kinds:

- ``raise`` — raise :class:`ChaosInjected` (a generic failure).
- ``flake`` — ``raise`` behind a seeded probability (default p=0.5).
- ``latency`` — sleep ``delay_s`` (default 0.05) then proceed.
- ``wedge`` — block until :func:`release` (or ``wedge_s``, default 30)
  — models the hang-not-raise failure a wedged XLA call produces.
- ``partition`` — raise :class:`ChaosPartition` (a ``ConnectionError``,
  so transport-level failover paths engage); scope with ``peer=`` to
  cut one peer/endpoint while the rest stay reachable.

Rule grammar (``;``-separated clauses; see docs/CHAOS.md):

    CASSMANTLE_CHAOS="seed=42;round.generate=flake:p=0.4;\
store.client.op=latency:delay_s=0.02,p=0.3;\
fabric.peer_http=partition:peer=w-b;queue.dispatch=wedge:after=3,times=1"

Shared params: ``p`` (fire probability), ``after`` (skip the first N
hits), ``times`` (max fires), ``peer`` (only hits from that peer),
``delay_s`` (latency), ``wedge_s`` (wedge timeout).
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from cassmantle_tpu.obs.recorder import flight_recorder
from cassmantle_tpu.utils.locks import OrderedLock
from cassmantle_tpu.utils.logging import get_logger, metrics

log = get_logger("chaos")

CHAOS_ENV = "CASSMANTLE_CHAOS"

# The canonical fault-point registry: every ``fault_point("name")`` /
# ``afault_point("name")`` literal in the package must appear here AND
# in the docs/CHAOS.md registry table (the ``fault-point`` lint,
# analysis/faultpoints.py, enforces the docs half both ways). Plans
# validate against this set so a typo'd drill fails loudly instead of
# silently injecting nothing.
FAULT_POINTS: Dict[str, str] = {
    "store.client.op": "native store command round trip "
                       "(native/client.py; peer=host:port)",
    "repl.leader_call": "replicated-store leader operation "
                        "(engine/store.py; peer=host:port)",
    "repl.pump": "log-shipping pump pass (engine/store.py)",
    "queue.dispatch": "batch handler on the dispatch thread "
                      "(serving/queue.py; peer=queue name)",
    "stage.denoise.tick": "staged denoise step tick "
                          "(serving/stages.py)",
    "round.generate": "content generation attempt "
                      "(engine/rounds.py; breaker-guarded)",
    "fabric.heartbeat": "membership heartbeat (fabric/membership.py)",
    "fabric.peer_http": "cluster peer HTTP fan-out "
                        "(server/app.py; peer=worker id)",
    "score.hedge": "cross-worker scorer hedge attempt "
                   "(server/app.py; peer=worker id)",
    "server.admit": "queue admission decision "
                    "(serving/queue.py submit; peer=queue name)",
    "overload.brownout": "brownout-ladder tier evaluation "
                         "(serving/overload.py)",
    "device.poison": "NaN/zero corruption of one dispatch-result "
                     "batch member (serving/integrity.py poison; "
                     "peer=pipeline)",
    "device.lost": "accelerator-runtime loss at a dispatch point "
                   "(serving dispatch regions; peer=pipeline)",
}

KINDS = ("raise", "flake", "latency", "wedge", "partition")


class ChaosInjected(RuntimeError):
    """An injected failure (kinds ``raise`` / ``flake``)."""


class ChaosPartition(ConnectionError):
    """An injected peer partition: a ``ConnectionError`` so the
    transport failover paths (store client drop + redial, replication
    leader election) treat it exactly like a real network cut."""


class ChaosRule:
    """One armed clause of the plan. Mutable counters are guarded by
    the plan lock; the release event is for ``wedge`` rules."""

    __slots__ = ("point", "kind", "p", "after", "times", "delay_s",
                 "wedge_s", "peer", "rng", "hits", "fires", "release")

    def __init__(self, point: str, kind: str, *, p: float = 1.0,
                 after: int = 0, times: Optional[int] = None,
                 delay_s: float = 0.05, wedge_s: float = 30.0,
                 peer: Optional[str] = None,
                 rng: Optional[random.Random] = None) -> None:
        self.point = point
        self.kind = kind
        self.p = p
        self.after = after
        self.times = times
        self.delay_s = delay_s
        self.wedge_s = wedge_s
        self.peer = peer
        self.rng = rng or random.Random(0)
        self.hits = 0
        self.fires = 0
        self.release = threading.Event()

    def snapshot(self) -> Dict[str, object]:
        return {
            "point": self.point, "kind": self.kind, "p": self.p,
            "after": self.after, "times": self.times, "peer": self.peer,
            "hits": self.hits, "fires": self.fires,
        }


def parse_spec(spec: str, default_seed: int = 0,
               ) -> Tuple[int, List[ChaosRule]]:
    """(seed, rules) from the ``CASSMANTLE_CHAOS`` grammar. Unknown
    points and kinds raise ValueError — a typo'd drill must fail at arm
    time, not silently inject nothing."""
    clauses = [c.strip() for c in spec.split(";") if c.strip()]
    seed = default_seed
    raw: List[Tuple[str, str, Dict[str, str]]] = []
    for clause in clauses:
        key, sep, val = clause.partition("=")
        key = key.strip()
        if not sep:
            raise ValueError(f"chaos clause {clause!r}: expected "
                             f"point=kind[:k=v,...] or seed=N")
        if key == "seed":
            seed = int(val)
            continue
        if key not in FAULT_POINTS:
            raise ValueError(
                f"chaos clause {clause!r}: unknown fault point {key!r} "
                f"(registry: {sorted(FAULT_POINTS)})")
        kind, _, params_raw = val.partition(":")
        kind = kind.strip()
        if kind not in KINDS:
            raise ValueError(f"chaos clause {clause!r}: unknown kind "
                             f"{kind!r} (kinds: {KINDS})")
        params: Dict[str, str] = {}
        for item in params_raw.split(","):
            item = item.strip()
            if not item:
                continue
            pk, psep, pv = item.partition("=")
            if not psep:
                raise ValueError(f"chaos clause {clause!r}: bad param "
                                 f"{item!r} (expected k=v)")
            params[pk.strip()] = pv.strip()
        unknown = set(params) - {"p", "after", "times", "delay_s",
                                 "wedge_s", "peer"}
        if unknown:
            raise ValueError(f"chaos clause {clause!r}: unknown "
                             f"param(s) {sorted(unknown)}")
        raw.append((key, kind, params))
    rules = []
    for i, (point, kind, params) in enumerate(raw):
        # per-rule PRNG seeded from (plan seed, point, kind, position):
        # each rule's fire/skip draws are a pure function of ITS hit
        # sequence — cross-point interleaving can never perturb them,
        # which is what makes the schedule replayable (acceptance)
        rng = random.Random(f"{seed}:{point}:{kind}:{i}")
        rules.append(ChaosRule(
            point, kind,
            p=float(params.get("p", "0.5" if kind == "flake" else "1.0")),
            after=int(params.get("after", "0")),
            times=int(params["times"]) if "times" in params else None,
            delay_s=float(params.get("delay_s", "0.05")),
            wedge_s=float(params.get("wedge_s", "30.0")),
            peer=params.get("peer"),
            rng=rng,
        ))
    return seed, rules


class ChaosPlan:
    """The armed fault schedule: rules indexed by point, a bounded
    fired-log for replay pinning, injectable sleeps for tests."""

    def __init__(self, seed: int, rules: List[ChaosRule], *,
                 sleep=time.sleep, max_log: int = 256) -> None:
        self.seed = seed
        self.rules = list(rules)
        self._by_point: Dict[str, List[ChaosRule]] = {}
        for rule in self.rules:
            self._by_point.setdefault(rule.point, []).append(rule)
        # leaf rank (docs/STATIC_ANALYSIS.md): hit bookkeeping nests
        # inside anything and holds nothing else
        self._lock = OrderedLock("chaos.plan", rank=60)
        self._sleep = sleep
        self._seq = 0
        self.fired: Deque[Dict[str, object]] = deque(maxlen=max_log)

    # -- decision (deterministic) -----------------------------------------
    def _decide(self, name: str, peer: Optional[str],
                ) -> Optional[ChaosRule]:
        rules = self._by_point.get(name)
        if not rules:
            return None
        with self._lock:
            for rule in rules:
                if rule.peer is not None and rule.peer != peer:
                    continue
                rule.hits += 1
                if rule.hits <= rule.after:
                    continue
                if rule.times is not None and rule.fires >= rule.times:
                    continue
                if rule.p < 1.0 and rule.rng.random() >= rule.p:
                    continue
                rule.fires += 1
                self._seq += 1
                self.fired.append({
                    "seq": self._seq, "point": name, "kind": rule.kind,
                    "peer": peer, "hit": rule.hits,
                })
                return rule
        return None

    def _record(self, rule: ChaosRule, name: str,
                peer: Optional[str]) -> None:
        metrics.inc("chaos.injections")
        # attr named ``fault`` (not ``kind``): the recorder's own first
        # parameter is the event kind
        flight_recorder.record("chaos.injected", point=name,
                               fault=rule.kind, peer=peer)
        # tail retention (ISSUE 18): whatever request this injection
        # landed in is a trace worth keeping — mark the ambient context
        # so the pending ring promotes it at root completion. Lazy
        # import: chaos must stay importable before the obs package.
        try:
            from cassmantle_tpu.obs.trace import tracer

            tracer.mark_retain("chaos")
        except Exception:
            pass
        log.warning("chaos: injecting %s at %s (peer=%s, fire %d)",
                    rule.kind, name, peer, rule.fires)

    # -- execution ---------------------------------------------------------
    def hit(self, name: str, peer: Optional[str] = None) -> None:
        """Sync fault point body (dispatch threads, the denoise loop)."""
        rule = self._decide(name, peer)
        if rule is None:
            return
        self._record(rule, name, peer)
        if rule.kind == "latency":
            self._sleep(rule.delay_s)
            return
        if rule.kind == "wedge":
            rule.release.wait(timeout=rule.wedge_s)
            return
        if rule.kind == "partition":
            raise ChaosPartition(f"chaos: partitioned {name} "
                                 f"(peer={peer})")
        raise ChaosInjected(f"chaos: injected failure at {name}")

    async def ahit(self, name: str, peer: Optional[str] = None) -> None:
        """Async fault point body (store ops, generation, fan-outs)."""
        import asyncio

        rule = self._decide(name, peer)
        if rule is None:
            return
        self._record(rule, name, peer)
        if rule.kind == "latency":
            await asyncio.sleep(rule.delay_s)
            return
        if rule.kind == "wedge":
            deadline = time.monotonic() + rule.wedge_s
            while not rule.release.is_set() and \
                    time.monotonic() < deadline:
                await asyncio.sleep(0.02)
            return
        if rule.kind == "partition":
            raise ChaosPartition(f"chaos: partitioned {name} "
                                 f"(peer={peer})")
        raise ChaosInjected(f"chaos: injected failure at {name}")

    # -- control -----------------------------------------------------------
    def release_point(self, name: str) -> int:
        """Release every wedge rule at a point; returns how many."""
        released = 0
        for rule in self._by_point.get(name, ()):
            if rule.kind == "wedge":
                rule.release.set()
                released += 1
        return released

    def status(self) -> Dict[str, object]:
        with self._lock:
            return {
                "armed": True,
                "seed": self.seed,
                "injections": self._seq,
                "rules": [r.snapshot() for r in self.rules],
                "recent": list(self.fired)[-10:],
            }

    def schedule(self) -> List[Dict[str, object]]:
        """The fired log so far (replay pinning: same seed + same hit
        sequence => identical schedules)."""
        with self._lock:
            return list(self.fired)


# -- module-level fault points (the zero-overhead contract) ----------------

_PLAN: Optional[ChaosPlan] = None


class _Done:
    """A reusable already-done awaitable: ``await afault_point(...)``
    while disarmed costs one global check + one empty iterator — no
    coroutine allocation on the hot path."""

    __slots__ = ()

    def __await__(self):
        return iter(())


_DONE = _Done()


def fault_point(name: str, peer: Optional[str] = None) -> None:
    """Sync fault point: a no-op unless a plan is armed."""
    if _PLAN is None:
        return
    _PLAN.hit(name, peer)


def afault_point(name: str, peer: Optional[str] = None):
    """Awaitable fault point: ``await afault_point("x")``. Disarmed it
    returns a shared no-op awaitable (no coroutine allocation)."""
    if _PLAN is None:
        return _DONE
    return _PLAN.ahit(name, peer)


def armed() -> bool:
    return _PLAN is not None


def plan() -> Optional[ChaosPlan]:
    return _PLAN


def configure(spec: object, *, sleep=time.sleep) -> Optional[ChaosPlan]:
    """Arm (or disarm, on an empty spec) the process-global plan.
    ``spec`` is a grammar string or a ``config.ChaosConfig``."""
    global _PLAN
    default_seed = 0
    if spec is not None and not isinstance(spec, str):
        default_seed = int(getattr(spec, "seed", 0))
        spec = getattr(spec, "spec", "")
    if not spec:
        disarm()
        return None
    seed, rules = parse_spec(spec, default_seed=default_seed)
    _PLAN = ChaosPlan(seed, rules, sleep=sleep)
    metrics.gauge("chaos.armed", 1.0)
    flight_recorder.record("chaos.armed", seed=seed, rules=len(rules))
    log.warning("chaos armed: seed=%d, %d rule(s) — this worker is "
                "running a DRILL (/readyz carries the chaos block)",
                seed, len(rules))
    return _PLAN


def configure_from_env(cfg: object = None) -> Optional[ChaosPlan]:
    """The server-boot entry: ``CASSMANTLE_CHAOS`` wins, else the
    config's ``ChaosConfig`` spec, else disarmed."""
    import os

    env_spec = os.environ.get(CHAOS_ENV, "")
    if env_spec:
        return configure(env_spec)
    if cfg is not None and getattr(cfg, "spec", ""):
        return configure(cfg)
    disarm()
    return None


def disarm() -> None:
    global _PLAN
    if _PLAN is not None:
        # unblock anything parked in a wedge before dropping the plan
        for rule in _PLAN.rules:
            rule.release.set()
    _PLAN = None
    metrics.gauge("chaos.armed", 0.0)


def release(name: str) -> int:
    """Release wedge rules at a point (the drill lever that ends a
    wedge-until-released fault)."""
    if _PLAN is None:
        return 0
    return _PLAN.release_point(name)


def status() -> Dict[str, object]:
    """The `/readyz` / `/healthz` chaos block: ``{"armed": False}``
    when disarmed, else the plan snapshot."""
    if _PLAN is None:
        return {"armed": False}
    return _PLAN.status()
