"""Batched embedding similarity scorer (MiniLM on device).

Replaces the reference's per-word synchronous word2vec lookups
(backend.py:45, 303-317) with fixed-shape batched MiniLM encodes: guesses
and answers tokenize on host, pad into one of a few static (batch, seq)
buckets, embed in a single device call, and score as a cosine dot — the
BASELINE.json "1k concurrent guesses coalesced onto HBM" path when driven
through the serving queue.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from cassmantle_tpu.chaos import fault_point
from cassmantle_tpu.config import MiniLMConfig
from cassmantle_tpu.models.minilm import MiniLMEncoder
from cassmantle_tpu.models.weights import (
    convert_minilm,
    init_params_cached,
    maybe_load,
)
from cassmantle_tpu.ops.embed_table import (
    EMBED_TABLE_PATH,
    EmbedTable,
    embed_table_disabled,
    normalize_key,
    read_header,
    table_signature,
    weights_fingerprint,
)
from cassmantle_tpu.serving import integrity
from cassmantle_tpu.serving.integrity import finite_verdict
from cassmantle_tpu.utils.compile_cache import (
    enable_compile_cache,
    param_cache_path,
)
from cassmantle_tpu.utils.logging import get_logger, metrics
from cassmantle_tpu.utils.profiling import block_timer
from cassmantle_tpu.utils.tokenizers import Tokenizer, load_tokenizer

log = get_logger("scorer")


def _pick_bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


class EmbeddingScorer:
    """Host-facing wrapper owning params, tokenizer, and jitted encode."""

    def __init__(
        self,
        cfg: MiniLMConfig,
        weights_dir=None,
        seq_len: int = 16,
        batch_buckets: Sequence[int] = (8, 64, 256, 1024),
        embed_cache_size: int = 2048,
        table="auto",
    ) -> None:
        self.cfg = cfg
        # Text -> unit-embedding LRU: /compute_score re-embeds the
        # round's FIXED answer words on every request, so a hit halves
        # the per-guess device batch (and duplicate answers within one
        # batch collapse to a single device row). Embeddings are
        # content-addressed by text — nothing ever invalidates.
        # Untracked short-hold leaf lock (docs/STATIC_ANALYSIS.md):
        # dict updates only, the device encode runs OUTSIDE it.
        self._embed_cache: OrderedDict = OrderedDict()
        self._embed_cache_size = embed_cache_size
        self._embed_cache_lock = threading.Lock()
        self.seq_len = min(seq_len, cfg.max_positions)
        self.batch_buckets = tuple(batch_buckets)
        self.tokenizer: Tokenizer = load_tokenizer(
            weights_dir, "minilm", cfg.vocab_size
        )
        model = MiniLMEncoder(cfg)
        sample_ids = jnp.zeros((1, self.seq_len), dtype=jnp.int32)
        sample_mask = jnp.ones((1, self.seq_len), dtype=jnp.int32)
        enable_compile_cache()

        def load_params() -> None:
            """Load/init the encoder tree; re-run by reload_params()
            during a device-loss rebuild (serving/device_recovery.py)."""
            self.params = (
                maybe_load(weights_dir, "minilm.safetensors",
                           lambda t: convert_minilm(t, cfg.num_layers),
                           "minilm")
                or init_params_cached(
                    model, 7, sample_ids, sample_mask,
                    cache_path=param_cache_path("minilm", cfg))
            )

        self._param_loader = load_params
        load_params()
        # the encode jit also returns the per-row integrity verdict
        # (serving/integrity.py): computed in-jit, transferred with the
        # embeddings — no extra dispatch or sync

        def encode_impl(params, ids, mask):
            emb = model.apply(params, ids, mask)
            return emb, finite_verdict(emb)

        self._encode = jax.jit(encode_impl)
        # roofline attribution (obs/costmodel.py): an encoder forward
        # costs ~2·N(params) FLOPs per token; resolved lazily from the
        # committed cost model (production MiniLM) or this tree
        self._flops_per_row = None
        # rung 0 of the scoring ladder: the committed int8 wordlist
        # table (ops/embed_table.py). ``table="auto"`` arms it only
        # when the artifact's signature matches THIS scorer's config +
        # wordlist + weights identity, so a test-config scorer or a
        # stale artifact silently keeps the LRU/device path. Pass an
        # EmbedTable to inject, or False/None to disable outright.
        if table == "auto":
            self.table = self._autoload_table(weights_dir)
        elif isinstance(table, EmbedTable):
            if table.dim != cfg.hidden_size:
                raise ValueError(
                    f"embed table dim {table.dim} != scorer hidden "
                    f"size {cfg.hidden_size}")
            self.table = table
        else:
            self.table = None
        if self.table is not None:
            metrics.gauge("scorer.table_rows", len(self.table))

    def reload_params(self) -> None:
        """Device-loss rebuild (serving/device_recovery.py): re-load
        the encoder tree (fingerprint-verified, utils/checkpoint.py)
        onto the fresh runtime. The embed LRU and the int8 table hold
        HOST arrays — content-addressed by text, runtime-independent —
        so neither needs invalidation; params re-enter the encode jit
        as arguments, so nothing recompiles."""
        self._param_loader()

    def _autoload_table(self, weights_dir):
        try:
            header = read_header(EMBED_TABLE_PATH)
        except (OSError, ValueError):
            return None
        from cassmantle_tpu.server.assets import load_wordlist

        expect = table_signature(
            self.cfg, self.seq_len,
            [normalize_key(w) for w in load_wordlist()],
            weights_fingerprint(weights_dir))
        if header["signature"] != expect:
            # info, not warning: every non-production scorer config
            # (tests, tools) lands here by design
            log.info(
                "embed table not armed: committed signature %s != "
                "expected %s", header["signature"], expect)
            return None
        return EmbedTable.load(EMBED_TABLE_PATH,
                               expected_signature=expect)

    def _row_flops(self) -> float:
        """Analytic FLOPs per encoded row (seq_len tokens)."""
        if self._flops_per_row is None:
            from cassmantle_tpu.obs import costmodel

            self._flops_per_row = costmodel.flops_per_item(
                "scorer",
                costmodel.scorer_signature(self.cfg, self.seq_len),
                tracer=lambda: 2.0 * costmodel.params_count(self.params)
                * self.seq_len,
            ) or 0.0
        return self._flops_per_row

    # -- host-side batching ----------------------------------------------
    def _tokenize_batch(self, texts: Sequence[str], batch: int
                        ) -> Tuple[np.ndarray, np.ndarray]:
        ids = np.full((batch, self.seq_len), self.tokenizer.pad_id,
                      dtype=np.int32)
        mask = np.zeros((batch, self.seq_len), dtype=np.int32)
        for i, text in enumerate(texts):
            toks = self.tokenizer.encode(text)[: self.seq_len]
            if not toks:
                toks = [self.tokenizer.pad_id]
            # lint: ignore[host-sync] — toks is a host token list, not a device array
            ids[i, : len(toks)] = np.asarray(toks, dtype=np.int32) % (
                self.cfg.vocab_size
            )
            mask[i, : len(toks)] = 1
        return ids, mask

    def _embed_device(self, texts: Sequence[str]
                      ) -> Tuple[np.ndarray, np.ndarray]:
        """The uncached device path: (n,) texts -> ((n, D) unit
        embeddings, (n,) validity) via padded buckets (one encode per
        bucket chunk). Validity is the in-jit verdict unioned with a
        host finiteness check of the transferred rows — all-True under
        the integrity kill switch."""
        n = len(texts)
        batch = _pick_bucket(n, self.batch_buckets)
        out_chunks = []
        ok_chunks = []
        for start in range(0, n, batch):
            chunk = texts[start : start + batch]
            ids, mask = self._tokenize_batch(chunk, batch)
            # device-synchronized stage span: for a /compute_score
            # request this is the trace's leaf — the MiniLM encode the
            # whole guess batch waited on. flops_est covers the PADDED
            # batch (the device computes pad rows too)
            with block_timer("scorer.encode_s",
                             flops_est=self._row_flops() * batch,
                             pipeline="scorer") as sink:
                fault_point("device.lost", peer="scorer")
                emb, verdict = self._encode(
                    self.params, jnp.asarray(ids), jnp.asarray(mask))
                sink.append(emb)
            # lint: ignore[host-sync] — one sync per dispatched chunk, not per text
            rows = integrity.poison(np.asarray(emb)[: len(chunk)],
                                    peer="scorer")
            out_chunks.append(rows)
            if integrity.integrity_disabled():
                ok_chunks.append(np.ones(len(chunk), dtype=bool))
            else:
                # the verdict rides the completed dispatch; judging the
                # transferred rows too catches host-side corruption
                # lint: ignore[host-sync] — one sync per dispatched chunk, not per text
                okj = np.asarray(verdict).astype(bool)[: len(chunk)]
                ok_chunks.append(okj & np.isfinite(rows).all(axis=-1))
        return (np.concatenate(out_chunks, axis=0),
                np.concatenate(ok_chunks, axis=0))

    def embed(self, texts: Sequence[str]) -> np.ndarray:
        """(n,) texts -> (n, D) unit embeddings via the scoring ladder:
        int8 table -> LRU -> device.

        Rung 0 is the committed wordlist table (when armed and the
        ``CASSMANTLE_NO_EMBED_TABLE`` kill switch is off): in-table
        texts are served as host int8 dequants with zero device work,
        counted by ``scorer.table_hits``; the rest count
        ``scorer.table_oov`` and fall through. The LRU/device rungs are
        unchanged and bit-exact when the table is skipped: rows already
        in the LRU (or duplicated within this call) never reach the
        device — only the unique uncached texts form the padded encode
        batch. ``scorer.embed_cache_misses`` therefore counts device
        rows actually embedded; ``scorer.embed_cache_hits`` counts rows
        served from the LRU. The returned array is always freshly
        assembled — callers may mutate it."""
        n = len(texts)
        if n == 0:
            return np.zeros((0, self.cfg.hidden_size), dtype=np.float32)
        out = np.zeros((n, self.cfg.hidden_size), dtype=np.float32)
        table = self.table \
            if self.table is not None and not embed_table_disabled() \
            else None
        if table is not None:
            rest: List[int] = []
            hits = 0
            for i, text in enumerate(texts):
                row = table.lookup(text)
                if row is None:
                    rest.append(i)
                else:
                    out[i] = row
                    hits += 1
            metrics.inc("scorer.table_hits", hits)
            metrics.inc("scorer.table_oov", len(rest))
        else:
            rest = list(range(n))
        miss_rows: "OrderedDict[str, list]" = OrderedDict()
        with self._embed_cache_lock:
            for i in rest:
                text = texts[i]
                emb = self._embed_cache.get(text)
                if emb is not None:
                    self._embed_cache.move_to_end(text)
                    out[i] = emb
                else:
                    miss_rows.setdefault(text, []).append(i)
        if miss_rows:
            fresh, ok = self._embed_device(list(miss_rows))
            bad_members: List[int] = []
            with self._embed_cache_lock:
                for row, valid, (text, idxs) in zip(
                        fresh, ok, miss_rows.items()):
                    if not valid:
                        # an invalid row never enters the LRU (a cached
                        # NaN would poison every later hit); the output
                        # rows stay NaN so downstream scoring fails
                        # loudly per pair, not silently as zeros
                        out[idxs] = np.nan
                        bad_members.extend(idxs)
                        continue
                    out[idxs] = row
                    if self._embed_cache_size > 0:
                        # copy: a row VIEW would pin the whole encode
                        # batch array alive for the entry's lifetime
                        self._embed_cache[text] = row.copy()
                        self._embed_cache.move_to_end(text)
                        while len(self._embed_cache) > \
                                self._embed_cache_size:
                            self._embed_cache.popitem(last=False)
            if bad_members:
                integrity.note_invalid("scorer", "encode",
                                       sorted(bad_members))
        metrics.inc("scorer.texts", n)
        metrics.inc("scorer.embed_cache_misses", len(miss_rows))
        metrics.inc("scorer.embed_cache_hits", len(rest) - len(miss_rows))
        return out

    def similarity(self, pairs: Sequence[Tuple[str, str]]) -> np.ndarray:
        """[(guess, answer)] -> cosine similarity per pair, one device
        batch for all guesses+answers."""
        if not pairs:
            return np.zeros((0,), dtype=np.float32)
        texts = [g for g, _ in pairs] + [a for _, a in pairs]
        emb = self.embed(texts)
        n = len(pairs)
        return np.sum(emb[:n] * emb[n:], axis=-1)

    def table_scores(self, pairs: Sequence[Tuple[str, str]]):
        """Rung-0 fused scoring for the service fast path:
        [(guess, answer)] -> (scores, served-mask) via the int8 table,
        or None when no table is armed / the kill switch is set. Pairs
        with ``served[i]`` True completed with zero device dispatches;
        the caller runs the full ladder for the rest only."""
        if self.table is None or embed_table_disabled():
            return None
        return self.table.score_pairs(list(pairs))

    def pin_answers(self, words: Sequence[str]) -> int:
        """Pin round answers into the armed table at promotion time:
        words not already in the table are embedded once through the
        normal LRU/device ladder, quantized with the committed scheme,
        and overlaid — so by the time guesses arrive, every (guess,
        answer) pair over the game vocabulary is rung-0-servable.
        Returns the number of rows pinned (``scorer.table_pins``)."""
        if self.table is None or embed_table_disabled():
            return 0
        todo: List[str] = []
        seen = set()
        for w in words:
            key = normalize_key(w)
            if key and key not in seen and not self.table.contains(key):
                seen.add(key)
                todo.append(key)
        if not todo:
            return 0
        rows = self.embed(todo)
        for w, row in zip(todo, rows):
            self.table.pin(w, row)
        return len(todo)

    def most_similar(self, word: str, candidates: Sequence[str],
                     top_k: int = 5) -> List[Tuple[str, float]]:
        """k nearest candidate words by embedding cosine (the reference's
        word2vec ``most_similar`` surface, backend.py:297-301, over an
        explicit candidate list instead of a fixed gensim vocabulary).

        Rides :meth:`embed`, so candidate ranking climbs the same
        table -> LRU -> device ladder: in-vocabulary candidates are
        served from the int8 table and only OOV text pays a padded
        device batch.
        """
        if not candidates:
            return []
        emb = self.embed([word] + list(candidates))
        sims = emb[1:] @ emb[0]
        order = np.argsort(-sims)[:top_k]
        # lint: ignore[host-sync] — sims is a host np array (embed returns host)
        return [(candidates[i], float(sims[i])) for i in order]

    async def similarity_async(self, pairs) -> np.ndarray:
        """engine.scoring.SimilarityFn adapter."""
        return self.similarity(list(pairs))
