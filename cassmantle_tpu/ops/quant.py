"""int8 quantization for serving: weights-only (w8a16) AND full W8A8.

The reference rents its LLM (Mistral-7B-Instruct, reference backend.py:25)
so it never faces the on-box memory/bandwidth question. Serving that model
locally does: 7B bf16 params are ~14 GB — at the edge of one v5e chip's
16 GB HBM before activations — and single-stream greedy decode is
weight-streaming-bound, so weight bytes ARE the step time. Per-channel
symmetric int8 storage halves both.

Design (TPU-first):
- ``QTensor``: a registered pytree (int8 data + per-out-channel fp32
  scale). Param trees keep their exact structure; only large matmul
  kernels are swapped for QTensors, so one tree works for any model.
- w8a16 (``quantize_tree`` + ``quantized_apply``): dequantization
  happens INSIDE the jitted computation (``dequantize_tree`` at the top
  of the wrapped apply): HBM holds int8, and XLA fuses the
  ``convert+scale`` producer into each kernel's consumer ops, upcasting
  tiles in VMEM rather than materializing a persistent bf16 copy of the
  weights.
- W8A8 (``ActQTensor`` + ``w8a8_tree_host``; ISSUE 20): selected
  kernel leaves become ``ActQTensor`` (int8 data + per-out-channel fp32
  weight scale + an optional STATIC per-tensor activation scale from
  the committed calibration artifact, parallel/calibrate.py). The
  module code at w8a8-capable sites (models/layers.py ``QDense``, the
  fused-conv glue) branches on ``isinstance(kernel, ActQTensor)`` and
  dispatches the int8×int8→int32 Pallas kernels (ops/quant_matmul.py)
  — the MXU runs int8, activations move at int8 width, and the scales
  fold into the int32→fp epilogue. Quantize-once-at-load is the
  contract: per-call weight requantization inside a dispatch path is a
  recompile/bandwidth cliff and is lint-pinned
  (analysis/recompile.py ``quant-in-dispatch``).
- Per-OUTPUT-channel scales (last axis): row x @ W column j sees one
  scale s_j, preserving matmul semantics exactly:
  x @ (s ⊙ W8) == (x @ W8) ⊙ s.
- Symmetric (no zero-point): zero-points force an extra correction
  matmul; absmax/127 keeps the kernel a pure dot.

Embeddings, norms, biases, and small kernels stay in the storage dtype —
they're a rounding error of the footprint and disproportionately
quality-sensitive.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp


class QTensor(NamedTuple):
    """int8 data + broadcastable fp32 scale. A pytree by construction."""

    data: jax.Array    # int8, original shape
    scale: jax.Array   # fp32, shape broadcastable to data (per out-channel)

    @property
    def shape(self):
        return self.data.shape

    def dequantize(self, dtype=jnp.bfloat16) -> jax.Array:
        return (self.data.astype(jnp.float32) * self.scale).astype(dtype)


def quantize_tensor(w: jax.Array, axis: int = -1) -> QTensor:
    """Symmetric per-channel int8: scale = absmax/127 along all axes
    except ``axis`` (the output-feature axis, kept per-channel)."""
    w32 = jnp.asarray(w, jnp.float32)
    reduce_axes = tuple(i for i in range(w32.ndim)
                        if i != (axis % w32.ndim))
    absmax = jnp.max(jnp.abs(w32), axis=reduce_axes, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    data = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return QTensor(data=data, scale=scale)


def default_predicate(path: tuple, leaf: Any) -> bool:
    """Quantize large matmul kernels only: param named 'kernel' with
    >=2 dims and enough elements to matter. Embeddings (named
    'embedding'), norms ('scale'/'bias'), and tiny projections pass
    through."""
    name = str(path[-1]) if path else ""
    return (
        "kernel" in name
        and hasattr(leaf, "ndim") and leaf.ndim >= 2
        and leaf.size >= 1 << 16
    )


def _walk(tree: Any, fn: Callable[[tuple, Any], Any], path: tuple = ()):
    if isinstance(tree, dict):
        return {k: _walk(v, fn, path + (k,)) for k, v in tree.items()}
    return fn(path, tree)


def quantize_tree(
    params: Any,
    predicate: Optional[Callable[[tuple, Any], bool]] = None,
) -> Any:
    """Swap selected leaves of a param tree for QTensors (same structure
    otherwise). Works on the plain-dict trees flax produces. The default
    predicate is resolved at call time (module attribute) so policy is
    overridable in one place."""
    if predicate is None:
        predicate = default_predicate

    def visit(path, leaf):
        if predicate(path, leaf):
            return quantize_tensor(leaf)
        return leaf

    return _walk(params, visit)


def quantize_tree_host(
    params: Any,
    predicate: Optional[Callable[[tuple, Any], bool]] = None,
) -> Any:
    """quantize_tree pinned to host CPU — the form to use as a loader
    ``transform`` (models/weights.py): quantizing BEFORE device placement
    keeps peak HBM at the int8 footprint. Quantizing after would hold the
    full fp tree and the int8 tree resident together, which is exactly
    what breaks a 7B-class model on a 16 GB chip."""
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        return quantize_tree(params, predicate)


def dequantize_tree(params: Any, dtype=jnp.bfloat16) -> Any:
    """Inverse of quantize_tree — call INSIDE jit so XLA fuses the
    upcast into each kernel's consumers (int8 stays the HBM format)."""
    return jax.tree_util.tree_map(
        lambda leaf: leaf.dequantize(dtype) if isinstance(leaf, QTensor)
        else leaf,
        params,
        is_leaf=lambda x: isinstance(x, QTensor),
    )


def quantized_apply(apply_fn: Callable, dtype=jnp.bfloat16) -> Callable:
    """Wrap ``apply_fn(params, *args, **kw)`` to accept a quantized tree:
    the returned function dequantizes first, so it drops into any
    call site that jits apply (decode prefill/step, pipelines)."""
    def wrapped(params, *args, **kwargs):
        return apply_fn(dequantize_tree(params, dtype), *args, **kwargs)

    return wrapped


_Q8_SUFFIX = ".q8"
_SCALE_SUFFIX = ".q8_scale"


def save_quantized(params: Any, path: str) -> None:
    """Persist a (possibly quantized) tree as flat safetensors: each
    QTensor becomes two entries, '<path>.q8' (int8) and
    '<path>.q8_scale' (fp32) — so a 7B-class model quantizes ONCE
    offline (tools/quantize_weights.py) and every later boot loads int8
    straight from disk, no fp pass, half the read bytes."""
    import os

    import numpy as np
    from safetensors import numpy as st_numpy

    flat: dict = {}

    def visit(path_t, leaf):
        key = "/".join(str(p) for p in path_t)
        if isinstance(leaf, QTensor):
            flat[key + _Q8_SUFFIX] = np.asarray(leaf.data)
            flat[key + _SCALE_SUFFIX] = np.asarray(
                leaf.scale, dtype=np.float32)
        else:
            flat[key] = np.asarray(leaf)
        return leaf

    _walk(params, visit)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    st_numpy.save_file(flat, path)


def load_quantized(path: str) -> Any:
    """Inverse of :func:`save_quantized`: rebuilds the tree with
    QTensor leaves (host arrays; push with tree_map(jnp.asarray, .))."""
    from cassmantle_tpu.models.weights import load_safetensors, set_in_tree

    flat = load_safetensors(path)
    tree: dict = {}
    for key, value in flat.items():
        if key.endswith(_SCALE_SUFFIX):
            continue
        if key.endswith(_Q8_SUFFIX):
            base = key[: -len(_Q8_SUFFIX)]
            set_in_tree(tree, base,
                        QTensor(data=value,
                                scale=flat[base + _SCALE_SUFFIX]))
        else:
            set_in_tree(tree, key, value)
    return tree


def tree_nbytes(params: Any) -> int:
    """HBM footprint of a (possibly quantized) tree, in bytes."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        total += getattr(leaf, "nbytes", 0)
    return total


def quantization_error(w: jax.Array, axis: int = -1) -> float:
    """Relative L2 reconstruction error (diagnostics/tests)."""
    q = quantize_tensor(w, axis)
    w32 = jnp.asarray(w, jnp.float32)
    err = jnp.linalg.norm(q.dequantize(jnp.float32) - w32)
    return float(err / (jnp.linalg.norm(w32) + 1e-9))


# ---------------------------------------------------------------------------
# W8A8: activation quantization + the serving tree transform (ISSUE 20)
# ---------------------------------------------------------------------------

#: int8 symmetric range. 127 (not 128) keeps the grid symmetric so
#: negation is exact and no zero-point correction term is needed.
ACT_QMAX = 127.0

#: fp8 e4m3 finite max — the "127" of the fp8 grid when hardware
#: supports fp8 matmuls behind the same interface (ops/quant_matmul.py).
FP8_E4M3_MAX = 448.0

#: absmax floor when computing activation scales: an all-zero
#: activation tensor (padded slot, masked batch row) must not produce a
#: 0 scale and a NaN-producing divide.
_ACT_EPS = 1e-8


def qmax_for(dtype) -> float:
    """Largest representable magnitude of the quantized grid."""
    if jnp.dtype(dtype) == jnp.int8:
        return ACT_QMAX
    return FP8_E4M3_MAX


def act_absmax(x: jax.Array, per_token: bool = False) -> jax.Array:
    """absmax statistic for activation scaling: a scalar (per-tensor,
    image pipelines) or shape (..., 1) reduced over the feature axis
    (per-token, the LM path — decode activations are outlier-heavy per
    position, so per-token scales cost one row-max and buy back most of
    the quality)."""
    x32 = jnp.abs(x.astype(jnp.float32))
    if per_token:
        return jnp.max(x32, axis=-1, keepdims=True)
    return jnp.max(x32)


def act_scale_from_absmax(absmax, dtype=jnp.int8) -> jax.Array:
    """absmax → symmetric scale on the target grid (int8 or fp8)."""
    return jnp.maximum(jnp.asarray(absmax, jnp.float32), _ACT_EPS) \
        / qmax_for(dtype)


def quantize_act(x: jax.Array, scale: jax.Array,
                 dtype=jnp.int8) -> jax.Array:
    """Quantize activations with a precomputed scale. int8 rounds and
    clips; fp8 just scales and casts (the fp8 grid rounds in hardware).
    Stays pure elementwise so XLA fuses it into the producer (GN/SiLU/
    norm epilogue) — the quantized tensor is written to HBM at one byte
    per element, never at full width."""
    x32 = x.astype(jnp.float32) / scale
    if jnp.dtype(dtype) == jnp.int8:
        return jnp.clip(jnp.round(x32), -ACT_QMAX, ACT_QMAX) \
            .astype(jnp.int8)
    return jnp.clip(x32, -FP8_E4M3_MAX, FP8_E4M3_MAX).astype(dtype)


class ActQTensor(NamedTuple):
    """A w8a8 weight leaf: int8 data + per-out-channel fp32 weight scale
    + optional STATIC per-tensor activation scale for this site (fp32
    scalar from the calibration artifact; ``None`` selects dynamic
    in-graph absmax scaling).

    Deliberately a distinct type from :class:`QTensor`: w8a16 trees are
    dequantized wholesale before apply (modules never see them), while
    ActQTensor leaves flow INTO apply and module code branches on them
    (models/layers.py ``QDense``). ``act_scale=None`` vs an array
    changes the pytree structure — that choice is fixed per pipeline
    build (calibrated or not), so bucket jits see one stable structure
    and never recompile over it."""

    data: jax.Array                    # int8 (or fp8), original shape
    scale: jax.Array                   # fp32 weight scale, per out-channel
    act_scale: Optional[jax.Array]     # fp32 scalar static act scale | None

    @property
    def shape(self):
        return self.data.shape

    def dequantize(self, dtype=jnp.bfloat16) -> jax.Array:
        return (self.data.astype(jnp.float32) * self.scale).astype(dtype)


def quantize_tensor_act(w: jax.Array, axis: int = -1,
                        act_scale: Optional[jax.Array] = None,
                        dtype=jnp.int8) -> ActQTensor:
    """quantize_tensor, but produce a w8a8 leaf (optionally carrying the
    site's static activation scale)."""
    if jnp.dtype(dtype) == jnp.int8:
        q = quantize_tensor(w, axis)
        data, scale = q.data, q.scale
    else:
        w32 = jnp.asarray(w, jnp.float32)
        reduce_axes = tuple(i for i in range(w32.ndim)
                            if i != (axis % w32.ndim))
        absmax = jnp.max(jnp.abs(w32), axis=reduce_axes, keepdims=True)
        scale = jnp.where(absmax > 0, absmax / FP8_E4M3_MAX, 1.0)
        data = jnp.clip(w32 / scale, -FP8_E4M3_MAX,
                        FP8_E4M3_MAX).astype(dtype)
    if act_scale is not None:
        act_scale = jnp.asarray(act_scale, jnp.float32)
    return ActQTensor(data=data, scale=scale, act_scale=act_scale)


#: Module names whose 'kernel' param is a w8a8-capable DENSE site: the
#: QDense instances in models/layers.py (attention q/k/v/qkv/kv/out
#: projections, transformer MLP fc1/fc2, GEGLU proj/out). The names are
#: a whitelist on purpose — plain nn.Dense sites (time embeds,
#: SpatialTransformer proj_in/proj_out, heads) would crash on a
#: quantized leaf, so the predicate must only ever select sites whose
#: module code branches on ActQTensor.
W8A8_DENSE_MODULES = frozenset(
    {"q", "k", "v", "qkv", "kv", "out", "proj", "fc1", "fc2"})

#: Module names whose 'kernel' is a w8a8-capable 3x3 CONV site: the
#: Conv3x3Params sites consumed by the fused GN+SiLU+conv glue
#: (models/layers.py fused_gn_silu_conv3x3). 1x1 skips, conv_in/out and
#: up/downsamplers are plain nn.Conv and stay fp.
W8A8_CONV_MODULES = frozenset({"conv1", "conv2"})

#: Minimum element count for a kernel to be worth quantizing — same
#: rationale as default_predicate. Tests override via the ``min_size``
#: argument (tiny-geometry kernels are below any sensible floor).
W8A8_MIN_SIZE = 1 << 16


def w8a8_default_predicate(path: tuple, leaf: Any,
                           min_size: int = W8A8_MIN_SIZE) -> bool:
    """True for kernel leaves at w8a8-capable sites (see the module
    whitelists above)."""
    if not path or str(path[-1]) != "kernel":
        return False
    if not hasattr(leaf, "ndim") or leaf.size < min_size:
        return False
    parent = str(path[-2]) if len(path) >= 2 else ""
    if leaf.ndim == 2 and parent in W8A8_DENSE_MODULES:
        return True
    return (leaf.ndim == 4 and leaf.shape[:2] == (3, 3)
            and parent in W8A8_CONV_MODULES)


def site_key(path: tuple) -> str:
    """Calibration-artifact key for a kernel param path: the module
    path, '/'-joined — identical to the key ``note_act_stat`` records
    (flax ``self.path`` of the owning module). A leading ``params``
    segment (the flax variable-collection root present in full
    variable trees but not in module paths) is stripped so both sides
    derive the same key."""
    parts = [str(p) for p in path[:-1]]
    if parts and parts[0] == "params":
        parts = parts[1:]
    return "/".join(parts)


def w8a8_tree(params: Any,
              act_scales: Optional[dict] = None,
              predicate: Optional[Callable[[tuple, Any], bool]] = None,
              dtype=jnp.int8) -> Any:
    """Swap w8a8-capable kernel leaves for ActQTensors. ``act_scales``
    maps site keys (:func:`site_key`) to calibrated absmax floats; sites
    present in the map get a STATIC activation scale folded in, absent
    sites fall back to dynamic in-graph scaling. One tree transform =
    quantize-once-at-load; never call this per dispatch (lint-pinned:
    analysis/recompile.py quant-in-dispatch)."""
    if predicate is None:
        predicate = w8a8_default_predicate

    def visit(path, leaf):
        if not predicate(path, leaf):
            return leaf
        a_scale = None
        if act_scales is not None:
            absmax = act_scales.get(site_key(path))
            if absmax is not None:
                a_scale = act_scale_from_absmax(absmax, dtype)
        return quantize_tensor_act(leaf, act_scale=a_scale, dtype=dtype)

    return _walk(params, visit)


def w8a8_tree_host(params: Any,
                   act_scales: Optional[dict] = None,
                   predicate: Optional[Callable] = None,
                   dtype=jnp.int8) -> Any:
    """w8a8_tree pinned to host CPU — the loader-transform form (same
    peak-HBM argument as :func:`quantize_tree_host`)."""
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        return w8a8_tree(params, act_scales, predicate, dtype)


def w8a8_site_count(params: Any) -> int:
    """Number of ActQTensor leaves in a tree (diagnostics/tests)."""
    count = 0
    for leaf in jax.tree_util.tree_leaves(
            params, is_leaf=lambda x: isinstance(x, ActQTensor)):
        if isinstance(leaf, ActQTensor):
            count += 1
    return count


def w8a8_calibrated(params: Any) -> bool:
    """True when any ActQTensor leaf carries a STATIC activation scale
    (i.e. the tree was built against a matching calibration artifact;
    dynamic-absmax trees have ``act_scale=None`` everywhere)."""
    for leaf in jax.tree_util.tree_leaves(
            params, is_leaf=lambda x: isinstance(x, ActQTensor)):
        if isinstance(leaf, ActQTensor) and leaf.act_scale is not None:
            return True
    return False


# -- calibration stat recorder ----------------------------------------------
# The calibration pass (parallel/calibrate.py) runs the UNMODIFIED fp
# path eagerly and collects per-site activation absmax through this
# thread-local sink. Module code at w8a8 sites calls note_act_stat with
# its flax path + the activation tensor; outside a collect_act_stats()
# context that call is a single falsy attribute read — zero traced ops,
# zero serving cost. Inside, values are reduced to host floats, which
# is why calibration must run eagerly (a tracer is skipped, never
# synced — so the recorder can't accidentally introduce a host sync
# into a jitted serving path either).

_act_tls = threading.local()


def act_stats_active() -> bool:
    return getattr(_act_tls, "sink", None) is not None


@contextmanager
def collect_act_stats():
    """Context manager yielding a dict that fills with
    {site_key: absmax float} as fp forwards run eagerly inside it."""
    sink: dict = {}
    prev = getattr(_act_tls, "sink", None)
    _act_tls.sink = sink
    try:
        yield sink
    finally:
        _act_tls.sink = prev


def note_act_stat(site: str, value: jax.Array) -> None:
    """Record max(|value|) for ``site`` into the active sink. No-op when
    no sink is active or under a trace (calibration is eager by
    contract)."""
    sink = getattr(_act_tls, "sink", None)
    if sink is None or isinstance(value, jax.core.Tracer):
        return
    # concrete array on host: float() here is a deliberate sync — this
    # only ever executes inside an eager calibration pass
    absmax = float(jnp.max(jnp.abs(value.astype(jnp.float32))))
    sink[site] = max(sink.get(site, 0.0), absmax)
