"""Weights-only int8 quantization for serving (w8a16).

The reference rents its LLM (Mistral-7B-Instruct, reference backend.py:25)
so it never faces the on-box memory/bandwidth question. Serving that model
locally does: 7B bf16 params are ~14 GB — at the edge of one v5e chip's
16 GB HBM before activations — and single-stream greedy decode is
weight-streaming-bound, so weight bytes ARE the step time. Per-channel
symmetric int8 storage halves both.

Design (TPU-first):
- ``QTensor``: a registered pytree (int8 data + per-out-channel fp32
  scale). Param trees keep their exact structure; only large matmul
  kernels are swapped for QTensors, so one tree works for any model.
- Dequantization happens INSIDE the jitted computation
  (``dequantize_tree`` at the top of the wrapped apply): HBM holds int8,
  and XLA fuses the ``convert+scale`` producer into each kernel's
  consumer ops, upcasting tiles in VMEM rather than materializing a
  persistent bf16 copy of the weights.
- Per-OUTPUT-channel scales (last axis): row x @ W column j sees one
  scale s_j, preserving matmul semantics exactly:
  x @ (s ⊙ W8) == (x @ W8) ⊙ s.
- Symmetric (no zero-point): zero-points force an extra correction
  matmul; absmax/127 keeps the kernel a pure dot.

Embeddings, norms, biases, and small kernels stay in the storage dtype —
they're a rounding error of the footprint and disproportionately
quality-sensitive.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp


class QTensor(NamedTuple):
    """int8 data + broadcastable fp32 scale. A pytree by construction."""

    data: jax.Array    # int8, original shape
    scale: jax.Array   # fp32, shape broadcastable to data (per out-channel)

    @property
    def shape(self):
        return self.data.shape

    def dequantize(self, dtype=jnp.bfloat16) -> jax.Array:
        return (self.data.astype(jnp.float32) * self.scale).astype(dtype)


def quantize_tensor(w: jax.Array, axis: int = -1) -> QTensor:
    """Symmetric per-channel int8: scale = absmax/127 along all axes
    except ``axis`` (the output-feature axis, kept per-channel)."""
    w32 = jnp.asarray(w, jnp.float32)
    reduce_axes = tuple(i for i in range(w32.ndim)
                        if i != (axis % w32.ndim))
    absmax = jnp.max(jnp.abs(w32), axis=reduce_axes, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    data = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return QTensor(data=data, scale=scale)


def default_predicate(path: tuple, leaf: Any) -> bool:
    """Quantize large matmul kernels only: param named 'kernel' with
    >=2 dims and enough elements to matter. Embeddings (named
    'embedding'), norms ('scale'/'bias'), and tiny projections pass
    through."""
    name = str(path[-1]) if path else ""
    return (
        "kernel" in name
        and hasattr(leaf, "ndim") and leaf.ndim >= 2
        and leaf.size >= 1 << 16
    )


def _walk(tree: Any, fn: Callable[[tuple, Any], Any], path: tuple = ()):
    if isinstance(tree, dict):
        return {k: _walk(v, fn, path + (k,)) for k, v in tree.items()}
    return fn(path, tree)


def quantize_tree(
    params: Any,
    predicate: Optional[Callable[[tuple, Any], bool]] = None,
) -> Any:
    """Swap selected leaves of a param tree for QTensors (same structure
    otherwise). Works on the plain-dict trees flax produces. The default
    predicate is resolved at call time (module attribute) so policy is
    overridable in one place."""
    if predicate is None:
        predicate = default_predicate

    def visit(path, leaf):
        if predicate(path, leaf):
            return quantize_tensor(leaf)
        return leaf

    return _walk(params, visit)


def quantize_tree_host(
    params: Any,
    predicate: Optional[Callable[[tuple, Any], bool]] = None,
) -> Any:
    """quantize_tree pinned to host CPU — the form to use as a loader
    ``transform`` (models/weights.py): quantizing BEFORE device placement
    keeps peak HBM at the int8 footprint. Quantizing after would hold the
    full fp tree and the int8 tree resident together, which is exactly
    what breaks a 7B-class model on a 16 GB chip."""
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        return quantize_tree(params, predicate)


def dequantize_tree(params: Any, dtype=jnp.bfloat16) -> Any:
    """Inverse of quantize_tree — call INSIDE jit so XLA fuses the
    upcast into each kernel's consumers (int8 stays the HBM format)."""
    return jax.tree_util.tree_map(
        lambda leaf: leaf.dequantize(dtype) if isinstance(leaf, QTensor)
        else leaf,
        params,
        is_leaf=lambda x: isinstance(x, QTensor),
    )


def quantized_apply(apply_fn: Callable, dtype=jnp.bfloat16) -> Callable:
    """Wrap ``apply_fn(params, *args, **kw)`` to accept a quantized tree:
    the returned function dequantizes first, so it drops into any
    call site that jits apply (decode prefill/step, pipelines)."""
    def wrapped(params, *args, **kwargs):
        return apply_fn(dequantize_tree(params, dtype), *args, **kwargs)

    return wrapped


_Q8_SUFFIX = ".q8"
_SCALE_SUFFIX = ".q8_scale"


def save_quantized(params: Any, path: str) -> None:
    """Persist a (possibly quantized) tree as flat safetensors: each
    QTensor becomes two entries, '<path>.q8' (int8) and
    '<path>.q8_scale' (fp32) — so a 7B-class model quantizes ONCE
    offline (tools/quantize_weights.py) and every later boot loads int8
    straight from disk, no fp pass, half the read bytes."""
    import os

    import numpy as np
    from safetensors import numpy as st_numpy

    flat: dict = {}

    def visit(path_t, leaf):
        key = "/".join(str(p) for p in path_t)
        if isinstance(leaf, QTensor):
            flat[key + _Q8_SUFFIX] = np.asarray(leaf.data)
            flat[key + _SCALE_SUFFIX] = np.asarray(
                leaf.scale, dtype=np.float32)
        else:
            flat[key] = np.asarray(leaf)
        return leaf

    _walk(params, visit)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    st_numpy.save_file(flat, path)


def load_quantized(path: str) -> Any:
    """Inverse of :func:`save_quantized`: rebuilds the tree with
    QTensor leaves (host arrays; push with tree_map(jnp.asarray, .))."""
    from cassmantle_tpu.models.weights import load_safetensors, set_in_tree

    flat = load_safetensors(path)
    tree: dict = {}
    for key, value in flat.items():
        if key.endswith(_SCALE_SUFFIX):
            continue
        if key.endswith(_Q8_SUFFIX):
            base = key[: -len(_Q8_SUFFIX)]
            set_in_tree(tree, base,
                        QTensor(data=value,
                                scale=flat[base + _SCALE_SUFFIX]))
        else:
            set_in_tree(tree, key, value)
    return tree


def tree_nbytes(params: Any) -> int:
    """HBM footprint of a (possibly quantized) tree, in bytes."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        total += getattr(leaf, "nbytes", 0)
    return total


def quantization_error(w: jax.Array, axis: int = -1) -> float:
    """Relative L2 reconstruction error (diagnostics/tests)."""
    q = quantize_tensor(w, axis)
    w32 = jnp.asarray(w, jnp.float32)
    err = jnp.linalg.norm(q.dequantize(jnp.float32) - w32)
    return float(err / (jnp.linalg.norm(w32) + 1e-9))
