"""Greedy text decode as prefill + ``lax.scan`` over KV-cache steps.

Replaces the reference's remote LLM call (backend.py:240-268). The whole
generation — prefill over the padded prompt bucket plus ``max_new_tokens``
cached decode steps — compiles to one XLA computation with static shapes.
Early stop is data-dependent, so instead of breaking the loop (illegal under
jit) tokens after EOS are overwritten with EOS and reported lengths stop at
the first EOS, matching the reference's "decode 32-96 tokens then trim"
behavior (backend.py:250-255, 265).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from cassmantle_tpu.utils.profiling import annotate


def make_apply_fns(model):
    """(prefill_fn, decode_step_fn, decode_chunk_fn) for any zoo LM
    exposing the prefill/decode_step/decode_chunk contract — the one
    definition of the calling convention the decode loops expect
    (params threaded first so weights stay traced jit arguments)."""
    cls = type(model)

    def prefill(params, ids, prompt_len, max_len):
        return model.apply(params, ids, prompt_len, max_len,
                           method=cls.prefill)

    def decode_step(params, token, index, cache, valid):
        return model.apply(params, token, index, cache, valid,
                           method=cls.decode_step)

    def decode_chunk(params, tokens, index, cache, valid):
        return model.apply(params, tokens, index, cache, valid,
                           method=cls.decode_chunk)

    return prefill, decode_step, decode_chunk


def make_apply_pair(model):
    """(prefill_fn, decode_step_fn) — the ``greedy_decode`` subset of
    :func:`make_apply_fns`, kept for callers that never draft."""
    return make_apply_fns(model)[:2]


@partial(jax.jit, static_argnums=(0, 5, 6, 7, 8))
def greedy_decode(
    model_apply_pair,          # (prefill_fn, decode_step_fn), static; both
                               # take ``params`` first so weights enter the
                               # jit as device buffers, NOT as captured
                               # constants baked into the HLO
    params,                    # model param tree (traced argument)
    input_ids: jax.Array,      # (B, P) right-padded prompt bucket
    prompt_len: jax.Array,     # (B,)
    rng: jax.Array,            # consumed only when temperature > 0
    max_new_tokens: int,
    eos_token: int,
    temperature: float = 0.0,
    top_k: int = 40,
) -> Tuple[jax.Array, jax.Array]:
    """Returns (generated (B, max_new_tokens), gen_len (B,)).

    ``temperature=0`` (default) is exact greedy argmax — the reference's
    hosted text-generation call decodes greedily (no sampling params,
    backend.py:250-255). ``temperature>0`` switches to top-k Gumbel
    sampling per step (the standard serving sampler), statically — the
    greedy graph carries no sampling ops."""
    prefill_fn, decode_step_fn = model_apply_pair
    b, p = input_ids.shape
    max_len = p + max_new_tokens

    last_logits, cache = prefill_fn(params, input_ids, prompt_len, max_len)

    positions = jnp.arange(max_len)[None, :]          # (1, L)
    prompt_valid = positions < prompt_len[:, None]     # (B, L)

    def pick(logits, i):
        if temperature <= 0.0:  # static branch: pure greedy
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        k = max(1, min(top_k, logits.shape[-1]))
        k_logits, k_idx = jax.lax.top_k(logits, k)
        choice = jax.random.categorical(
            jax.random.fold_in(rng, i),
            k_logits.astype(jnp.float32) / temperature, axis=-1)
        return jnp.take_along_axis(
            k_idx, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)

    def step(carry, i):
        logits, cache, done = carry
        token = pick(logits, i)
        token = jnp.where(done, jnp.int32(eos_token), token)
        emitted = token
        done = done | (token == eos_token)
        # All rows decode at cache index P+i. Rows whose prompt is shorter
        # than P see a small position-id offset; the serving layer keeps
        # buckets tight so the offset stays negligible, and masked padding
        # positions are never attended either way.
        idx = jnp.int32(p + i)
        valid = prompt_valid | (
            (positions >= p) & (positions <= idx)
        )
        logits, cache = decode_step_fn(params, token, idx, cache, valid)
        return (logits, cache, done), emitted

    init_done = jnp.zeros((b,), dtype=bool)
    (_, _, _), tokens = jax.lax.scan(
        step, (last_logits, cache, init_done), jnp.arange(max_new_tokens)
    )
    tokens = tokens.T  # (B, max_new_tokens)
    is_eos = tokens == eos_token
    gen_len = jnp.where(
        is_eos.any(axis=1),
        jnp.argmax(is_eos, axis=1),
        jnp.int32(max_new_tokens),
    )
    return tokens, gen_len


# -- speculative decoding ---------------------------------------------------
#
# The greedy loop above is memory-bound: every emitted token reads the full
# weight set once (docs/PERF_NOTES.md "LM decode accounting"). Speculative
# decoding amortizes that read: a cheap DRAFT proposes ``gamma`` tokens and
# the target scores all gamma+1 positions in ONE ``decode_chunk`` forward.
# Because serving decodes greedily (temperature=0 — the reference's decode
# mode), acceptance is exact argmax match: every committed token is, by
# construction, the token the target's own argmax chain would have emitted,
# so the output is bit-identical to ``greedy_decode`` — CPU-testable, no
# distribution-level rejection sampling needed.


class NgramDraft(NamedTuple):
    """Self-drafting prompt-lookup draft: the longest recent ``ngram``
    suffix of the already-decoded context is matched against earlier
    context and the continuation after the match is proposed. Zero extra
    HBM (no second model), effective whenever generations echo the
    prompt or loop on phrases. Static/hashable: lives in the jit key."""

    ngram: int = 3


class ModelDraft(NamedTuple):
    """A smaller zoo LM drafting for the target (gpt2-small for
    gpt2-large/Mistral). ``prefill_fn``/``step_fn`` follow the
    make_apply_fns convention; the draft's params ride as the traced
    ``draft_params`` argument. The draft MUST share the target's
    tokenizer/vocab — token ids are compared directly."""

    prefill_fn: Callable
    step_fn: Callable


def _ngram_propose(ctx, prompt_len, prompt_width, n_gen, gamma, k):
    """Propose (B, gamma) continuation tokens by suffix lookup.

    ``ctx`` (B, L) is the bucket-layout context buffer: the right-padded
    prompt occupies columns < ``prompt_width`` (real tokens only below
    each row's ``prompt_len``) and ``n_gen`` committed/known generated
    tokens sit at ``prompt_width..prompt_width+n_gen-1``. The last ``k``
    known tokens are matched against every earlier window (pad gaps are
    blanked to -1 so they can never fake a match); the rightmost match
    wins (most recent context) and the ``gamma`` tokens after it are the
    proposal. No match → propose the last token repeated (the degenerate
    loop draft). Pure function of traced values — fixed shapes, no
    syncs; correctness never depends on proposal quality (verify
    corrects everything)."""
    b, length = ctx.shape
    pos = jnp.arange(length)[None, :]
    end = jnp.int32(prompt_width) + n_gen          # one past the known region
    real = (pos < prompt_len[:, None]) | (
        (pos >= prompt_width) & (pos < end))
    mctx = jnp.where(real, ctx, jnp.int32(-1))
    suffix = jax.lax.dynamic_slice(
        mctx, (jnp.int32(0), end - k), (b, k))     # (B, k) last known tokens
    # all length-k windows, via k static shifts: windows[j] = mctx[:, j:j+k]
    shifted = jnp.stack(
        [mctx[:, t:length - k + t] for t in range(k)], axis=-1
    )                                              # (B, L-k, k)
    match = jnp.all(shifted == suffix[:, None, :], axis=-1)
    window_j = jnp.arange(length - k)[None, :]
    # the window must end strictly before the suffix so a continuation
    # exists (and the suffix can't trivially match itself)
    match = match & (window_j < end - k)
    j_star = jnp.max(jnp.where(match, window_j, -1), axis=-1)   # (B,)
    found = j_star >= 0

    def take(row, start):
        return jax.lax.dynamic_slice(row, (start,), (gamma,))

    start = jnp.clip(j_star + k, 0, length - gamma)
    proposal = jax.vmap(take)(ctx, start)
    last = jax.lax.dynamic_slice(mctx, (jnp.int32(0), end - 1), (b, 1))
    return jnp.where(found[:, None], proposal,
                     jnp.broadcast_to(last, (b, gamma))).astype(jnp.int32)


@partial(jax.jit, static_argnums=(0, 4, 5, 6, 7))
def speculative_decode(
    model_apply_fns,           # (prefill_fn, decode_step_fn, decode_chunk_fn)
    params,                    # target param tree (traced)
    input_ids: jax.Array,      # (B, P) right-padded prompt bucket
    prompt_len: jax.Array,     # (B,)
    max_new_tokens: int,
    eos_token: int,
    gamma: int,                # drafted tokens per chunk
    draft,                     # NgramDraft | ModelDraft (static)
    draft_params=None,         # draft LM params (ModelDraft only; traced)
    row_mask=None,             # (B,) True = real row; None = all real
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Draft/verify greedy decode, bit-identical to ``greedy_decode``.

    Returns (generated (B, max_new_tokens), gen_len (B,), stats (3,)
    int32 = [chunks, drafted, accepted]).

    Loop shape: a ``lax.while_loop`` over fixed-size chunks — every
    chunk's verify forward scores ``gamma+1`` positions (the known-next
    token plus the gamma drafts) in one ``decode_chunk``, commits the
    accepted prefix plus the correction, and stops as soon as every
    live row is finished. Best case the loop runs ⌈max_new/γ⌉ chunks
    (full acceptance, the γ+1-fold weight-read amortization); worst
    case it degrades to one committed token per chunk, never fewer —
    all shapes static either way, so the serving buckets compile once.

    Batch rows advance in LOCKSTEP: the committed count per chunk is the
    minimum across live rows (keeping the kv-cache append index scalar —
    the decode_step/decode_chunk cache convention). Finished rows and
    ``row_mask=False`` rows (the serving layer's batch-bucket padding
    dummies) are excluded from that min so they never throttle real
    rows; masked rows' outputs are deterministic but NOT parity-checked
    (the serving layer drops them).

    Rollback needs no copies: a rejected suffix simply stays out of the
    next chunk's validity mask and is overwritten by the next
    chunk-append (the valid-mask convention, models/layers.py).
    """
    prefill_fn, _, chunk_fn = model_apply_fns
    b, p = input_ids.shape
    g1 = gamma + 1
    # scratch tail: the last chunk's full-width append may land past the
    # budget; committed output is sliced back to max_new_tokens
    max_len = p + max_new_tokens + g1
    eos = jnp.int32(eos_token)

    last_logits, cache = prefill_fn(params, input_ids, prompt_len, max_len)

    positions = jnp.arange(max_len)[None, :]          # (1, L)
    prompt_valid = positions < prompt_len[:, None]     # (B, L)

    is_model_draft = isinstance(draft, ModelDraft)
    if is_model_draft:
        _, d_cache = draft.prefill_fn(draft_params, input_ids, prompt_len,
                                      max_len)
    else:
        d_cache = ()
    # context buffer for the n-gram draft: bucket layout + scratch tail
    # (a model draft keeps its context in its own kv cache — no buffer)
    ctx = (jnp.zeros((b, 0), jnp.int32) if is_model_draft
           else jnp.pad(input_ids.astype(jnp.int32),
                        ((0, 0), (0, max_new_tokens + g1))))
    out = jnp.zeros((b, max_new_tokens + g1), dtype=jnp.int32)
    done = jnp.zeros((b,), dtype=bool)
    stats = jnp.zeros((3,), dtype=jnp.int32)          # chunks/drafted/accepted
    # last committed token, for the model draft's cache-sync step; the
    # initial value re-writes the last prompt column's kv verbatim
    # (k/v at a position depend only on that position's token)
    prev_tok = input_ids[:, p - 1].astype(jnp.int32)

    def live_done(done):
        return done if row_mask is None else (done | ~row_mask)

    def cond(carry):
        g, out, last_logits, cache, d_cache, ctx, prev, done, stats = carry
        return (g < max_new_tokens) & ~jnp.all(live_done(done))

    def chunk(carry):
        g, out, last_logits, cache, d_cache, ctx, prev, done, stats = carry
        idx = jnp.int32(p) + g                         # cache index of y_first
        y_first = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
        y_first = jnp.where(done, eos, y_first)

        # -- draft: gamma proposals continuing after y_first -----------
        if is_model_draft:
            with annotate("spec_draft"):
                # cache-sync step: the previous chunk committed through
                # position idx-1, but the draft's own scan last wrote
                # kv for ITS tokens — on a rejection the slot at the
                # correction position holds the rejected token's kv, and
                # on full acceptance it was never written at all. One
                # step re-feeding the last committed token repairs the
                # slot (k/v depend only on that position's token), so
                # stale kv never accumulates to erode the accept rate.
                sync_valid = prompt_valid | (
                    (positions >= p) & (positions <= idx - 1))
                _, d_cache = draft.step_fn(draft_params, prev, idx - 1,
                                           d_cache, sync_valid)

                def d_step(state, _):
                    dc, cur, tok = state
                    valid = prompt_valid | (
                        (positions >= p) & (positions <= cur))
                    logits, dc = draft.step_fn(draft_params, tok, cur, dc,
                                               valid)
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    return (dc, cur + 1, nxt), nxt
                (d_cache, _, _), drafts = jax.lax.scan(
                    d_step, (d_cache, idx, y_first), None, length=gamma)
                drafts = drafts.T                      # (B, gamma)
            new_ctx = ctx
        else:
            ctx_y = jax.lax.dynamic_update_slice(
                ctx, y_first[:, None], (jnp.int32(0), idx))
            drafts = _ngram_propose(ctx_y, prompt_len, p, g + 1, gamma,
                                    draft.ngram)
            new_ctx = ctx_y

        # -- verify: ONE target forward over [y_first, drafts] ---------
        chunk_toks = jnp.concatenate([y_first[:, None], drafts], axis=1)
        valid = prompt_valid | (
            (positions >= p) & (positions <= idx + gamma))
        with annotate("spec_verify"):
            logits, new_cache = chunk_fn(params, chunk_toks, idx, cache,
                                         valid)
        preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (B, g1)

        # true greedy continuation under the eos-freeze convention
        # (tokens after EOS are EOS — greedy_decode's step semantics),
        # and the leading-match accept count, unrolled over static gamma
        emit = [y_first]
        cur_done = done | (y_first == eos)
        accept = jnp.ones((b,), dtype=bool)
        acc_count = jnp.zeros((b,), jnp.int32)
        for j in range(gamma):
            tok = jnp.where(cur_done, eos, preds[:, j])
            emit.append(tok)
            accept = accept & (drafts[:, j] == tok)
            acc_count = acc_count + accept.astype(jnp.int32)
            cur_done = cur_done | (tok == eos)
        emit = jnp.stack(emit, axis=1)                 # (B, g1)

        # lockstep commit: min over LIVE rows; finished/dummy rows are
        # masked to full width so they never drag the batch
        c_rows = jnp.where(live_done(done), jnp.int32(g1), 1 + acc_count)
        c = jnp.minimum(jnp.min(c_rows),
                        jnp.int32(max_new_tokens) - g)  # never overshoot

        out = jax.lax.dynamic_update_slice(out, emit, (jnp.int32(0), g))
        if not is_model_draft:
            new_ctx = jax.lax.dynamic_update_slice(
                new_ctx, emit, (jnp.int32(0), idx))
        committed = jnp.arange(g1)[None, :] < c
        done = done | jnp.any((emit == eos) & committed, axis=1)
        last_logits = jax.lax.dynamic_index_in_dim(
            logits, c - 1, axis=1, keepdims=False)
        new_prev = jax.lax.dynamic_index_in_dim(
            emit, c - 1, axis=1, keepdims=False)       # last committed token
        stats = stats + jnp.stack(
            [jnp.int32(1), jnp.int32(gamma), c - 1])
        return (g + c, out, last_logits, new_cache, d_cache, new_ctx,
                new_prev, done, stats)

    g, out, _, _, _, _, _, done, stats = jax.lax.while_loop(
        cond, chunk,
        (jnp.int32(0), out, last_logits, cache, d_cache, ctx, prev_tok,
         done, stats))

    # positions past the stop point: every live row is done there, and
    # greedy emits EOS after EOS — fill, then trim the scratch tail
    tokens = jnp.where(jnp.arange(max_new_tokens + g1)[None, :] >= g,
                       eos, out)[:, :max_new_tokens]
    is_eos = tokens == eos
    gen_len = jnp.where(
        is_eos.any(axis=1),
        jnp.argmax(is_eos, axis=1),
        jnp.int32(max_new_tokens),
    )
    return tokens, gen_len, stats
