"""Greedy text decode as prefill + ``lax.scan`` over KV-cache steps.

Replaces the reference's remote LLM call (backend.py:240-268). The whole
generation — prefill over the padded prompt bucket plus ``max_new_tokens``
cached decode steps — compiles to one XLA computation with static shapes.
Early stop is data-dependent, so instead of breaking the loop (illegal under
jit) tokens after EOS are overwritten with EOS and reported lengths stop at
the first EOS, matching the reference's "decode 32-96 tokens then trim"
behavior (backend.py:250-255, 265).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp


def make_apply_pair(model):
    """(prefill_fn, decode_step_fn) for any zoo LM exposing the
    prefill/decode_step contract — the one definition of the calling
    convention ``greedy_decode`` expects (params threaded first so
    weights stay traced jit arguments)."""
    cls = type(model)

    def prefill(params, ids, prompt_len, max_len):
        return model.apply(params, ids, prompt_len, max_len,
                           method=cls.prefill)

    def decode_step(params, token, index, cache, valid):
        return model.apply(params, token, index, cache, valid,
                           method=cls.decode_step)

    return prefill, decode_step


@partial(jax.jit, static_argnums=(0, 5, 6, 7, 8))
def greedy_decode(
    model_apply_pair,          # (prefill_fn, decode_step_fn), static; both
                               # take ``params`` first so weights enter the
                               # jit as device buffers, NOT as captured
                               # constants baked into the HLO
    params,                    # model param tree (traced argument)
    input_ids: jax.Array,      # (B, P) right-padded prompt bucket
    prompt_len: jax.Array,     # (B,)
    rng: jax.Array,            # consumed only when temperature > 0
    max_new_tokens: int,
    eos_token: int,
    temperature: float = 0.0,
    top_k: int = 40,
) -> Tuple[jax.Array, jax.Array]:
    """Returns (generated (B, max_new_tokens), gen_len (B,)).

    ``temperature=0`` (default) is exact greedy argmax — the reference's
    hosted text-generation call decodes greedily (no sampling params,
    backend.py:250-255). ``temperature>0`` switches to top-k Gumbel
    sampling per step (the standard serving sampler), statically — the
    greedy graph carries no sampling ops."""
    prefill_fn, decode_step_fn = model_apply_pair
    b, p = input_ids.shape
    max_len = p + max_new_tokens

    last_logits, cache = prefill_fn(params, input_ids, prompt_len, max_len)

    positions = jnp.arange(max_len)[None, :]          # (1, L)
    prompt_valid = positions < prompt_len[:, None]     # (B, L)

    def pick(logits, i):
        if temperature <= 0.0:  # static branch: pure greedy
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        k = max(1, min(top_k, logits.shape[-1]))
        k_logits, k_idx = jax.lax.top_k(logits, k)
        choice = jax.random.categorical(
            jax.random.fold_in(rng, i),
            k_logits.astype(jnp.float32) / temperature, axis=-1)
        return jnp.take_along_axis(
            k_idx, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)

    def step(carry, i):
        logits, cache, done = carry
        token = pick(logits, i)
        token = jnp.where(done, jnp.int32(eos_token), token)
        emitted = token
        done = done | (token == eos_token)
        # All rows decode at cache index P+i. Rows whose prompt is shorter
        # than P see a small position-id offset; the serving layer keeps
        # buckets tight so the offset stays negligible, and masked padding
        # positions are never attended either way.
        idx = jnp.int32(p + i)
        valid = prompt_valid | (
            (positions >= p) & (positions <= idx)
        )
        logits, cache = decode_step_fn(params, token, idx, cache, valid)
        return (logits, cache, done), emitted

    init_done = jnp.zeros((b,), dtype=bool)
    (_, _, _), tokens = jax.lax.scan(
        step, (last_logits, cache, init_done), jnp.arange(max_new_tokens)
    )
    tokens = tokens.T  # (B, max_new_tokens)
    is_eos = tokens == eos_token
    gen_len = jnp.where(
        is_eos.any(axis=1),
        jnp.argmax(is_eos, axis=1),
        jnp.int32(max_new_tokens),
    )
    return tokens, gen_len
