"""Gaussian blur on device: separable depthwise convolution.

The reference blurs with PIL per request on the host CPU
(backend.py:322-324, SURVEY.md §3.3 "CPU hot spot"). Here the reveal blur is
two 1-D depthwise convs (separable Gaussian) compiled once for a static tap
count; the per-request blur *radius* arrives as data (the kernel weights
vector), so every radius reuses one compiled graph — no recompiles, no PIL.

Matches PIL semantics closely enough for the game's purposes: PIL's
GaussianBlur approximates a Gaussian with box blurs; we use the exact
truncated Gaussian (radius = 3.5 sigma, SciPy/PIL-like truncation).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

# Max radius 15 (backend.py:319) -> ~2.3 sigma·3.5 taps each side at
# sigma≈radius/2... keep a generous static width: 2*31+1 taps.
MAX_TAPS = 63
_HALF = MAX_TAPS // 2


def gaussian_taps(radius: float) -> np.ndarray:
    """Host-side: blur radius -> (MAX_TAPS,) normalized weights.

    PIL's GaussianBlur(radius=r) uses sigma = r; taps beyond the static
    window are truncated (negligible for r <= 15 with 31 taps per side at
    sigma<=15: window covers ±2 sigma... adequate for a reveal effect).
    """
    if radius <= 0.05:
        w = np.zeros(MAX_TAPS, dtype=np.float32)
        w[_HALF] = 1.0
        return w
    sigma = float(radius)
    x = np.arange(-_HALF, _HALF + 1, dtype=np.float32)
    w = np.exp(-0.5 * (x / sigma) ** 2)
    return (w / w.sum()).astype(np.float32)


@jax.jit
def blur_image(image_u8: jax.Array, taps: jax.Array) -> jax.Array:
    """(H, W, 3) uint8 + (MAX_TAPS,) weights -> blurred (H, W, 3) uint8."""
    img = image_u8.astype(jnp.float32)[None]          # (1, H, W, 3)
    c = img.shape[-1]
    # PIL-style border behavior: extend edges, then VALID conv.
    img = jnp.pad(img, ((0, 0), (_HALF, _HALF), (_HALF, _HALF), (0, 0)),
                  mode="edge")
    kh = jnp.tile(taps[:, None, None, None], (1, 1, 1, c))  # (T,1,1,C)
    kw = jnp.tile(taps[None, :, None, None], (1, 1, 1, c))
    dn = jax.lax.conv_dimension_numbers(
        img.shape, kh.shape, ("NHWC", "HWIO", "NHWC")
    )
    out = jax.lax.conv_general_dilated(
        img, kh, window_strides=(1, 1), padding="VALID",
        dimension_numbers=dn, feature_group_count=c,
    )
    out = jax.lax.conv_general_dilated(
        out, kw, window_strides=(1, 1), padding="VALID",
        dimension_numbers=dn, feature_group_count=c,
    )
    return jnp.clip(jnp.round(out[0]), 0, 255).astype(jnp.uint8)


def device_blur(image: np.ndarray, radius: float) -> np.ndarray:
    """Game-facing BlurFn (engine/game.py): host arrays in/out."""
    taps = jnp.asarray(gaussian_taps(radius))
    return np.asarray(blur_image(jnp.asarray(image), taps))
