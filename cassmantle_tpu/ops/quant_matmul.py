"""Pallas int8×int8→int32 matmul + conv3x3 kernels for W8A8 serving.

Why this exists (ISSUE 20; ROADMAP item 4; PAPERS.md Efficient Diffusion
survey): the repo's weights-only w8a16 path (ops/quant.py) halves weight
HBM reads but the MXU still multiplies bf16 and activations still move at
full width. W8A8 closes both gaps: weights AND activations are int8 in
HBM/VMEM, the MXU runs its int8 mode (2× the bf16 MAC rate on v5e-class
chips), and the int32 accumulator is rescaled to fp in a fused epilogue —
per-output-channel weight scale × per-tensor (or per-token, LM) activation
scale, exactly the symmetric scheme ops/quant.py pins algebraically:

    x ≈ s_a · X8,  W ≈ W8 ⊙ s_w   ⇒   x @ W ≈ (X8 @ W8)_i32 · s_a ⊙ s_w

Two kernels, mirroring the repo's Pallas conventions (ops/fused_conv.py):

- ``int8_matmul``: (M, K) × (K, N) grid over (M-tile, N-tile), whole-K
  blocks, int32 MXU accumulation, epilogue = row-scale × col-scale ×
  acc + bias. Per-token activation scales are just a non-constant row
  scale — same kernel, no second code path.
- ``int8_conv3x3``: stride-1 SAME NHWC conv as nine shifted (H·W, C) ×
  (C, F) int8 matmuls per (batch, F-block) program — the im2col-free
  formulation of fused_conv.py, minus the in-kernel GN/SiLU (see below).

The fused GN+SiLU+conv path gets its int8 variant via
``gn_silu_conv3x3_w8a8``: the GN affine + SiLU + activation-quantize
chain runs as one XLA elementwise fusion that WRITES int8 (half the HBM
bytes the bf16 path writes), and the conv reads int8. The normalized
tensor does hit HBM here — unlike the fp fused kernel — because dynamic
per-tensor scaling needs a global absmax before quantizing; with static
calibrated scales the write is still int8-wide, so the traffic trade is
(½·write + ½·read) vs the fp kernel's (0·write + 1·read): even, while
the MXU rate doubles. docs/PERF_NOTES.md "Quantized serving accounting"
carries the full byte math.

fp8 rides the same interface: ``quantize_act``/``quantize_tensor_act``
accept fp8 dtypes (e4m3 grid, ops/quant.py), and the dense/conv entry
points dispatch fp8 leaves to an XLA dot that uses native fp8 MXU
support where the hardware has it (v5p+) and fp32 upcast where it
doesn't — so flipping a pipeline to fp8 is a dtype argument, not a
rewrite.

Parity pinning: ``*_reference`` functions compute the SAME integer math
in plain lax (int32 accumulation, identical epilogue order), and
tests/test_w8a8.py pins kernel-vs-reference in interpret mode on CPU —
tier-1 executes the real kernels, channel padding included.

Dispatch: interpret mode auto-selects off-TPU; shapes whose working set
misses the VMEM budget fall back to the reference (still int8 math, XLA
lowered); the serving-level ``CASSMANTLE_NO_W8A8`` kill switch is read
at pipeline BUILD time (serving/pipeline.py) — reverting bit-exactly to
the fp path requires never having quantized the weights, so the switch
gates the load-time tree transform, not this module's call sites.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from cassmantle_tpu.ops.quant import (
    ActQTensor,
    act_absmax,
    act_scale_from_absmax,
    quantize_act,
)

_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

# Per-program VMEM budget (same conservative bar as ops/fused_conv.py).
VMEM_BUDGET_BYTES = 12 * 1024 * 1024

# int8 MXU tiling: 32 sublanes × 128 lanes is the minimum int8 tile, so
# every padded dim is a multiple of these.
_SUBLANE = 32
_LANE = 128

_BLOCK_M = 128
_BLOCK_N = 128
_CONV_F_CANDIDATES = (256, 128, 64, 32)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def w8a8_disabled() -> bool:
    """Operator kill switch (same parse as CASSMANTLE_NO_FUSED_CONV).
    Consulted at pipeline BUILD time: with the switch set the loaders
    never quantize, modules take the plain branch, and serving is
    bit-exactly the pre-w8a8 path — which is the whole point of a
    quantization kill switch (an already-int8 tree can't round-trip
    back)."""
    return os.environ.get("CASSMANTLE_NO_W8A8", "").lower() \
        not in ("", "0", "false", "no", "off")


def describe(calibrated: bool, sites: int) -> str:
    """One-line w8a8 execution-strategy description for pipeline startup
    logs (the fused_conv.describe pattern)."""
    scales = "static calibrated" if calibrated else "dynamic absmax"
    return (f"w8a8: int8 Pallas matmul/conv active at {sites} sites, "
            f"{scales} activation scales")


def round_up(n: int, mult: int) -> int:
    if mult <= 0:
        return n
    return ((n + mult - 1) // mult) * mult


def _pad_dim(t: jax.Array, axis: int, to: int) -> jax.Array:
    pad = to - t.shape[axis]
    if pad == 0:
        return t
    widths = [(0, 0)] * t.ndim
    widths[axis] = (0, pad)
    return jnp.pad(t, widths)


# ---------------------------------------------------------------------------
# int8 matmul
# ---------------------------------------------------------------------------

def _matmul_blocks(mp: int, kp: int, np_: int):
    """(M-block, N-block) fitting the VMEM budget, or None."""
    bm = _BLOCK_M if mp >= _BLOCK_M else mp
    bn = _BLOCK_N if np_ >= _BLOCK_N else np_
    while bm >= _SUBLANE:
        used = (bm * kp            # x block, int8
                + kp * bn          # w block, int8
                + bm * bn * 4      # int32/fp32 accumulator
                + 2 * bm * bn * 4  # double-buffered output blocks
                + bm * 4 + 2 * bn * 4 * 2)  # scales + bias
        if used <= VMEM_BUDGET_BYTES:
            return bm, bn
        bm //= 2
    return None


def int8_matmul_ok(m: int, k: int, n: int) -> bool:
    """Shapes the Pallas kernel handles (others → lax reference, same
    integer math)."""
    mp = round_up(m, _SUBLANE)
    kp = round_up(k, _LANE)
    np_ = round_up(n, _LANE)
    return _matmul_blocks(mp, kp, np_) is not None


def _matmul_kernel(x_ref, w_ref, sr_ref, sc_ref, bias_ref, o_ref):
    acc = jax.lax.dot_general(
        x_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    out = acc.astype(jnp.float32) * sr_ref[:] * sc_ref[:]
    out = out + bias_ref[:]
    o_ref[:] = out.astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("out_dtype", "interpret", "bm", "bn"))
def _matmul_padded(x_q, w_q, row_scale, col_scale, bias, out_dtype,
                   interpret: bool, bm: int, bn: int):
    mp, kp = x_q.shape
    np_ = w_q.shape[-1]
    grid = (mp // bm, np_ // bn)
    flops = 2.0 * mp * kp * np_
    return pl.pallas_call(
        _matmul_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, kp), lambda i, j: (i, 0)),
            pl.BlockSpec((kp, bn), lambda i, j: (0, j)),
            pl.BlockSpec((bm, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), out_dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=flops,
            bytes_accessed=mp * kp + kp * np_
            + mp * np_ * jnp.dtype(out_dtype).itemsize,
            transcendentals=0,
        ),
        interpret=interpret,
    )(x_q, w_q, row_scale, col_scale, bias)


def int8_matmul_reference(x_q, w_q, row_scale, col_scale, bias,
                          out_dtype=jnp.float32):
    """Pure-lax reference: identical int32 accumulation and epilogue
    order as the kernel (parity is near-bitwise; fp32 epilogue rounding
    is the only freedom)."""
    acc = jax.lax.dot_general(
        x_q, w_q, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    out = acc.astype(jnp.float32) * row_scale * col_scale
    out = out + bias
    return out.astype(out_dtype)


def int8_matmul(x_q, w_q, row_scale, col_scale, bias=None,
                out_dtype=jnp.float32, interpret=None):
    """(M, K) int8 × (K, N) int8 → (M, N) ``out_dtype`` with the scaled
    epilogue ``acc_i32 · row_scale · col_scale + bias``.

    ``row_scale`` is (M, 1) fp32 (per-token activation scales, or a
    broadcast per-tensor scalar), ``col_scale`` (1, N) fp32 (per-output-
    channel weight scale, activation scale may be pre-folded in). Pads
    M/K/N up to int8 MXU tiles (zero int8 pads contribute zero to the
    int32 dot; pad rows/cols are sliced off).
    """
    if interpret is None:
        interpret = not _on_tpu()
    m, k = x_q.shape
    n = w_q.shape[-1]
    if bias is None:
        bias = jnp.zeros((n,), jnp.float32)
    bias = bias.astype(jnp.float32).reshape(1, n)
    row_scale = jnp.broadcast_to(
        jnp.asarray(row_scale, jnp.float32), (m, 1))
    col_scale = jnp.asarray(col_scale, jnp.float32).reshape(1, n)
    mp = round_up(m, _SUBLANE)
    kp = round_up(k, _LANE)
    np_ = round_up(n, _LANE)
    blocks = _matmul_blocks(mp, kp, np_)
    if blocks is None:
        return int8_matmul_reference(x_q, w_q, row_scale, col_scale,
                                     bias, out_dtype)
    bm, bn = blocks
    # re-pad so the grid tiles exactly (Pallas grids are exact)
    mp = round_up(mp, bm)
    np_ = round_up(np_, bn)
    xq = _pad_dim(_pad_dim(x_q, 0, mp), 1, kp)
    wq = _pad_dim(_pad_dim(w_q, 0, kp), 1, np_)
    sr = _pad_dim(row_scale, 0, mp)
    sc = _pad_dim(col_scale, 1, np_)
    bp = _pad_dim(bias, 1, np_)
    out = _matmul_padded(xq, wq, sr, sc, bp, jnp.dtype(out_dtype),
                         bool(interpret), bm, bn)
    return out[:m, :n]


# ---------------------------------------------------------------------------
# w8a8 dense entry point (QDense in models/layers.py dispatches here)
# ---------------------------------------------------------------------------

def _dense_scales(x, q: ActQTensor, per_token: bool):
    """(quantized activations, row_scale (M,1)) for a flattened (M, K)
    activation block."""
    qdtype = q.data.dtype
    if per_token or q.act_scale is None:
        scale = act_scale_from_absmax(
            act_absmax(x, per_token=per_token), qdtype)
    else:
        scale = q.act_scale
    x_q = quantize_act(x, scale, qdtype)
    row = jnp.asarray(scale, jnp.float32)
    if row.ndim:
        row = row.reshape(x.shape[0], 1)          # per-token (M, 1)
    row = jnp.broadcast_to(row, (x.shape[0], 1))  # per-tensor scalar
    return x_q, row


def w8a8_dense(x, q: ActQTensor, bias=None, out_dtype=None,
               per_token: bool = False, interpret=None):
    """Dense layer on a quantized leaf: quantize activations (static
    calibrated scale when the leaf carries one, dynamic absmax
    otherwise; per-token row scales for the LM path), run the int8
    kernel, epilogue in fp32, cast to ``out_dtype`` (default: x.dtype).

    fp8 leaves take the XLA-dot path: native fp8 MXU where hardware
    supports it (TPU), fp32 upcast elsewhere — same interface either
    way."""
    out_dtype = out_dtype or x.dtype
    lead = x.shape[:-1]
    k = x.shape[-1]
    n = q.data.shape[-1]
    x2 = x.reshape(-1, k)
    col_scale = q.scale.reshape(1, n)
    if jnp.dtype(q.data.dtype) != jnp.int8:   # fp8 leaf
        qdtype = q.data.dtype
        if per_token or q.act_scale is None:
            a_scale = act_scale_from_absmax(
                act_absmax(x2, per_token=per_token), qdtype)
        else:
            a_scale = q.act_scale
        x_q = quantize_act(x2, a_scale, qdtype)
        compute = qdtype if _on_tpu() else jnp.float32
        acc = jax.lax.dot_general(
            x_q.astype(compute), q.data.astype(compute),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        out = acc * jnp.asarray(a_scale, jnp.float32).reshape(-1, 1) \
            * col_scale
        if bias is not None:
            out = out + bias.astype(jnp.float32).reshape(1, n)
        return out.astype(out_dtype).reshape(lead + (n,))
    x_q, row_scale = _dense_scales(x2, q, per_token)
    if int8_matmul_ok(x2.shape[0], k, n):
        out = int8_matmul(x_q, q.data, row_scale, col_scale, bias,
                          out_dtype=out_dtype, interpret=interpret)
    else:
        b = jnp.zeros((1, n), jnp.float32) if bias is None \
            else bias.astype(jnp.float32).reshape(1, n)
        out = int8_matmul_reference(x_q, q.data, row_scale, col_scale,
                                    b, out_dtype)
    return out.reshape(lead + (n,))


# ---------------------------------------------------------------------------
# int8 conv3x3 (stride-1 SAME, NHWC) + the fused GN+SiLU int8 variant
# ---------------------------------------------------------------------------

def _conv_blocks(h: int, w: int, c: int, f: int):
    """Output-channel block for the whole-image conv program, or None
    when even the smallest block misses the VMEM budget."""
    cands = [b for b in _CONV_F_CANDIDATES if f % b == 0]
    if f <= 512 and f not in cands:
        cands.insert(0, f)
    for bf in cands:
        used = ((h + 2) * (w + 2) * c       # padded int8 image
                + 9 * c * bf                # int8 kernel block
                + h * w * bf * 4            # int32/fp32 accumulator
                + 2 * h * w * bf * 4        # double-buffered out blocks
                + 4 * bf * 2)               # scale + bias rows
        if used <= VMEM_BUDGET_BYTES:
            return bf
    return None


def int8_conv_ok(x_q: jax.Array, kernel: jax.Array) -> bool:
    """NHWC (B, H, W, C) int8 × HWIO (3, 3, C, F) int8, whole image per
    program. Covers every SD1.5-512 and SDXL-1024 ResBlock shape (the
    int8 image is small: 128·128·320 ≈ 5 MB); misses fall back to the
    lax reference."""
    if x_q.ndim != 4 or kernel.ndim != 4:
        return False
    b, h, w, c = x_q.shape
    kh, kw, kc, f = kernel.shape
    if (kh, kw) != (3, 3) or kc != c:
        return False
    if h < 3 or w < 3:
        return False
    return _conv_blocks(h, w, c, f) is not None


def _conv_kernel(x_ref, k_ref, sc_ref, bias_ref, o_ref, *,
                 h: int, w: int):
    c = x_ref.shape[-1]
    bf = k_ref.shape[-1]
    acc = jnp.zeros((h * w, bf), jnp.int32)
    for dy in range(3):
        for dx in range(3):
            patch = x_ref[0, dy:dy + h, dx:dx + w, :]
            patch = patch.reshape(h * w, c)
            acc += jax.lax.dot_general(
                patch, k_ref[dy, dx],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
    out = acc.astype(jnp.float32) * sc_ref[:]
    out = out + bias_ref[:]
    o_ref[0] = out.reshape(h, w, bf).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("out_dtype", "interpret", "bf"))
def _conv_padded(x_q, kernel, col_scale, bias, out_dtype,
                 interpret: bool, bf: int):
    bsz, hp, wp, c = x_q.shape
    h, w = hp - 2, wp - 2
    f = kernel.shape[-1]
    grid = (bsz, f // bf)
    kern = functools.partial(_conv_kernel, h=h, w=w)
    flops = 2.0 * bsz * h * w * 9 * c * f
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, hp, wp, c), lambda bi, j: (bi, 0, 0, 0)),
            pl.BlockSpec((3, 3, c, bf), lambda bi, j: (0, 0, 0, j)),
            pl.BlockSpec((1, bf), lambda bi, j: (0, j)),
            pl.BlockSpec((1, bf), lambda bi, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((1, h, w, bf),
                               lambda bi, j: (bi, 0, 0, j)),
        out_shape=jax.ShapeDtypeStruct((bsz, h, w, f), out_dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=flops,
            bytes_accessed=bsz * hp * wp * c + 9 * c * f
            + bsz * h * w * f * jnp.dtype(out_dtype).itemsize,
            transcendentals=0,
        ),
        interpret=interpret,
    )(x_q, kernel, col_scale, bias)


def int8_conv3x3_reference(x_q, kernel, col_scale, bias,
                           out_dtype=jnp.float32):
    """Pure-lax reference with the kernel's exact integer math: SAME
    zero padding, nine shifted int8 dots accumulated in int32, fp32
    epilogue."""
    b, h, w, c = x_q.shape
    f = kernel.shape[-1]
    xp = jnp.pad(x_q, ((0, 0), (1, 1), (1, 1), (0, 0)))
    acc = jnp.zeros((b, h, w, f), jnp.int32)
    for dy in range(3):
        for dx in range(3):
            patch = jax.lax.dynamic_slice(
                xp, (0, dy, dx, 0), (b, h, w, c))
            acc += jax.lax.dot_general(
                patch, kernel[dy, dx],
                (((3,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
    out = acc.astype(jnp.float32) * col_scale.reshape(1, 1, 1, f)
    out = out + bias.astype(jnp.float32).reshape(1, 1, 1, f)
    return out.astype(out_dtype)


def int8_conv3x3(x_q, kernel, col_scale, bias, out_dtype=jnp.float32,
                 interpret=None):
    """(B, H, W, C) int8 NHWC conv with (3, 3, C, F) int8 HWIO weights,
    stride-1 SAME, epilogue ``acc_i32 · col_scale + bias`` (col_scale =
    activation scale × per-channel weight scale, pre-folded fp32
    (F,))."""
    if interpret is None:
        interpret = not _on_tpu()
    f = kernel.shape[-1]
    col = jnp.asarray(col_scale, jnp.float32).reshape(1, f)
    b = bias.astype(jnp.float32).reshape(1, f)
    if not int8_conv_ok(x_q, kernel):
        return int8_conv3x3_reference(x_q, kernel, col, b, out_dtype)
    bf = _conv_blocks(x_q.shape[1], x_q.shape[2], x_q.shape[3], f)
    xp = jnp.pad(x_q, ((0, 0), (1, 1), (1, 1), (0, 0)))
    return _conv_padded(xp, kernel, col, b, jnp.dtype(out_dtype),
                        bool(interpret), bf)


def gn_silu_conv3x3_w8a8(
    x: jax.Array,          # (B, H, W, C) activations
    a: jax.Array,          # (B, C) fp32 GroupNorm affine scale
    b: jax.Array,          # (B, C) fp32 GroupNorm affine shift
    q: ActQTensor,         # (3, 3, C, F) quantized HWIO conv weights
    bias: jax.Array,       # (F,)
    *,
    pad_to: int = 0,
    interpret=None,
) -> jax.Array:
    """int8 variant of the fused GN+SiLU+conv contract
    (ops/fused_conv.py): GN affine + SiLU + quantize fuse into one XLA
    elementwise pass writing int8, then the int8 Pallas conv. Static
    calibrated activation scale when the leaf carries one, dynamic
    global absmax otherwise. ``pad_to`` rounds C/F up exactly like the
    fp kernel (int8 zero pads are exact zeros through the integer
    dot)."""
    dt = x.dtype
    h = x * a[:, None, None, :].astype(dt) + b[:, None, None, :].astype(dt)
    h = jax.nn.silu(h)
    qdtype = q.data.dtype
    if q.act_scale is None:
        a_scale = act_scale_from_absmax(act_absmax(h), qdtype)
    else:
        a_scale = q.act_scale
    f = q.data.shape[-1]
    col_scale = (jnp.asarray(a_scale, jnp.float32)
                 * q.scale.reshape(f))
    if jnp.dtype(qdtype) != jnp.int8:   # fp8 leaf → XLA dot path
        h_q = quantize_act(h, a_scale, qdtype)
        compute = qdtype if _on_tpu() else jnp.float32
        out = jax.lax.conv_general_dilated(
            h_q.astype(compute), q.data.astype(compute),
            window_strides=(1, 1), padding=((1, 1), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.float32,
        )
        out = out * col_scale.reshape(1, 1, 1, f) \
            + bias.astype(jnp.float32).reshape(1, 1, 1, f)
        return out.astype(dt)
    h_q = quantize_act(h, a_scale, jnp.int8)
    c = h_q.shape[-1]
    cp = round_up(c, pad_to)
    fp = round_up(f, pad_to)
    hq = _pad_dim(h_q, -1, cp)
    kq = q.data
    if cp != c:
        kq = jnp.pad(kq, ((0, 0), (0, 0), (0, cp - c), (0, 0)))
    kq = _pad_dim(kq, -1, fp)
    colp = _pad_dim(col_scale.reshape(1, f), -1, fp).reshape(fp)
    biasp = _pad_dim(bias.astype(jnp.float32).reshape(1, f),
                     -1, fp).reshape(fp)
    out = int8_conv3x3(hq, kq, colp, biasp, out_dtype=dt,
                       interpret=interpret)
    return out[..., :f]


def gn_silu_conv3x3_w8a8_reference(x, a, b, q: ActQTensor, bias):
    """Whole-contract lax reference (quantize + integer conv + epilogue,
    no Pallas) for parity tests."""
    dt = x.dtype
    h = x * a[:, None, None, :].astype(dt) + b[:, None, None, :].astype(dt)
    h = jax.nn.silu(h)
    if q.act_scale is None:
        a_scale = act_scale_from_absmax(act_absmax(h), q.data.dtype)
    else:
        a_scale = q.act_scale
    h_q = quantize_act(h, a_scale, q.data.dtype)
    f = q.data.shape[-1]
    col = (jnp.asarray(a_scale, jnp.float32)
           * q.scale.reshape(f)).reshape(1, f)
    return int8_conv3x3_reference(
        h_q, q.data, col, bias, out_dtype=dt)
