from cassmantle_tpu.ops.attention import multi_head_attention  # noqa: F401
