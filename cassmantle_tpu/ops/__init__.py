"""Device kernels + the host-side scoring table.

The package import itself stays jax-free: ``ops.embed_table`` must be
importable from --fake workers (bench.py rooms_load / overload drills)
that never pay — or hang on — an accelerator backend import, the same
contract as serving/fake_scorer.py. The ``multi_head_attention``
re-export resolves lazily (PEP 562) so ``from cassmantle_tpu.ops import
multi_head_attention`` keeps working without an eager ``ops.attention``
(jax) import at package-import time.
"""


def __getattr__(name):
    if name == "multi_head_attention":
        from cassmantle_tpu.ops.attention import multi_head_attention

        return multi_head_attention
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
