"""Pallas TPU flash attention: blockwise online-softmax, O(N) memory.

The UNet's self-attention over image tokens is the framework's "long
sequence" axis (SURVEY.md §5.7): 4,096 tokens at 512² latents, 16k+ at
SDXL-1024. This kernel tiles Q into VMEM blocks and streams K/V blocks
through the grid's innermost dimension, keeping the running max/denominator
(online softmax) in fp32 scratch — attention never materializes the (S, S)
score matrix in HBM.

Layout: callers pass q/k/v as (..., S, H, D); the wrapper folds batch×heads
into the leading grid dimension. Scores accumulate in fp32 on the MXU
(``preferred_element_type``); probabilities are cast back to the value dtype
for the P·V matmul so both matmuls hit the MXU in bf16 on TPU.

Dispatch rules (``flash_attention_ok``): self-attention (no mask), sequence
divisible into blocks, head_dim bounded. Cross-attention with ragged
S_k (the UNet's text context, S_k=77) takes :func:`flash_cross_attention`:
K/V pad to one 128-wide block and the kernel masks the pad columns via a
static ``kv_len`` — the score matrix (4096×77 per head at 512² level 0,
materialized to HBM on the XLA path) never leaves VMEM. Tiny text-model
sequences stay on the XLA path where fusion is already optimal.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams across releases; take
# whichever the installed version exports.
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")

# 1024-blocks measured ~2x faster than 512 at the UNet's level-0 site
# (S=4096, d=40, bh=64) on v5e: fewer grid programs amortize the per-
# program MXU setup over more work. (1024, 40)-bf16 q/k/v tiles plus two
# (1024, 1024)-fp32 intermediates stay well inside VMEM. Env-tunable so
# a hardware window can sweep block sizes without an edit-reinstall
# cycle (tools/profile_unet.py A/Bs per-resolution; each sweep point is
# its own process, so import-time read is right).
import os as _os

def _block_env(name: str, default: int) -> int:
    v = int(_os.environ.get(name, str(default)))
    if v < 128 or v % 128:
        # fail at import, not mid-sweep: 0 would ZeroDivision in the
        # dispatch gate, negatives slip through it into a negative
        # Pallas grid, and non-lane-multiples can't tile the MXU
        raise ValueError(f"{name}={v}: need a positive multiple of 128")
    return v


BLOCK_Q = _block_env("CASSMANTLE_FLASH_BLOCK_Q", 1024)
BLOCK_K = _block_env("CASSMANTLE_FLASH_BLOCK_K", 1024)
MAX_HEAD_DIM = 256
_NEG_INF = -1e30


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def flash_attention_ok(q: jax.Array, k: jax.Array) -> bool:
    """Shapes the kernel handles profitably (others -> XLA path)."""
    sq, sk, d = q.shape[-3], k.shape[-3], q.shape[-1]
    return (
        sq % BLOCK_Q == 0
        and sk % BLOCK_K == 0
        and sq >= BLOCK_Q
        and sk >= BLOCK_K
        and d <= MAX_HEAD_DIM
        and q.ndim >= 4
    )


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                  scale: float, num_k_blocks: int, block_k: int,
                  kv_len: int = 0):
    k_idx = pl.program_id(2)

    @pl.when(k_idx == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0]                      # (BQ, D)
    k = k_ref[0]                      # (BK, D)
    v = v_ref[0]                      # (BK, D)

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                          # (BQ, BK) fp32

    if kv_len:  # static: ragged K/V padded into the last block
        col = (jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
               + k_idx * block_k)
        s = jnp.where(col < kv_len, s, _NEG_INF)

    m_prev = m_ref[:, :1]             # (BQ, 1)
    l_prev = l_ref[:, :1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)   # (BQ, 1)
    p = jnp.exp(s - m_new)            # (BQ, BK) fp32
    l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)

    pv = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                  # (BQ, D) fp32
    acc_ref[:] = acc_ref[:] * alpha + pv
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(k_idx == num_k_blocks - 1)
    def _finish():
        o_ref[0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=(
    "scale", "interpret", "block_q", "block_k", "kv_len"))
def _flash_bhsd(q: jax.Array, k: jax.Array, v: jax.Array, scale: float,
                interpret: bool, block_q: int = BLOCK_Q,
                block_k: int = BLOCK_K, kv_len: int = 0) -> jax.Array:
    """(BH, S, D) flash attention. ``kv_len`` > 0 marks K/V as padded to
    the block grid with only the first kv_len columns valid."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq, nk = sq // block_q, sk // block_k

    grid = (bh, nq, nk)
    kernel = functools.partial(_flash_kernel, scale=scale, num_k_blocks=nk,
                               block_k=block_k, kv_len=kv_len)
    # Only the k-block axis carries state (online-softmax scratch); the
    # batch*heads and q-block axes are embarrassingly parallel.
    compiler_params = _CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"),
    )
    flops = 2 * 2 * bh * sq * sk * d  # QK^T + PV
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # running max
            pltpu.VMEM((block_q, 128), jnp.float32),   # running denom
            pltpu.VMEM((block_q, d), jnp.float32),     # output accumulator
        ],
        compiler_params=compiler_params,
        cost_estimate=pl.CostEstimate(
            flops=flops,
            bytes_accessed=(2 * bh * sq * d + 2 * bh * sk * d) * 2,
            transcendentals=bh * sq * sk,
        ),
        interpret=interpret,
    )(q, k, v)


def _fold_heads(t, s, d):
    t = jnp.moveaxis(t, -2, -3)                   # (..., H, S, D)
    return t.reshape((-1, s, d))


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    scale=None, interpret=None,
                    block_q: int = BLOCK_Q,
                    block_k: int = BLOCK_K) -> jax.Array:
    """(..., S, H, D) self-attention via the Pallas kernel.

    ``block_q``/``block_k`` override the default tiles — the wide-head
    dispatch (``flash_wide_ok``) shrinks them so fat single-head VMEM
    working sets (the VAE mid-block's D=512) still fit."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = not _on_tpu()

    *batch, sq, h, d = q.shape
    sk = k.shape[-3]

    qf = _fold_heads(q, sq, d)
    kf, vf = _fold_heads(k, sk, d), _fold_heads(v, sk, d)
    out = _flash_bhsd(qf, kf, vf, float(scale), bool(interpret),
                      block_q=block_q, block_k=block_k)
    out = out.reshape(tuple(batch) + (h, sq, d))
    return jnp.moveaxis(out, -3, -2)              # (..., S, H, D)


# Wide-head self-attention: the VAE mid block attends single-head over
# H·W image tokens at the FULL channel width (D = 512 at production
# geometry) — S hits 16,384 at SDXL's 128² latent, where the XLA path
# materializes a 16k×16k fp32 score matrix (1 GB per image) in HBM. The
# main kernel's 1024-tiles would blow VMEM at D=512 (two (BQ, BK) fp32
# intermediates + three (BK, D) operand tiles), so this dispatch runs
# the SAME kernel at 512-blocks: ~5 MB/program working set, scores
# never leave VMEM. Gated to D above MAX_HEAD_DIM so it can't shadow
# the tuned main path.
WIDE_BLOCK = 512
MAX_WIDE_HEAD_DIM = 512


def flash_wide_ok(q: jax.Array, k: jax.Array) -> bool:
    """Self-attention shapes for the wide-head (VAE mid-block) variant:
    D past the main kernel's bound but within the 512-block VMEM
    budget, and a sequence that tiles into 512-blocks."""
    sq, sk, d = q.shape[-3], k.shape[-3], q.shape[-1]
    return (
        sq == sk
        and sq % WIDE_BLOCK == 0
        and sq >= WIDE_BLOCK
        and MAX_HEAD_DIM < d <= MAX_WIDE_HEAD_DIM
        and q.ndim >= 4
    )


# Cross-attention K/V blocks: the text context is short (77 for CLIP), so
# one lane-width block holds it after padding; queries keep large blocks.
CROSS_BLOCK_K = 128
MAX_CROSS_KV = 1024


def flash_cross_ok(q: jax.Array, k: jax.Array) -> bool:
    """Ragged-K/V shapes worth padding into the kernel: long aligned
    query axis (image tokens), short unaligned context. The XLA path
    for these materializes a (S_q, S_k) score matrix per head in HBM;
    here it stays in VMEM."""
    sq, sk, d = q.shape[-3], k.shape[-3], q.shape[-1]
    return (
        sq % BLOCK_Q == 0
        and sq >= BLOCK_Q
        and 0 < sk <= MAX_CROSS_KV
        and d <= MAX_HEAD_DIM
        and q.ndim >= 4
        # anything the plain kernel takes (sk in full BLOCK_K blocks)
        # stays there; this path covers every remaining short-context
        # shape, aligned-to-128 included (pad=0, kv_len exact)
        and not flash_attention_ok(q, k)
    )


def flash_cross_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                          scale=None, interpret=None) -> jax.Array:
    """(..., S_q, H, D) x (..., S_k, H, D) cross-attention with ragged
    S_k: K/V zero-pad to the block width and the kernel masks pad
    columns via the static ``kv_len`` (exact — pad keys get -inf scores
    before the online softmax, so they contribute nothing)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = not _on_tpu()

    *batch, sq, h, d = q.shape
    sk = k.shape[-3]
    pad = (-sk) % CROSS_BLOCK_K
    widths = [(0, 0)] * (k.ndim - 3) + [(0, pad), (0, 0), (0, 0)]
    kp = jnp.pad(k, widths)
    vp = jnp.pad(v, widths)

    qf = _fold_heads(q, sq, d)
    kf, vf = _fold_heads(kp, sk + pad, d), _fold_heads(vp, sk + pad, d)
    out = _flash_bhsd(qf, kf, vf, float(scale), bool(interpret),
                      block_k=CROSS_BLOCK_K, kv_len=sk)
    out = out.reshape(tuple(batch) + (h, sq, d))
    return jnp.moveaxis(out, -3, -2)
