"""Pallas TPU flash attention: blockwise online-softmax, O(N) memory.

The UNet's self-attention over image tokens is the framework's "long
sequence" axis (SURVEY.md §5.7): 4,096 tokens at 512² latents, 16k+ at
SDXL-1024. This kernel tiles Q into VMEM blocks and streams K/V blocks
through the grid's innermost dimension, keeping the running max/denominator
(online softmax) in fp32 scratch — attention never materializes the (S, S)
score matrix in HBM.

Layout: callers pass q/k/v as (..., S, H, D); the wrapper folds batch×heads
into the leading grid dimension. Scores accumulate in fp32 on the MXU
(``preferred_element_type``); probabilities are cast back to the value dtype
for the P·V matmul so both matmuls hit the MXU in bf16 on TPU.

Dispatch rules (``flash_attention_ok``): self-attention (no mask), sequence
divisible into blocks, head_dim bounded — everything else (cross-attention
with S_k=77, tiny text sequences) stays on the XLA path where fusion is
already optimal.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# 1024-blocks measured ~2x faster than 512 at the UNet's level-0 site
# (S=4096, d=40, bh=64) on v5e: fewer grid programs amortize the per-
# program MXU setup over more work. (1024, 40)-bf16 q/k/v tiles plus two
# (1024, 1024)-fp32 intermediates stay well inside VMEM.
BLOCK_Q = 1024
BLOCK_K = 1024
MAX_HEAD_DIM = 256
_NEG_INF = -1e30


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def flash_attention_ok(q: jax.Array, k: jax.Array) -> bool:
    """Shapes the kernel handles profitably (others -> XLA path)."""
    sq, sk, d = q.shape[-3], k.shape[-3], q.shape[-1]
    return (
        sq % BLOCK_Q == 0
        and sk % BLOCK_K == 0
        and sq >= BLOCK_Q
        and sk >= BLOCK_K
        and d <= MAX_HEAD_DIM
        and q.ndim >= 4
    )


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                  scale: float, num_k_blocks: int):
    k_idx = pl.program_id(2)

    @pl.when(k_idx == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0]                      # (BQ, D)
    k = k_ref[0]                      # (BK, D)
    v = v_ref[0]                      # (BK, D)

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                          # (BQ, BK) fp32

    m_prev = m_ref[:, :1]             # (BQ, 1)
    l_prev = l_ref[:, :1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)   # (BQ, 1)
    p = jnp.exp(s - m_new)            # (BQ, BK) fp32
    l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)

    pv = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                  # (BQ, D) fp32
    acc_ref[:] = acc_ref[:] * alpha + pv
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(k_idx == num_k_blocks - 1)
    def _finish():
        o_ref[0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def _flash_bhsd(q: jax.Array, k: jax.Array, v: jax.Array, scale: float,
                interpret: bool) -> jax.Array:
    """(BH, S, D) flash attention."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq, nk = sq // BLOCK_Q, sk // BLOCK_K

    grid = (bh, nq, nk)
    kernel = functools.partial(_flash_kernel, scale=scale, num_k_blocks=nk)
    # Only the k-block axis carries state (online-softmax scratch); the
    # batch*heads and q-block axes are embarrassingly parallel.
    compiler_params = pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"),
    )
    flops = 2 * 2 * bh * sq * sk * d  # QK^T + PV
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, BLOCK_Q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, BLOCK_K, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, BLOCK_K, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, BLOCK_Q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((BLOCK_Q, 128), jnp.float32),   # running max
            pltpu.VMEM((BLOCK_Q, 128), jnp.float32),   # running denom
            pltpu.VMEM((BLOCK_Q, d), jnp.float32),     # output accumulator
        ],
        compiler_params=compiler_params,
        cost_estimate=pl.CostEstimate(
            flops=flops,
            bytes_accessed=(2 * bh * sq * d + 2 * bh * sk * d) * 2,
            transcendentals=bh * sq * sk,
        ),
        interpret=interpret,
    )(q, k, v)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    scale=None, interpret=None) -> jax.Array:
    """(..., S, H, D) self-attention via the Pallas kernel."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = not _on_tpu()

    *batch, sq, h, d = q.shape
    sk = k.shape[-3]

    def fold(t, s):
        t = jnp.moveaxis(t, -2, -3)               # (..., H, S, D)
        return t.reshape((-1, s, d))

    qf, kf, vf = fold(q, sq), fold(k, sk), fold(v, sk)
    out = _flash_bhsd(qf, kf, vf, float(scale), bool(interpret))
    out = out.reshape(tuple(batch) + (h, sq, d))
    return jnp.moveaxis(out, -3, -2)              # (..., S, H, D)
