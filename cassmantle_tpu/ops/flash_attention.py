"""Pallas flash attention (placeholder until the kernel lands).

The real blockwise online-softmax kernel is task 5; this stub keeps the
dispatch seam in ops/attention.py honest: ``flash_attention_ok`` returns
False so all callers use the XLA path.
"""

from __future__ import annotations

import jax


def flash_attention_ok(q: jax.Array, k: jax.Array) -> bool:
    return False


def flash_attention(q, k, v, scale=None):  # pragma: no cover
    raise NotImplementedError("pallas flash attention lands in ops task 5")
