"""DDIM sampler as a jit-compiled ``lax.scan``.

Replaces the reference's remote txt2img call (backend.py:270-295) with an
on-device denoise loop: the entire 50-step trajectory compiles to ONE XLA
computation — no host round-trips between steps, no data-dependent Python
control flow (SURVEY.md §7 stage 3). Classifier-free guidance runs the
conditional and unconditional halves in a single 2B batch so the UNet's
matmuls stay large for the MXU.

Schedule: Stable Diffusion's "scaled linear" beta schedule (1000 train
steps), strided to ``num_steps`` inference steps; eta=0 (deterministic DDIM)
by default, eta>0 adds the stochastic DDPM-style term.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp



def alpha_bars_full(
    num_train_steps: int = 1000,
    beta_start: float = 0.00085,
    beta_end: float = 0.012,
):
    """ᾱ_t for SD's scaled-linear beta schedule, fp64 numpy (host-side).

    The single source of the schedule constants — every sampler kind
    (DDIM here, Euler/DPM++ in ops/samplers.py) derives from this so
    they all integrate the same discretization of the same ODE.
    """
    import numpy as np

    betas = np.linspace(beta_start**0.5, beta_end**0.5, num_train_steps,
                        dtype=np.float64) ** 2
    return np.cumprod(1.0 - betas)


def strided_timesteps(num_steps: int, num_train_steps: int = 1000):
    """Descending int32 inference timesteps, diffusers "leading" spacing
    (t = i·stride)."""
    import numpy as np

    stride = num_train_steps // num_steps
    return (np.arange(num_steps) * stride)[::-1].astype(np.int32)


@dataclasses.dataclass(frozen=True)
class DDIMSchedule:
    """Precomputed per-inference-step coefficients (host-side, tiny)."""

    timesteps: jnp.ndarray        # (T,) int32, descending
    alpha_bars: jnp.ndarray       # (T,) float32 ᾱ_t
    alpha_bars_prev: jnp.ndarray  # (T,) float32 ᾱ_{t-1}

    @staticmethod
    def create(
        num_steps: int,
        num_train_steps: int = 1000,
        beta_start: float = 0.00085,
        beta_end: float = 0.012,
        start: int = 0,
    ) -> "DDIMSchedule":
        """``start`` > 0 drops the first inference steps (img2img tails)."""
        import numpy as np

        ab_full = alpha_bars_full(num_train_steps, beta_start, beta_end)
        ts = strided_timesteps(num_steps, num_train_steps)[start:]
        ab = ab_full[ts].astype(np.float32)
        ab_prev = np.concatenate(
            [ab_full[ts[1:]], [1.0]]
        ).astype(np.float32)
        return DDIMSchedule(
            timesteps=jnp.asarray(ts),
            alpha_bars=jnp.asarray(ab),
            alpha_bars_prev=jnp.asarray(ab_prev),
        )


def ddim_update(x, eps, a_t, a_prev):
    """One deterministic DDIM transition x_t -> x_{t-1} (eta = 0)."""
    x0 = (x - jnp.sqrt(1.0 - a_t) * eps) / jnp.sqrt(a_t)
    dir_xt = jnp.sqrt(1.0 - a_prev) * eps
    return jnp.sqrt(a_prev) * x0 + dir_xt


def ddim_sample_deepcache(
    denoise_full: Callable,       # (x, t) -> (eps, deep_features)
    denoise_shallow: Callable,    # (x, t, deep_features) -> eps
    latents: jax.Array,
    schedule: DDIMSchedule,
) -> jax.Array:
    """DDIM with deep-feature reuse (DeepCache-style serving): steps run
    in pairs — a FULL UNet pass whose deepest-levels output is cached,
    then a SHALLOW pass (level-0 blocks only) reusing it. Deep
    activations vary slowly across adjacent steps, so quality stays
    near the full trajectory at ~60% of the compute (models/unet.py
    documents the split). Deterministic (eta=0); even step count
    required."""
    n = schedule.timesteps.shape[0]
    assert n % 2 == 0, f"deepcache pairing needs an even step count, got {n}"

    def pack(a):
        return a.reshape(n // 2, 2)

    def pair_step(x, per):
        t, a_t, a_prev = per
        eps, deep = denoise_full(x, t[0])
        x = ddim_update(x, eps, a_t[0], a_prev[0])
        eps = denoise_shallow(x, t[1], deep)
        x = ddim_update(x, eps, a_t[1], a_prev[1])
        return x, None

    final, _ = jax.lax.scan(
        pair_step, latents,
        (pack(schedule.timesteps), pack(schedule.alpha_bars),
         pack(schedule.alpha_bars_prev)),
    )
    return final


# -- encoder propagation (Faster Diffusion, PAPERS.md) -----------------------
#
# The UNet's ENCODER (conv_in + down levels + mid block) drifts slowly
# across adjacent denoise steps; the decoder (up path) is what turns the
# current x_t into eps. Encoder propagation runs the full UNet only at
# KEY steps, captures the encoder feature cache (skip stack + up-path
# entry, models/unet.py ``return_skips``), and at the propagated steps
# in between runs ONLY the decoder against that cache. Because the
# decoder never reads x_t (x_t enters the UNet solely through the
# encoder), every propagated eps in a segment depends only on the cache
# and its own timestep — so a whole segment's decoder passes stack into
# ONE batched forward (the paper's parallel-decoder follow-on win).


def encprop_disabled() -> bool:
    """Operator kill switch (docs/DEPLOY.md §6): any truthy
    CASSMANTLE_NO_ENCPROP reverts encprop-configured serving to full
    forwards at every step (read at pipeline trace time, like
    CASSMANTLE_NO_FUSED_CONV — set it before serving starts)."""
    import os

    return os.environ.get("CASSMANTLE_NO_ENCPROP", "").lower() \
        not in ("", "0", "false", "no", "off")


def encprop_key_indices(num_steps: int, stride: int,
                        dense_steps: int = 0):
    """Key-step indices for an encprop schedule: the first
    ``dense_steps`` positions are ALL keys (encoder features drift
    fastest early in sampling, per Faster Diffusion — denser keys
    there), then every ``stride``-th step. Step 0 is always a key (the
    first propagated step needs a cache to exist). Host-side numpy; the
    single source of the key/propagated split — the sampler engine,
    the pipelines' accounting counters, and the cost model in
    tools/profile_unet.py all derive from it."""
    import numpy as np

    assert stride >= 1, f"encprop stride must be >= 1, got {stride}"
    assert 0 <= dense_steps <= num_steps, (
        f"dense_steps {dense_steps} outside [0, {num_steps}]")
    dense = list(range(dense_steps))
    rest = list(range(dense_steps, num_steps, stride))
    return np.asarray(dense + rest, dtype=np.int64)


def _encprop_plan(num_steps: int, stride: int, dense_steps: int):
    """(dense prefix length, full-segment count, tail length): after the
    dense all-key prefix the remaining steps split into segments of
    exactly ``stride`` (key + stride-1 propagated) plus one shorter
    tail segment for the remainder."""
    rest = num_steps - dense_steps
    return dense_steps, rest // stride, rest % stride


def encprop_step_counts(num_steps: int, stride: int, dense_steps: int,
                        deepcache: bool = False):
    """(key, shallow, propagated) step counts for a schedule — the
    accounting the ``pipeline.encprop_*`` diagnosis counters report.
    Without deepcache, shallow is 0 and every non-key step is a
    decoder-only propagated forward; in the composed loop the SECOND
    step of each (length ≥ 2) segment is a DeepCache shallow pass
    (fresh level-0 encoder, reads x_t — NOT a decoder-only forward),
    so it must not be counted as propagated."""
    keys = len(encprop_key_indices(num_steps, stride, dense_steps))
    shallow = 0
    if deepcache:
        _, nseg, tail = _encprop_plan(num_steps, stride, dense_steps)
        shallow = (nseg if stride >= 2 else 0) + (1 if tail >= 2 else 0)
    return keys, shallow, num_steps - keys - shallow


def encprop_sample(
    spec: dict,
    denoise_key: Callable,      # (x, t) -> (eps, skips_cache[, deep])
    denoise_prop: Callable,     # (skips_cache, ts (P,)) -> (P, B, ...) eps
    latents: jax.Array,
    stride: int,
    dense_steps: int = 0,
    denoise_shallow: Optional[Callable] = None,
    batch_props: bool = True,
) -> jax.Array:
    """Generic encoder-propagation sampling engine, parameterized by a
    solver ``spec`` so DDIM/Euler/DPM++(2M) share one loop:

    - ``spec["timesteps"]``: (T,) int32 descending;
    - ``spec["coefs"]``: tuple of (T,) per-step coefficient arrays;
    - ``spec["init"](latents) -> carry`` (tuple of latent-shaped arrays);
    - ``spec["x_for"](carry, coefs_i) -> x`` the denoiser input;
    - ``spec["update"](carry, eps, coefs_i) -> carry``;
    - ``spec["final"](carry) -> x0`` latents.

    The loop runs as two ``lax.scan``s — the dense all-key prefix, then
    uniform (key + stride-1 propagated) segments — plus an unrolled
    tail for the remainder, so compile cost stays one key body + one
    segment body regardless of step count (never 50 unrolled UNets).
    At stride 1 every step is a key step and the math reduces exactly
    to the plain sampler's scan (the stride-1 bit-parity bar,
    tests/test_encprop.py).

    ``denoise_shallow`` composes DeepCache: when given, ``denoise_key``
    must also return the deep cache, the SECOND step of each segment
    runs as a DeepCache shallow pass (fresh level-0 encoder + cached
    deep activation — it still sees x_t), and only the remaining steps
    propagate. Deep-cache refreshes then happen exactly at encoder key
    steps (deep cache keys ⊆ encoder keys).

    ``batch_props=False`` runs each propagated step as its own
    single-timestep decoder call — the reference arm of the
    batched-decoder equivalence test."""
    ts = spec["timesteps"]
    coefs = tuple(spec["coefs"])
    n = int(ts.shape[0])
    dense, nseg, tail = _encprop_plan(n, stride, dense_steps)

    def coefs_at(arrs, i):
        return tuple(a[i] for a in arrs)

    def key_step(carry, t, coefs_i):
        out = denoise_key(spec["x_for"](carry, coefs_i), t)
        eps, cache, rest = out[0], out[1], out[2:]
        return spec["update"](carry, eps, coefs_i), cache, rest

    def prop_updates(carry, cache, seg_ts, seg_coefs, start):
        """Advance positions ``start..len-1`` of a segment off one
        batched decoder forward (or per-step forwards when unbatched)."""
        p = seg_ts.shape[0] - start
        if p <= 0:
            return carry
        if batch_props:
            eps_all = denoise_prop(cache, seg_ts[start:])
        for j in range(p):
            if not batch_props:
                eps = denoise_prop(cache, seg_ts[start + j:start + j + 1])[0]
            else:
                eps = eps_all[j]
            carry = spec["update"](
                carry, eps, coefs_at(seg_coefs, start + j))
        return carry

    def segment(carry, seg_ts, seg_coefs):
        carry, cache, rest = key_step(carry, seg_ts[0], coefs_at(seg_coefs, 0))
        start = 1
        if denoise_shallow is not None and seg_ts.shape[0] > 1:
            eps = denoise_shallow(
                spec["x_for"](carry, coefs_at(seg_coefs, 1)),
                seg_ts[1], rest[0])
            carry = spec["update"](carry, eps, coefs_at(seg_coefs, 1))
            start = 2
        return prop_updates(carry, cache, seg_ts, seg_coefs, start)

    carry = spec["init"](latents)
    if dense:
        def dense_body(c, per):
            t, coefs_i = per[0], per[1:]
            c, _, _ = key_step(c, t, coefs_i)
            return c, None

        carry, _ = jax.lax.scan(
            dense_body, carry, (ts[:dense],) + tuple(a[:dense] for a in coefs))
    if nseg:
        stop = dense + nseg * stride

        def pack(a):
            return a[dense:stop].reshape(nseg, stride)

        def seg_body(c, per):
            seg_ts, seg_coefs = per[0], per[1:]
            return segment(c, seg_ts, seg_coefs), None

        carry, _ = jax.lax.scan(
            seg_body, carry, (pack(ts),) + tuple(pack(a) for a in coefs))
    if tail:
        lo = n - tail
        carry = segment(carry, ts[lo:], tuple(a[lo:] for a in coefs))
    return spec["final"](carry)


def ddim_spec(schedule: DDIMSchedule) -> dict:
    """DDIM solver spec for :func:`encprop_sample` — the per-step
    arithmetic is :func:`ddim_update` verbatim, so a stride-1 encprop
    trajectory is bit-identical to :func:`ddim_sample` at eta 0."""
    return {
        "timesteps": schedule.timesteps,
        "coefs": (schedule.alpha_bars, schedule.alpha_bars_prev),
        "init": lambda latents: (latents,),
        "x_for": lambda carry, coefs_i: carry[0],
        "update": lambda carry, eps, coefs_i: (
            ddim_update(carry[0], eps, coefs_i[0], coefs_i[1]),),
        "final": lambda carry: carry[0],
    }


def ddim_sample_encprop(
    denoise_key: Callable,
    denoise_prop: Callable,
    latents: jax.Array,
    schedule: DDIMSchedule,
    stride: int,
    dense_steps: int = 0,
    denoise_shallow: Optional[Callable] = None,
    batch_props: bool = True,
) -> jax.Array:
    """DDIM with encoder propagation (deterministic, eta=0): full UNet
    forwards only at the key steps of
    :func:`encprop_key_indices`(T, stride, dense_steps); propagated
    steps run the decoder alone against the cached encoder features,
    batched per segment. See :func:`encprop_sample`."""
    return encprop_sample(
        ddim_spec(schedule), denoise_key, denoise_prop, latents,
        stride, dense_steps, denoise_shallow=denoise_shallow,
        batch_props=batch_props)


def ddim_sample(
    denoise: Callable[[jax.Array, jax.Array], jax.Array],
    latents: jax.Array,
    schedule: DDIMSchedule,
    eta: float = 0.0,
    rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Run the full DDIM loop as a lax.scan.

    ``denoise(x_t, t)`` predicts noise ε for the (already guided) batch.
    ``latents`` is x_T ~ N(0, I). Returns x_0-schedule-final latents.
    """
    if eta > 0.0 and rng is None:
        raise ValueError("eta > 0 requires an rng key")
    noise_rng = rng if rng is not None else jax.random.PRNGKey(0)

    def step(carry, per_step):
        x, key = carry
        t, a_t, a_prev = per_step
        eps = denoise(x, t)
        x0 = (x - jnp.sqrt(1.0 - a_t) * eps) / jnp.sqrt(a_t)
        sigma = eta * jnp.sqrt(
            (1.0 - a_prev) / (1.0 - a_t)
        ) * jnp.sqrt(1.0 - a_t / a_prev)
        dir_xt = jnp.sqrt(jnp.maximum(1.0 - a_prev - sigma**2, 0.0)) * eps
        key, sub = jax.random.split(key)
        noise = jax.random.normal(sub, x.shape, dtype=x.dtype)
        x_prev = jnp.sqrt(a_prev) * x0 + dir_xt + sigma * noise
        return (x_prev, key), None

    (final, _), _ = jax.lax.scan(
        step,
        (latents, noise_rng),
        (schedule.timesteps, schedule.alpha_bars, schedule.alpha_bars_prev),
    )
    return final


def _cfg_context(context, uncond_context, addition_embeds,
                 uncond_addition_embeds):
    """Stack the unconditional and conditional conditioning into the 2B
    CFG batch (shared by every CFG denoiser variant)."""
    full_context = jnp.concatenate([uncond_context, context], axis=0)
    full_addition = None
    if addition_embeds is not None:
        uncond_add = (uncond_addition_embeds
                      if uncond_addition_embeds is not None
                      else jnp.zeros_like(addition_embeds))
        full_addition = jnp.concatenate([uncond_add, addition_embeds], axis=0)
    return full_context, full_addition


def _cfg_double(x, t):
    """(x, t) -> the duplicated (x2, t2) the 2B CFG batch consumes."""
    x2 = jnp.concatenate([x, x], axis=0)
    t2 = jnp.full((2 * x.shape[0],), t, dtype=jnp.int32)
    return x2, t2


def _cfg_guide(eps, guidance_scale):
    eps_uncond, eps_cond = jnp.split(eps, 2, axis=0)
    return eps_uncond + guidance_scale * (eps_cond - eps_uncond)


def make_cfg_denoiser(
    unet_apply: Callable,
    params,
    context: jax.Array,          # (B, S, D) conditional text states
    uncond_context: jax.Array,   # (B, S, D) unconditional ("") states
    guidance_scale: float,
    addition_embeds: Optional[jax.Array] = None,         # (B, A) SDXL
    uncond_addition_embeds: Optional[jax.Array] = None,  # (B, A) SDXL
) -> Callable[[jax.Array, jax.Array], jax.Array]:
    """Classifier-free guidance denoiser: one 2B-batch UNet call per step.

    For SDXL, ``addition_embeds`` carries the pooled-text + time-ids
    micro-conditioning vector; it rides the same 2B batch as the context.
    """
    full_context, full_addition = _cfg_context(
        context, uncond_context, addition_embeds, uncond_addition_embeds)

    def denoise(x, t):
        x2, t2 = _cfg_double(x, t)
        if full_addition is None:
            eps = unet_apply(params, x2, t2, full_context)
        else:
            eps = unet_apply(params, x2, t2, full_context, full_addition)
        return _cfg_guide(eps, guidance_scale)

    return denoise


def make_slot_denoiser(
    unet_apply: Callable,
    guidance_scale: float,
) -> Callable:
    """CFG denoiser for the staged step-level serving loop
    (serving/stages.py): conditioning arrives as per-slot ARGUMENTS
    (slot contents change between steps, so nothing can be closed over)
    and the timestep is a per-slot ``(C,)`` vector — each slot sits at
    its own schedule position. Otherwise the arithmetic is exactly
    :func:`make_cfg_denoiser`'s 2C-batch CFG, so a solo slot's
    trajectory matches the monolithic scan bit for bit (the rows of the
    CFG batch are computation-independent)."""

    def denoise(params, x, t, context, uncond_context,
                addition_embeds=None, uncond_addition_embeds=None):
        full_context, full_addition = _cfg_context(
            context, uncond_context, addition_embeds,
            uncond_addition_embeds)
        x2 = jnp.concatenate([x, x], axis=0)
        t2 = jnp.concatenate([t, t], axis=0)
        if full_addition is None:
            eps = unet_apply(params, x2, t2, full_context)
        else:
            eps = unet_apply(params, x2, t2, full_context, full_addition)
        return _cfg_guide(eps, guidance_scale)

    return denoise


def make_cfg_denoiser_pair(
    unet_apply: Callable,
    params,
    context: jax.Array,
    uncond_context: jax.Array,
    guidance_scale: float,
    addition_embeds: Optional[jax.Array] = None,
    uncond_addition_embeds: Optional[jax.Array] = None,
) -> Tuple[Callable, Callable]:
    """CFG denoiser pair for deep-feature reuse: ``full(x, t)`` returns
    (guided eps, deep features of the 2B CFG batch); ``shallow(x, t,
    deep)`` reuses them. The cache rides the same cond+uncond batch, so
    both guidance halves reuse their own deep features. SDXL
    micro-conditioning rides along exactly as in make_cfg_denoiser."""
    full_context, full_addition = _cfg_context(
        context, uncond_context, addition_embeds, uncond_addition_embeds)

    def denoise_full(x, t):
        x2, t2 = _cfg_double(x, t)
        eps, deep = unet_apply(params, x2, t2, full_context,
                               full_addition, None, True)
        return _cfg_guide(eps, guidance_scale), deep

    def denoise_shallow(x, t, deep):
        x2, t2 = _cfg_double(x, t)
        eps = unet_apply(params, x2, t2, full_context, full_addition, deep)
        return _cfg_guide(eps, guidance_scale)

    return denoise_full, denoise_shallow


def _tile_rows(t: jax.Array, p) -> jax.Array:
    """Tile a (B, ...) tensor to (P*B, ...) — row b of copy p lands at
    p*B + b, matching ``jnp.repeat(ts, B)`` timestep ordering."""
    return jnp.tile(t, (p,) + (1,) * (t.ndim - 1))


def make_cfg_denoiser_encprop(
    unet_apply: Callable,
    params,
    context: jax.Array,
    uncond_context: jax.Array,
    guidance_scale: float,
    addition_embeds: Optional[jax.Array] = None,
    uncond_addition_embeds: Optional[jax.Array] = None,
    deepcache: bool = False,
) -> Tuple[Callable, Callable, Optional[Callable]]:
    """CFG denoiser triple for encoder propagation:

    - ``key(x, t)`` — full forward; returns (guided eps, encoder cache
      [, deep cache when ``deepcache``]). The cache rides the 2B
      cond+uncond batch, so both guidance halves propagate their own
      encoder features.
    - ``prop(cache, ts)`` — ONE batched decoder forward for a whole
      propagated segment: the 2B cache rows tile P× along batch
      (copy p = timestep ts[p] for every row), the decoder runs once at
      (P*2B), and the result unstacks to per-step guided eps (P, B,
      H, W, C). Exact relative to P single-step decoder calls — batch
      rows are computation-independent (the batched-decoder equivalence
      bar, tests/test_encprop.py).
    - ``shallow(x, t, deep)`` — the DeepCache shallow pass for the
      composed loop; None unless ``deepcache``.
    """
    full_context, full_addition = _cfg_context(
        context, uncond_context, addition_embeds, uncond_addition_embeds)

    def denoise_key(x, t):
        x2, t2 = _cfg_double(x, t)
        if deepcache:
            eps, deep, cache = unet_apply(
                params, x2, t2, full_context, full_addition, None, True,
                None, True)
            return _cfg_guide(eps, guidance_scale), cache, deep
        eps, cache = unet_apply(
            params, x2, t2, full_context, full_addition, None, False,
            None, True)
        return _cfg_guide(eps, guidance_scale), cache

    def denoise_prop(cache, ts):
        p = ts.shape[0]
        b2 = full_context.shape[0]                     # 2B CFG batch
        skips, up_entry = cache
        tiled = (tuple(_tile_rows(s, p) for s in skips),
                 _tile_rows(up_entry, p))
        t_all = jnp.repeat(ts.astype(jnp.int32), b2)   # (P*2B,)
        ctx_all = _tile_rows(full_context, p)
        add_all = (None if full_addition is None
                   else _tile_rows(full_addition, p))
        eps = unet_apply(params, None, t_all, ctx_all, add_all, None,
                         False, tiled)
        eps = eps.reshape((p, b2) + eps.shape[1:])
        eps_uncond, eps_cond = jnp.split(eps, 2, axis=1)
        return eps_uncond + guidance_scale * (eps_cond - eps_uncond)

    denoise_shallow = None
    if deepcache:
        def denoise_shallow(x, t, deep):
            x2, t2 = _cfg_double(x, t)
            eps = unet_apply(params, x2, t2, full_context, full_addition,
                             deep)
            return _cfg_guide(eps, guidance_scale)

    return denoise_key, denoise_prop, denoise_shallow


def initial_latents(
    rng: jax.Array, batch: int, image_size: int, vae_scale: int = 8,
    channels: int = 4,
) -> jax.Array:
    h = w = image_size // vae_scale
    return jax.random.normal(rng, (batch, h, w, channels), dtype=jnp.float32)
