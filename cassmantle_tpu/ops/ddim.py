"""DDIM sampler as a jit-compiled ``lax.scan``.

Replaces the reference's remote txt2img call (backend.py:270-295) with an
on-device denoise loop: the entire 50-step trajectory compiles to ONE XLA
computation — no host round-trips between steps, no data-dependent Python
control flow (SURVEY.md §7 stage 3). Classifier-free guidance runs the
conditional and unconditional halves in a single 2B batch so the UNet's
matmuls stay large for the MXU.

Schedule: Stable Diffusion's "scaled linear" beta schedule (1000 train
steps), strided to ``num_steps`` inference steps; eta=0 (deterministic DDIM)
by default, eta>0 adds the stochastic DDPM-style term.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp



def alpha_bars_full(
    num_train_steps: int = 1000,
    beta_start: float = 0.00085,
    beta_end: float = 0.012,
):
    """ᾱ_t for SD's scaled-linear beta schedule, fp64 numpy (host-side).

    The single source of the schedule constants — every sampler kind
    (DDIM here, Euler/DPM++ in ops/samplers.py) derives from this so
    they all integrate the same discretization of the same ODE.
    """
    import numpy as np

    betas = np.linspace(beta_start**0.5, beta_end**0.5, num_train_steps,
                        dtype=np.float64) ** 2
    return np.cumprod(1.0 - betas)


def strided_timesteps(num_steps: int, num_train_steps: int = 1000):
    """Descending int32 inference timesteps, diffusers "leading" spacing
    (t = i·stride)."""
    import numpy as np

    stride = num_train_steps // num_steps
    return (np.arange(num_steps) * stride)[::-1].astype(np.int32)


@dataclasses.dataclass(frozen=True)
class DDIMSchedule:
    """Precomputed per-inference-step coefficients (host-side, tiny)."""

    timesteps: jnp.ndarray        # (T,) int32, descending
    alpha_bars: jnp.ndarray       # (T,) float32 ᾱ_t
    alpha_bars_prev: jnp.ndarray  # (T,) float32 ᾱ_{t-1}

    @staticmethod
    def create(
        num_steps: int,
        num_train_steps: int = 1000,
        beta_start: float = 0.00085,
        beta_end: float = 0.012,
        start: int = 0,
    ) -> "DDIMSchedule":
        """``start`` > 0 drops the first inference steps (img2img tails)."""
        import numpy as np

        ab_full = alpha_bars_full(num_train_steps, beta_start, beta_end)
        ts = strided_timesteps(num_steps, num_train_steps)[start:]
        ab = ab_full[ts].astype(np.float32)
        ab_prev = np.concatenate(
            [ab_full[ts[1:]], [1.0]]
        ).astype(np.float32)
        return DDIMSchedule(
            timesteps=jnp.asarray(ts),
            alpha_bars=jnp.asarray(ab),
            alpha_bars_prev=jnp.asarray(ab_prev),
        )


def ddim_update(x, eps, a_t, a_prev):
    """One deterministic DDIM transition x_t -> x_{t-1} (eta = 0)."""
    x0 = (x - jnp.sqrt(1.0 - a_t) * eps) / jnp.sqrt(a_t)
    dir_xt = jnp.sqrt(1.0 - a_prev) * eps
    return jnp.sqrt(a_prev) * x0 + dir_xt


def ddim_sample_deepcache(
    denoise_full: Callable,       # (x, t) -> (eps, deep_features)
    denoise_shallow: Callable,    # (x, t, deep_features) -> eps
    latents: jax.Array,
    schedule: DDIMSchedule,
) -> jax.Array:
    """DDIM with deep-feature reuse (DeepCache-style serving): steps run
    in pairs — a FULL UNet pass whose deepest-levels output is cached,
    then a SHALLOW pass (level-0 blocks only) reusing it. Deep
    activations vary slowly across adjacent steps, so quality stays
    near the full trajectory at ~60% of the compute (models/unet.py
    documents the split). Deterministic (eta=0); even step count
    required."""
    n = schedule.timesteps.shape[0]
    assert n % 2 == 0, f"deepcache pairing needs an even step count, got {n}"

    def pack(a):
        return a.reshape(n // 2, 2)

    def pair_step(x, per):
        t, a_t, a_prev = per
        eps, deep = denoise_full(x, t[0])
        x = ddim_update(x, eps, a_t[0], a_prev[0])
        eps = denoise_shallow(x, t[1], deep)
        x = ddim_update(x, eps, a_t[1], a_prev[1])
        return x, None

    final, _ = jax.lax.scan(
        pair_step, latents,
        (pack(schedule.timesteps), pack(schedule.alpha_bars),
         pack(schedule.alpha_bars_prev)),
    )
    return final


def ddim_sample(
    denoise: Callable[[jax.Array, jax.Array], jax.Array],
    latents: jax.Array,
    schedule: DDIMSchedule,
    eta: float = 0.0,
    rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Run the full DDIM loop as a lax.scan.

    ``denoise(x_t, t)`` predicts noise ε for the (already guided) batch.
    ``latents`` is x_T ~ N(0, I). Returns x_0-schedule-final latents.
    """
    if eta > 0.0 and rng is None:
        raise ValueError("eta > 0 requires an rng key")
    noise_rng = rng if rng is not None else jax.random.PRNGKey(0)

    def step(carry, per_step):
        x, key = carry
        t, a_t, a_prev = per_step
        eps = denoise(x, t)
        x0 = (x - jnp.sqrt(1.0 - a_t) * eps) / jnp.sqrt(a_t)
        sigma = eta * jnp.sqrt(
            (1.0 - a_prev) / (1.0 - a_t)
        ) * jnp.sqrt(1.0 - a_t / a_prev)
        dir_xt = jnp.sqrt(jnp.maximum(1.0 - a_prev - sigma**2, 0.0)) * eps
        key, sub = jax.random.split(key)
        noise = jax.random.normal(sub, x.shape, dtype=x.dtype)
        x_prev = jnp.sqrt(a_prev) * x0 + dir_xt + sigma * noise
        return (x_prev, key), None

    (final, _), _ = jax.lax.scan(
        step,
        (latents, noise_rng),
        (schedule.timesteps, schedule.alpha_bars, schedule.alpha_bars_prev),
    )
    return final


def _cfg_context(context, uncond_context, addition_embeds,
                 uncond_addition_embeds):
    """Stack the unconditional and conditional conditioning into the 2B
    CFG batch (shared by every CFG denoiser variant)."""
    full_context = jnp.concatenate([uncond_context, context], axis=0)
    full_addition = None
    if addition_embeds is not None:
        uncond_add = (uncond_addition_embeds
                      if uncond_addition_embeds is not None
                      else jnp.zeros_like(addition_embeds))
        full_addition = jnp.concatenate([uncond_add, addition_embeds], axis=0)
    return full_context, full_addition


def _cfg_double(x, t):
    """(x, t) -> the duplicated (x2, t2) the 2B CFG batch consumes."""
    x2 = jnp.concatenate([x, x], axis=0)
    t2 = jnp.full((2 * x.shape[0],), t, dtype=jnp.int32)
    return x2, t2


def _cfg_guide(eps, guidance_scale):
    eps_uncond, eps_cond = jnp.split(eps, 2, axis=0)
    return eps_uncond + guidance_scale * (eps_cond - eps_uncond)


def make_cfg_denoiser(
    unet_apply: Callable,
    params,
    context: jax.Array,          # (B, S, D) conditional text states
    uncond_context: jax.Array,   # (B, S, D) unconditional ("") states
    guidance_scale: float,
    addition_embeds: Optional[jax.Array] = None,         # (B, A) SDXL
    uncond_addition_embeds: Optional[jax.Array] = None,  # (B, A) SDXL
) -> Callable[[jax.Array, jax.Array], jax.Array]:
    """Classifier-free guidance denoiser: one 2B-batch UNet call per step.

    For SDXL, ``addition_embeds`` carries the pooled-text + time-ids
    micro-conditioning vector; it rides the same 2B batch as the context.
    """
    full_context, full_addition = _cfg_context(
        context, uncond_context, addition_embeds, uncond_addition_embeds)

    def denoise(x, t):
        x2, t2 = _cfg_double(x, t)
        if full_addition is None:
            eps = unet_apply(params, x2, t2, full_context)
        else:
            eps = unet_apply(params, x2, t2, full_context, full_addition)
        return _cfg_guide(eps, guidance_scale)

    return denoise


def make_slot_denoiser(
    unet_apply: Callable,
    guidance_scale: float,
) -> Callable:
    """CFG denoiser for the staged step-level serving loop
    (serving/stages.py): conditioning arrives as per-slot ARGUMENTS
    (slot contents change between steps, so nothing can be closed over)
    and the timestep is a per-slot ``(C,)`` vector — each slot sits at
    its own schedule position. Otherwise the arithmetic is exactly
    :func:`make_cfg_denoiser`'s 2C-batch CFG, so a solo slot's
    trajectory matches the monolithic scan bit for bit (the rows of the
    CFG batch are computation-independent)."""

    def denoise(params, x, t, context, uncond_context,
                addition_embeds=None, uncond_addition_embeds=None):
        full_context, full_addition = _cfg_context(
            context, uncond_context, addition_embeds,
            uncond_addition_embeds)
        x2 = jnp.concatenate([x, x], axis=0)
        t2 = jnp.concatenate([t, t], axis=0)
        if full_addition is None:
            eps = unet_apply(params, x2, t2, full_context)
        else:
            eps = unet_apply(params, x2, t2, full_context, full_addition)
        return _cfg_guide(eps, guidance_scale)

    return denoise


def make_cfg_denoiser_pair(
    unet_apply: Callable,
    params,
    context: jax.Array,
    uncond_context: jax.Array,
    guidance_scale: float,
    addition_embeds: Optional[jax.Array] = None,
    uncond_addition_embeds: Optional[jax.Array] = None,
) -> Tuple[Callable, Callable]:
    """CFG denoiser pair for deep-feature reuse: ``full(x, t)`` returns
    (guided eps, deep features of the 2B CFG batch); ``shallow(x, t,
    deep)`` reuses them. The cache rides the same cond+uncond batch, so
    both guidance halves reuse their own deep features. SDXL
    micro-conditioning rides along exactly as in make_cfg_denoiser."""
    full_context, full_addition = _cfg_context(
        context, uncond_context, addition_embeds, uncond_addition_embeds)

    def denoise_full(x, t):
        x2, t2 = _cfg_double(x, t)
        eps, deep = unet_apply(params, x2, t2, full_context,
                               full_addition, None, True)
        return _cfg_guide(eps, guidance_scale), deep

    def denoise_shallow(x, t, deep):
        x2, t2 = _cfg_double(x, t)
        eps = unet_apply(params, x2, t2, full_context, full_addition, deep)
        return _cfg_guide(eps, guidance_scale)

    return denoise_full, denoise_shallow


def initial_latents(
    rng: jax.Array, batch: int, image_size: int, vae_scale: int = 8,
    channels: int = 4,
) -> jax.Array:
    h = w = image_size // vae_scale
    return jax.random.normal(rng, (batch, h, w, channels), dtype=jnp.float32)
