"""Attention dispatch: XLA reference path + Pallas flash-attention kernel.

Every attention site in the model zoo (UNet spatial transformers, CLIP/GPT-2
/MiniLM text blocks) funnels through :func:`multi_head_attention`, so the
Pallas kernel swap happens in exactly one place. The reference has no
attention code at all — its models live behind the HF Inference API
(reference backend.py:240-295) — so this op is the heart of the "replace the
remote API with local TPU compute" north star.

Dispatch policy:
- TPU + no mask + seq long enough to tile → Pallas flash attention
  (blockwise online-softmax, O(N) memory; ops/flash_attention.py);
- otherwise → jnp.einsum attention, which XLA fuses well on its own.
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Optional

import jax
import jax.numpy as jnp

# When set, every attention site uses the plain XLA path — used when
# tracing for a non-TPU device (e.g. CPU-side param init) while the default
# backend is TPU.
_FORCE_XLA = contextvars.ContextVar("cassmantle_force_xla", default=False)

# When set to (mesh, axis_name, batch_axis), CAUSAL self-attention sites
# run sequence-parallel over that mesh axis (zigzag ring schedule). The
# caller owns the data layout: sequences must already be zigzag-permuted
# (parallel/ring.py) and stay permuted through the whole network.
_CONTEXT_PARALLEL = contextvars.ContextVar(
    "cassmantle_context_parallel", default=None
)


@contextlib.contextmanager
def xla_only():
    token = _FORCE_XLA.set(True)
    try:
        yield
    finally:
        _FORCE_XLA.reset(token)


@contextlib.contextmanager
def context_parallel(mesh, axis_name: str = "sp",
                     batch_axis: Optional[str] = "dp"):
    """Route every causal self-attention traced inside this context
    through the sequence-parallel zigzag ring over ``mesh[axis_name]``
    (the long-context trace context; see parallel/lm_train.py)."""
    token = _CONTEXT_PARALLEL.set((mesh, axis_name, batch_axis))
    try:
        yield
    finally:
        _CONTEXT_PARALLEL.reset(token)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def xla_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    scale: Optional[float] = None,
) -> jax.Array:
    """Reference attention. q: (..., Sq, H, D), k/v: (..., Sk, H, D).

    ``mask`` broadcasts against (..., H, Sq, Sk); True = attend.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("...qhd,...khd->...hqk", q, k) * scale
    if mask is not None:
        big_neg = jnp.finfo(logits.dtype).min
        logits = jnp.where(mask, logits, big_neg)
    weights = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    weights = weights.astype(v.dtype)
    return jnp.einsum("...hqk,...khd->...qhd", weights, v)


# Pallas kernel lands in ops/flash_attention.py; until then this alias keeps
# the dispatch seam stable.
def multi_head_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    use_flash: Optional[bool] = None,
    causal: bool = False,
) -> jax.Array:
    """Attention entry point used by all models.

    Shapes: q (..., Sq, H, D); k, v (..., Sk, H, D); returns (..., Sq, H, D).
    ``causal=True`` (with no explicit mask) lets this layer own the
    triangular masking — and, inside a :func:`context_parallel` region,
    dispatch to sequence-parallel zigzag ring attention instead of ever
    materializing the (S, S) mask.
    """
    if causal and mask is None and q.shape == k.shape:
        cp = _CONTEXT_PARALLEL.get()
        if cp is not None and q.ndim == 4:
            from cassmantle_tpu.parallel.ring import (
                zigzag_sharded_attention,
            )

            mesh, axis_name, batch_axis = cp
            return zigzag_sharded_attention(
                q, k, v, mesh, axis_name=axis_name, scale=scale,
                batch_axis=batch_axis,
            )
    if causal and mask is None:
        # bottom-right-aligned band: when s_q != s_k (cached decode, where
        # the queries are the LAST s_q positions of the sequence), query i
        # attends keys [0, s_k - s_q + i]; reduces to plain tril at
        # s_q == s_k
        s_q, s_k = q.shape[-3], k.shape[-3]
        assert s_q <= s_k, (
            f"causal decode needs s_q <= s_k, got {s_q} > {s_k}"
        )
        mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool), k=s_k - s_q)
    if _FORCE_XLA.get():
        use_flash = False
    if use_flash is None:
        use_flash = _on_tpu() and mask is None
    if use_flash and mask is None:
        from cassmantle_tpu.ops.flash_attention import (
            flash_attention_ok,
            flash_cross_ok,
            flash_wide_ok,
        )

        if flash_attention_ok(q, k):
            from cassmantle_tpu.ops.flash_attention import flash_attention

            return flash_attention(q, k, v, scale=scale)
        if flash_wide_ok(q, k):
            # wide-head self-attention (the VAE mid block: single head
            # over H·W tokens at full channel width — S=16k, D=512 at
            # SDXL decode): same kernel at 512-blocks so the fat head
            # fits VMEM; the XLA path would materialize the (S, S)
            # score matrix in HBM.
            from cassmantle_tpu.ops.flash_attention import (
                WIDE_BLOCK,
                flash_attention,
            )

            return flash_attention(q, k, v, scale=scale,
                                   block_q=WIDE_BLOCK, block_k=WIDE_BLOCK)
        if flash_cross_ok(q, k):
            import os

            # ragged-S_k cross-attention (UNet text context, S_k=77):
            # K/V pad into the kernel, pad columns masked by kv_len.
            # CASSMANTLE_NO_FLASH_CROSS=1 is the operator kill switch —
            # one env var reverts every cross site to the XLA path if
            # this newer kernel misbehaves on some TPU generation,
            # without touching the proven self-attention flash path.
            if os.environ.get(
                    "CASSMANTLE_NO_FLASH_CROSS", ""
            ).lower() in ("", "0", "false", "no", "off"):
                from cassmantle_tpu.ops.flash_attention import (
                    flash_cross_attention,
                )

                return flash_cross_attention(q, k, v, scale=scale)
    return xla_attention(q, k, v, mask=mask, scale=scale)
