"""Diffusion samplers beyond DDIM: Euler and DPM-Solver++(2M).

The reference's hosted SDXL endpoint (backend.py:270-295) exposes no
sampler choice; serving locally we can trade steps for latency — DPM++(2M)
at 20-25 steps matches 50-step DDIM quality, roughly halving image latency
on the same chip. All samplers here keep the DDIM contract from ops/ddim.py:

- ``denoise(x_t, t) -> eps`` with x_t in VP space (unit-variance latents),
  ``t`` an int train-timestep — so the CFG denoiser and the UNet are shared
  unchanged across samplers;
- the full trajectory is ONE ``lax.scan`` under jit: per-step coefficients
  are precomputed host-side into fixed-shape arrays (no data-dependent
  control flow, no recompiles per step).

Schedules use SD's scaled-linear betas with "leading" uniform timestep
spacing (t = i·stride, the same spacing DDIMSchedule.create uses, so all
sampler kinds integrate the same discretization of the same ODE).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from cassmantle_tpu.ops.ddim import (
    DDIMSchedule,
    alpha_bars_full as _alpha_bars,
    ddim_sample,
    strided_timesteps as _strided_timesteps,
)

SAMPLER_KINDS = ("ddim", "euler", "dpmpp_2m")

#: PRNG seed of the deterministic re-noise ladder multistep consistency
#: sampling uses between f-evaluations: step noise is
#: ``normal(fold_in(PRNGKey(seed), t), latent_row_shape)`` — a pure
#: function of the TIMESTEP, shared across batch rows. That makes the
#: sampler deterministic (no carried key chain), batch-invariant (a
#: request's trajectory does not depend on what it batched with), and
#: replayable at step granularity by the staged slot stepper (each slot
#: folds its own current timestep), which is what lets few-step
#: requests ride the continuous-batching path (eta>0-style carried
#: chains cannot — see make_slot_sampler's rejection).
CONSISTENCY_NOISE_SEED = 0x1C3


def consistency_disabled() -> bool:
    """Operator kill switch (docs/DEPLOY.md §6): any truthy
    CASSMANTLE_NO_CONSISTENCY reverts consistency-configured serving to
    the TEACHER path — the plain configured sampler kind at
    ``SamplerConfig.consistency_teacher_steps`` — bit-exactly (read at
    pipeline build/trace time, like CASSMANTLE_NO_ENCPROP: set it
    before serving starts)."""
    import os

    return os.environ.get("CASSMANTLE_NO_CONSISTENCY", "").lower() \
        not in ("", "0", "false", "no", "off")


def consistency_boundary(sigma, sigma_min, sigma_data: float = 0.5):
    """The consistency-model boundary-condition parameterization
    (c_skip, c_out) at noise level ``sigma`` (k-space,
    sqrt((1-ᾱ)/ᾱ)): f(x, σ) = c_skip(σ)·x + c_out(σ)·x0_pred(x, σ).
    At σ = σ_min this is EXACTLY (1, 0) — f is the identity at the
    clean boundary, the constraint that makes the distilled student a
    consistency function rather than a free-form few-step net.

    Written with ``** 0.5`` (not jnp.sqrt) so host-side schedule
    precomputation stays numpy even when it happens inside a jit trace
    (run_cfg_denoise builds the schedule at pipeline trace time) while
    the SAME expression serves traced sigmas in the distillation
    step."""
    c_skip = sigma_data**2 / ((sigma - sigma_min) ** 2 + sigma_data**2)
    c_out = (sigma_data * (sigma - sigma_min)
             / (sigma**2 + sigma_data**2) ** 0.5)
    return c_skip, c_out


def consistency_renoise(t, shape, dtype=jnp.float32):
    """The deterministic per-step re-noise draw (see
    CONSISTENCY_NOISE_SEED): one latent ROW of noise keyed on the
    timestep, broadcast across the batch. Shared verbatim by the
    monolithic scan, the slot stepper, and the reference loop in
    tests/test_samplers.py."""
    key = jax.random.fold_in(
        jax.random.PRNGKey(CONSISTENCY_NOISE_SEED), t)
    return jax.random.normal(key, shape, dtype)


@dataclasses.dataclass(frozen=True)
class ConsistencySchedule:
    """Few-step consistency/LCM sampling schedule, all step math
    precomputed host-side. Timesteps are drawn FROM THE TEACHER SOLVER
    DISCRETIZATION — the same ``strided_timesteps(teacher_steps)`` grid
    ``ConsistencyDistillTrainer`` trains on (the LCM recipe: the student
    only ever sees schedule positions of the teacher's ODE
    discretization, so serving must query exactly those points, never
    interpolate past them). Within that grid the selection is TRAILING
    (start at the grid's noisiest point, stride down, never reach the
    grid's final t=0 entry) so the LAST f-evaluation sits at a genuinely
    noisy timestep and its output IS the final x0 — touching t=0 would
    spend the final UNet forward evaluating f where the boundary
    condition makes it the identity."""

    timesteps: jnp.ndarray        # (T,) int32 descending, last > 0
    alpha_bars: jnp.ndarray       # (T,) float32 ᾱ at each f-eval step
    alpha_bars_next: jnp.ndarray  # (T,) ᾱ of the re-noise target; last=1
    c_skip: jnp.ndarray           # (T,) boundary coefficients
    c_out: jnp.ndarray            # (T,)

    @staticmethod
    def create(num_steps: int, teacher_steps: int = 50,
               num_train_steps: int = 1000,
               sigma_data: float = 0.5) -> "ConsistencySchedule":
        from cassmantle_tpu.ops.ddim import strided_timesteps

        assert num_steps >= 1
        ab_full = _alpha_bars(num_train_steps)
        # the trainer's grid, minus its final t=0 point (the trainer
        # never queries the student there — skip ≥ 1 — and f is the
        # identity there by the boundary condition)
        grid = strided_timesteps(teacher_steps, num_train_steps)[:-1]
        assert num_steps <= len(grid), (
            f"consistency needs num_steps {num_steps} <= "
            f"teacher_steps-1 = {len(grid)} (the student is only "
            f"trained on the teacher discretization's query points)")
        ts = grid[(len(grid) // num_steps)
                  * np.arange(num_steps)].astype(np.int32)
        ab = ab_full[ts]
        ab_next = np.concatenate([ab[1:], [1.0]])
        sigma = np.sqrt((1.0 - ab) / ab)
        sigma_min = float(np.sqrt((1.0 - ab_full[0]) / ab_full[0]))
        c_skip, c_out = consistency_boundary(sigma, sigma_min, sigma_data)
        f32 = lambda a: jnp.asarray(np.asarray(a, np.float32))  # noqa: E731
        return ConsistencySchedule(
            timesteps=jnp.asarray(ts), alpha_bars=f32(ab),
            alpha_bars_next=f32(ab_next), c_skip=f32(c_skip),
            c_out=f32(c_out))


def consistency_sample(
    denoise: Callable[[jax.Array, jax.Array], jax.Array],
    latents: jax.Array,
    schedule: ConsistencySchedule,
) -> jax.Array:
    """Multistep consistency sampling: per step, ONE UNet forward maps
    the current state straight to an x0 estimate through the boundary
    parameterization, then the state re-noises to the next (lower)
    evaluation timestep — num_steps total UNet forwards per image,
    which is the whole point (docs/PERF_NOTES.md "Few-step
    accounting"). ``latents`` standard normal (VP convention, same as
    every other sampler); one ``lax.scan``, deterministic (see
    consistency_renoise). The final step's ᾱ_next is 1.0, so its
    update reduces exactly to the x0 estimate."""

    def step(x, per):
        t, ab, ab_next, c_skip, c_out = per
        eps = denoise(x, t)
        x0 = (x - jnp.sqrt(1.0 - ab) * eps) / jnp.sqrt(ab)
        f = c_skip * x + c_out * x0
        noise = consistency_renoise(t, x.shape[1:], x.dtype)
        x = jnp.sqrt(ab_next) * f + jnp.sqrt(1.0 - ab_next) * noise
        return x, None

    final, _ = jax.lax.scan(
        step, latents,
        (schedule.timesteps, schedule.alpha_bars,
         schedule.alpha_bars_next, schedule.c_skip, schedule.c_out),
    )
    return final


def make_consistency_sampler(num_steps: int, teacher_steps: int = 50):
    """num_steps (1–8) -> ``sample(denoise, latents, rng=None)`` — the
    few-step counterpart of :func:`make_sampler` (rng accepted for
    signature parity and ignored: the re-noise ladder is deterministic
    by construction). ``teacher_steps`` is the solver discretization
    the student was distilled on (``SamplerConfig.
    consistency_teacher_steps``) — the grid the schedule queries."""
    schedule = ConsistencySchedule.create(num_steps, teacher_steps)

    def sample(denoise, latents, rng=None):
        return consistency_sample(denoise, latents, schedule)

    return sample


@dataclasses.dataclass(frozen=True)
class EulerSchedule:
    """k-diffusion sigma ladder; x evolves in k-space (x_vp * sqrt(1+s²))."""

    timesteps: jnp.ndarray   # (T,) int32 descending
    sigmas: jnp.ndarray      # (T+1,) float32, sigmas[-1] == 0

    @staticmethod
    def create(num_steps: int, start: int = 0) -> "EulerSchedule":
        """``start`` > 0 drops the first steps (img2img tails)."""
        ab = _alpha_bars()
        ts = _strided_timesteps(num_steps)[start:]
        sig = np.sqrt((1.0 - ab[ts]) / ab[ts])
        sig = np.concatenate([sig, [0.0]]).astype(np.float32)
        return EulerSchedule(timesteps=jnp.asarray(ts),
                             sigmas=jnp.asarray(sig))


def euler_sample(
    denoise: Callable[[jax.Array, jax.Array], jax.Array],
    latents: jax.Array,
    schedule: EulerSchedule,
    prescaled: bool = False,
) -> jax.Array:
    """Deterministic Euler solver over the k-diffusion ODE.

    ``latents`` is standard normal (VP convention, same as ddim_sample)
    and gets scaled by sigma_max here — unless ``prescaled``, in which
    case the caller already built the k-space state (img2img tails).
    Returns VP-space x_0 latents.
    """
    x = latents if prescaled else latents * schedule.sigmas[0]

    def step(x, per_step):
        t, sigma, sigma_next = per_step
        x_vp = x / jnp.sqrt(1.0 + sigma * sigma)
        eps = denoise(x_vp, t)
        # k-diffusion derivative for eps-prediction is eps itself
        x = x + (sigma_next - sigma) * eps
        return x, None

    final, _ = jax.lax.scan(
        step, x,
        (schedule.timesteps, schedule.sigmas[:-1], schedule.sigmas[1:]),
    )
    return final  # sigma -> 0 lands in VP space already


@dataclasses.dataclass(frozen=True)
class DPMppSchedule:
    """DPM-Solver++(2M) with all step math precomputed host-side.

    Update (data-prediction form): x <- c_skip·x + c_d0·m0 + c_d1·m1
    where m0/m1 are this/previous step's predicted x0. First and last
    steps are first-order (c_d1 = 0) — the standard multistep warmup and
    ``lower_order_final`` boundary handling, which also keeps every
    coefficient finite (the final step's h is infinite only in the
    analytic form; here it resolves to c_skip=0, c_d0=1).
    """

    timesteps: jnp.ndarray  # (T,) int32 descending
    alphas: jnp.ndarray     # (T,) sqrt(abar) at each step (for x0 recovery)
    sigmas: jnp.ndarray     # (T,) sqrt(1-abar)
    c_skip: jnp.ndarray     # (T,)
    c_d0: jnp.ndarray       # (T,)
    c_d1: jnp.ndarray       # (T,)

    @staticmethod
    def create(num_steps: int, start: int = 0) -> "DPMppSchedule":
        """``start`` > 0 drops the first steps (img2img tails); the
        first kept step is automatically first-order (its h_prev is
        undefined), which is exactly the multistep warmup."""
        ab = _alpha_bars()
        ts = _strided_timesteps(num_steps)[start:]
        alpha = np.sqrt(ab[ts])
        sigma = np.sqrt(1.0 - ab[ts])
        # targets: step i maps state at ts[i] -> ts[i+1] (final -> clean)
        alpha_next = np.concatenate([alpha[1:], [1.0]])
        sigma_next = np.concatenate([sigma[1:], [0.0]])
        lam = np.log(alpha) - np.log(sigma)
        with np.errstate(divide="ignore"):
            lam_next = np.log(alpha_next) - np.log(
                np.where(sigma_next > 0, sigma_next, 1e-300)
            )
        h = lam_next - lam                       # (T,), last is huge/inf
        h_prev = np.concatenate([[np.nan], h[:-1]])
        em1 = np.where(np.isfinite(h), np.expm1(-h), -1.0)  # exp(-h)-1

        # 2M correction weight 1/(2·r0) with r0 = h_prev/h, i.e. h/(2·h_prev)
        with np.errstate(divide="ignore", invalid="ignore"):
            inv2r = h / (2.0 * h_prev)
            inv2r = np.where(np.isfinite(inv2r), inv2r, 0.0)
        first_order = np.zeros(len(ts), dtype=bool)
        first_order[0] = True                    # multistep warmup
        first_order[-1] = True                   # lower_order_final
        inv2r = np.where(first_order, 0.0, inv2r)

        c_skip = np.where(sigma > 0, sigma_next / sigma, 0.0)
        c_d0 = -alpha_next * em1 * (1.0 + inv2r)
        c_d1 = alpha_next * em1 * inv2r
        f32 = lambda a: jnp.asarray(a.astype(np.float32))  # noqa: E731
        return DPMppSchedule(
            timesteps=jnp.asarray(ts), alphas=f32(alpha), sigmas=f32(sigma),
            c_skip=f32(c_skip), c_d0=f32(c_d0), c_d1=f32(c_d1),
        )


def dpmpp_2m_sample(
    denoise: Callable[[jax.Array, jax.Array], jax.Array],
    latents: jax.Array,
    schedule: DPMppSchedule,
) -> jax.Array:
    """DPM-Solver++(2M): 2nd-order multistep in data-prediction form.

    ``latents`` standard normal; x stays in VP space throughout.
    """

    def step(carry, per_step):
        x, m1 = carry
        t, alpha, sigma, c_skip, c_d0, c_d1 = per_step
        eps = denoise(x, t)
        m0 = (x - sigma * eps) / alpha
        x = c_skip * x + c_d0 * m0 + c_d1 * m1
        return (x, m0), None

    (final, _), _ = jax.lax.scan(
        step, (latents, jnp.zeros_like(latents)),
        (schedule.timesteps, schedule.alphas, schedule.sigmas,
         schedule.c_skip, schedule.c_d0, schedule.c_d1),
    )
    return final


def dpmpp_2m_sample_deepcache(
    denoise_full: Callable,     # (x, t) -> (eps, deep_features)
    denoise_shallow: Callable,  # (x, t, deep_features) -> eps
    latents: jax.Array,
    schedule: DPMppSchedule,
) -> jax.Array:
    """DPM-Solver++(2M) with deep-feature reuse — the two serving
    speedups COMPOSED: half the steps of DDIM-50 (2M multistep) and
    ~60% UNet compute on alternate steps (DeepCache pairing from
    ops/ddim.py:ddim_sample_deepcache, same full/shallow contract).

    Steps run in (full, shallow) pairs; an odd step count runs its
    final step as an unpaired FULL pass — the t→0 step where accuracy
    matters most never consumes a stale cache. The multistep history
    m1 (previous step's predicted x0) threads through pairs unchanged,
    so the integrator is exactly dpmpp_2m_sample wherever the eps
    values agree.
    """
    n = schedule.timesteps.shape[0]
    pairs = n // 2

    def sl(a):
        return a[: 2 * pairs].reshape(pairs, 2)

    def one_update(x, m1, eps, alpha, sigma, c_skip, c_d0, c_d1):
        m0 = (x - sigma * eps) / alpha
        x = c_skip * x + c_d0 * m0 + c_d1 * m1
        return x, m0

    def pair_step(carry, per):
        x, m1 = carry
        t, alpha, sigma, c_skip, c_d0, c_d1 = per
        eps, deep = denoise_full(x, t[0])
        x, m1 = one_update(x, m1, eps, alpha[0], sigma[0],
                           c_skip[0], c_d0[0], c_d1[0])
        eps = denoise_shallow(x, t[1], deep)
        x, m1 = one_update(x, m1, eps, alpha[1], sigma[1],
                           c_skip[1], c_d0[1], c_d1[1])
        return (x, m1), None

    (x, m1), _ = jax.lax.scan(
        pair_step, (latents, jnp.zeros_like(latents)),
        (sl(schedule.timesteps), sl(schedule.alphas), sl(schedule.sigmas),
         sl(schedule.c_skip), sl(schedule.c_d0), sl(schedule.c_d1)),
    )
    if n % 2:
        eps, _ = denoise_full(x, schedule.timesteps[-1])
        x, _ = one_update(x, m1, eps, schedule.alphas[-1],
                          schedule.sigmas[-1], schedule.c_skip[-1],
                          schedule.c_d0[-1], schedule.c_d1[-1])
    return x


def euler_spec(schedule: EulerSchedule) -> dict:
    """Euler solver spec for :func:`cassmantle_tpu.ops.ddim.encprop_sample`
    — per-step arithmetic verbatim from :func:`euler_sample` (x carried
    in k-space; the denoiser sees the VP-space projection)."""
    return {
        "timesteps": schedule.timesteps,
        "coefs": (schedule.sigmas[:-1], schedule.sigmas[1:]),
        "init": lambda latents: (latents * schedule.sigmas[0],),
        "x_for": lambda carry, c: carry[0] / jnp.sqrt(1.0 + c[0] * c[0]),
        "update": lambda carry, eps, c: (carry[0] + (c[1] - c[0]) * eps,),
        "final": lambda carry: carry[0],
    }


def dpmpp_2m_spec(schedule: DPMppSchedule) -> dict:
    """DPM-Solver++(2M) spec for encprop sampling — the scan-body
    expressions of :func:`dpmpp_2m_sample` verbatim; carry is (x, m1)
    with the multistep history threading through key and propagated
    steps unchanged."""
    def update(carry, eps, c):
        x, m1 = carry
        alpha, sigma, c_skip, c_d0, c_d1 = c
        m0 = (x - sigma * eps) / alpha
        return (c_skip * x + c_d0 * m0 + c_d1 * m1, m0)

    return {
        "timesteps": schedule.timesteps,
        "coefs": (schedule.alphas, schedule.sigmas, schedule.c_skip,
                  schedule.c_d0, schedule.c_d1),
        "init": lambda latents: (latents, jnp.zeros_like(latents)),
        "x_for": lambda carry, c: carry[0],
        "update": update,
        "final": lambda carry: carry[0],
    }


def euler_sample_encprop(denoise_key, denoise_prop, latents,
                         schedule: EulerSchedule, stride: int,
                         dense_steps: int = 0,
                         batch_props: bool = True) -> jax.Array:
    """Euler with encoder propagation (see ops/ddim.py::encprop_sample;
    no deepcache composition — euler has no deepcache loop to compose
    with)."""
    from cassmantle_tpu.ops.ddim import encprop_sample

    return encprop_sample(
        euler_spec(schedule), denoise_key, denoise_prop, latents,
        stride, dense_steps, batch_props=batch_props)


def dpmpp_2m_sample_encprop(denoise_key, denoise_prop, latents,
                            schedule: DPMppSchedule, stride: int,
                            dense_steps: int = 0,
                            denoise_shallow=None,
                            batch_props: bool = True) -> jax.Array:
    """DPM-Solver++(2M) with encoder propagation; ``denoise_shallow``
    composes DeepCache exactly as in ops/ddim.py::encprop_sample."""
    from cassmantle_tpu.ops.ddim import encprop_sample

    return encprop_sample(
        dpmpp_2m_spec(schedule), denoise_key, denoise_prop, latents,
        stride, dense_steps, denoise_shallow=denoise_shallow,
        batch_props=batch_props)


def make_encprop_sampler(kind: str, num_steps: int, stride: int,
                         dense_steps: int = 0, deepcache: bool = False):
    """(kind, steps, key schedule) ->
    ``sample(denoise_key, denoise_prop, latents, denoise_shallow=None)``
    — the encoder-propagation counterpart of :func:`make_sampler`,
    covering every deterministic sampler kind. ``deepcache`` marks the
    composed loop (the caller must then pass ``denoise_shallow`` and a
    ``denoise_key`` that also returns the deep cache); euler+deepcache
    is rejected here exactly as the plain deepcache path rejects it."""
    from cassmantle_tpu.ops.ddim import (
        DDIMSchedule,
        ddim_sample_encprop,
    )

    if deepcache and kind not in ("ddim", "dpmpp_2m"):
        raise AssertionError(
            f"deepcache composes with ddim or dpmpp_2m, not {kind!r}")

    if kind == "ddim":
        schedule = DDIMSchedule.create(num_steps)

        def sample(dk, dp, latents, denoise_shallow=None,
                   batch_props=True):
            return ddim_sample_encprop(
                dk, dp, latents, schedule, stride, dense_steps,
                denoise_shallow=denoise_shallow, batch_props=batch_props)

        return sample
    if kind == "euler":
        eschedule = EulerSchedule.create(num_steps)

        def sample(dk, dp, latents, denoise_shallow=None,
                   batch_props=True):
            assert denoise_shallow is None, "euler has no deepcache loop"
            return euler_sample_encprop(
                dk, dp, latents, eschedule, stride, dense_steps,
                batch_props=batch_props)

        return sample
    if kind == "dpmpp_2m":
        dschedule = DPMppSchedule.create(num_steps)

        def sample(dk, dp, latents, denoise_shallow=None,
                   batch_props=True):
            return dpmpp_2m_sample_encprop(
                dk, dp, latents, dschedule, stride, dense_steps,
                denoise_shallow=denoise_shallow, batch_props=batch_props)

        return sample
    raise ValueError(f"unknown sampler kind {kind!r}; "
                     f"choose from {SAMPLER_KINDS}")


def make_slot_sampler(kind: str, num_steps: int, eta: float = 0.0,
                      teacher_steps: int = 50):
    """Step-granular counterpart of :func:`make_sampler` for the staged
    serving path (serving/stages.py): instead of one ``lax.scan``
    position shared by the whole batch, every slot carries its OWN step
    index and the per-step coefficients gather per slot — so requests
    can sit at different schedule positions inside one fixed-capacity
    step dispatch.

    Returns ``(prepare, slot_step, num_steps)``:

    - ``prepare(latents) -> (x, aux)`` maps standard-normal latents to
      the solver-space entry state (identity for DDIM/DPM++, the
      sigma-max scale for Euler) plus the per-slot auxiliary state
      (DPM++'s multistep history m1; zeros where the solver has none);
    - ``slot_step(denoise, x, aux, idx) -> (x', aux')`` advances every
      slot one step: ``x``/``aux`` are ``(C, H, W, Ch)``, ``idx`` is
      ``(C,)`` int32 (each slot's current step), and ``denoise(x, t)``
      receives the per-slot int timestep vector ``t``.

    The per-slot arithmetic is EXACTLY the matching ``make_sampler``
    scan body (same schedule arrays, same expressions), so a solo
    staged trajectory is bit-identical to the monolithic scan — the
    staged-vs-monolithic parity bar (tests/test_stages.py). Only
    deterministic samplers qualify: ``eta > 0`` draws per-step noise
    from a carried key chain that step-boundary admission cannot
    replay, so it (and deepcache's paired steps) stays monolithic.
    """
    if eta != 0.0:
        raise ValueError(
            "staged serving needs a deterministic sampler (eta=0); "
            "eta>0 carries a per-step noise key chain that step-level "
            "admission cannot replay")

    def _b(a):  # (C,) -> (C, 1, 1, 1) for latent broadcasting
        return a[:, None, None, None]

    if kind == "ddim":
        schedule = DDIMSchedule.create(num_steps)

        def prepare(latents):
            return latents, jnp.zeros_like(latents)

        def slot_step(denoise, x, aux, idx):
            t = schedule.timesteps[idx]
            a_t = _b(schedule.alpha_bars[idx])
            a_prev = _b(schedule.alpha_bars_prev[idx])
            eps = denoise(x, t)
            # ddim_sample's step body with eta pinned to 0: sigma is
            # exactly zero, so the stochastic term vanishes and the
            # remaining expressions are kept verbatim for bit parity
            x0 = (x - jnp.sqrt(1.0 - a_t) * eps) / jnp.sqrt(a_t)
            sigma = 0.0 * jnp.sqrt(
                (1.0 - a_prev) / (1.0 - a_t)
            ) * jnp.sqrt(1.0 - a_t / a_prev)
            dir_xt = jnp.sqrt(
                jnp.maximum(1.0 - a_prev - sigma**2, 0.0)) * eps
            return jnp.sqrt(a_prev) * x0 + dir_xt, aux

        return prepare, slot_step, num_steps

    if kind == "euler":
        eschedule = EulerSchedule.create(num_steps)

        def prepare(latents):
            return latents * eschedule.sigmas[0], jnp.zeros_like(latents)

        def slot_step(denoise, x, aux, idx):
            t = eschedule.timesteps[idx]
            sigma = _b(eschedule.sigmas[idx])
            sigma_next = _b(eschedule.sigmas[idx + 1])
            x_vp = x / jnp.sqrt(1.0 + sigma * sigma)
            eps = denoise(x_vp, t)
            return x + (sigma_next - sigma) * eps, aux

        return prepare, slot_step, num_steps

    if kind == "dpmpp_2m":
        dschedule = DPMppSchedule.create(num_steps)

        def prepare(latents):
            # the multistep history m1 enters zero, exactly as
            # dpmpp_2m_sample's scan carry initializes it
            return latents, jnp.zeros_like(latents)

        def slot_step(denoise, x, aux, idx):
            t = dschedule.timesteps[idx]
            alpha = _b(dschedule.alphas[idx])
            sigma = _b(dschedule.sigmas[idx])
            c_skip = _b(dschedule.c_skip[idx])
            c_d0 = _b(dschedule.c_d0[idx])
            c_d1 = _b(dschedule.c_d1[idx])
            eps = denoise(x, t)
            m0 = (x - sigma * eps) / alpha
            # first/last-step first-order handling rides the
            # precomputed coefficients (c_d1 = 0 there), so a slot
            # admitted mid-flight warms up exactly like a fresh scan
            return c_skip * x + c_d0 * m0 + c_d1 * aux, m0

        return prepare, slot_step, num_steps

    if kind == "consistency":
        # the few-step student rides the staged continuous-batching
        # path: each slot folds its OWN timestep into the deterministic
        # re-noise ladder, so the per-slot arithmetic is exactly
        # consistency_sample's scan body and a solo staged trajectory
        # is bit-identical to the monolithic scan
        cschedule = ConsistencySchedule.create(num_steps, teacher_steps)

        def prepare(latents):
            return latents, jnp.zeros_like(latents)

        def slot_step(denoise, x, aux, idx):
            t = cschedule.timesteps[idx]
            ab = _b(cschedule.alpha_bars[idx])
            ab_next = _b(cschedule.alpha_bars_next[idx])
            c_skip = _b(cschedule.c_skip[idx])
            c_out = _b(cschedule.c_out[idx])
            eps = denoise(x, t)
            x0 = (x - jnp.sqrt(1.0 - ab) * eps) / jnp.sqrt(ab)
            f = c_skip * x + c_out * x0
            noise = jax.vmap(
                lambda ti: consistency_renoise(ti, x.shape[1:], x.dtype)
            )(t)
            return jnp.sqrt(ab_next) * f + \
                jnp.sqrt(1.0 - ab_next) * noise, aux

        return prepare, slot_step, num_steps

    raise ValueError(f"unknown sampler kind {kind!r}; "
                     f"choose from {SAMPLER_KINDS} or 'consistency'")


def make_img2img_sampler(kind: str, num_steps: int, start: int,
                         eta: float = 0.0):
    """Tail sampling from schedule position ``start`` (img2img).

    Returns ``(prepare, sample)``: ``prepare(x0_latents, noise)`` builds
    the solver-space state at the start step (VP for DDIM/DPM++, k-space
    for Euler); ``sample(denoise, x, rng)`` runs the remaining steps and
    returns x0 latents. Every kind integrates the same ODE as its full-
    schedule counterpart in :func:`make_sampler`.
    """
    ab = _alpha_bars()
    ts = _strided_timesteps(num_steps)
    a0 = float(ab[ts[start]])
    if kind == "euler":
        es = EulerSchedule.create(num_steps, start)
        sigma0 = float(np.sqrt((1.0 - a0) / a0))

        def prepare(x0, noise):
            return x0 + sigma0 * noise          # k-space

        def sample(denoise, x, rng=None):
            return euler_sample(denoise, x, es, prescaled=True)

        return prepare, sample

    def prepare(x0, noise):                      # VP space
        return jnp.sqrt(a0) * x0 + jnp.sqrt(1.0 - a0) * noise

    if kind == "ddim":
        ds = DDIMSchedule.create(num_steps, start=start)

        def sample(denoise, x, rng=None):
            return ddim_sample(denoise, x, ds, eta=eta, rng=rng)

        return prepare, sample
    if kind == "dpmpp_2m":
        ps = DPMppSchedule.create(num_steps, start)

        def sample(denoise, x, rng=None):
            return dpmpp_2m_sample(denoise, x, ps)

        return prepare, sample
    raise ValueError(f"unknown sampler kind {kind!r}; "
                     f"choose from {SAMPLER_KINDS}")


def make_sampler(kind: str, num_steps: int, eta: float = 0.0):
    """(kind, steps) -> ``sample(denoise, latents, rng) -> x0 latents``.

    ``latents`` standard normal in every case, so pipelines switch
    samplers by config without touching their latent setup.
    """
    if kind == "ddim":
        schedule = DDIMSchedule.create(num_steps)

        def sample(denoise, latents, rng=None):
            return ddim_sample(denoise, latents, schedule, eta=eta, rng=rng)

        return sample
    if kind == "euler":
        eschedule = EulerSchedule.create(num_steps)

        def sample(denoise, latents, rng=None):
            return euler_sample(denoise, latents, eschedule)

        return sample
    if kind == "dpmpp_2m":
        dschedule = DPMppSchedule.create(num_steps)

        def sample(denoise, latents, rng=None):
            return dpmpp_2m_sample(denoise, latents, dschedule)

        return sample
    raise ValueError(f"unknown sampler kind {kind!r}; "
                     f"choose from {SAMPLER_KINDS}")
