"""Pallas TPU fused GroupNorm+SiLU+conv3x3 for the UNet residual hot loop.

Why this exists (docs/PERF_NOTES.md "What the table says" #1/#3 and
VERDICT r5 "Next round" #2): 3x3 convolutions are ~45% of the SD1.5 UNet's
analytic FLOPs and, until this op, had zero conv-side optimization. On TPU
the convolution is a fusion ROOT for XLA — the GroupNorm affine and SiLU
feeding each ResBlock conv are materialized to HBM before the conv reads
them back, so every norm+act+conv sequence pays an extra round trip of the
level's full activation tensor (20 MB at the 64x64x320 level, x2 convs
x ~8 blocks x 100 CFG forwards per image). This kernel computes

    conv3x3(silu(x * a + b)) + bias        (NHWC, stride 1, SAME)

in one pass: x stays in HBM and each grid program DMAs just its row tile
(plus one halo row above/below) into VMEM, normalizes+activates it there,
and runs the 3x3 conv as nine shifted (TH*W, C) x (C, F) MXU matmuls
accumulated in fp32 — the im2col-free formulation that keeps the lane
dimension on channels, which is exactly the layout the UNet already uses
everywhere (NHWC end to end; models/unet.py docstring). The normalized
tensor never exists in HBM.

The three levers this module lands, per the round-6 plan:

1. **Fusion** — one HBM read of x (row tiles + 2 halo rows), one HBM
   write of the conv output; the GN affine (computed per-(batch,channel)
   in fp32 by ``layers.GroupNorm32(return_affine=True)``, the numerically
   sensitive reduction) stays outside the kernel, so the kernel itself is
   exact up to matmul ordering.
2. **NHWC layout pinning** — both the kernel and the ``lax`` reference
   path fix ``dimension_numbers=("NHWC", "HWIO", "NHWC")`` explicitly,
   so no flax/XLA default change can silently insert transposes around
   the hot loop.
3. **MXU channel padding** (``pad_to``) — SD1.5's 320/960-channel levels
   fill 2.5/7.5 128-lane MXU tiles; rounding the contraction and output
   channel dims up to a ``pad_to`` multiple (zeros feed zeros, the pad
   output slice is dropped) trades a few % nominal FLOPs for full tile
   occupancy. 640/1280/2560 are already lane-aligned and pad to
   themselves.

Block sizing is adaptive (``_choose_blocks``): the row-tile height and
output-channel block shrink together until the per-program working set
fits the VMEM budget, so every SD1.5-512 ResBlock shape (64x64x320
through 8x8x2560 skip-concats) and the SDXL-1024 128x128 levels dispatch
to the kernel rather than silently falling back.

Parity pinning: ``gn_silu_conv3x3_reference`` is the pure-lax
implementation of the same contract; ``tests/test_fused_conv.py`` pins
the Pallas kernel against it (interpret mode on CPU, so tier-1 tests
execute the real kernel — DMA halo logic included) across shapes
including the padded-channel case and a multi-row-tile case, plus an
end-to-end tiny-pipeline flag-on/flag-off comparison.

Dispatch mirrors ops/flash_attention.py: ``fused_conv_ok`` gates on
shapes/VMEM, interpret mode auto-selects off-TPU, and the
``CASSMANTLE_NO_FUSED_CONV`` env var is the operator kill switch that
reverts every site to the XLA path without a config edit.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams across releases; take
# whichever the installed version exports.
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

# Per-program VMEM budget for the block chooser below (raw + normalized
# scratch, double-buffered weight/output blocks, fp32 accumulator).
# Conservative against the ~16 MB/core physical VMEM.
VMEM_BUDGET_BYTES = 12 * 1024 * 1024

# Row-tile and output-channel block candidates, widest first. A tile
# must divide the corresponding dim (Pallas grids are exact); the
# chooser walks these until the working set fits.
_BLOCK_H_CANDIDATES = (32, 16, 8, 4, 2)
_BLOCK_F_CANDIDATES = (256, 128, 64)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def kill_switch_set() -> bool:
    """Operator kill switch (same parse as the flash-cross switch in
    ops/attention.py): any truthy CASSMANTLE_NO_FUSED_CONV reverts every
    fused-conv site to the XLA reference path."""
    return os.environ.get("CASSMANTLE_NO_FUSED_CONV", "").lower() \
        not in ("", "0", "false", "no", "off")


def describe(unet_cfg) -> str:
    """One-line conv-side execution-strategy description for pipeline
    startup logs (serving/pipeline.py, serving/sdxl.py): makes the
    A/B arm visible in serving logs the way lm_int8 logs its footprint.
    Empty when the fused path is off."""
    if not getattr(unet_cfg, "fused_conv", False):
        return ""
    pad = getattr(unet_cfg, "conv_pad_to", 0)
    mode = "kill-switched to XLA" if kill_switch_set() else "active"
    return (f"fused_conv: GroupNorm+SiLU+conv3x3 Pallas path {mode}"
            + (f", channels padded to multiples of {pad}" if pad else ""))


def round_up(n: int, mult: int) -> int:
    """n rounded up to a multiple of ``mult`` (mult<=0 -> n unchanged)."""
    if mult <= 0:
        return n
    return ((n + mult - 1) // mult) * mult


def _vmem_bytes(th: int, w: int, c: int, bf: int, itemsize: int) -> int:
    raw = (th + 2) * w * c * itemsize          # DMA'd rows (tile + halo)
    xn = (th + 2) * (w + 2) * c * itemsize     # normalized, W-padded
    k_blk = 9 * c * bf * itemsize
    out_blk = th * w * bf * itemsize
    acc = th * w * bf * 4
    return raw + xn + 2 * (k_blk + out_blk) + acc


def _choose_blocks(h: int, w: int, c: int, f: int, itemsize: int):
    """(row-tile height, output-channel block) fitting the VMEM budget,
    or None when no candidate combination fits. Largest tiles first:
    fewer grid programs amortize per-program setup; shrinking TH first
    keeps the MXU's N dimension wide as long as possible."""
    th_cands = [t for t in _BLOCK_H_CANDIDATES if h % t == 0 and t < h]
    if h <= _BLOCK_H_CANDIDATES[0]:
        th_cands.insert(0, h)
    bf_cands = [b for b in _BLOCK_F_CANDIDATES if f % b == 0]
    if f <= 512:
        bf_cands.insert(0, f)
    for bf in bf_cands:
        for th in th_cands:
            if _vmem_bytes(th, w, c, bf, itemsize) <= VMEM_BUDGET_BYTES:
                return th, bf
    return None


def fused_conv_ok(x: jax.Array, kernel: jax.Array) -> bool:
    """Shapes the kernel handles profitably (others -> XLA reference).

    Requires NHWC x (B, H, W, C) and HWIO kernel (3, 3, C, F), stride-1
    SAME — the only conv shape in the ResBlock hot loop — and a
    (row-tile, F-block) combination whose working set fits the VMEM
    budget. With the adaptive chooser this holds for every SD1.5-512
    ResBlock shape (64x64x320..8x8x2560) and the SDXL-1024 128x128
    levels; exotic shapes fall back to the reference."""
    if x.ndim != 4 or kernel.ndim != 4:
        return False
    b, h, w, c = x.shape
    kh, kw, kc, f = kernel.shape
    if (kh, kw) != (3, 3) or kc != c:
        return False
    if h < 3 or w < 3:
        return False  # border taps would cross the whole image
    return _choose_blocks(h, w, c, f, x.dtype.itemsize) is not None


def gn_silu_conv3x3_reference(
    x: jax.Array,          # (B, H, W, C) activations
    a: jax.Array,          # (B, C) fp32 GroupNorm affine scale (inv*gamma)
    b: jax.Array,          # (B, C) fp32 GroupNorm affine shift
    kernel: jax.Array,     # (3, 3, C, F) HWIO conv weights
    bias: jax.Array,       # (F,)
) -> jax.Array:
    """Pure-lax reference for the fused contract, layout-pinned NHWC/HWIO.

    Matches the unfused module path bit-for-bit in spirit: the affine
    applies as one FMA in the activation dtype (exactly what
    ``layers._GroupNormCore`` does), SiLU in the activation dtype, and
    the conv computes in the activation dtype like ``nn.Conv(dtype=...)``.
    """
    dt = x.dtype
    h = x * a[:, None, None, :].astype(dt) + b[:, None, None, :].astype(dt)
    h = jax.nn.silu(h)
    out = jax.lax.conv_general_dilated(
        h, kernel.astype(dt), window_strides=(1, 1),
        padding=((1, 1), (1, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return out + bias.astype(dt)[None, None, None, :]


def _fused_kernel(x_hbm, a_ref, b_ref, k_ref, bias_ref, o_ref,
                  raw_ref, xn_ref, sems, *,
                  th: int, w: int, nh: int):
    """One (batch, row-tile, F-block) program.

    At f-block 0 the program DMAs its row tile plus one halo row
    above/below from HBM (x never materializes normalized), applies the
    GN affine + SiLU in fp32, and writes the result into zero-bordered
    VMEM scratch; the F axis is sequential, so later F blocks of the
    same tile reuse the scratch. Then nine shifted MXU matmuls
    accumulate the conv in fp32.
    """
    bi = pl.program_id(0)
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _load_and_normalize():
        row0 = i * th
        # main rows -> raw[1 : th+1]
        main = pltpu.make_async_copy(
            x_hbm.at[bi, pl.ds(row0, th)],
            raw_ref.at[pl.ds(1, th)], sems.at[0])
        main.start()

        @pl.when(i > 0)
        def _top():
            top = pltpu.make_async_copy(
                x_hbm.at[bi, pl.ds(row0 - 1, 1)],
                raw_ref.at[pl.ds(0, 1)], sems.at[1])
            top.start()
            top.wait()

        @pl.when(i < nh - 1)
        def _bottom():
            bot = pltpu.make_async_copy(
                x_hbm.at[bi, pl.ds(row0 + th, 1)],
                raw_ref.at[pl.ds(th + 1, 1)], sems.at[2])
            bot.start()
            bot.wait()

        main.wait()
        xv = raw_ref[:].astype(jnp.float32)             # (TH+2, W, C)
        av = a_ref[0].astype(jnp.float32)               # (C,)
        bv = b_ref[0].astype(jnp.float32)
        xn = xv * av[None, None, :] + bv[None, None, :]
        xn = xn * jax.nn.sigmoid(xn)                    # SiLU, fp32
        xn_ref[:] = jnp.zeros(xn_ref.shape, xn_ref.dtype)
        xn_ref[:, 1:w + 1, :] = xn.astype(xn_ref.dtype)

        # image-edge halo rows are SAME zero padding, not data (the raw
        # rows there were never DMA'd — whatever the scratch held must
        # not leak through silu(affine(.)) into the border taps)
        @pl.when(i == 0)
        def _zero_top():
            xn_ref[0:1, :, :] = jnp.zeros(
                (1,) + xn_ref.shape[1:], xn_ref.dtype)

        @pl.when(i == nh - 1)
        def _zero_bottom():
            xn_ref[th + 1:th + 2, :, :] = jnp.zeros(
                (1,) + xn_ref.shape[1:], xn_ref.dtype)

    bf = k_ref.shape[-1]
    acc = jnp.zeros((th * w, bf), jnp.float32)
    for dy in range(3):
        for dx in range(3):
            patch = xn_ref[dy:dy + th, dx:dx + w, :]
            patch = patch.reshape(th * w, patch.shape[-1])
            acc += jax.lax.dot_general(
                patch, k_ref[dy, dx],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
    acc += bias_ref[0].astype(jnp.float32)[None, :]
    o_ref[0] = acc.reshape(th, w, bf).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("interpret", "block_h", "block_f"))
def _fused_bhwc(x, a, b, kernel, bias, interpret: bool,
                block_h: int, block_f: int):
    """(B, H, W, C) fused GN-affine+SiLU+conv3x3 -> (B, H, W, F)."""
    bsz, h, w, c = x.shape
    f = kernel.shape[-1]
    nh = h // block_h
    nf = f // block_f
    grid = (bsz, nh, nf)
    kern = functools.partial(_fused_kernel, th=block_h, w=w, nh=nh)
    compiler_params = _CompilerParams(
        # batch rows independent; row tiles independent; the F axis
        # reuses each tile's normalized scratch sequentially
        dimension_semantics=("parallel", "arbitrary", "arbitrary"),
    )
    flops = 2.0 * bsz * h * w * 9 * c * f
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),      # x stays in HBM
            pl.BlockSpec((1, c), lambda bi, i, j: (bi, 0)),
            pl.BlockSpec((1, c), lambda bi, i, j: (bi, 0)),
            pl.BlockSpec((3, 3, c, block_f), lambda bi, i, j: (0, 0, 0, j)),
            pl.BlockSpec((1, block_f), lambda bi, i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((1, block_h, w, block_f),
                               lambda bi, i, j: (bi, i, 0, j)),
        out_shape=jax.ShapeDtypeStruct((bsz, h, w, f), x.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_h + 2, w, c), x.dtype),      # raw rows
            pltpu.VMEM((block_h + 2, w + 2, c), x.dtype),  # silu(gn(x))
            pltpu.SemaphoreType.DMA((3,)),
        ],
        compiler_params=compiler_params,
        cost_estimate=pl.CostEstimate(
            flops=flops,
            bytes_accessed=(bsz * h * w * (c + f) + 9 * c * f)
            * x.dtype.itemsize,
            transcendentals=bsz * h * w * c,  # the sigmoid
        ),
        interpret=interpret,
    )(x, a, b, kernel, bias)


def _pad_last(t: jax.Array, to: int) -> jax.Array:
    pad = to - t.shape[-1]
    if pad == 0:
        return t
    widths = [(0, 0)] * (t.ndim - 1) + [(0, pad)]
    return jnp.pad(t, widths)


def gn_silu_conv3x3(
    x: jax.Array,          # (B, H, W, C)
    a: jax.Array,          # (B, C) fp32 GroupNorm affine scale
    b: jax.Array,          # (B, C) fp32 GroupNorm affine shift
    kernel: jax.Array,     # (3, 3, C, F) HWIO
    bias: jax.Array,       # (F,)
    *,
    pad_to: int = 0,
    interpret=None,
) -> jax.Array:
    """Fused ``conv3x3(silu(gn_affine(x))) + bias`` with dispatch.

    ``pad_to`` > 0 rounds the C and F channel dims up to that multiple
    (zero channels: a zero input channel contributes silu(0)=0 through
    zero kernel rows; pad output channels are sliced off) so the MXU
    contraction/output tiles fill — the 320->384 / 960->1024 trade at
    SD1.5's non-aligned levels. Shapes the kernel can't take, or a set
    CASSMANTLE_NO_FUSED_CONV, fall back to the layout-pinned lax
    reference (still one call site, so the A/B stays honest).
    """
    if interpret is None:
        interpret = not _on_tpu()
    c = x.shape[-1]
    f = kernel.shape[-1]
    cp = round_up(c, pad_to)
    fp = round_up(f, pad_to)
    if kill_switch_set():
        return gn_silu_conv3x3_reference(x, a, b, kernel, bias)
    xp = _pad_last(x, cp)
    kp = kernel
    if cp != c:
        kp = jnp.pad(kp, ((0, 0), (0, 0), (0, cp - c), (0, 0)))
    kp = _pad_last(kp, fp)
    if not fused_conv_ok(xp, kp):
        return gn_silu_conv3x3_reference(x, a, b, kernel, bias)
    blocks = _choose_blocks(x.shape[1], x.shape[2], cp, fp,
                            x.dtype.itemsize)
    ap = _pad_last(a, cp)
    bp = _pad_last(b, cp)
    biasp = _pad_last(bias, fp).astype(jnp.float32)[None, :]
    out = _fused_bhwc(
        xp, ap.astype(jnp.float32), bp.astype(jnp.float32),
        kp.astype(x.dtype), biasp,
        bool(interpret), blocks[0], blocks[1],
    )
    return out[..., :f]
