"""Host-memory int8 wordlist embedding table: the scoring ladder's rung 0.

Guesses are the only traffic that scales with users, yet the guess
vocabulary is finite — ``data/wordlist.txt`` plus the round answers
known at promotion time. So the scorer embedding for the entire
vocabulary is precomputed once (tools/build_embed_table.py) and served
from host memory: a fully in-vocabulary guess completes as one int8 dot
product with ZERO device dispatches, no queue hop, and no admission
check, while OOV text keeps the full ladder (LRU → queue → breaker →
device). This is the same placement argument the cost model makes for
stages — put each stage on the cheapest compute that can serve it, and
for a known-word dot product that is the host, not the chip.

Artifact format (``data/embed_table.bin``)::

    magic  b"CMETB1\\n"
    uint64 little-endian header length
    JSON header {version, signature, wordlist_digest, scorer_signature,
                 weights_fingerprint, dim, count, seq_len, words, ...}
    zero padding to a 64-byte boundary
    int8   rows   (count, dim)   symmetric per-row quantized embeddings
    f32    scales (count,)       absmax/127 per row (provenance; the
                                 unit-cosine math below cancels it)
    f32    norms  (count,)       ||int8 row||_2, precomputed

The table is signature-stamped exactly like ``data/cost_model.json``:
the signature digests the wordlist content, the scorer config
(obs/costmodel.scorer_signature), and the weights identity, so config
or wordlist drift makes the runtime refuse to arm the stale table (and
a tier-1 gate in tests/test_embed_table.py fails until it is rebuilt).

Fidelity: rows are stored int8 with per-row symmetric scales. Lookup
returns ``q / ||q||`` — the unit vector of the dequantized row (the
scale cancels) — and the fused ``score_pairs`` path computes
``int32_dot(q_g, q_a) / (||q_g||·||q_a||)``, which is EXACTLY the
cosine of the vectors lookup returns. The two rungs therefore agree to
float rounding, and the only error vs the fp32 scorer is quantization
noise, bounded and test-pinned across the full committed wordlist.

Deliberately jax-free (like serving/fake_scorer.py): --fake drill
workers arm a hash-embedding variant of this same table, and they must
never pay (or hang on) an accelerator backend import.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import struct
import threading
import unicodedata
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from cassmantle_tpu.obs.costmodel import _digest, scorer_signature
from cassmantle_tpu.utils.logging import get_logger, metrics

log = get_logger("embed_table")

TABLE_VERSION = 1
_MAGIC = b"CMETB1\n"
_ALIGN = 64

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
EMBED_TABLE_PATH = os.path.join(_REPO_ROOT, "data", "embed_table.bin")


def embed_table_disabled() -> bool:
    """Kill switch: ``CASSMANTLE_NO_EMBED_TABLE=1`` skips the table rung
    everywhere (scorer ladder, service fast path, answer pinning),
    reverting bit-exactly to the LRU/device path. Read per call so an
    operator toggle takes effect without a restart."""
    return os.environ.get(
        "CASSMANTLE_NO_EMBED_TABLE", "").lower() in ("1", "true", "yes", "on")


def fake_table_enabled() -> bool:
    """Opt-in arming of the hash-embedding table on --fake workers
    (``CASSMANTLE_FAKE_EMBED_TABLE=1``). Off by default so existing fake
    benches/tests keep their bit-identical hash-similarity scores; the
    rooms_load/overload A/B arms flip it per worker."""
    return os.environ.get(
        "CASSMANTLE_FAKE_EMBED_TABLE", "").lower() in (
            "1", "true", "yes", "on")


def normalize_key(text: str) -> str:
    """Table lookup key: NFKC + casefold + strip. Safe because both
    sides of every scored pair are already ``.strip().lower()``-ed by
    the engine (engine/scoring.py) and the WordPiece/BPE tokenizers
    lowercase anyway (utils/tokenizers.py), so two texts mapping to one
    key embed identically on the device path too."""
    return unicodedata.normalize("NFKC", text).casefold().strip()


# -- signatures -------------------------------------------------------------

def wordlist_digest(words: Sequence[str]) -> str:
    h = hashlib.sha256()
    for w in words:
        h.update(w.encode("utf-8"))
        h.update(b"\n")
    return h.hexdigest()[:16]


def weights_fingerprint(weights_dir: Optional[str]) -> str:
    """Identity of the encoder parameters the rows came from: sha256 of
    minilm.safetensors when real weights exist, else the deterministic
    random-init marker (models/weights.py init_params_cached, seed 7)."""
    if weights_dir:
        path = os.path.join(weights_dir, "minilm.safetensors")
        if os.path.exists(path):
            h = hashlib.sha256()
            with open(path, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
            return "sha256:" + h.hexdigest()[:16]
    return "random-init:seed7"


def table_signature(mcfg, seq_len: int, words: Sequence[str],
                    weights_fp: str) -> str:
    """One digest binding everything the rows depend on — same
    discipline as data/cost_model.json entries: artifact and runtime
    derive the signature from the same definition, or the match
    silently never fires and the device path serves everything."""
    return _digest("embed_table", TABLE_VERSION, wordlist_digest(words),
                   scorer_signature(mcfg, seq_len), weights_fp)


# -- quantization -----------------------------------------------------------

def quantize_rows(emb: np.ndarray
                  ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """fp32 rows -> (int8 rows, per-row scales, int8-row L2 norms).

    Symmetric per-row absmax quantization. Norms are ||q||_2 of the
    INT8 rows: lookup and the fused dot both divide by them, making the
    two rungs produce identical cosines by construction."""
    emb = np.asarray(emb, dtype=np.float32)
    absmax = np.max(np.abs(emb), axis=1)
    scales = (np.maximum(absmax, 1e-8) / 127.0).astype(np.float32)
    q = np.clip(np.rint(emb / scales[:, None]), -127, 127).astype(np.int8)
    norms = np.sqrt(
        np.sum(q.astype(np.float32) ** 2, axis=1)).astype(np.float32)
    # an all-zero fp row quantizes to all-zero int8; keep its norm
    # divisor finite (the unit vector is then the zero vector)
    norms = np.maximum(norms, 1e-8).astype(np.float32)
    return q, scales, norms


# -- artifact I/O -----------------------------------------------------------

def _pad_to(n: int, align: int = _ALIGN) -> int:
    return (align - n % align) % align


def write_table(path: str, words: Sequence[str], emb: np.ndarray,
                mcfg, seq_len: int, weights_fp: str,
                generated_by: str = "tools/build_embed_table.py") -> Dict:
    """Quantize ``emb`` (len(words), dim) and write the artifact.
    Returns the header dict (with the stamped signature)."""
    words = [normalize_key(w) for w in words]
    if len(set(words)) != len(words):
        raise ValueError("wordlist collapses under normalize_key; "
                         "dedupe before emitting")
    q, scales, norms = quantize_rows(emb)
    header = {
        "version": TABLE_VERSION,
        "signature": table_signature(mcfg, seq_len, words, weights_fp),
        "wordlist_digest": wordlist_digest(words),
        "scorer_signature": scorer_signature(mcfg, seq_len),
        "weights_fingerprint": weights_fp,
        "dim": int(q.shape[1]),
        "count": int(q.shape[0]),
        "seq_len": int(seq_len),
        "generated_by": generated_by,
        "words": list(words),
    }
    blob = json.dumps(header, separators=(",", ":")).encode("utf-8")
    buf = io.BytesIO()
    buf.write(_MAGIC)
    buf.write(struct.pack("<Q", len(blob)))
    buf.write(blob)
    buf.write(b"\0" * _pad_to(buf.tell()))
    buf.write(q.tobytes(order="C"))
    buf.write(scales.astype(np.float32).tobytes())
    buf.write(norms.astype(np.float32).tobytes())
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(buf.getvalue())
    os.replace(tmp, path)
    return header


def _read_header_raw(path: str) -> Tuple[Dict, int]:
    """(header dict, byte offset of the int8 row data)."""
    with open(path, "rb") as f:
        magic = f.read(len(_MAGIC))
        if magic != _MAGIC:
            raise ValueError(f"{path}: not an embed table (bad magic)")
        (hlen,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(hlen).decode("utf-8"))
    if header.get("version") != TABLE_VERSION:
        raise ValueError(
            f"{path}: table version {header.get('version')} != "
            f"{TABLE_VERSION}")
    data_off = len(_MAGIC) + 8 + hlen
    return header, data_off + _pad_to(data_off)


def read_header(path: str) -> Dict:
    """Cheap header-only read (no row data touched) — what the tier-1
    drift gate and the runtime signature check use."""
    return _read_header_raw(path)[0]


# -- the table --------------------------------------------------------------

class EmbedTable:
    """Memory-mapped int8 embedding table + runtime answer-pin overlay.

    Lookups and pins are served under a short-hold leaf lock
    (docs/STATIC_ANALYSIS.md): dict/array reads only — quantization of
    a pinned row happens outside it, and no other lock is ever taken
    while holding it."""

    def __init__(self, words: Sequence[str], rows: np.ndarray,
                 norms: np.ndarray, header: Optional[Dict] = None) -> None:
        self._index: Dict[str, int] = {
            w: i for i, w in enumerate(words)}
        self._rows = rows            # (count, dim) int8 (mmap or array)
        self._norms = norms          # (count,) f32
        self.header = header or {}
        self.dim = int(rows.shape[1])
        self.signature = self.header.get("signature", "")
        # runtime overlay: round answers pinned at promotion time,
        # quantized with the SAME scheme so pinned words score through
        # the identical int8 math as committed rows
        self._pins: Dict[str, Tuple[np.ndarray, np.float32]] = {}
        self._lock = threading.Lock()

    # -- constructors --------------------------------------------------

    @classmethod
    def load(cls, path: str = EMBED_TABLE_PATH,
             expected_signature: Optional[str] = None
             ) -> Optional["EmbedTable"]:
        """mmap the committed artifact; None (never raise) when the file
        is absent, malformed, or — the drift case — its signature does
        not match ``expected_signature``. A stale table must never arm:
        serving wrong-embedding scores silently is worse than paying
        the device path."""
        try:
            header, data_off = _read_header_raw(path)
        except (OSError, ValueError) as exc:
            log.info("embed table not armed (%s)", exc)
            return None
        if expected_signature is not None and \
                header["signature"] != expected_signature:
            log.warning(
                "embed table signature mismatch (committed %s != "
                "expected %s); not arming — rebuild with "
                "`python -m cassmantle_tpu build-embed-table --emit`",
                header["signature"], expected_signature)
            return None
        count, dim = header["count"], header["dim"]
        rows = np.memmap(path, dtype=np.int8, mode="r",
                         offset=data_off, shape=(count, dim))
        norms_off = data_off + count * dim + count * 4  # skip scales
        norms = np.array(np.memmap(path, dtype=np.float32, mode="r",
                                   offset=norms_off, shape=(count,)))
        return cls(header["words"], rows, norms, header=header)

    @classmethod
    def from_embeddings(cls, words: Sequence[str], emb: np.ndarray,
                        signature: str = "") -> "EmbedTable":
        """In-memory table from fp32 rows (tests, fake workers)."""
        keys = [normalize_key(w) for w in words]
        q, _scales, norms = quantize_rows(emb)
        return cls(keys, q, norms, header={"signature": signature})

    # -- reads ---------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._index) + len(self._pins)

    def _get(self, key: str) -> Optional[Tuple[np.ndarray, np.float32]]:
        with self._lock:
            i = self._index.get(key)
            if i is not None:
                return self._rows[i], self._norms[i]
            return self._pins.get(key)

    def contains(self, text: str) -> bool:
        return self._get(normalize_key(text)) is not None

    def lookup(self, text: str) -> Optional[np.ndarray]:
        """word -> fresh (dim,) f32 UNIT embedding, or None when OOV.
        The unit vector of the dequantized row: the per-row scale
        cancels, so only q and its precomputed norm are needed."""
        hit = self._get(normalize_key(text))
        if hit is None:
            return None
        q, norm = hit
        return q.astype(np.float32) / np.float32(norm)

    def score_pairs(self, pairs: Sequence[Tuple[str, str]]
                    ) -> Tuple[np.ndarray, np.ndarray]:
        """Fused int8-dot scoring: [(guess, answer)] ->
        (scores f32 (n,), served bool (n,)). A pair is served only when
        BOTH sides are in the table; unserved pairs score 0 here and
        keep the full ladder. ``scorer.table_hits`` counts texts served
        (2 per served pair), mirroring ``scorer.texts`` units."""
        n = len(pairs)
        scores = np.zeros((n,), dtype=np.float32)
        served = np.zeros((n,), dtype=bool)
        hits = 0
        for i, (g, a) in enumerate(pairs):
            gq = self._get(normalize_key(g))
            if gq is None:
                continue
            aq = self._get(normalize_key(a))
            if aq is None:
                continue
            # int32 accumulate: dim<=1024 rows of |q|<=127 can't overflow
            dot = np.dot(gq[0].astype(np.int32), aq[0].astype(np.int32))
            scores[i] = np.float32(dot) / (np.float32(gq[1])
                                           * np.float32(aq[1]))
            served[i] = True
            hits += 2
        if hits:
            metrics.inc("scorer.table_hits", hits)
        return scores, served

    # -- runtime pins --------------------------------------------------

    def pin(self, word: str, emb: np.ndarray) -> None:
        """Overlay a round answer at promotion time: quantize the fp32
        embedding with the committed scheme and serve it from the same
        int8 math. Pins accumulate for the process lifetime (a handful
        of words per round — bounded by round cadence, not traffic)."""
        key = normalize_key(word)
        if not key:
            return
        q, _scales, norms = quantize_rows(
            np.asarray(emb, dtype=np.float32)[None, :])
        row, norm = q[0], norms[0]
        with self._lock:
            if key in self._index:
                return
            self._pins[key] = (row, np.float32(norm))
        metrics.inc("scorer.table_pins", 1)


# -- fake-worker wiring -----------------------------------------------------

def build_fake_table(extra_words: Sequence[str] = ()) -> EmbedTable:
    """Hash-embedding table over the full wordlist for --fake workers:
    the same table rung and int8 math as production, with
    engine/content.hash_embed standing in for the MiniLM encoder (the
    established fake-scorer stand-in). Jax-free by construction."""
    from cassmantle_tpu.engine.content import hash_embed
    from cassmantle_tpu.server.assets import load_wordlist

    seen = dict.fromkeys(
        normalize_key(w) for w in load_wordlist())
    for w in extra_words:
        seen.setdefault(normalize_key(w))
    words = [w for w in seen if w]
    emb = hash_embed(words)
    table = EmbedTable.from_embeddings(words, emb, signature="fake")
    metrics.gauge("scorer.table_rows", len(table))
    return table


class TableFirstSimilarity:
    """SimilarityFn wrapper: table rung first, ``fallback`` for the
    rest. This is the --fake worker's ladder (real workers wire the
    table through InferenceService.similarity instead, where the fast
    path must also skip the breaker/queue machinery)."""

    def __init__(self, table: EmbedTable, fallback) -> None:
        self._table = table
        self._fallback = fallback

    async def __call__(self, pairs) -> np.ndarray:
        pairs = list(pairs)
        if embed_table_disabled():
            return np.asarray(await self._fallback(pairs),
                              dtype=np.float32)
        scores, served = self._table.score_pairs(pairs)
        rest = [i for i in range(len(pairs)) if not served[i]]
        if len(rest) < len(pairs):
            # same attribution the production fast path records via
            # serving.overload.note_table_served (counted here directly
            # to keep ops free of a serving-layer import)
            metrics.inc("overload.table_served", len(pairs) - len(rest))
        if rest:
            oov = sum(
                1
                for i in rest
                for side in pairs[i]
                if not self._table.contains(side))
            if oov:
                metrics.inc("scorer.table_oov", oov)
            fb = np.asarray(
                await self._fallback([pairs[i] for i in rest]),
                dtype=np.float32)
            for j, i in enumerate(rest):
                scores[i] = fb[j]
        return scores


def pin_answers_hash(table: EmbedTable, words: Sequence[str]) -> int:
    """Fake-worker pin hook: embed unseen answers with hash_embed and
    pin them (the fake templates include words absent from the
    wordlist, e.g. 'crooked'). Returns pins performed."""
    from cassmantle_tpu.engine.content import hash_embed

    todo: List[str] = []
    for w in words:
        key = normalize_key(w)
        if key and key not in todo and not table.contains(key):
            todo.append(key)
    if not todo:
        return 0
    emb = hash_embed(todo)
    for w, row in zip(todo, emb):
        table.pin(w, row)
    return len(todo)
