"""HTTP/WS API surface (aiohttp) — the reference's FastAPI layer rebuilt.

Route-for-route parity with the reference (SURVEY.md §1 L4, §3.3-3.5):

- ``GET  /``               game page (static/index.html)
- ``GET  /init``           new session id in a cookie (main.py:47-53)
- ``GET  /client/status``  {won, needInitialization} (main.py:81-93)
- ``GET  /fetch/contents`` {image: b64 jpeg (per-session blur), prompt
                            json, story} (main.py:95-111)
- ``POST /compute_score``  {inputs: {mask_idx: guess}} -> scores
                            (main.py:113-120)
- ``WS   /clock``          1 Hz {time, reset, conns} push (main.py:55-79)
- ``GET  /metrics``        JSON snapshot by default; Prometheus text
                           exposition under ``Accept: text/plain``;
                           ``?scope=cluster`` federates every live
                           member's registry into one view and
                           ``?format=state`` is the peer wire format
                           (new; SURVEY.md §5.5, ISSUES 3+9)
- ``GET  /debugz``         flight-recorder event ring + trace lookup
                           (``?trace=<X-Trace-Id>``; ``&scope=cluster``
                           merges the trace across workers) — the
                           serving black box (new; ISSUES 3+9)
- ``GET  /sloz``           SLO burn-rate verdicts per objective
                           (obs/slo.py; advisory in /readyz) (new;
                           ISSUE 9)
- ``GET  /healthz``        liveness: process + store + device (new)
- ``GET  /readyz``         readiness: supervisor verdict — breakers,
                           dispatch watchdog, device health fused; 503 +
                           Retry-After while degraded (new; ISSUE 2)
- ``POST /debug/trace``    on-demand jax.profiler capture (new; §5.1;
                            loopback or cluster-token, single-flight)
- static mounts ``/static`` and ``/data`` (main.py:25-27)

Rate limits mirror the reference: 3/s default, 2/s API routes, per IP.
"""

from __future__ import annotations

import asyncio
import functools
import math
import os
import re
import tempfile
import uuid
from typing import Optional

from aiohttp import WSMsgType, web

from cassmantle_tpu import chaos
from cassmantle_tpu.chaos import afault_point
from cassmantle_tpu.config import FrameworkConfig, ObsConfig
from cassmantle_tpu.engine.game import PROBE_ROOM, Game
from cassmantle_tpu.fabric.rooms import RoomFabric
from cassmantle_tpu.obs import configure_observability, flight_recorder, tracer
from cassmantle_tpu.obs.device import device_metrics
from cassmantle_tpu.obs.process import ProcessMetrics
from cassmantle_tpu.obs.slo import SloEngine, default_objectives
from cassmantle_tpu.obs.trace import (
    current_ctx,
    current_marks,
    format_traceparent,
    parse_traceparent,
)
from cassmantle_tpu.serving import overload
from cassmantle_tpu.serving.queue import OverloadShed
from cassmantle_tpu.utils import leak_sentinel
from cassmantle_tpu.utils.logging import (
    NULL_METRICS,
    get_logger,
    merge_states,
    metrics,
)

log = get_logger("app")

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
STATIC_DIR = os.path.join(_ROOT, "static")
DATA_DIR = os.path.join(_ROOT, "data")
MEDIA_DIR = os.path.join(_ROOT, "media")

_FABRIC = web.AppKey("fabric", RoomFabric)
_TRACE_STATE = web.AppKey("trace_state", dict)
_OBS_CFG = web.AppKey("obs_cfg", ObsConfig)
_SLO = web.AppKey("slo_engine", SloEngine)
_PROCESS = web.AppKey("process_metrics", ProcessMetrics)
# mutable holders (aiohttp freezes app keys at startup): the lazy peer
# ClientSession for cluster fan-outs, and the background obs tasks
_PEER_HTTP = web.AppKey("peer_http", dict)
_OBS_TASKS = web.AppKey("obs_tasks", list)
# mutable holder for the canary prober (None when CASSMANTLE_NO_PROBER
# disabled it at boot — /readyz then reports {"enabled": False})
_PROBER = web.AppKey("prober", dict)


def _env_flag_set(name: str) -> bool:
    """One truthy-parse for the obs kill switches (1/true/yes/on)."""
    return os.environ.get(name, "").lower() in ("1", "true", "yes",
                                                "on")


def _cluster_obs_enabled() -> bool:
    """CASSMANTLE_NO_CLUSTER_OBS=1 turns off the cross-worker surface:
    inbound trace contexts are ignored and cluster fan-outs answer
    worker-local — the kill switch for a fleet where the peer trust
    set (membership-advertised hosts) cannot be relied on."""
    return not _env_flag_set("CASSMANTLE_NO_CLUSTER_OBS")


def _client_ip(request: web.Request) -> str:
    peer = request.transport.get_extra_info("peername") if request.transport else None
    return peer[0] if peer else "?"


def _session_id(request: web.Request) -> Optional[str]:
    # the ?session= fallback keeps identity across a cross-worker 307:
    # cookies are host-scoped, a query param rides the Location header
    return request.cookies.get("session_id") or \
        request.query.get("session")


def _explicit_room(request: web.Request) -> Optional[str]:
    return request.query.get("room") or request.headers.get("X-Room") \
        or request.cookies.get("room")


def _room_of(request: web.Request) -> str:
    """The room this request belongs to: an explicit ?room= / X-Room /
    cookie wins; otherwise the session (or client IP) consistent-hashes
    onto the room list — the same room on every request, from any
    worker, with no stored mapping (fabric/directory.py)."""
    explicit = _explicit_room(request)
    if explicit:
        return explicit
    fabric = request.app[_FABRIC]
    principal = _session_id(request) or _client_ip(request)
    return fabric.directory.room_for_session(principal)


def _check_room_ownership(request: web.Request, fabric: RoomFabric,
                          room: str) -> None:
    """The ONE ownership gate for every room-scoped route: a room owned
    by another worker answers 307 to the owner's advertised address;
    with no advertised owner address the room serves locally — the
    per-room store locks keep that merely suboptimal, never unsafe.

    The Location pins the resolved room AND the session as query
    params: cookies are host-scoped and do not survive the hop, so a
    cookie-only client would otherwise re-resolve a DIFFERENT room on
    the target worker (redirect ping-pong between owners)."""
    if request.headers.get("X-Score-Hedge") == "1" and \
            _is_cluster_peer(request, fabric):
        # an authenticated scorer hedge from a sick peer (ISSUE 12):
        # the room's owner IS the worker that hedged here, so the
        # ownership redirect would bounce the request straight back.
        # Serve it locally — the shared store keeps the session/score
        # writes consistent, the same contract ownerless foreign
        # serves already rely on.
        metrics.inc("score.hedge_served")
        return
    if fabric.is_local(room):
        return
    addr = fabric.owner_addr(room)
    if not addr:
        metrics.inc("fabric.foreign_serves")
        return
    metrics.inc("fabric.redirects")
    url = request.rel_url.update_query(room=room)
    session = _session_id(request)
    if session:
        url = url.update_query(session=session)
    # the Location also pins the ACTIVE trace context (ISSUE 9): headers
    # don't survive a redirect, a query param does — the owner worker
    # continues this trace instead of starting a fresh one, so the hop
    # and the owner's device stages read as ONE trace. The redirect is
    # carried BACK by the (untrusted) client, whose IP proves nothing,
    # so the param travels with an HMAC signature under the store-
    # distributed cluster secret: the owner honors the signature, not
    # the bearer.
    ctx = current_ctx()
    if ctx is not None:
        tp = format_traceparent(ctx)
        url = url.update_query(traceparent=tp)
        sig = fabric.sign_trace(tp)
        if sig:
            url = url.update_query(tracesig=sig)
    raise web.HTTPTemporaryRedirect(location=addr.rstrip("/") + str(url))


async def _resolve_probe_game(request: web.Request,
                              fabric: RoomFabric):
    """(PROBE_ROOM, probe game) for an authenticated canary request
    (ISSUE 18). The probe room exists on EVERY worker (no directory
    entry, no ownership gate — a probe targets a specific worker and
    must be answered by it, never redirected), is invisible to
    outsiders (404, exactly like any unknown room), and lazily seeds
    its known-answer round so a cross-worker probe landing on a cold
    peer still plays a full game. The request's trace is marked: probe
    traffic bypasses admission control (serving/queue.py) and is
    always tail-retained."""
    from cassmantle_tpu.obs.prober import ensure_probe_round

    if not _is_cluster_peer(request, fabric):
        # indistinguishable from a nonexistent room: the probe surface
        # must not advertise itself to players
        raise web.HTTPNotFound(text=f"unknown room {PROBE_ROOM!r}")
    game = fabric.probe_game()
    await ensure_probe_round(game)
    ctx = current_ctx()
    if ctx is not None:
        ctx.marks["probe"] = True
    tracer.mark_retain("probe")
    return PROBE_ROOM, game


async def _resolve_game(request: web.Request):
    """(room, game) for this request, after the ownership gate."""
    fabric = request.app[_FABRIC]
    if _explicit_room(request) == PROBE_ROOM:
        return await _resolve_probe_game(request, fabric)
    room = _room_of(request)
    if not fabric.directory.has_room(room):
        raise web.HTTPNotFound(text=f"unknown room {room!r}")
    _check_room_ownership(request, fabric, room)
    try:
        return room, await fabric.game_for(room)
    except KeyError:
        raise web.HTTPNotFound(text=f"unknown room {room!r}")


def _is_loopback(request: web.Request) -> bool:
    """Fail closed: an unresolvable peer (unix socket behind a proxy)
    is NOT local — same rule as /debug/trace."""
    return request.remote in ("127.0.0.1", "::1")


def _is_cluster_peer(request: web.Request, fabric: RoomFabric) -> bool:
    """The cluster trust gate, three legs: loopback; the connecting
    host exactly matches a live member's advertised address
    (fabric.peer_hosts); or the request bears the cluster-secret
    token (``X-Cluster-Auth``, fabric.cluster_token — what peer
    fan-outs send, and the leg that works when advertised addresses
    are DNS names or egress is NATed). All three anchor in state the
    fleet already trusts (the process, the shared store). Guards the
    /debugz and cluster-federation surfaces; an outsider is counted
    and refused, never honored."""
    if _is_loopback(request):
        return True
    if request.remote in fabric.peer_hosts():
        return True
    token = request.headers.get("X-Cluster-Auth")
    return bool(token) and fabric.verify_cluster_token(token)


@web.middleware
async def cors_middleware(request: web.Request, handler):
    if request.method == "OPTIONS":
        response = web.Response()
    else:
        response = await handler(request)
    response.headers["Access-Control-Allow-Origin"] = "*"
    response.headers["Access-Control-Allow-Credentials"] = "true"
    response.headers["Access-Control-Allow-Methods"] = "GET, POST"
    response.headers["Access-Control-Allow-Headers"] = "*"
    return response


@web.middleware
async def tracing_middleware(request: web.Request, handler):
    """One root span per request; the trace ID returns as ``X-Trace-Id``
    (sampled traces are then queryable at ``/debugz?trace=<id>``).
    Static asset mounts and the probe/scrape surfaces skip tracing —
    a 1/s readiness probe plus a Prometheus scraper would otherwise
    FIFO-flush the bounded trace ring of the player-request traces an
    operator actually triages."""
    # /clock also skips: its WS handshake is prepared before the
    # middleware regains control (the header could never be returned)
    # and app.js's 2 s reconnect loop would mint a ring-flushing trace
    # per flap
    if request.path.startswith(("/static", "/data", "/media")) or \
            request.path in ("/healthz", "/readyz", "/metrics",
                             "/debugz", "/debug/trace", "/clock",
                             "/sloz"):
        return await handler(request)
    fabric = request.app[_FABRIC]
    # inbound trace context (ISSUE 9): a traceparent header (peer
    # fan-out, mesh) or query param (rides a cross-worker 307 Location
    # through the redirecting client) CONTINUES that trace — honored
    # from cluster members/loopback, or via the QUERY param when it
    # carries a valid ``tracesig`` (the redirecting worker's HMAC under
    # the cluster secret — an external player following a 307 keeps one
    # trace). The two channels are judged independently: an
    # OTel-instrumented client auto-injecting its own traceparent
    # HEADER must not shadow the signed query context the redirect
    # pinned. Anything that passes no leg is counted and ignored: a
    # client-minted context must not join foreign traces or pollute
    # the ring.
    remote_ctx = None
    header_tp = request.headers.get("traceparent")
    query_tp = request.query.get("traceparent")
    if (header_tp or query_tp) and _cluster_obs_enabled():
        chosen = None
        sig = request.query.get("tracesig")
        if query_tp and sig and fabric.verify_trace_sig(query_tp, sig):
            # a validly SIGNED query context wins over everything: the
            # signature binds it to this exact hop, where a header is
            # just ambient client instrumentation
            chosen = query_tp
        elif _is_cluster_peer(request, fabric):
            chosen = header_tp or query_tp
        remote_ctx = parse_traceparent(chosen) if chosen else None
        if remote_ctx is not None:
            metrics.inc("obs.trace_joins")
        else:
            metrics.inc("obs.trace_ctx_rejected")
    name = f"http.{request.method.lower()} {request.path}"
    with tracer.span(name, root=remote_ctx is None, parent=remote_ctx,
                     attrs={"worker": fabric.worker_id}) as span:
        try:
            response = await handler(request)
        except web.HTTPException as exc:
            span.attrs["status"] = exc.status
            exc.headers["X-Trace-Id"] = span.trace_id
            # tail-retention verdicts (ISSUE 18): a shed (503) is one
            # of the traces the pending ring exists to keep; routine
            # redirects/4xx (the 307 ownership hop, rate-limit 429s,
            # bad input) are healthy-baseline — retaining every one
            # would flush the durable ring with non-incidents
            if exc.status == 503:
                tracer.mark_retain("shed", span.ctx)
            elif exc.status < 500:
                tracer.mark_retain("baseline", span.ctx)
            raise
        except asyncio.CancelledError:
            raise
        except Exception:
            # a handler bug: answer the 500 OURSELVES so the response
            # still carries the trace id — the one trace an operator
            # most wants to look up from a user report. The log line
            # carries the same id (JSON formatter), replacing aiohttp's
            # anonymous error log.
            span.attrs["status"] = 500
            # the span exits cleanly (we return, not raise): mark it so
            # the tail verdict still reads this trace as an error
            tracer.mark_retain("error", span.ctx)
            log.exception("unhandled error serving %s %s",
                          request.method, request.path)
            return web.Response(
                status=500, text="500 Internal Server Error",
                headers={"X-Trace-Id": span.trace_id})
        span.attrs["status"] = response.status
        if response.status >= 500:
            # handler-returned 5xx (integrity failures surface this
            # way): same retention verdict as a raised one
            tracer.mark_retain("error", span.ctx)
        if not response.prepared:
            # a prepared response (WS handshake already sent) can't
            # take new headers
            response.headers["X-Trace-Id"] = span.trace_id
            tier = overload.current_tier()
            if tier:
                # honesty header (ISSUE 13): while the brownout ladder
                # is degrading quality, every game response says so —
                # clients and operators can tell a browned-out image
                # from a generation bug
                response.headers["X-Quality-Degraded"] = f"tier-{tier}"
                tracer.mark_retain("degraded", span.ctx)
        return response


def make_ratelimit_middleware(cfg: FrameworkConfig):
    from cassmantle_tpu.server.ratelimit import RateLimiter

    limiter = RateLimiter()
    api_routes = {"/init", "/client/status", "/fetch/contents",
                  "/compute_score"}

    @web.middleware
    async def ratelimit(request: web.Request, handler):
        if request.path in api_routes:
            rate = cfg.game.rate_limit_api
        else:
            rate = cfg.game.rate_limit_default
        # (client IP, room): a noisy room drains only its own quota,
        # not the same client's allowance in another room. The IP stays
        # the identity half — session ids are client-minted and would
        # let one abuser grow a fresh full-burst bucket per request —
        # and the room half only honors rooms that EXIST, so ?room=
        # can mint at most num_rooms buckets per client.
        fabric = request.app[_FABRIC]
        explicit = _explicit_room(request)
        if explicit and fabric.directory.has_room(explicit):
            room = explicit
        else:
            who = _session_id(request) or _client_ip(request)
            room = fabric.directory.room_for_session(who)
        principal = (_client_ip(request), room)
        if not limiter.allow(principal, request.path, rate):
            metrics.inc("http.rate_limited")
            # Retry-After computed from THIS bucket's actual refill
            # time (tokens missing / refill rate), not a constant 1 —
            # a client that obeys it is admitted on its next try
            # instead of bouncing off an empty bucket (ISSUE 13)
            retry = limiter.retry_after_s(principal, request.path)
            raise web.HTTPTooManyRequests(
                text="rate limit exceeded",
                headers={"Retry-After": str(max(1, math.ceil(retry)))})
        return await handler(request)

    return ratelimit


async def handle_root(request: web.Request) -> web.StreamResponse:
    return web.FileResponse(os.path.join(STATIC_DIR, "index.html"))


async def handle_init(request: web.Request) -> web.Response:
    # a fresh session has no cookie yet: the room still resolves
    # deterministically from the NEW session id, so the cookie pair
    # (session_id, room) this response sets stays self-consistent
    session_id = _session_id(request) or str(uuid.uuid4())
    fabric = request.app[_FABRIC]
    if _explicit_room(request) == PROBE_ROOM:
        # canary init (ISSUE 18): resets the probe session to the
        # unsolved known-answer round; no cookies (the prober carries
        # ?session=) and no http.init — probe traffic must be
        # invisible to player-facing counters
        room, game = await _resolve_probe_game(request, fabric)
        await game.init_client(session_id)
        return web.json_response(
            {"message": "Session initialized",
             "session_id": session_id, "room": room})
    room = _explicit_room(request) or \
        fabric.directory.room_for_session(session_id)
    if not fabric.directory.has_room(room):
        raise web.HTTPNotFound(text=f"unknown room {room!r}")
    # same ownership discipline as every other room-scoped route: init
    # on a non-owner must redirect, not quietly start a duplicate room
    # engine (and a second round clock) on this worker
    _check_room_ownership(request, fabric, room)
    game = await fabric.game_for(room)
    await game.init_client(session_id)
    response = web.json_response(
        {"message": "Session initialized", "session_id": session_id,
         "room": room}
    )
    response.set_cookie("session_id", session_id)
    response.set_cookie("room", room)
    metrics.inc("http.init")
    return response


async def handle_status(request: web.Request) -> web.Response:
    _, game = await _resolve_game(request)
    return web.json_response(await game.client_status(_session_id(request)))


async def handle_fetch_contents(request: web.Request) -> web.Response:
    room, game = await _resolve_game(request)
    session = _session_id(request) or str(uuid.uuid4())
    await game.ensure_client(session)
    # probe requests bypass the route histogram: the canary plays this
    # path constantly, and its timings must not dilute the player
    # latency series the SLOs and exemplars are built from (ISSUE 18)
    registry = NULL_METRICS if room == PROBE_ROOM else metrics
    with registry.timer("http.fetch_contents_s"):
        image_b64 = await game.fetch_masked_image_b64(session)
        prompt = await game.fetch_prompt_json(session)
        story = await game.fetch_story()
    response = web.json_response({
        "image": image_b64,
        "prompt": prompt,
        "story": story,
    })
    if not _session_id(request):
        response.set_cookie("session_id", session)
    return response


# Bounded hedge fan: a sick cluster must not retry-storm itself — at
# most this many peers are dialed per shed request, each under the
# cluster fan-out timeout, and a hedged request NEVER re-hedges.
SCORE_HEDGE_MAX_ATTEMPTS = 2


async def _hedge_score(request: web.Request, room: str, session: str,
                       payload: dict) -> Optional[dict]:
    """Cross-worker scorer failover (ISSUE 12): when the local score
    path is provably dark, dial a healthy fabric peer's /compute_score
    with the cluster token and the ``X-Score-Hedge`` marker (the peer
    serves the foreign room locally and never re-hedges, so a fully
    sick cluster degrades after one bounded fan instead of storming).
    Returns the peer's scores dict, or None when no peer answered —
    floor scores are the caller's LAST resort, not its first."""
    fabric = request.app[_FABRIC]
    token = fabric.cluster_token()
    if token is None:
        return None
    try:
        table = await fabric.membership.table()
    # lint: ignore[swallowed-error] — hedge is best-effort: None means "no peer answered" and the caller's floor-score path takes over
    except Exception:
        return None
    peers = []
    for worker, row in sorted(table.items()):
        if worker == fabric.worker_id or row["stale"] or \
                not row["info"].get("addr"):
            continue
        if row["info"].get("shed") or row["info"].get("btier"):
            # the peer's own heartbeat already advertises overload
            # (admission shedding / an engaged brownout tier,
            # serving/overload.py peer_advert): hedging into it would
            # trade a local floor score for a remote 503 — skip it
            metrics.inc("score.hedge_skipped_overloaded")
            continue
        peers.append((worker, row["info"].get("addr")))
    http = _peer_session(request)
    attempts = 0
    for worker, addr in peers:
        if attempts >= SCORE_HEDGE_MAX_ATTEMPTS:
            break
        attempts += 1
        metrics.inc("score.hedge_attempts")
        try:
            await afault_point("score.hedge", peer=worker)
            async with http.post(
                addr.rstrip("/") + "/compute_score",
                params={"room": room, "session": session},
                json=payload,
                headers={"X-Cluster-Auth": token,
                         "X-Score-Hedge": "1"},
            ) as res:
                if res.status != 200:
                    # a degraded peer sheds hedges with 503: try the
                    # next one, never loop back
                    metrics.inc("score.hedge_failures")
                    continue
                data = await res.json()
        except Exception:
            metrics.inc("score.hedge_failures")
            continue
        metrics.inc("score.hedge_success")
        flight_recorder.record("score.hedge", peer=worker, room=room)
        return data
    return None


async def handle_compute_score(request: web.Request) -> web.Response:
    room, game = await _resolve_game(request)
    supervisor = game.supervisor
    session = _session_id(request) or str(uuid.uuid4())
    try:
        data = await request.json()
        inputs = data["inputs"]
        assert isinstance(inputs, dict)
    except Exception:
        raise web.HTTPBadRequest(text="body must be {inputs: {idx: guess}}")
    if supervisor.shed_scores() or supervisor.device_unhealthy():
        # the local scorer is provably dark (breaker open / device
        # verdict false). Failover ladder (ISSUE 12): (1) a request
        # that IS someone else's hedge sheds 503 + Retry-After so the
        # origin tries its next peer — hedges must never cascade;
        # (2) hedge to a healthy fabric peer (real scores); (3) floor
        # scores as the LAST resort, honestly marked.
        if request.headers.get("X-Score-Hedge") == "1":
            metrics.inc("http.score_shed")
            raise web.HTTPServiceUnavailable(
                text="scoring degraded; retry shortly",
                headers={"Retry-After":
                         str(int(supervisor.retry_after_s()))})
        hedged = await _hedge_score(request, room, session,
                                    {"inputs": inputs})
        if hedged is not None:
            response = web.json_response(hedged)
            response.headers["X-Score-Hedged"] = "1"
            return response
        metrics.inc("score.hedge_floor")
        flight_recorder.record("score.floor", room=room)
        # fall through: the breaker-aware local path serves floor
        # scores (engine min_score), marked so clients/operators can
        # tell degradation from wrong guesses
    await game.ensure_client(session)
    # same exclusion as fetch: the canary's score timings stay out of
    # the player histogram (its own series is probe.e2e_s)
    registry = NULL_METRICS if room == PROBE_ROOM else metrics
    try:
        with registry.timer("http.compute_score_s"):
            scores = await game.compute_client_scores(session, inputs)
    except OverloadShed as exc:
        # adaptive admission shed this request (serving/overload.py):
        # answer in <50 ms with the COMPUTED Retry-After the limiter's
        # predicted-wait estimator produced — a well-behaved client
        # that obeys it lands when a slot is actually free
        metrics.inc("overload.score_shed")
        raise web.HTTPServiceUnavailable(
            text="overloaded; retry later",
            headers={"Retry-After":
                     str(max(1, math.ceil(exc.retry_after_s))),
                     "X-Overload-Shed": exc.reason})
    response = web.json_response(scores)
    if supervisor.shed_scores() or supervisor.device_unhealthy():
        response.headers["X-Score-Degraded"] = "floor"
    # client-side latency attribution: how long this request's guess
    # batch waited to coalesce vs how long the device batch it rode
    # took (filled by BatchingQueue into the request's trace marks;
    # absent on paths that never touched a queue, e.g. fake backends)
    marks = current_marks()
    if marks and "queue_wait_s" in marks:
        response.headers["X-Queue-Wait"] = f"{marks['queue_wait_s']:.6f}"
        response.headers["X-Service-Time"] = f"{marks['service_s']:.6f}"
    return response


async def handle_clock(request: web.Request) -> web.WebSocketResponse:
    # room-scoped BEFORE the handshake: a redirect (room owned
    # elsewhere) must go out as a plain 307 while headers can still be
    # sent — each room's WS feed carries that room's clock and player
    # count only
    _, game = await _resolve_game(request)
    session = _session_id(request)
    ws = web.WebSocketResponse(heartbeat=30.0)
    await ws.prepare(request)
    log.info("client %s connected", session)
    metrics.inc("ws.connections")

    async def sender() -> None:
        # first tick goes out immediately: a fresh client (or a canary
        # probe on a tight timeout) sees the clock without waiting out
        # the first sleep
        while not ws.closed:
            if session:
                await game.sessions.add_client(session)
            await ws.send_json(await game.clock_payload())
            await asyncio.sleep(1.0)

    send_task = asyncio.ensure_future(sender())
    try:
        # consume incoming frames until the client goes away
        async for msg in ws:
            if msg.type in (WSMsgType.CLOSE, WSMsgType.ERROR):
                break
    except (ConnectionResetError, asyncio.CancelledError):
        pass
    finally:
        send_task.cancel()
        try:
            await send_task
        except (asyncio.CancelledError, ConnectionResetError, Exception):
            pass
        log.info("client %s disconnected", session)
        if session:
            await game.sessions.remove_connection(session)
        metrics.inc("ws.disconnections")
    return ws


def _peer_session(request: web.Request):
    """Lazy per-app aiohttp ClientSession for cluster fan-outs (created
    on first use so it binds the serving loop; closed at app cleanup)."""
    import aiohttp

    holder = request.app[_PEER_HTTP]
    if holder.get("session") is None:
        obs_cfg = request.app[_OBS_CFG]
        holder["session"] = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(
                total=obs_cfg.cluster_fanout_timeout_s))
    return holder["session"]


async def _peer_fanout(request: web.Request, path: str, params: dict):
    """Fan one GET out to every live member CONCURRENTLY, self
    excluded (the whole fan-out costs ~one ``cluster_fanout_timeout_s``
    even with several dark peers, not one per). Returns ``(worker,
    row)`` pairs where row is ``{"status": ...}`` plus the decoded JSON
    body under ``data`` on success. Stale/dead/addressless peers are
    MARKED (status stale/no_addr/error/http_<code>) rather than
    silently dropped — the merged view must say who is missing from
    it. Requests carry the cluster token so the peer's gate admits us
    regardless of how its membership addresses resolve."""
    fabric = request.app[_FABRIC]
    session = _peer_session(request)
    headers = {}
    token = fabric.cluster_token()
    if token:
        headers["X-Cluster-Auth"] = token

    async def fetch(worker: str, addr: str):
        try:
            # peer-fan-out fault point: a worker-scoped partition marks
            # exactly that peer errored in the merged view while the
            # rest of the fleet stays readable (docs/CHAOS.md)
            await afault_point("fabric.peer_http", peer=worker)
            async with session.get(addr.rstrip("/") + path,
                                   params=params,
                                   headers=headers) as res:
                if res.status != 200:
                    return worker, {"status": f"http_{res.status}"}
                data = await res.json()
            return worker, {"status": "ok", "data": data}
        except Exception as exc:
            metrics.inc("obs.federation_peer_errors")
            return worker, {"status": "error",
                            "error": type(exc).__name__}

    results = []
    fetches = []
    table = await fabric.membership.table()
    for worker, row in sorted(table.items()):
        if worker == fabric.worker_id:
            continue
        if row["stale"]:
            results.append((worker, {"status": "stale",
                                     "age_s": row["age_s"]}))
            continue
        addr = row["info"].get("addr")
        if not addr:
            results.append((worker, {"status": "no_addr"}))
            continue
        fetches.append(fetch(worker, addr))
    results.extend(await asyncio.gather(*fetches))
    return results


async def _federated_metrics(request: web.Request):
    """(merged registry, federation block): this worker's full registry
    state plus every reachable peer's, merged per utils/logging.py
    merge_states — counters sum, gauges get a ``worker`` label,
    fixed-bucket histograms merge exactly. ``federation.peer_up``
    gauges in the merged registry mark each peer's reachability so a
    Prometheus scrape of the cluster view carries its own coverage."""
    fabric = request.app[_FABRIC]
    states = [(fabric.worker_id, metrics.dump_state())]
    federation = {fabric.worker_id: {"status": "self"}}
    for worker, row in await _peer_fanout(request, "/metrics",
                                          {"format": "state"}):
        state = row.get("data", {}).get("state") \
            if row["status"] == "ok" else None
        if state is not None:
            states.append((worker, state))
            federation[worker] = {"status": "ok"}
        elif row["status"] == "ok":
            # a 200 without the state payload (mid-rollout peer still
            # serving the legacy snapshot): mark it, don't 500 the
            # whole cluster scrape
            federation[worker] = {"status": "bad_payload"}
        else:
            federation[worker] = row
    cluster_metrics = merge_states(states)
    for worker, row in federation.items():
        cluster_metrics.gauge(
            "federation.peer_up",
            1.0 if row["status"] in ("self", "ok") else 0.0,
            labels={"worker": worker})
    return cluster_metrics, federation


async def handle_metrics(request: web.Request) -> web.Response:
    """Content-negotiated: Prometheus text exposition when the client
    asks for text/plain (a scraper's Accept header), the historical
    JSON snapshot otherwise — existing dashboards keep their shape.

    ``?scope=cluster`` federates: one scrape (or one curl) answers for
    the whole cluster — peers discovered via membership, counters
    summed, gauges worker-labeled, histogram buckets merged exactly,
    unreachable peers marked (``federation`` block / the
    ``federation.peer_up`` gauge). ``?format=state`` serves this
    worker's full-fidelity registry state — the peer-to-peer wire
    format the federation rides (and always worker-local: a peer's
    federation request must never recurse into a second fan-out).

    The plain per-worker scrape stays public (status quo); the two
    CLUSTER forms are gated like /debugz (loopback/members/token) —
    an open ``scope=cluster`` would hand any client an N-fold request
    amplifier against the whole fleet."""
    proc = request.app[_PROCESS]
    proc.sample()            # scrapes always see fresh process gauges
    device_metrics.sample()  # ...and fresh per-device HBM gauges
    fabric = request.app[_FABRIC]
    fmt_state = request.query.get("format") == "state"
    cluster = request.query.get("scope") == "cluster"
    if (fmt_state or cluster) and \
            not _is_cluster_peer(request, fabric):
        raise web.HTTPForbidden(
            text="cluster metrics: loopback or cluster peers only")
    if fmt_state:
        return web.json_response({"worker": fabric.worker_id,
                                  "state": metrics.dump_state()})
    federation = None
    registry = metrics
    if cluster:
        if _cluster_obs_enabled():
            registry, federation = await _federated_metrics(request)
        else:
            federation = {"disabled": True}
    accept = request.headers.get("Accept", "")
    if "application/openmetrics-text" in accept:
        # OpenMetrics exposition (ISSUE 18): same series as the plain
        # text form plus histogram-bucket exemplar annotations
        # ({trace_id=...} → /debugz?trace=) and the # EOF terminator
        return web.Response(
            body=registry.openmetrics().encode(),
            headers={"Content-Type": "application/openmetrics-text; "
                                     "version=1.0.0; charset=utf-8"})
    if "text/plain" in accept or "openmetrics" in accept:
        return web.Response(
            body=registry.prometheus().encode(),
            headers={"Content-Type":
                     "text/plain; version=0.0.4; charset=utf-8"})
    snap = registry.snapshot(
        exemplars=request.query.get("exemplars") == "1")
    if federation is not None:
        snap["federation"] = federation
    return web.json_response(snap)


async def handle_debugz(request: web.Request) -> web.Response:
    """The serving black box: ``?trace=<id>`` returns one trace's spans
    (the id a response's ``X-Trace-Id`` carried); otherwise the
    flight-recorder tail — breaker transitions, watchdog fires,
    deadline expiries, reserve rotations, round promotions — in causal
    order (``?n=`` limits, ``?kind=`` filters by kind or ``prefix.``).

    Operator surface, gated to loopback OR cluster members (the peer
    gate lets `?scope=cluster` fan-outs read each other): trace spans
    carry other players' request timings and the event ring exposes
    internal serving state — not a player-facing page.

    ``?trace=<id>&scope=cluster`` merges the trace across the fleet: a
    request that 307'd between workers leaves its spans split across
    their per-process rings; the cluster mode fans out to every live
    member (membership discovery), dedupes by span id, and returns one
    time-ordered view with a per-peer coverage block — the full story,
    readable from any worker."""
    if not _is_cluster_peer(request, request.app[_FABRIC]):
        raise web.HTTPForbidden(text="loopback or cluster peers only")
    trace_id = request.query.get("trace")
    if trace_id:
        if request.query.get("scope") == "cluster" and \
                _cluster_obs_enabled():
            return await _cluster_trace(request, trace_id)
        spans = tracer.get_trace(trace_id)
        if spans is None:
            raise web.HTTPNotFound(
                text=f"trace {trace_id!r} not resident (bounded ring "
                     f"keeps {tracer.capacity} traces)")
        spans.sort(key=lambda s: s["start_ts"])
        return web.json_response({"trace_id": trace_id, "spans": spans})
    try:
        n = int(request.query.get("n", "200"))
    except ValueError:
        raise web.HTTPBadRequest(text="n must be an integer")
    events = flight_recorder.tail(n, kind=request.query.get("kind"))
    return web.json_response({
        "events": events,
        "recorder": flight_recorder.stats(),
        "tracer": tracer.stats(),
        # newest last; each id is fetchable via ?trace=
        "recent_traces": tracer.trace_ids()[-25:],
    })


async def _cluster_trace(request: web.Request,
                         trace_id: str) -> web.Response:
    """The merged cross-worker trace view behind
    ``/debugz?trace=<id>&scope=cluster``. Peers answer their LOCAL
    trace lookup (never another fan-out); a peer without the trace is a
    ``miss`` (evicted or never sampled there), a dark peer is marked —
    partial coverage is reported, not hidden."""
    fabric = request.app[_FABRIC]
    merged = {s["span_id"]: s
              for s in (tracer.get_trace(trace_id) or [])}
    peers = {fabric.worker_id: {"status": "self", "spans": len(merged)}}
    for worker, row in await _peer_fanout(request, "/debugz",
                                          {"trace": trace_id}):
        if row["status"] == "ok":
            remote = row["data"].get("spans", [])
            for span in remote:
                merged.setdefault(span["span_id"], span)
            peers[worker] = {"status": "ok", "spans": len(remote)}
        elif row["status"] == "http_404":
            peers[worker] = {"status": "miss"}
        else:
            peers[worker] = row
    if not merged:
        raise web.HTTPNotFound(
            text=f"trace {trace_id!r} not resident on any reachable "
                 f"worker")
    spans = sorted(merged.values(), key=lambda s: s["start_ts"])
    return web.json_response({"trace_id": trace_id, "scope": "cluster",
                              "spans": spans, "peers": peers})


async def handle_sloz(request: web.Request) -> web.Response:
    """The SLO page: every objective's state (ok/burning), fast/slow
    burn rates, and targets — evaluated fresh on each hit (internally
    rate-limited) from the same registry `/metrics` serves. Advisory by
    design: `/readyz` embeds the same block without gating on it."""
    engine = request.app[_SLO]
    engine.evaluate()
    return web.json_response(engine.status())


async def _probe_store(fabric: RoomFabric) -> bool:
    try:
        await asyncio.wait_for(fabric.store.exists("healthz"), timeout=2.0)
        return True
    # lint: ignore[swallowed-error] — liveness probe: False IS the signal, surfaced as the /healthz verdict the orchestrator acts on
    except Exception:
        return False


async def handle_healthz(request: web.Request) -> web.Response:
    """LIVENESS: process up + store reachable + device responsive. Both
    probes carry deadlines (a wedged store connection or chip reports
    unhealthy instead of hanging the endpoint) and run concurrently.
    Carries the supervisor block for operators, but only store/device
    drive the status code — a degraded-but-serving worker must not be
    restarted by a liveness probe (that's `/readyz`'s job to report)."""
    fabric = request.app[_FABRIC]
    supervisor = fabric.supervisor
    store_ok, device_ok = await asyncio.gather(
        _probe_store(fabric), supervisor.probe_device())
    ok = store_ok and device_ok is not False
    return web.json_response(
        {
            "ok": ok,
            "store": store_ok,
            "device": device_ok is not False,
            "supervisor": supervisor.status(
                device_ok=device_ok, include_events=_is_loopback(request)),
        },
        status=200 if ok else 503,
    )


async def handle_readyz(request: web.Request) -> web.Response:
    """READINESS: can this worker produce fresh content and real scores
    right now? Fuses breaker states, the dispatch watchdog, and the
    device probe (ServingSupervisor.status) — plus, on a fabric worker,
    the cluster block (worker identity, room placement + per-worker
    room counts, live membership, replication leader + lag). Degraded
    -> 503 + Retry-After so load balancers drain the worker while the
    game keeps serving reserve rounds to players already on it."""
    fabric = request.app[_FABRIC]
    supervisor = fabric.supervisor
    store_ok, device_ok = await asyncio.gather(
        _probe_store(fabric), supervisor.probe_device())
    # the embedded event tail is internal serving state: loopback
    # operators only (the /debugz boundary) — remote probes/players get
    # the verdict without the history
    status = supervisor.status(
        device_ok=device_ok, include_events=_is_loopback(request))
    status["store"] = store_ok
    ready = bool(status["ready"]) and store_ok
    if fabric.draining:
        # graceful handoff in progress (SIGTERM): admission must stop —
        # load balancers drain NOW, while in-flight requests finish and
        # peers adopt the rooms (fabric/rooms.py RoomFabric.handoff)
        ready = False
        status["state"] = "draining"
    status["ready"] = ready
    # the SLO block is ADVISORY, never gating: burn rates tell the
    # operator where the error budget goes; draining a worker stays a
    # supervisor decision made on direct evidence (obs/slo.py).
    # Evaluate-on-read (internally rate-limited) so the block stays
    # live even with the background loop disabled (CASSMANTLE_NO_SLO)
    engine = request.app[_SLO]
    engine.evaluate()
    status["slo"] = engine.status()
    # the overload control plane's live state (ISSUE 13): the brownout
    # tier (also stamped on responses as X-Quality-Degraded) and every
    # queue's adaptive admission limit — advisory like the SLO block;
    # shedding/browning-out is the system WORKING, not a failure
    status["overload"] = overload.status_block()
    # device cost & capacity (ISSUE 14, obs/device.py): per-device HBM
    # (or the explicit "unavailable" marker on hosts without HBM
    # telemetry — never zeros), per-pipeline dispatch highwater, and
    # the jit sentinel's compile-cost summary. Advisory: the page that
    # drains a worker also says whether HBM pressure or a compile
    # storm explains it
    status["device_telemetry"] = device_metrics.device_block()
    # the canary block (ISSUE 18): last black-box probe verdict per
    # target worker. Advisory like the SLO block — a failing canary is
    # the "players can't play" smoking gun next to whatever white-box
    # verdict drained the worker
    prober = request.app[_PROBER].get("prober")
    if prober is not None:
        status["canary"] = prober.status_block()
    else:
        status["canary"] = {"enabled": False}
    if ready:
        return web.json_response(status)
    if status.get("state") != "draining":
        status["state"] = "degraded"
    retry_after = str(int(supervisor.retry_after_s()))
    return web.json_response(
        status, status=503, headers={"Retry-After": retry_after})


async def handle_debug_trace(request: web.Request) -> web.Response:
    """On-demand jax.profiler capture (SURVEY.md §5.1 — the reference has
    no tracing at all): ``POST /debug/trace?seconds=N[&name=subdir]``
    records N seconds of device+host activity to a TensorBoard trace
    directory while live traffic runs, and returns its path. Gated like
    `/debugz` — loopback OR the cluster-secret token (ISSUE 14: an
    operator triaging from another worker's shell, or tooling holding
    the token, can capture without an ssh hop) — an operator surface,
    never a player one. Single-flight: the ``active`` flag is
    checked-and-set before the first await, so a second concurrent
    capture answers 409 instead of interleaving ``start_trace`` /
    ``stop_trace`` (the profiler is process-global; interleaved
    captures corrupt both traces).

    The write path is never request-chosen: captures land under a fixed
    root (``CASSMANTLE_TRACE_ROOT`` env or the system tempdir), and the
    optional ``name`` selects only a single sanitized subdirectory —
    a same-host reverse proxy forwarding this route cannot turn it into
    an arbitrary-filesystem-write primitive."""
    if not _is_cluster_peer(request, request.app[_FABRIC]):
        raise web.HTTPForbidden(text="loopback or cluster peers only")
    try:
        seconds = min(60.0, float(request.query.get("seconds", "5")))
    except ValueError:
        raise web.HTTPBadRequest(text="seconds must be a number")
    name = request.query.get("name", "capture")
    if not re.fullmatch(r"[A-Za-z0-9._-]{1,64}", name) or ".." in name:
        raise web.HTTPBadRequest(text="name must be [A-Za-z0-9._-]{1,64}")
    root = os.environ.get(
        "CASSMANTLE_TRACE_ROOT",
        os.path.join(tempfile.gettempdir(), "cassmantle_trace"),
    )
    log_dir = os.path.join(root, name)
    trace_state = request.app[_TRACE_STATE]
    if trace_state["active"]:
        raise web.HTTPConflict(text="a trace capture is already running")
    trace_state["active"] = True
    try:
        import jax

        loop = asyncio.get_running_loop()
        # start/stop in an executor: the first profiler call can trigger
        # jax backend init, which must never block the serving event loop
        await loop.run_in_executor(
            None, jax.profiler.start_trace, log_dir)
        try:
            await asyncio.sleep(seconds)
        finally:
            await loop.run_in_executor(None, jax.profiler.stop_trace)
    finally:
        trace_state["active"] = False
    metrics.inc("obs.profiler_captures")
    return web.json_response({"trace_dir": log_dir, "seconds": seconds})


# (wordlist tuple, payload bytes, quoted ETag) — keyed on the IDENTITY
# of load_wordlist()'s cached tuple. The strong reference pins the tuple
# alive, so its id can never be reused by a successor; payload and ETag
# (one sha256 over ~0.4 MB) are computed exactly once per lexicon
# object, not per request, and recompute if the assets cache is ever
# cleared and rebuilt (tests regenerating the lexicon).
_WORDLIST_CACHE: Optional[tuple] = None


def _wordlist_payload() -> bytes:
    """The ~38k-word response serialized ONCE: the lexicon is immutable
    at runtime and /wordlist is hit per page load — re-serializing
    ~0.4 MB of JSON (or re-hashing it for the ETag) on the event loop
    per request would stall the 1 Hz WS clock pushes."""
    global _WORDLIST_CACHE
    import hashlib
    import json

    from cassmantle_tpu.engine.masking import STOPWORDS
    from cassmantle_tpu.server.assets import load_wordlist

    words = load_wordlist()
    cache = _WORDLIST_CACHE
    if cache is not None and cache[0] is words:
        return cache[1]
    payload = json.dumps({
        "words": list(words),
        "stopwords": sorted(STOPWORDS),
        "min_len": 2,
    }).encode()
    etag = '"' + hashlib.sha256(payload).hexdigest()[:16] + '"'
    _WORDLIST_CACHE = (words, payload, etag)
    return payload


def _wordlist_etag() -> str:
    _wordlist_payload()
    return _WORDLIST_CACHE[2]


async def handle_wordlist(request: web.Request) -> web.Response:
    """Dictionary + stopwords for client-side spellcheck (replaces the
    reference's vendored hunspell dictionary + typo.js, §2 F3; the client
    runs static/spell.js check/suggest over these words).

    Served with a content-hash ETag and ``no-cache`` (= cache but
    revalidate): a plain max-age would keep a regenerated lexicon — and
    its suggestion ranking — stale in browsers for the full window after
    a redeploy, while revalidation costs one conditional request
    answered 304 with no body."""
    etag = _wordlist_etag()
    headers = {"Cache-Control": "no-cache", "ETag": etag}
    inm = request.headers.get("If-None-Match", "")
    # weak-aware, list-aware compare: a compressing reverse proxy may
    # weaken the validator to W/"..." and clients echo it back that
    # way; an exact string compare would silently defeat every 304
    client_tags = {t.strip().removeprefix("W/")
                   for t in inm.split(",") if t.strip()}
    if etag in client_tags or inm.strip() == "*":
        return web.Response(status=304, headers=headers)
    return web.Response(
        body=_wordlist_payload(),
        content_type="application/json",
        headers=headers,
    )


def create_app(game: "Game | RoomFabric", cfg: FrameworkConfig,
               start_timer: bool = True,
               device_health: bool = False,
               self_addr: Optional[str] = None) -> web.Application:
    """Build the aiohttp app over a Game (legacy single-room callers)
    or a RoomFabric (sharded multi-room serving). A bare Game wraps
    into a one-room fabric whose default room is that game — identical
    behavior to the pre-fabric server."""
    # apply the observability knobs before any route can record
    # (tracer/recorder/metrics are process globals; idempotent)
    configure_observability(cfg.obs)
    # arm (or disarm) the fault-injection plan: CASSMANTLE_CHAOS wins
    # over cfg.chaos.spec; disarmed, every fault point stays a no-op
    # (docs/CHAOS.md). /readyz + /healthz carry the chaos block while
    # armed, so a drill can never be mistaken for an incident.
    chaos.configure_from_env(cfg.chaos)
    if isinstance(game, RoomFabric):
        fabric = game
        fabric.start_timers = start_timer
    else:
        fabric = RoomFabric.for_game(game, cfg, start_timers=start_timer)
    # ratelimit OUTSIDE tracing: a client spamming to 429s must shed at
    # the limiter without minting root traces (ring-flush vector)
    app = web.Application(middlewares=[
        cors_middleware, make_ratelimit_middleware(cfg), tracing_middleware
    ])
    app[_FABRIC] = fabric
    # mutable holder created before the app starts: flipping a field at
    # request time is legal where reassigning an app key is not (aiohttp
    # deprecates, and 4.x forbids, mutating a started app's keys)
    app[_TRACE_STATE] = {"active": False}
    app[_OBS_CFG] = cfg.obs
    app[_PEER_HTTP] = {"session": None}
    app[_OBS_TASKS] = []
    app[_PROBER] = {"prober": None}
    app[_SLO] = SloEngine(
        default_objectives(cfg),
        fast_window_s=cfg.obs.slo_fast_window_s,
        slow_window_s=cfg.obs.slo_slow_window_s)
    # the SLO-driven brownout ladder (serving/overload.py) subscribes
    # to every evaluation pass; CASSMANTLE_NO_BROWNOUT=1 pins tier 0
    overload.configure_brownout(cfg, app[_SLO])
    app[_PROCESS] = ProcessMetrics()
    if device_health:
        from cassmantle_tpu.utils.health import DeviceHealth

        # the supervisor owns the prober and fuses its verdict into
        # /healthz and /readyz (supervisor.probe_device)
        dh = DeviceHealth()
        fabric.supervisor.device_health = dh
        recovery = getattr(fabric.supervisor, "recovery", None)
        if recovery is not None:
            # probe raises ride the device-loss classifier
            # (serving/device_recovery.py): a dispatch-quiet worker
            # still detects runtime loss through its health probes
            dh.on_probe_error = recovery.note_probe_exception
    app.router.add_get("/", handle_root)
    app.router.add_get("/init", handle_init)
    app.router.add_get("/client/status", handle_status)
    app.router.add_get("/fetch/contents", handle_fetch_contents)
    app.router.add_post("/compute_score", handle_compute_score)
    app.router.add_get("/clock", handle_clock)
    app.router.add_get("/metrics", handle_metrics)
    app.router.add_get("/debugz", handle_debugz)
    app.router.add_get("/sloz", handle_sloz)
    app.router.add_get("/healthz", handle_healthz)
    app.router.add_get("/readyz", handle_readyz)
    app.router.add_get("/wordlist", handle_wordlist)
    app.router.add_post("/debug/trace", handle_debug_trace)
    if os.path.isdir(STATIC_DIR):
        app.router.add_static("/static", STATIC_DIR)
    if os.path.isdir(DATA_DIR):
        app.router.add_static("/data", DATA_DIR)
    if os.path.isdir(MEDIA_DIR):
        # brand/UI assets, the reference's third static mount
        # (main.py:25-27); all files here are original SVGs
        app.router.add_static("/media", MEDIA_DIR)

    async def _slo_loop(engine: SloEngine, interval_s: float) -> None:
        while True:
            await asyncio.sleep(interval_s)
            try:
                engine.evaluate()
            except Exception:
                # advisory machinery: an evaluation bug must never take
                # the loop (or anything else) down with it — but a
                # silently dead evaluator means burn-rate alerts stop
                # firing, so the failure itself must be countable
                metrics.inc("slo.eval_failures")
                log.exception("slo evaluation failed; continuing")

    async def on_startup(app_: web.Application) -> None:
        await fabric.startup()
        loop = asyncio.get_running_loop()
        tasks = app_[_OBS_TASKS]
        tasks.append(loop.create_task(
            app_[_PROCESS].run(cfg.obs.process_sample_interval_s)))
        # device HBM sampler: same cadence as the process self-metrics
        # (obs/device.py — a worker nobody scrapes still carries fresh
        # HBM gauges into its federation view)
        tasks.append(loop.create_task(
            device_metrics.run(cfg.obs.process_sample_interval_s)))
        if not _env_flag_set("CASSMANTLE_NO_SLO"):
            tasks.append(loop.create_task(
                _slo_loop(app_[_SLO], cfg.obs.slo_eval_interval_s)))
        # the synthetic canary (ISSUE 18): plays the real game surface
        # over this worker's own listener (self_addr) and every live
        # peer's. CASSMANTLE_NO_PROBER=1 at boot leaves ZERO probe
        # artifacts — no task, no metrics, no store keys, no /readyz
        # canary verdicts (the block reports enabled: false)
        if not _env_flag_set("CASSMANTLE_NO_PROBER"):
            from cassmantle_tpu.obs.prober import CanaryProber

            prober = CanaryProber(fabric, cfg, self_addr=self_addr)
            app_[_PROBER]["prober"] = prober
            tasks.append(loop.create_task(prober.run()))
        # opt-in leak census (CASSMANTLE_LEAK_SENTINEL=1): log-only —
        # thread/task origin tracking plus a periodic scan() that
        # counts leaks.* and flight-records leak.detected when the
        # live census grows past its high-water mark. Same cadence as
        # the process self-metrics: leak growth IS a process self-
        # metric.
        leak_sentinel.maybe_enable_from_env()
        if leak_sentinel.sentinel_active():
            async def _leak_scan_loop() -> None:
                while True:
                    await asyncio.sleep(cfg.obs.process_sample_interval_s)
                    leak_sentinel.scan()

            tasks.append(loop.create_task(_leak_scan_loop()))

    async def on_shutdown(app_: web.Application) -> None:
        # graceful SIGTERM handoff (ISSUE 12): leave membership, drain
        # rooms, wait for peers to adopt — BEFORE the process dies, so
        # the ring moves on a peer beat instead of after the staleness
        # TTL. aiohttp has already closed the listeners by this hook,
        # so new connections are refused (the LB's drain signal) while
        # in-flight requests finish under the shutdown grace. For an
        # operator-initiated drain with the listener still up, calling
        # RoomFabric.handoff() directly serves 307s to the adopters
        # and /readyz reports "draining" throughout.
        try:
            await fabric.handoff()
        # lint: ignore[swallowed-error] — best-effort drain while the process is exiting: the log is for the operator tailing the drain, and handoff() counts its own moves
        except Exception:
            log.exception("graceful handoff failed; shutting down anyway")

    async def on_cleanup(app_: web.Application) -> None:
        for task in app_[_OBS_TASKS]:
            task.cancel()
        for task in app_[_OBS_TASKS]:
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        session = app_[_PEER_HTTP].get("session")
        if session is not None:
            await session.close()
        await fabric.shutdown()

    app.on_startup.append(on_startup)
    app.on_shutdown.append(on_shutdown)
    app.on_cleanup.append(on_cleanup)
    return app


def _build_store(store_addr: Optional[str], cfg: FrameworkConfig):
    """The worker's shared store: MemoryStore (single process),
    MantleStore (``native[:port]`` — one shared node), or
    ReplicatedStore (``repl:host:port,host:port`` / the configured
    ``fabric.repl_endpoints`` / CASSMANTLE_REPL_ENDPOINTS — a
    leader+followers mantlestore cluster with lease failover)."""
    from cassmantle_tpu.engine.store import MemoryStore, ReplicatedStore

    endpoints = os.environ.get("CASSMANTLE_REPL_ENDPOINTS", "")
    endpoints = tuple(e.strip() for e in endpoints.split(",") if e.strip()) \
        or tuple(cfg.fabric.repl_endpoints)
    if store_addr and store_addr.startswith("repl:"):
        endpoints = tuple(
            e.strip() for e in store_addr[len("repl:"):].split(",")
            if e.strip())
        store_addr = None
    if endpoints:
        lease_ms = os.environ.get("CASSMANTLE_REPL_LEASE_MS")
        poll_ms = os.environ.get("CASSMANTLE_REPL_POLL_MS")
        return ReplicatedStore(
            list(endpoints),
            poll_interval_s=(float(poll_ms) / 1000.0 if poll_ms
                             else cfg.fabric.repl_poll_s),
            lease_timeout_s=(float(lease_ms) / 1000.0 if lease_ms
                             else cfg.fabric.repl_lease_s),
        )
    if store_addr:
        import re

        m = re.fullmatch(r"native(?::(\d+))?", store_addr)
        if not m:
            # fail loudly: a typo'd store string silently falling back
            # to a per-process MemoryStore would split-brain a
            # multi-worker fleet
            raise ValueError(
                f"unknown store address {store_addr!r} (expected "
                f"'native[:port]' or 'repl:host:port,host:port')")
        from cassmantle_tpu.native.client import MantleStore

        return MantleStore(port=int(m.group(1) or 7070))
    return MemoryStore()


def _serving_components(cfg: FrameworkConfig, fake: bool,
                        weights_dir: Optional[str], supervisor):
    """(backend, embed, similarity, blur_fn, pin_answers) — built ONCE
    per worker and shared by every room's game, so N rooms' round
    generation funnels into the same batched device path (the fabric
    scales the game, not the model count). ``pin_answers`` is the
    RoundManager promotion hook that pins round answers into the int8
    embed table (ops/embed_table.py), or None when no table is armed."""
    if fake:
        from cassmantle_tpu.engine.content import (
            FakeContentBackend,
            hash_embed,
            hash_similarity,
        )

        similarity = hash_similarity
        pin_answers = None
        if cfg.serving.fake_score_batch_ms > 0:
            # overload-drill wiring (bench.py overload_drill): the fake
            # scorer rides a REAL BatchingQueue whose handler simulates
            # device batch cost, so synthetic load exercises the real
            # admission/priority/Retry-After machinery on a CPU host
            from cassmantle_tpu.serving.fake_scorer import (
                FakeQueuedScorer,
            )

            similarity = FakeQueuedScorer(cfg, supervisor).similarity
        from cassmantle_tpu.ops.embed_table import fake_table_enabled

        if fake_table_enabled():
            # A/B arm for the table rung on jax-free drill workers
            # (CASSMANTLE_FAKE_EMBED_TABLE=1, docs/DEPLOY.md §6): the
            # same EmbedTable + int8 math as production, rows from
            # hash_embed instead of MiniLM, in FRONT of whatever fake
            # ladder is armed above — in-vocabulary pairs skip the
            # queue exactly like production rung 0
            from cassmantle_tpu.ops.embed_table import (
                TableFirstSimilarity,
                build_fake_table,
                pin_answers_hash,
            )

            table = build_fake_table()
            similarity = TableFirstSimilarity(table, similarity)
            pin_answers = functools.partial(pin_answers_hash, table)
        return FakeContentBackend(image_size=256), hash_embed, \
            similarity, None, pin_answers
    from cassmantle_tpu.serving.service import InferenceService

    service = InferenceService(cfg, weights_dir=weights_dir,
                               supervisor=supervisor)
    return service.content_backend, service.embed, service.similarity, \
        service.blur, service.pin_answers


def build_game(cfg: FrameworkConfig, fake: bool = False,
               weights_dir: Optional[str] = None,
               store_addr: Optional[str] = None) -> Game:
    """Assemble a single Game with real TPU serving or the fake backend.

    ``store_addr`` like ``"native:7070"`` connects to a shared mantlestore
    (multi-worker deployments, one store per host like the reference's
    Redis); default is the in-process MemoryStore. Multi-room serving
    goes through :func:`build_fabric` instead.
    """
    from cassmantle_tpu.serving.supervisor import ServingSupervisor

    # ONE supervisor per worker: the engine's content breaker and the
    # inference service's score breaker + queue watchdogs must fuse into
    # the same /readyz verdict
    supervisor = ServingSupervisor()
    store = _build_store(store_addr, cfg)
    backend, embed, similarity, blur_fn, pin_answers = \
        _serving_components(cfg, fake, weights_dir, supervisor)
    return Game(cfg, store, backend, embed=embed, similarity=similarity,
                blur_fn=blur_fn, supervisor=supervisor,
                pin_answers=pin_answers)


def apply_fabric_env(cfg: FrameworkConfig) -> FrameworkConfig:
    """Fold runtime fabric env overrides into the config — applied by
    build_fabric AND by the server entry before create_app, so every
    consumer of cfg.fabric (room lists, middleware) sees ONE value."""
    import dataclasses

    rooms_env = os.environ.get("CASSMANTLE_ROOM_COUNT")
    if rooms_env:
        cfg = cfg.replace(fabric=dataclasses.replace(
            cfg.fabric, num_rooms=int(rooms_env)))
    return cfg


def build_fabric(cfg: FrameworkConfig, fake: bool = False,
                 weights_dir: Optional[str] = None,
                 store_addr: Optional[str] = None,
                 worker_id: Optional[str] = None,
                 advertise_addr: Optional[str] = None) -> RoomFabric:
    """Assemble the room fabric for one worker: a shared (possibly
    replicated) store, one serving stack, and per-room Games created on
    demand (fabric/rooms.py). Env overrides (docs/DEPLOY.md §6):
    CASSMANTLE_ROOM_COUNT, CASSMANTLE_ROOM_WORKER_ID,
    CASSMANTLE_ROOM_ADVERTISE, CASSMANTLE_REPL_ENDPOINTS,
    CASSMANTLE_REPL_LEASE_MS, CASSMANTLE_REPL_POLL_MS."""
    from cassmantle_tpu.serving.supervisor import ServingSupervisor

    cfg = apply_fabric_env(cfg)
    worker_id = (worker_id
                 or os.environ.get("CASSMANTLE_ROOM_WORKER_ID")
                 or cfg.fabric.worker_id
                 or f"{os.uname().nodename}:{os.getpid()}")
    advertise_addr = (advertise_addr
                      or os.environ.get("CASSMANTLE_ROOM_ADVERTISE")
                      or cfg.fabric.advertise_addr)
    supervisor = ServingSupervisor()
    store = _build_store(store_addr, cfg)
    backend, embed, similarity, blur_fn, pin_answers = \
        _serving_components(cfg, fake, weights_dir, supervisor)

    def game_factory(room: str, room_store) -> Game:
        # room= labels the game's engine metric series (game.guesses,
        # round.generate_s, ...) so N rooms on this worker stay
        # distinguishable on /metrics (docs/OBSERVABILITY.md)
        return Game(cfg, room_store, backend, embed=embed,
                    similarity=similarity, blur_fn=blur_fn,
                    supervisor=supervisor, room=room,
                    pin_answers=pin_answers)

    return RoomFabric(cfg, store, game_factory, worker_id=worker_id,
                      advertise_addr=advertise_addr,
                      supervisor=supervisor)


def main() -> None:
    import argparse

    parser = argparse.ArgumentParser(description="cassmantle-tpu server")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--fake", action="store_true",
                        help="deterministic fake content backend (no TPU)")
    parser.add_argument("--weights", default=None,
                        help="safetensors checkpoint directory")
    parser.add_argument("--round-seconds", type=float, default=None)
    parser.add_argument("--store", default=None,
                        help="'native[:port]' = shared C++ mantlestore "
                             "(spawn with native/build/mantlestore "
                             "[port] [snapshot_path [interval_s]]; a "
                             "snapshot path makes rounds survive store "
                             "restarts); 'repl:host:port,host:port' = "
                             "replicated mantlestore cluster (leader "
                             "writes + log-shipping + lease failover — "
                             "docs/DEPLOY.md multi-worker runbook)")
    parser.add_argument("--rooms", type=int, default=None,
                        help="concurrent game rooms (each with its own "
                             "round clock/content/scores, sessions "
                             "consistent-hashed across them; default 1 "
                             "= the classic single global round)")
    parser.add_argument("--worker-id", default=None,
                        help="stable worker identity for room placement "
                             "(default host:pid)")
    parser.add_argument("--advertise", default=None,
                        help="address peers redirect room traffic to, "
                             "e.g. http://10.0.0.3:8000 (unset = no "
                             "cross-worker redirects; foreign rooms "
                             "serve locally)")
    parser.add_argument("--preset", default="sd15",
                        choices=("sd15", "sdxl", "fast", "deepcache",
                                 "turbo"),
                        help="model/sampler preset: sd15 = SD1.5-512 "
                             "DDIM-50; sdxl = SDXL-base 1024 (the "
                             "reference's image model); fast = SD1.5 "
                             "with DPM++(2M) @ 25 steps; deepcache = "
                             "DDIM-50 with deep-feature reuse (~60% "
                             "UNet compute); turbo = the two composed "
                             "(DPM++(2M)@24 + deepcache)")
    parser.add_argument("--platform", default="auto",
                        choices=("auto", "cpu"),
                        help="'cpu' pins jax to host devices — e.g. "
                             "--fake serving on a box whose accelerator "
                             "tunnel is absent or down")
    parser.add_argument("--lm", default="gpt2",
                        choices=("gpt2", "mistral"),
                        help="prompt-LM family: gpt2 (default) or a "
                             "Mistral-7B-class model (the reference's "
                             "actual LLM, reference backend.py:25)")
    parser.add_argument("--lm-int8", action="store_true",
                        help="weights-only int8 for the prompt LM "
                             "(ops/quant.py) — what fits Mistral-7B-"
                             "class weights + decode on one 16 GB chip")
    parser.add_argument("--workers", type=int, default=1,
                        help="worker processes sharing the port "
                             "(SO_REUSEPORT) and one --store "
                             "(required >1) — the multi-worker layout "
                             "the reference ran as multi-worker "
                             "uvicorn (main.py:37-40): every worker "
                             "runs the lock-guarded global timer, "
                             "exactly one generates per round")
    args = parser.parse_args()

    if args.platform == "cpu":
        from cassmantle_tpu.utils.xla_flags import pin_cpu_platform

        pin_cpu_platform(virtual_devices=False)

    if args.preset == "sdxl":
        from cassmantle_tpu.config import sdxl_config

        cfg = sdxl_config()
    elif args.preset == "fast":
        from cassmantle_tpu.config import fast_serving_config

        cfg = fast_serving_config()
    elif args.preset == "deepcache":
        from cassmantle_tpu.config import deepcache_serving_config

        cfg = deepcache_serving_config()
    elif args.preset == "turbo":
        from cassmantle_tpu.config import turbo_serving_config

        cfg = turbo_serving_config()
    else:
        cfg = FrameworkConfig()
    import dataclasses

    if args.round_seconds:
        cfg = cfg.replace(
            game=dataclasses.replace(cfg.game,
                                     time_per_prompt=args.round_seconds)
        )
    if args.lm == "mistral" or args.lm_int8:
        from cassmantle_tpu.config import MistralConfig

        models = cfg.models
        if args.lm == "mistral":
            models = dataclasses.replace(models, mistral=MistralConfig())
        if args.lm_int8:
            models = dataclasses.replace(models, lm_int8=True)
        cfg = cfg.replace(models=models)
    if args.workers > 1:
        import multiprocessing
        import signal
        import threading

        if not (args.store and args.store.startswith(("native", "repl:"))):
            parser.error("--workers > 1 requires --store native[:port] "
                         "or repl:... (a shared native store is the "
                         "coordination plane; per-process MemoryStores "
                         "would each run their own game)")
        if not (args.fake or args.platform == "cpu"):
            parser.error("--workers > 1 needs --fake or --platform cpu: "
                         "one accelerator chip has one owning process — "
                         "TPU-backed serving runs single-worker (the "
                         "inference queue already coalesces requests)")
        procs = []
        for _ in range(args.workers - 1):
            p = multiprocessing.Process(
                target=_run_worker, args=(args, cfg), daemon=True)
            p.start()
            procs.append(p)

        def _watch() -> None:
            # a silently-dead worker degrades capacity invisibly; wait
            # on ALL sentinels at once (a sequential join would sit on
            # the first worker while a later one dies unreported)
            from multiprocessing.connection import wait as mp_wait

            pending = {p.sentinel: p for p in procs}
            while pending:
                for sentinel in mp_wait(list(pending)):
                    p = pending.pop(sentinel)
                    p.join()
                    if p.exitcode not in (0, None, -signal.SIGINT,
                                          -signal.SIGTERM):
                        # a dead sibling is degraded capacity, not just
                        # a log line (ISSUE 12 satellite): count it,
                        # flight-record it, and let the supervisor's
                        # /readyz watchdog block surface the total
                        log.error("worker pid=%s died with exit code %s",
                                  p.pid, p.exitcode)
                        metrics.inc("server.worker_deaths")
                        flight_recorder.record(
                            "server.worker_death", pid=p.pid,
                            exitcode=p.exitcode)

        threading.Thread(target=_watch, daemon=True).start()
        try:
            _run_worker(args, cfg)
        finally:
            # graceful first (aiohttp on_cleanup -> game.shutdown drops
            # store locks); only then force-kill stragglers
            for p in procs:
                if p.is_alive():
                    os.kill(p.pid, signal.SIGINT)
            for p in procs:
                p.join(timeout=5.0)
            for p in procs:
                if p.is_alive():
                    p.terminate()
        return
    _run_worker(args, cfg)


def _run_worker(args, cfg: FrameworkConfig) -> None:
    import dataclasses

    if getattr(args, "rooms", None):
        cfg = cfg.replace(fabric=dataclasses.replace(
            cfg.fabric, num_rooms=args.rooms))
    # one cfg for everything: the env override must reach create_app's
    # consumers too, not just the fabric build
    cfg = apply_fabric_env(cfg)
    fabric = build_fabric(cfg, fake=args.fake, weights_dir=args.weights,
                          store_addr=args.store,
                          worker_id=getattr(args, "worker_id", None),
                          advertise_addr=getattr(args, "advertise", None))
    web.run_app(create_app(fabric, cfg, device_health=not args.fake,
                           # the canary dials this worker's own
                           # listener over loopback — the probe must
                           # traverse the real HTTP stack, middlewares
                           # included, not call handlers in-process
                           self_addr=f"http://127.0.0.1:{args.port}"),
                host=args.host, port=args.port,
                reuse_port=(args.workers > 1))


if __name__ == "__main__":
    main()
