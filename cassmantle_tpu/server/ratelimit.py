"""Per-IP token-bucket rate limiting.

The reference rate-limits with slowapi (3/s default, 2/s API routes;
main.py:19, 43-48, 82, 96, 114). Same policy here, implemented as a small
token bucket so there is no external dependency.
"""

from __future__ import annotations

import time
from typing import Dict, Tuple


class TokenBucket:
    def __init__(self, rate: float, burst: float = None) -> None:
        self.rate = rate
        self.burst = burst if burst is not None else max(1.0, rate)
        self.tokens = self.burst
        self.updated = time.monotonic()

    def allow(self) -> bool:
        now = time.monotonic()
        self.tokens = min(
            self.burst, self.tokens + (now - self.updated) * self.rate
        )
        self.updated = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class RateLimiter:
    """Buckets keyed by (ip, class); stale buckets evicted lazily."""

    def __init__(self, max_entries: int = 10000) -> None:
        self._buckets: Dict[Tuple[str, str], TokenBucket] = {}
        self.max_entries = max_entries

    def allow(self, ip: str, route_class: str, rate: float) -> bool:
        key = (ip, route_class)
        bucket = self._buckets.get(key)
        if bucket is None:
            if len(self._buckets) >= self.max_entries:
                self._buckets.clear()  # crude flush; per-IP state is cheap
            bucket = self._buckets[key] = TokenBucket(rate)
        return bucket.allow()
