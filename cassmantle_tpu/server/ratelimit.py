"""Per-principal token-bucket rate limiting.

The reference rate-limits with slowapi (3/s default, 2/s API routes;
main.py:19, 43-48, 82, 96, 114). Same policy here, implemented as a small
token bucket so there is no external dependency.

Buckets are keyed by ``(principal, route_class)`` where the principal is
``(client-ip, room)``: with the room fabric, one client can play in
several rooms, and a noisy room (a hot round's guess storm) must drain
only its own quota — client-only buckets would let room A's burst
starve the same client's requests in room B (ISSUE 8 satellite;
eviction behavior at this key shape is pinned in tests/test_server.py).
The identity half stays the IP — session ids are client-minted and
would let an abuser grow a fresh full-burst bucket per request — and
the middleware only honors room values that exist, so ``?room=`` can
mint at most num_rooms buckets per client.
"""

from __future__ import annotations

import time
from typing import Dict, Tuple

# (client-ip, room) — the unit that owns a quota
Principal = Tuple[str, str]


class TokenBucket:
    def __init__(self, rate: float, burst: float = None) -> None:
        self.rate = rate
        self.burst = burst if burst is not None else max(1.0, rate)
        self.tokens = self.burst
        self.updated = time.monotonic()

    def allow(self) -> bool:
        now = time.monotonic()
        self.tokens = min(
            self.burst, self.tokens + (now - self.updated) * self.rate
        )
        self.updated = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False

    def retry_after_s(self) -> float:
        """Seconds until this bucket refills one whole token — the
        COMPUTED Retry-After a 429 should carry (ISSUE 13 satellite;
        tokens were already refreshed by the failing allow())."""
        if self.tokens >= 1.0 or self.rate <= 0:
            return 0.0
        return (1.0 - self.tokens) / self.rate


class RateLimiter:
    """Buckets keyed by (principal, class); stale buckets evicted on
    overflow.

    Eviction is targeted, never a flush: clearing the whole table when
    full would reset EVERY active client's bucket to a full burst at
    once — a synchronized admission spike exactly when the table is
    busiest. Instead, overflow drops buckets idle longer than
    ``stale_s``, then (if still full) the longest-idle tail, so active
    clients keep their spent tokens.
    """

    def __init__(self, max_entries: int = 10000,
                 stale_s: float = 60.0) -> None:
        self._buckets: Dict[Tuple[Principal, str], TokenBucket] = {}
        self.max_entries = max_entries
        self.stale_s = stale_s

    def _evict(self) -> None:
        now = time.monotonic()
        stale = [k for k, b in self._buckets.items()
                 if now - b.updated > self.stale_s]
        for k in stale:
            del self._buckets[k]
        if len(self._buckets) >= self.max_entries:
            # still full of active clients: shed the longest-idle tenth
            by_idle = sorted(self._buckets, key=lambda k: self._buckets[k].updated)
            for k in by_idle[:max(1, self.max_entries // 10)]:
                del self._buckets[k]

    def allow(self, principal: Principal, route_class: str,
              rate: float) -> bool:
        key = (principal, route_class)
        bucket = self._buckets.get(key)
        if bucket is None:
            if len(self._buckets) >= self.max_entries:
                self._evict()
            bucket = self._buckets[key] = TokenBucket(rate)
        return bucket.allow()

    def retry_after_s(self, principal: Principal,
                      route_class: str) -> float:
        """The rejecting bucket's actual refill time (0 when absent —
        a race with eviction; the caller floors the header at 1)."""
        bucket = self._buckets.get((principal, route_class))
        return bucket.retry_after_s() if bucket is not None else 0.0
