"""Story seeds and art styles (original content, reference-shaped).

The reference ships 17 one-line story seed titles and 7 style names as text
files (data/seeds.txt, data/styles.txt; SURVEY.md §2 #13). We keep the same
file format and loading contract but ship our own content, and fall back to
built-ins when the data files are absent.
"""

from __future__ import annotations

import functools
import os
import re
from typing import List, Tuple

DATA_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "data")

_DEFAULT_SEEDS = [
    "The Cartographer of Drowned Cities",
    "A Winter Without Clocks",
    "The Orchard at the Edge of the Map",
    "Letters from the Glass Lighthouse",
    "The Night the Trains Sang",
    "Keeper of the Paper Storms",
    "The Astronomer's Unsent Telegrams",
    "Salt Roads and Silver Rivers",
    "The Museum of Almost-Forgotten Sounds",
    "A Harbor for Runaway Shadows",
    "The Clockmaker's Second Moon",
    "Embers over the Quiet Canyon",
    "The Librarian Who Collected Horizons",
    "Caravan of the Painted Comets",
    "The Garden Below the Ice",
    "Signals from the Tin Observatory",
    "The Last Ferry to the Floating Market",
]

_DEFAULT_STYLES = [
    "Watercolor",
    "Art deco",
    "Ukiyo-e woodblock",
    "Low-poly 3D render",
    "Charcoal sketch",
    "Stained glass",
    "Vaporwave",
]


def _load_lines(path: str, fallback: List[str]) -> List[str]:
    try:
        with open(path, "r") as f:
            lines = [ln.strip() for ln in f if ln.strip()]
        return lines or list(fallback)
    except OSError:
        return list(fallback)


def load_seeds() -> List[str]:
    return _load_lines(os.path.join(DATA_DIR, "seeds.txt"), _DEFAULT_SEEDS)


def load_styles() -> List[str]:
    return _load_lines(os.path.join(DATA_DIR, "styles.txt"), _DEFAULT_STYLES)


@functools.lru_cache(maxsize=1)
def load_wordlist() -> Tuple[str, ...]:
    """Dictionary words backing client-side spellcheck (data/wordlist.txt
    + every word appearing in seeds/styles; the reference ships a hunspell
    en_US dictionary for the same purpose, SURVEY.md §2 #13/F3). FILE
    ORDER IS PRESERVED: tools/build_wordlist.py writes most-common-first,
    and both spellcheckers rank suggestions by list position. Seed/style
    vocabulary appends after the file (always checkable, ranked behind
    the mined body). Cached: immutable at runtime, /wordlist per page
    load."""
    # one insertion-ordered dict: order is the rank, keys the dedup
    seen = dict.fromkeys(
        _load_lines(os.path.join(DATA_DIR, "wordlist.txt"), []))
    for line in load_seeds() + load_styles():
        for token in line.lower().split():
            token = token.strip("'-.,;:!?\"")
            # whole token (keeps 'ukiyo-e', 'low-poly' checkable exactly)
            if re.fullmatch(r"[a-z]+(?:[-'][a-z]+)*", token) and \
                    len(token) >= 2:
                seen.setdefault(token)
            # plus each alpha run, so the parts are guessable too
            for part in re.findall(r"[a-z]+", token):
                if len(part) >= 2:
                    seen.setdefault(part)
    return tuple(seen)
