"""Typed configuration for the whole framework.

The reference scatters its tunables across constructor kwargs and hardcoded
constants (server.py:15-24, backend.py:20-26, 47-50, 319; SURVEY.md §5.6).
Here everything lives in one tree of frozen dataclasses so a single
``FrameworkConfig`` names the model zoo, samplers, parallelism mesh, serving
queue, and game constants, and can be overridden per-test or per-deployment.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from cassmantle_tpu.utils.logging import DEFAULT_BUCKETS_S as _DEFAULT_BUCKETS_S


@dataclasses.dataclass(frozen=True)
class ClipTextConfig:
    """SD1.5's text tower (OpenAI CLIP ViT-L/14 text model) dimensions."""

    vocab_size: int = 49408
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    max_positions: int = 77
    # ViT-L/14 trained with quick_gelu; OpenCLIP bigG with exact gelu —
    # the published hidden_act of each checkpoint.
    hidden_act: str = "quick_gelu"
    # SDXL adds a second, bigger text tower (OpenCLIP ViT-bigG); same module,
    # different dims.
    @staticmethod
    def sdxl_big() -> "ClipTextConfig":
        return ClipTextConfig(
            vocab_size=49408,
            hidden_size=1280,
            intermediate_size=5120,
            num_layers=32,
            num_heads=20,
            max_positions=77,
            hidden_act="gelu",
        )


@dataclasses.dataclass(frozen=True)
class UNetConfig:
    """Diffusion UNet. Defaults = SD1.5; ``sdxl()`` = SDXL-base geometry."""

    sample_channels: int = 4
    base_channels: int = 320
    channel_mults: Tuple[int, ...] = (1, 2, 4, 4)
    # Per-level: whether the level's resnet blocks carry transformer
    # (self+cross attention) blocks.
    attention_levels: Tuple[bool, ...] = (True, True, True, False)
    # Transformer depth per level (SDXL uses 2/10 at its two attn levels).
    transformer_depth: Tuple[int, ...] = (1, 1, 1, 1)
    blocks_per_level: int = 2
    num_heads: int = 8
    context_dim: int = 768
    time_embed_dim: int = 1280
    # SDXL micro-conditioning (added time-embedding channels); 0 disables.
    addition_embed_dim: int = 0
    dtype: str = "bfloat16"
    # Fused GroupNorm+SiLU+conv3x3 Pallas path for the ResBlock hot loop
    # (ops/fused_conv.py): the normalized/activated tensor stays in VMEM
    # instead of round-tripping HBM before every 3x3 conv (~45% of UNet
    # FLOPs are these convs — docs/PERF_NOTES.md). Param tree, checkpoint
    # layout, and outputs are unchanged (parity-pinned,
    # tests/test_fused_conv.py); A/B measured by the `sd15_fusedconv`
    # bench entry. CASSMANTLE_NO_FUSED_CONV=1 is the runtime kill switch.
    fused_conv: bool = False
    # With fused_conv: round conv channel dims up to this multiple so
    # MXU tiles fill (SD1.5's 320/960 levels are 2.5/7.5 lanes-tiles
    # wide; 128 trades ~3.4% UNet FLOPs for full tile occupancy —
    # docs/PERF_NOTES.md). 0 disables padding.
    conv_pad_to: int = 0

    def arch(self) -> "UNetConfig":
        """This config with execution-strategy flags cleared — the
        ARCHITECTURE identity (param tree + numerics), used for param
        cache keys and ``share_params_with`` compatibility: fused_conv /
        conv_pad_to change how convs execute, never what the tree is."""
        return dataclasses.replace(self, fused_conv=False, conv_pad_to=0)

    @staticmethod
    def sdxl() -> "UNetConfig":
        return UNetConfig(
            base_channels=320,
            channel_mults=(1, 2, 4),
            attention_levels=(False, True, True),
            transformer_depth=(0, 2, 10),
            num_heads=None,  # SDXL uses fixed head_dim 64 -> heads = ch // 64
            context_dim=2048,
            time_embed_dim=1280,
            addition_embed_dim=2816,
        )


@dataclasses.dataclass(frozen=True)
class VAEConfig:
    """SD autoencoder (decoder is the serving hot path)."""

    latent_channels: int = 4
    base_channels: int = 128
    channel_mults: Tuple[int, ...] = (1, 2, 4, 4)
    blocks_per_level: int = 2
    scaling_factor: float = 0.18215  # SD1.5; SDXL uses 0.13025
    # bf16 compute (fp32 GroupNorm statistics via GroupNorm32): the decode
    # is a one-shot memory-bound pass; bf16 halves its HBM traffic.
    dtype: str = "bfloat16"
    # Fused GroupNorm+SiLU+conv3x3 Pallas path for the VAE ResBlock
    # pairs (ops/fused_conv.py — the same kernel, return_affine +
    # Conv3x3Params trick, and CASSMANTLE_NO_FUSED_CONV kill switch the
    # UNet ResBlocks use): the cost table prices VAE decode at 10.47 TF
    # per SDXL image and, like the UNet's, each of its norm→act→conv
    # sequences otherwise round-trips the level activation through HBM.
    # Param tree/checkpoint layout unchanged (parity-pinned,
    # tests/test_encprop.py). VAE channels (128/256/512) are already
    # 128-lane aligned, so no conv_pad_to analogue is needed.
    fused_conv: bool = False

    def arch(self) -> "VAEConfig":
        """This config with execution-strategy flags cleared — the
        ARCHITECTURE identity (param tree + numerics), mirroring
        UNetConfig.arch(): ``fused_conv`` changes how the decode
        executes, never what the tree is. Used for param cache keys and
        ``share_params_with`` compatibility."""
        return dataclasses.replace(self, fused_conv=False)


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    """GPT-2-small for prompt/hint generation (greedy decode)."""

    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_positions: int = 1024
    dtype: str = "bfloat16"


@dataclasses.dataclass(frozen=True)
class MistralConfig:
    """Mistral-7B-Instruct-class causal LM — the reference's actual prompt
    model (backend.py:25 calls the hosted Mistral-7B-Instruct-v0.1 endpoint).

    Architecture: RoPE positions, grouped-query attention (8 KV heads),
    sliding-window attention, RMSNorm, SwiGLU MLP. Defaults are the 7B
    geometry; ``tiny()`` is the CPU-test variant.
    """

    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    max_positions: int = 4096
    sliding_window: int = 4096
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    dtype: str = "bfloat16"

    @staticmethod
    def tiny() -> "MistralConfig":
        return MistralConfig(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
            max_positions=64, sliding_window=16, dtype="float32",
        )


@dataclasses.dataclass(frozen=True)
class MiniLMConfig:
    """all-MiniLM-L6-v2-class sentence encoder for guess scoring."""

    vocab_size: int = 30522
    hidden_size: int = 384
    intermediate_size: int = 1536
    num_layers: int = 6
    num_heads: int = 12
    max_positions: int = 512
    dtype: str = "float32"


@dataclasses.dataclass(frozen=True)
class ModelZooConfig:
    clip_text: ClipTextConfig = dataclasses.field(default_factory=ClipTextConfig)
    # SDXL's second text tower (OpenCLIP bigG); None for SD1.5.
    clip_text_2: Optional[ClipTextConfig] = None
    unet: UNetConfig = dataclasses.field(default_factory=UNetConfig)
    vae: VAEConfig = dataclasses.field(default_factory=VAEConfig)
    gpt2: GPT2Config = dataclasses.field(default_factory=GPT2Config)
    # Optional Mistral-7B-class prompt LM; when set, the serving layer
    # generates story episodes with it instead of GPT-2 (the reference's
    # actual LLM family, backend.py:25).
    mistral: Optional[MistralConfig] = None
    minilm: MiniLMConfig = dataclasses.field(default_factory=MiniLMConfig)
    # Directory holding safetensors checkpoints; None -> deterministic
    # random-init (fixed PRNG) so the full pipeline runs without artifacts.
    weights_dir: Optional[str] = None
    # Storage dtype for UNet/text-model params ("bfloat16" halves HBM
    # weight traffic per denoise step — the TPU-standard serving layout;
    # norm layers still compute fp32 internally). "float32" to disable.
    param_dtype: str = "bfloat16"
    # Weights-only int8 for the prompt LM's matmul kernels (ops/quant.py):
    # halves weight HBM footprint and streaming bytes — what makes the
    # Mistral-7B-class prompt model (the reference's LLM family) fit and
    # decode fast on a single 16 GB chip. Embeddings/norms stay bf16.
    lm_int8: bool = False
    # Weights-only int8 for the diffusion UNet's large matmul/conv
    # kernels: halves denoise-loop weight streaming (the per-step HBM
    # read of ~1.7 GB bf16 UNet params). Dequantization happens inside
    # the jit (per-output-channel scales, ops/quant.py) so the MXU still
    # sees bf16 tiles. Quality must be re-gated via tools/clip_report.py
    # when enabled.
    unet_int8: bool = False
    # Full W8A8 for the diffusion UNet (ISSUE 20): selected kernel
    # leaves become ActQTensors (ops/quant.py w8a8_tree_host) and the
    # attention/MLP/fused-conv sites dispatch the int8 Pallas kernels
    # (ops/quant_matmul.py) — int8 weights AND activations, scales
    # folded into the int32→fp epilogue. Requires unet.fused_conv for
    # the conv sites; mutually exclusive with unet_int8. Static
    # activation scales load from the calibration artifact
    # (parallel/calibrate.py, data/act_scales.json) when its signature
    # matches, dynamic absmax otherwise. CASSMANTLE_NO_W8A8 kill switch
    # reverts bit-exactly at pipeline build (never quantizes).
    unet_w8a8: bool = False
    # Full W8A8 for the prompt LM with PER-TOKEN activation scales
    # (models/gpt2.py); mutually exclusive with lm_int8. Same artifact,
    # kill switch, and epilogue scheme as unet_w8a8.
    lm_w8a8: bool = False
    # Minimum weight-element count for a site to quantize under w8a8
    # (ops/quant.py w8a8_default_predicate): small kernels aren't worth
    # the quantize/dequantize round-trip. Tests drop it to 0 so reduced
    # test-geometry models still exercise the int8 kernel path.
    w8a8_min_size: int = 1 << 16


@dataclasses.dataclass(frozen=True)
class SamplerConfig:
    """Image sampler + greedy text decode settings.

    ``kind``: "ddim" (default), "euler", or "dpmpp_2m" (ops/samplers.py;
    DPM++(2M) reaches DDIM-50 quality in ~20-25 steps — the fast-serving
    configuration).
    """

    kind: str = "ddim"
    num_steps: int = 50
    guidance_scale: float = 7.5
    eta: float = 0.0
    image_size: int = 512
    # CFG negative conditioning — the reference passes this to its
    # hosted diffusion call (backend.py:284); "" disables (plain
    # unconditional arm). Tokenized host-side per batch, so changing it
    # never recompiles.
    negative_prompt: str = "blurry, distorted, fake, abstract, negative"
    # Deep-feature reuse (DeepCache-style): steps run in full/shallow
    # pairs, the shallow pass reusing the previous step's deepest-level
    # activations (~60% of full compute; ddim only, even num_steps).
    deepcache: bool = False
    # Encoder propagation (Faster Diffusion, PAPERS.md): run the full
    # UNet only at key steps; in between, reuse the key step's encoder
    # features (skip stack + mid output) and run ONLY the decoder —
    # batched across each segment's propagated steps in one forward,
    # since the decoder never reads x_t (ops/ddim.py, models/unet.py
    # ``return_skips``/``skips_cache``). Composes with ``deepcache``
    # (deep-cache refreshes happen exactly at encoder key steps) and
    # with every deterministic sampler kind; eta>0 is rejected and the
    # staged denoise path falls back to monolithic.
    # CASSMANTLE_NO_ENCPROP=1 is the runtime kill switch (docs/DEPLOY.md
    # §6). Quality is gated by eval/clip_parity.py::encprop_quality_report
    # (stride 1 is exact full-forward parity by construction).
    encprop: bool = False
    # Key-step cadence: one full forward every ``encprop_stride`` steps
    # after the dense prefix. Stride 1 = full forward every step
    # (bit-identical to the plain sampler).
    encprop_stride: int = 3
    # Leading steps that are ALL key steps — encoder features drift
    # fastest early in sampling (Faster Diffusion's non-uniform key
    # schedule), so keys are denser there. With the 50-step default and
    # stride 3 this yields 20 encoder forwards per trajectory (the
    # encoder is skipped on 60% of steps).
    encprop_dense_steps: int = 5
    # Few-step consistency serving (ops/samplers.py::consistency_sample;
    # ISSUE 15): sample with a consistency/LCM-distilled student —
    # ``num_steps`` (1-8) direct x0 predictions through the boundary
    # c_skip/c_out parameterization instead of a long ODE solve. The
    # student shares the teacher's UNetConfig arch and checkpoint
    # layout (parallel/train.py::ConsistencyDistillTrainer), so it
    # loads through the unchanged utils/checkpoint.py / share_compatible
    # machinery. Does NOT compose with deepcache/encprop (the student
    # is trained for direct few-step prediction — there is no long loop
    # to cache into); composes with the staged continuous-batching path
    # (a consistency slot stepper) and the execution-level levers
    # (fused_conv, int8). CASSMANTLE_NO_CONSISTENCY=1 is the runtime
    # kill switch: it reverts serving bit-exactly to the TEACHER path —
    # the plain ``kind`` sampler at ``consistency_teacher_steps``.
    # Quality gates via eval/clip_parity.py::consistency_quality_report.
    consistency: bool = False
    # The deployed UNet checkpoint IS a consistency-distilled student,
    # even though serving defaults to the teacher schedule — the signal
    # that lets the brownout ladder's few-step tier step INTO
    # consistency sampling under SLO burn (serving/overload.py). Stock
    # (undistilled) checkpoints MUST leave this False: 4-step
    # boundary-parameterized sampling through an eps-net that was never
    # distilled produces near-noise, so without this flag the ladder
    # skips the few-step delta and falls through to the resolution tier
    # instead. ``consistency=True`` implies a student checkpoint and
    # does not need this flag.
    consistency_available: bool = False
    # The teacher schedule the kill switch reverts to — and the solver
    # discretization the distillation trainer integrates.
    consistency_teacher_steps: int = 50
    # Text decode (reference decodes 32-96 new tokens, backend.py:250-255;
    # its hosted call samples greedily — temperature 0 is reference
    # parity, >0 enables top-k Gumbel sampling for story variety).
    min_new_tokens: int = 32
    max_new_tokens: int = 96
    prompt_pad_len: int = 77
    text_temperature: float = 0.0
    text_top_k: int = 40


@dataclasses.dataclass(frozen=True)
class SpecDecodeConfig:
    """Speculative decoding for the prompt-LM serving decode
    (ops/decode.py::speculative_decode): a draft proposes ``gamma``
    tokens and the target scores all gamma+1 positions in one
    ``decode_chunk`` forward, amortizing one full weight read over the
    chunk — the step-count lever for the memory-bound greedy loop
    (docs/PERF_NOTES.md "LM decode accounting").

    Engages only when ``sampler.text_temperature == 0`` (greedy — the
    reference's decode mode), where acceptance is exact argmax match and
    output is bit-identical to the plain greedy scan
    (tests/test_spec_decode.py). ``CASSMANTLE_NO_SPEC_DECODE=1`` is the
    runtime kill switch (docs/DEPLOY.md §6)."""

    # "off" | "ngram" (self-drafting prompt lookup, zero extra HBM) |
    # "draft_model" (a smaller zoo LM with its own prefill/decode cache)
    mode: str = "off"
    # drafted tokens per verify chunk: each chunk commits 1..gamma+1
    # tokens for one target forward of width gamma+1
    gamma: int = 4
    # suffix length for the "ngram" prompt-lookup draft
    ngram: int = 3
    # the "draft_model" draft: a smaller GPT-2-family config sharing the
    # target's tokenizer/vocab (gpt2-small drafting for gpt2-large; its
    # checkpoint loads from <weights_dir>/gpt2_draft.safetensors). When
    # it EQUALS the target's gpt2 config the target's own params are
    # reused (the self-draft degenerate, useful in tests).
    draft_model: Optional[GPT2Config] = None


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical device mesh. Axes follow the scaling-book convention:

    - ``dp``: data parallel (batch sharding) — rides ICI within a slice.
    - ``tp``: tensor parallel (attention heads / MLP columns).
    - ``sp``: sequence/context parallel (ring attention over image tokens).
    - ``pp``: pipeline parallel (layer stages; activations ppermute
      stage-to-stage, parallel/pipeline.py).
    - ``ep``: expert parallel (MoE experts sharded; token dispatch
      all-to-all inserted by GSPMD, models/moe.py).
    Sizes of -1 mean "fill with remaining devices".
    """

    dp: int = -1
    tp: int = 1
    sp: int = 1
    pp: int = 1
    ep: int = 1
    # Axis names, in mesh order.
    axis_names: Tuple[str, ...] = ("dp", "pp", "tp", "sp", "ep")


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Continuous-batching queue bounds (fixed shapes; no recompile storms)."""

    image_batch_sizes: Tuple[int, ...] = (1, 4, 8)
    # 2048 covers guesses+answers of a full 1k-pair scoring in ONE device
    # dispatch (each dispatch pays the host<->device round trip).
    score_batch_sizes: Tuple[int, ...] = (8, 64, 256, 1024, 2048)
    max_queue_delay_ms: float = 25.0
    max_pending: int = 4096
    # -- supervision (serving/queue.py, serving/supervisor.py) ------------
    # Per-request deadline: a submitted item whose batch never resolves
    # (wedged XLA call) fails its future instead of hanging the caller.
    # None disables. Sized to survive a legitimate cold-cache first
    # compile (minutes) — it bounds hangs, it is NOT a latency SLO;
    # latency-sensitive callers pass a tighter submit(deadline_s=...).
    submit_deadline_s: Optional[float] = 300.0
    # Dispatch watchdog: a handler exceeding this has wedged the dispatch
    # thread — the batch fails, the thread is disowned + replaced, the
    # supervisor flips degraded. Generous: first-dispatch XLA compiles
    # legitimately take minutes on cold caches. None disables.
    dispatch_hang_s: Optional[float] = 300.0
    # Tightened admission bound while the supervisor reports degraded —
    # a sick device gets a short queue, not max_pending of doomed work.
    degraded_max_pending: int = 256
    # -- overload control plane (serving/overload.py; ISSUE 13) ------------
    # Adaptive (AIMD) admission per queue: the effective pending bound
    # tracks measured queue-wait + batch-service latency against this
    # target, between admission_min_pending and max_pending. Rejections
    # carry a COMPUTED Retry-After (predicted wait = depth × observed
    # per-item service time) and predicted-late submissions fail at
    # submit. CASSMANTLE_NO_ADAPTIVE_ADMISSION=1 reverts to the static
    # max_pending/degraded_max_pending pair.
    queue_latency_target_s: float = 1.0
    admission_min_pending: int = 8
    # Background work (round generation, reserve refill, bench) sheds
    # at this fraction of the adaptive limit — first under pressure.
    admission_background_fraction: float = 0.5
    # Starvation bound for the background tier: after this many
    # consecutive batches dispatched with background work pending, the
    # oldest background item heads the next batch (rounds keep rotating
    # under sustained interactive load).
    background_every_batches: int = 8
    # Event-loop saturation threshold: when the server.loop_lag_s
    # sleep-overshoot gauge (obs/process.py) exceeds this, background
    # submissions shed BEFORE queues back up (interactive sheds at 4x).
    loop_lag_shed_s: float = 0.25
    # -- SLO-driven brownout ladder (serving/overload.py) ------------------
    # Dwell before stepping UP a quality tier on sustained fast-window
    # burn, and — the hysteresis — before stepping DOWN after the slow
    # window recovers. CASSMANTLE_NO_BROWNOUT=1 pins tier 0.
    brownout_step_up_dwell_s: float = 10.0
    brownout_step_down_dwell_s: float = 30.0
    # SLO objectives the ladder watches (obs/slo.py default_objectives
    # names); replication lag is deliberately absent — quality tiers
    # cannot fix a store problem.
    brownout_objectives: Tuple[str, ...] = ("score_latency",
                                            "round_generation")
    # Drill/test stand-in for device scoring cost on the FAKE backend:
    # >0 routes fake similarity through a real BatchingQueue whose
    # handler holds the dispatch thread this long per batch — what lets
    # `bench.py overload_drill` exercise the real admission path on a
    # CPU-only host. 0 (the default) keeps the instant hash scorer.
    fake_score_batch_ms: float = 0.0
    # -- stage-disaggregated image serving (serving/stages.py) -------------
    # Split the image path into encode / denoise / decode stages, each
    # independently batched, with the denoise stage running step-level
    # continuous batching over a fixed-capacity slot tensor: a request
    # arriving mid-denoise of another joins at the next STEP boundary
    # instead of waiting a whole image's latency for the dispatch lock
    # (ROADMAP item 1; SwiftDiffusion / LegoDiffusion, PAPERS.md). Solo
    # output is bit-identical to the monolithic path
    # (tests/test_stages.py); CASSMANTLE_NO_STAGED_SERVING=1 is the
    # runtime kill switch (docs/DEPLOY.md §6). Configs the slot stepper
    # cannot replay exactly (deepcache pairing, eta>0, a dp/sp mesh)
    # fall back to the monolithic dispatch automatically.
    staged_serving: bool = False
    # Fixed denoise slot capacity. The slot tensor keeps this shape
    # forever; each step gathers live slots into the smallest
    # power-of-two width bucket ≥ occupancy, so the step function
    # compiles once per bucket (never per admission/retirement) and
    # per-step compute tracks load.
    denoise_slots: int = 4
    # Bucket ladders for the encode/decode stage queues (batch dims pad
    # to the next bucket, shapes stay static across calls).
    stage_encode_batch_sizes: Tuple[int, ...] = (1, 2, 4, 8)
    stage_decode_batch_sizes: Tuple[int, ...] = (1, 2, 4)
    # Coalescing window for the encode/decode stage queues. Short: the
    # denoise stage's step-boundary admission does the real batching,
    # so holding encode work to widen a batch only adds latency.
    stage_max_delay_ms: float = 3.0


@dataclasses.dataclass(frozen=True)
class ObsConfig:
    """Observability knobs (cassmantle_tpu/obs/, utils/logging.py).

    Applied to the process-global tracer / flight recorder / metrics
    registry by ``obs.configure_observability`` at server build."""

    # Healthy-baseline sampling FLOOR (ISSUE 18): fraction of root
    # spans retained unconditionally (head-certain). Every other trace
    # buffers in the pending ring and is tail-retained only when its
    # root completes slow/errored/marked; IDs always propagate
    # (X-Trace-Id stays useful for log correlation) either way.
    # CASSMANTLE_NO_TAIL_SAMPLING=1 reverts this to the pre-tail
    # head-sampling decision (docs/DEPLOY.md §6).
    trace_sample_rate: float = 1.0
    # Bounded per-trace span sink: how many traces stay queryable at
    # /debugz?trace=... (LRU eviction), and the per-trace span cap.
    trace_capacity: int = 256
    trace_max_spans: int = 512
    # -- tail retention (ISSUE 18) -----------------------------------------
    # Pending ring for traces awaiting their root's retention verdict:
    # occupancy cap, and the TTL sweep that reclaims traces whose root
    # never completes (client disconnect, watchdog kill) — counted
    # obs.traces_abandoned.
    trace_pending_capacity: int = 512
    trace_pending_ttl_s: float = 120.0
    # Per-route slow thresholds for tail retention: a completed root
    # span at least this slow is promoted. Keyed by root span name
    # ("http.post /compute_score"); ()-pairs because the dataclass is
    # frozen/hashable.
    tail_slow_default_s: float = 1.0
    tail_slow_routes: Tuple[Tuple[str, float], ...] = ()
    # Flight-recorder ring: how many structured events /debugz replays.
    recorder_capacity: int = 512
    # Default latency-histogram bucket bounds (seconds, cumulative) —
    # the single definition lives in utils/logging.py so series created
    # before configure_observability runs get the SAME ladder.
    latency_buckets_s: Tuple[float, ...] = _DEFAULT_BUCKETS_S
    # -- cluster observability (ISSUE 9) -----------------------------------
    # Per-peer timeout for cluster fan-outs (/metrics?scope=cluster,
    # /debugz?trace=&scope=cluster): a dark peer costs at most this per
    # scrape and is marked, never silently dropped.
    cluster_fanout_timeout_s: float = 2.0
    # Background cadence of the process self-metrics sampler (uptime,
    # rss, cpu, event-loop lag; obs/process.py).
    process_sample_interval_s: float = 5.0
    # -- SLO burn-rate engine (obs/slo.py) ---------------------------------
    # Evaluation cadence of the background loop; /sloz also evaluates
    # on scrape (rate-limited internally). CASSMANTLE_NO_SLO=1 disables
    # the background loop (docs/DEPLOY.md §6).
    slo_eval_interval_s: float = 10.0
    # Multi-window burn rates: trip on the fast window, recover on the
    # slow one (obs/slo.py module docstring).
    slo_fast_window_s: float = 300.0
    slo_slow_window_s: float = 3600.0
    # Default objective thresholds (obs/slo.py default_objectives):
    # p99 bound for /compute_score, round-generation success ratio,
    # replication-lag bound in log commands.
    slo_score_p99_s: float = 2.0
    slo_generation_ratio: float = 0.9
    slo_repl_lag_max: float = 512.0
    # -- synthetic canary prober (obs/prober.py, ISSUE 18) -----------------
    # Background cadence of the end-to-end probe loop (self + peers)
    # and the per-leg HTTP timeout. CASSMANTLE_NO_PROBER=1 disables the
    # loop; CASSMANTLE_PROBE_INTERVAL_S overrides the cadence
    # (docs/DEPLOY.md §6).
    probe_interval_s: float = 15.0
    probe_timeout_s: float = 5.0
    # Black-box SLO objectives fed by probe verdicts: minimum probe
    # success ratio, and the p99 bound on probe end-to-end time.
    probe_success_ratio: float = 0.95
    probe_p99_s: float = 3.0


@dataclasses.dataclass(frozen=True)
class GameConfig:
    """Round/game constants (reference values cited in SURVEY.md §2/§5.6)."""

    min_score: float = 0.01          # server.py:17
    time_per_prompt: float = 900.0   # main.py:23 (15 min)
    buffer_at_fraction: float = 0.7  # server.py:162
    num_masked: int = 2              # backend.py:49
    episodes_per_story: int = 20     # backend.py:50
    min_blur: float = 0.0            # backend.py:319
    max_blur: float = 15.0           # backend.py:319
    lock_timeout: float = 120.0      # backend.py:47
    acquire_timeout: float = 2.0     # backend.py:48
    max_retries: int = 5             # server.py:19
    rate_limit_default: float = 3.0  # req/s per IP, main.py:19
    rate_limit_api: float = 2.0      # main.py:48 etc.
    # Round-reserve ring (engine/reserve.py): archived rounds rotated in
    # while generation is dark, so degraded rounds stay FRESH puzzles
    # instead of replaying one. 0 disables (pure reference replay).
    reserve_capacity: int = 8


@dataclasses.dataclass(frozen=True)
class FabricConfig:
    """Room fabric: sharded multi-room game over the shared store
    (cassmantle_tpu/fabric/). One worker with one room (the defaults)
    is exactly the pre-fabric game — the default room lives at the
    legacy un-prefixed store keys, so old stores resume and old
    frontends keep working."""

    # Concurrent rooms, each with its own round clock, content, and
    # score state. Room ids are ``default_room`` plus room-1..room-N-1;
    # sessions consistent-hash onto them (fabric/directory.py).
    num_rooms: int = 1
    # The room legacy un-roomed requests map to (empty key prefix).
    default_room: str = "lobby"
    # Stable worker identity for room placement; "" derives host:pid
    # (CASSMANTLE_ROOM_WORKER_ID overrides at runtime).
    worker_id: str = ""
    # Address peers should redirect to for rooms this worker owns,
    # e.g. "http://10.0.0.3:8000" (CASSMANTLE_ROOM_ADVERTISE overrides);
    # "" means this worker cannot be redirected to (single-worker).
    advertise_addr: str = ""
    # Membership heartbeat cadence and staleness cutoff: a worker whose
    # last heartbeat is older than ``membership_ttl_s`` leaves the ring
    # and its rooms re-place onto the survivors.
    heartbeat_s: float = 2.0
    membership_ttl_s: float = 6.0
    # Virtual nodes per worker on the consistent-hash ring (higher =
    # smoother room distribution, slower ring rebuild).
    vnodes: int = 64
    # Replicated-store endpoints ("host:port", ...): when non-empty the
    # worker talks to the mantlestore cluster through ReplicatedStore
    # (leader writes, log-shipping pump, lease failover) instead of a
    # single node. CASSMANTLE_REPL_ENDPOINTS overrides.
    repl_endpoints: Tuple[str, ...] = ()
    # Pump poll cadence (replication lag floor) and leader lease TTL
    # (failover detection time); CASSMANTLE_REPL_POLL_MS /
    # CASSMANTLE_REPL_LEASE_MS override.
    repl_poll_s: float = 0.05
    repl_lease_s: float = 3.0
    # Graceful SIGTERM handoff bound (fabric/rooms.py RoomFabric.handoff):
    # after leaving membership and draining rooms, the worker waits up to
    # this long for every live peer to heartbeat PAST the departure — the
    # beat that rebuilds the peer's ring and adopts the rooms — so
    # adoption happens before process exit, not after the staleness TTL.
    handoff_grace_s: float = 5.0


@dataclasses.dataclass(frozen=True)
class ChaosConfig:
    """Deterministic fault injection (cassmantle_tpu/chaos/,
    docs/CHAOS.md). ``spec`` uses the same grammar as the
    ``CASSMANTLE_CHAOS`` env lever (which wins when both are set):
    ``seed=N;point=kind:k=v,...`` clauses against the fault-point
    registry. Empty spec (the default) = disarmed, and every fault
    point is a zero-overhead no-op."""

    spec: str = ""
    # Default plan seed when the spec carries no ``seed=`` clause —
    # the same seed replays the same fault schedule.
    seed: int = 0


@dataclasses.dataclass(frozen=True)
class QualityGateConfig:
    """CLIP-parity thresholds a fast preset must clear before its
    throughput counts as a win (BASELINE.md quality gate). Enforced by
    tools/clip_report.py whenever the report is a real measurement
    (real_weights=true); advisory on random-init plumbing runs. Keyed
    by preset name; a preset absent here is reported but not gated.

    Ratios are preset clip_sim_mean / ddim50 anchor clip_sim_mean.
    DPM-Solver++(2M)@25 and deepcache claim DDIM-50-class quality, so
    they gate at 0.97; the composed turbo path trades a little more;
    int8 is a weights-only quantization and must stay ~lossless."""

    parity_vs_ddim50: Tuple[Tuple[str, float], ...] = (
        ("dpmpp25", 0.97),
        ("deepcache", 0.97),
        ("turbo", 0.95),
        ("int8", 0.98),
        # encoder propagation reuses key-step encoder features on 60%
        # of steps; like deepcache it claims near-anchor quality
        ("encprop", 0.95),
        # the 4-step consistency student trades the most quality for
        # the biggest step-count win (LCM-class results, PAPERS.md
        # Efficient Diffusion Models survey)
        ("lcm", 0.90),
        # full W8A8 (int8 weights AND activations, ISSUE 20) rounds
        # twice per matmul; with per-channel weight scales + calibrated
        # activation scales it must stay near-lossless, a hair below
        # the weights-only int8 bar. One row per image pipeline —
        # SDXL's depth-10 transformer level accumulates more
        # quantization noise than SD1.5's depth-1 blocks.
        ("w8a8", 0.98),
        ("sdxl_w8a8", 0.98),
    )
    # absolute floor for the anchor itself: catches a pipeline bug that
    # degrades every preset uniformly (ratios would all still pass)
    ddim50_min_sim: float = 0.18

    def threshold_for(self, preset: str):
        return dict(self.parity_vs_ddim50).get(preset)


@dataclasses.dataclass(frozen=True)
class FrameworkConfig:
    models: ModelZooConfig = dataclasses.field(default_factory=ModelZooConfig)
    sampler: SamplerConfig = dataclasses.field(default_factory=SamplerConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    serving: ServingConfig = dataclasses.field(default_factory=ServingConfig)
    game: GameConfig = dataclasses.field(default_factory=GameConfig)
    obs: ObsConfig = dataclasses.field(default_factory=ObsConfig)
    fabric: FabricConfig = dataclasses.field(default_factory=FabricConfig)
    chaos: ChaosConfig = dataclasses.field(default_factory=ChaosConfig)
    spec_decode: SpecDecodeConfig = dataclasses.field(
        default_factory=SpecDecodeConfig)
    quality: QualityGateConfig = dataclasses.field(
        default_factory=QualityGateConfig)
    seed: int = 0

    def replace(self, **kw) -> "FrameworkConfig":
        return dataclasses.replace(self, **kw)


def sdxl_config() -> FrameworkConfig:
    """SDXL-base-1.0 at 1024×1024: dual text towers (CLIP-L + OpenCLIP
    bigG), micro-conditioned UNet, 0.13025 VAE scaling — the BASELINE.md
    "SDXL-base 1024 batched prompts, data-parallel" workload."""

    return FrameworkConfig(
        models=ModelZooConfig(
            clip_text=ClipTextConfig(),
            clip_text_2=ClipTextConfig.sdxl_big(),
            unet=UNetConfig.sdxl(),
            vae=VAEConfig(scaling_factor=0.13025),
        ),
        sampler=SamplerConfig(image_size=1024),
    )


def fast_serving_config() -> FrameworkConfig:
    """Low-latency game serving: DPM-Solver++(2M) at 25 steps reaches
    DDIM-50 visual quality in half the denoise time (ops/samplers.py).
    The benchmark keeps the 50-step DDIM north-star config; this preset
    is for round serving where latency budget matters
    (reference budget: 270 s per round, server.py:162)."""

    return FrameworkConfig(
        sampler=SamplerConfig(kind="dpmpp_2m", num_steps=25)
    )


def turbo_serving_config() -> FrameworkConfig:
    """The two workload-level speedups COMPOSED: DPM-Solver++(2M) at 24
    steps (half of DDIM-50) with deep-feature reuse on alternate steps
    (~60% UNet compute). Relative to the DDIM-50 north star this is
    ~3.3x fewer UNet-FLOPs per image — the route past BASELINE.md's
    ~2.5 img/s/chip bf16 ceiling toward the 4 img/s target. Quality is
    gated by tools/clip_report.py's parity_vs_ddim50, like every other
    preset. Even step count keeps the (full, shallow) pairing uniform."""

    return FrameworkConfig(
        sampler=SamplerConfig(kind="dpmpp_2m", num_steps=24, deepcache=True)
    )


def fusedconv_serving_config() -> FrameworkConfig:
    """The fixed DDIM-50 north-star config with the conv-side Pallas
    path on: fused GroupNorm+SiLU+conv3x3 in every UNet ResBlock plus
    128-lane channel padding at the non-aligned 320/960 levels
    (UNetConfig.fused_conv / conv_pad_to; ops/fused_conv.py). Same
    trajectory and param tree as the plain config — this is the ON arm
    of the `sd15_fusedconv` bench A/B, and it composes with the
    workload-level presets (deepcache/dpmpp/int8) because it changes
    how ResBlock convs execute, not what they compute."""

    base = FrameworkConfig()
    return base.replace(models=dataclasses.replace(
        base.models, unet=dataclasses.replace(
            base.models.unet, fused_conv=True, conv_pad_to=128)))


def w8a8_serving_config() -> FrameworkConfig:
    """The fixed DDIM-50 config served fully W8A8 (ISSUE 20): int8
    weights AND activations at every attention/MLP/GEGLU projection and
    fused-conv ResBlock site in the UNet, plus the prompt LM with
    per-token activation scales — the quantization lever the Efficient
    Diffusion survey (PAPERS.md) ranks beside step reduction, composing
    multiplicatively with encprop/LCM/staged since it changes how
    matmuls execute, not what the schedule computes. Rides the fused
    GN+SiLU+conv path (fused_conv=True + 128-lane padding), so this is
    fusedconv_serving_config plus quantized trees. Static activation
    scales come from the committed calibration artifact
    (data/act_scales.json) when its signature matches this config;
    quality gates via the `w8a8` QualityGateConfig row; this is the ON
    arm of the `sd15_w8a8`/`gpt2_w8a8` bench A/Bs.
    CASSMANTLE_NO_W8A8=1 reverts bit-exactly at pipeline build."""

    base = FrameworkConfig()
    return base.replace(models=dataclasses.replace(
        base.models,
        unet=dataclasses.replace(
            base.models.unet, fused_conv=True, conv_pad_to=128),
        unet_w8a8=True, lm_w8a8=True))


def spec_decode_serving_config() -> FrameworkConfig:
    """The default serving config with speculative decoding on for the
    prompt LM, self-drafting n-gram mode (zero extra HBM, no draft
    checkpoint needed — works in every deployment). Same decode output
    as the plain config by construction (exact greedy acceptance); this
    is the ON arm of the `gpt2_spec` bench A/B. Swap ``mode`` to
    "draft_model" with a gpt2-small config to draft with a second zoo
    LM instead."""

    return FrameworkConfig(
        spec_decode=SpecDecodeConfig(mode="ngram", gamma=4, ngram=3))


def staged_serving_config() -> FrameworkConfig:
    """The fixed DDIM-50 config served through the stage graph
    (serving/stages.py): CLIP encode, denoise, and VAE decode run as
    independently batched stages, and the denoise loop admits/retires
    requests at STEP granularity over a fixed slot tensor — a request
    landing one step after another's dispatch starts denoising at the
    next step boundary instead of waiting a whole image's latency.
    Same trajectory per request as the monolithic path (solo output is
    bit-identical, tests/test_stages.py); this is the ON arm of the
    `sd15_staged` mixed-load bench A/B. CASSMANTLE_NO_STAGED_SERVING=1
    is the runtime kill switch."""

    return FrameworkConfig(serving=ServingConfig(staged_serving=True))


def encprop_serving_config() -> FrameworkConfig:
    """DDIM-50 with encoder propagation AND the decode-side kernels on:
    full UNet forwards only at the 20 key steps of the default schedule
    (5 dense + every 3rd), decoder-only forwards — batched per segment
    — on the other 30, plus fused GroupNorm+SiLU+conv3x3 VAE ResBlocks.
    This is the ON arm of the `sd15_encprop` bench A/B; the SDXL arm
    (`sdxl_encprop`) applies the same sampler/vae replaces to
    sdxl_config(), where the encoder (down+mid, 43% of UNet FLOPs —
    much of it the mid-block half of the depth-10 transformer level)
    is the profile-driven lever for the >80%-of-ceiling ROADMAP
    target. Quality gates via
    eval/clip_parity.py (encprop row in QualityGateConfig);
    CASSMANTLE_NO_ENCPROP=1 is the runtime kill switch."""

    base = FrameworkConfig()
    return base.replace(
        sampler=dataclasses.replace(base.sampler, encprop=True),
        models=dataclasses.replace(
            base.models,
            vae=dataclasses.replace(base.models.vae, fused_conv=True)))


def lcm_serving_config() -> FrameworkConfig:
    """Few-step image serving (ROADMAP item 3a, ISSUE 15): a
    consistency/LCM-distilled student of the zoo UNet sampled at FOUR
    direct x0 predictions per image instead of the 50-step DDIM solve —
    the step-COUNT lever the Efficient Diffusion Models survey
    (PAPERS.md) names as the largest remaining family, ~9x fewer
    per-image FLOPs than the north star (docs/PERF_NOTES.md "Few-step
    accounting"). The student shares the teacher's param tree and
    checkpoint layout (distill with
    parallel/train.py::ConsistencyDistillTrainer, serve its checkpoint
    through the unchanged weights path); quality gates via
    eval/clip_parity.py::consistency_quality_report and the `lcm` row
    of QualityGateConfig. This is the ON arm of the `sd15_lcm` bench
    A/B; CASSMANTLE_NO_CONSISTENCY=1 reverts bit-exactly to the
    teacher's DDIM-50 path."""

    return FrameworkConfig(
        sampler=SamplerConfig(consistency=True, num_steps=4))


def deepcache_serving_config() -> FrameworkConfig:
    """DDIM-50 with deep-feature reuse (SamplerConfig.deepcache): the
    full 50-step trajectory at ~60% of the UNet compute — alternate
    steps reuse the previous step's deepest-level activations
    (models/unet.py, ops/ddim.py). The second workload-level serving
    speedup next to fast_serving_config's fewer-steps route."""

    return FrameworkConfig(sampler=SamplerConfig(deepcache=True))


def test_config() -> FrameworkConfig:
    """A tiny config for CPU tests: small models, fast rounds, 64px images."""

    return FrameworkConfig(
        models=ModelZooConfig(
            clip_text=ClipTextConfig(
                vocab_size=1024, hidden_size=64, intermediate_size=128,
                num_layers=2, num_heads=4, max_positions=16,
            ),
            unet=UNetConfig(
                base_channels=32, channel_mults=(1, 2), num_heads=4,
                attention_levels=(True, False), transformer_depth=(1, 0),
                blocks_per_level=1, context_dim=64, time_embed_dim=128,
                dtype="float32",
            ),
            vae=VAEConfig(base_channels=32, channel_mults=(1, 2),
                          blocks_per_level=1, dtype="float32"),
            gpt2=GPT2Config(vocab_size=256, hidden_size=64, num_layers=2,
                            num_heads=4, max_positions=64, dtype="float32"),
            minilm=MiniLMConfig(vocab_size=512, hidden_size=64,
                                intermediate_size=128, num_layers=2,
                                num_heads=4, max_positions=32),
            # fp32 storage on CPU tests: keeps golden/parity tolerances
            # tight and bit-stable
            param_dtype="float32",
        ),
        # negative_prompt neutral: with random-init weights the uncond
        # arm's content only adds noise to statistical test properties;
        # the wiring is covered explicitly (test_pipeline.py)
        sampler=SamplerConfig(num_steps=4, image_size=64, max_new_tokens=8,
                              min_new_tokens=2, prompt_pad_len=16,
                              negative_prompt=""),
        game=GameConfig(time_per_prompt=2.0, lock_timeout=5.0,
                        acquire_timeout=0.5),
    )


def test_sdxl_config() -> FrameworkConfig:
    """Tiny SDXL-shaped config for CPU tests: dual towers, micro-conds."""

    base = test_config()
    tower = base.models.clip_text
    tower2 = dataclasses.replace(tower, hidden_size=96, num_heads=4)
    return base.replace(
        models=dataclasses.replace(
            base.models,
            clip_text_2=tower2,
            unet=UNetConfig(
                base_channels=32, channel_mults=(1, 2), num_heads=4,
                attention_levels=(False, True), transformer_depth=(0, 2),
                blocks_per_level=1, context_dim=tower.hidden_size + 96,
                time_embed_dim=128,
                # pooled (96) + 6 sinusoidal time_ids × 32
                addition_embed_dim=96 + 6 * 32,
                dtype="float32",
            ),
            vae=dataclasses.replace(base.models.vae, scaling_factor=0.13025),
        ),
    )
