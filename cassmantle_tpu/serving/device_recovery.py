"""Device-loss detection and serving-state rebuild (ISSUE 17, rung 3).

A TPU runtime can die under a live server — preempted VM, wedged PCIe
tunnel, driver crash. jax surfaces that as ``XlaRuntimeError`` (or a
transport error wrapping one) on the NEXT dispatch, and every buffer the
process holds (params, staged-slot tensors, compiled-executable device
state) is garbage from that point on. Without handling, each request
thereafter burns a full dispatch timeout before failing, and nothing
ever repairs the process short of a restart.

This module closes the loop:

- :func:`classify_device_loss` decides whether an exception from a
  dispatch region (or a DeviceHealth probe) means the *runtime* is gone,
  as opposed to a data-dependent failure (OutputInvalid), a deadline, or
  a wedge (the watchdog's department).
- :class:`DeviceRecoveryManager` owns the single-flight recovery: flip
  the supervisor into ``device_lost`` (queues fail fast, `/readyz`
  serves 503 naming the state), then rebuild serving state on a
  background thread — re-upload checkpoints through the
  fingerprint-verified load path (utils/checkpoint.py) and re-warm the
  hot dispatch paths under a ``no_new_compiles`` window. Bounded
  retries with backoff ride a token-bucket :class:`~cassmantle_tpu.
  utils.retry.RetryBudget`; exhaustion is PERMANENT loss — the worker
  stays ``device_lost`` (the LB drains on the 503, docs/DEPLOY.md §7b)
  and the optional ``on_permanent`` hook fires.

Kill switch (docs/DEPLOY.md §6): ``CASSMANTLE_NO_DEVICE_RECOVERY``
disables the REBUILD only — a classified loss still flips the
supervisor (fail-fast + 503 beat timing out every request), it just
stays there for the operator. Read per-call so flipping the env var
needs no restart.

Chaos: the ``device.lost`` fault point (serving dispatch regions)
raises ``ChaosInjected`` with the fault name in its message, which
classifies exactly like a real loss — the ``device_loss_drill`` bench
entry drives this whole path end to end.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from cassmantle_tpu.obs.recorder import flight_recorder
from cassmantle_tpu.utils.logging import get_logger, metrics
from cassmantle_tpu.utils.retry import RetryBudget

log = get_logger("device_recovery")

# Exception type names (matched anywhere in the cause/context chain)
# that mean the accelerator runtime itself failed. Name-matched, not
# isinstance: jaxlib's XlaRuntimeError moves modules across versions,
# and tests raise look-alikes without a dead TPU to hand.
_LOSS_TYPES = frozenset({"XlaRuntimeError", "DeadBufferError"})

# Message substrings (lowercased) that mark runtime loss even under a
# generic exception type. "device.lost" is the chaos fault-point name —
# ChaosInjected carries it, so drills classify like real losses.
_LOSS_MARKERS = (
    "device.lost",
    "device is lost",
    "device lost",
    "runtime is gone",
    "data transfer failed",
    "failed to enqueue",
    "hardware failure",
    "tpu driver",
)


def recovery_disabled() -> bool:
    """CASSMANTLE_NO_DEVICE_RECOVERY kill switch, read per-call."""
    return os.environ.get(
        "CASSMANTLE_NO_DEVICE_RECOVERY", ""
    ).lower() not in ("", "0", "false", "no", "off")


def classify_device_loss(exc: BaseException) -> Optional[str]:
    """A short reason string when ``exc`` (or anything in its
    cause/context chain) looks like accelerator-runtime loss, else
    None. Deliberately conservative: deadlines, backpressure, and
    invalid-output failures are NOT losses — misclassifying those
    would bounce serving through a needless rebuild."""
    seen = set()
    node: Optional[BaseException] = exc
    while node is not None and id(node) not in seen:
        seen.add(id(node))
        name = type(node).__name__
        if name in _LOSS_TYPES:
            return f"{name}: {str(node)[:120]}"
        text = str(node).lower()
        for marker in _LOSS_MARKERS:
            if marker in text:
                return f"{name}: {marker}"
        node = node.__cause__ or node.__context__
    return None


class DeviceRecoveryManager:
    """Single-flight device-loss recovery.

    ``rebuild`` performs ONE rebuild attempt (re-upload params; raises
    on failure); ``warm`` optionally re-drives the hot paths after a
    successful rebuild (a failure there fails the attempt — a rebuilt
    device that cannot serve is not recovered). Both run on the
    manager's daemon thread, never on a dispatch thread.
    """

    def __init__(
        self,
        *,
        supervisor,
        rebuild: Callable[[], None],
        warm: Optional[Callable[[], None]] = None,
        on_permanent: Optional[Callable[[str], None]] = None,
        max_attempts: int = 3,
        backoff_s: float = 2.0,
        budget: Optional[RetryBudget] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ) -> None:
        self.supervisor = supervisor
        self.rebuild = rebuild
        self.warm = warm
        # wired by the server layer when a fabric is serving (begin the
        # PR 12 drain); default None leaves the worker device_lost —
        # /readyz 503 IS the drain signal for the LB
        self.on_permanent = on_permanent
        self.max_attempts = max_attempts
        self.backoff_s = backoff_s
        # rebuilds re-upload multi-GB checkpoints: a flapping device
        # must not melt the host re-reading them in a tight loop. ~6
        # attempts burst, one earned back per minute.
        self.budget = budget or RetryBudget(
            "device_recovery", capacity=6.0, refill_per_s=1.0 / 60.0,
            clock=clock)
        self.clock = clock
        self.sleep = sleep
        self._lock = threading.Lock()
        self._recovering = False
        self._thread: Optional[threading.Thread] = None
        self.permanent = False

    # -- classification entry points --------------------------------------
    def note_dispatch_exception(self, exc: BaseException) -> bool:
        """Called from dispatch error paths (BatchingQueue
        ``on_dispatch_error``, the service's generate/similarity arms).
        Returns True when ``exc`` classified as device loss (recovery
        has been kicked off or is already in flight)."""
        reason = classify_device_loss(exc)
        if reason is None:
            return False
        self.begin_recovery(reason)
        return True

    # DeviceHealth probe raises funnel through the same classifier; a
    # probe that RAISES (vs times out) carries the runtime's own error
    note_probe_exception = note_dispatch_exception

    # -- recovery ----------------------------------------------------------
    def begin_recovery(self, reason: str) -> None:
        """Flip the supervisor and start the single-flight rebuild
        thread. Re-entrant: concurrent classifications during an active
        recovery (every queue fails fast with the same root cause)
        coalesce into the one in-flight attempt."""
        with self._lock:
            if self._recovering or self.permanent:
                return
            self._recovering = True
        self.supervisor.note_device_lost(reason)
        if recovery_disabled():
            log.error(
                "device recovery disabled (CASSMANTLE_NO_DEVICE_RECOVERY);"
                " worker stays device_lost: %s", reason)
            with self._lock:
                self._recovering = False
            return
        thread = threading.Thread(
            target=self._recover, args=(reason,), daemon=True,
            name="device-recovery")
        with self._lock:
            self._thread = thread
        thread.start()

    def join(self, timeout: Optional[float] = None) -> None:
        """Wait for an in-flight recovery thread (tests, drills)."""
        thread = self._thread
        if thread is not None:
            thread.join(timeout)

    @property
    def recovering(self) -> bool:
        with self._lock:
            return self._recovering

    def _recover(self, reason: str) -> None:
        start = self.clock()
        try:
            for attempt in range(1, self.max_attempts + 1):
                if not self.budget.acquire():
                    log.error("device recovery: retry budget exhausted "
                              "after %d attempt(s)", attempt - 1)
                    break
                try:
                    self.rebuild()
                    if self.warm is not None:
                        self.warm()
                except Exception as exc:
                    log.exception("device recovery attempt %d/%d failed",
                                  attempt, self.max_attempts)
                    flight_recorder.record(
                        "device.recovery_failed", attempt=attempt,
                        error=f"{type(exc).__name__}: {str(exc)[:160]}")
                    if attempt < self.max_attempts:
                        self.sleep(self.backoff_s * attempt)
                    continue
                elapsed = self.clock() - start
                metrics.inc("device.recoveries")
                metrics.observe("device.recovery_s", elapsed)
                self.supervisor.note_device_recovered()
                log.warning("device recovered in %.2fs (attempt %d/%d)",
                            elapsed, attempt, self.max_attempts)
                return
            # attempts (or budget) exhausted: permanent loss. The worker
            # stays device_lost — queues fail fast, /readyz serves 503
            # until the operator replaces it (docs/DEPLOY.md §7b).
            self.permanent = True
            metrics.inc("device.recovery_permanent")
            flight_recorder.record("device.recovery_permanent",
                                   reason=reason)
            log.critical(
                "device recovery FAILED permanently (%s); worker stays "
                "device_lost — drain and replace it", reason)
            if self.on_permanent is not None:
                try:
                    self.on_permanent(reason)
                # lint: ignore[swallowed-error] — advisory drain hook: the permanent-loss event itself is counted and flight-recorded just above
                except Exception:
                    log.exception("permanent-loss drain hook failed")
        finally:
            with self._lock:
                self._recovering = False
