"""Inference service: wires pipelines + batching queues into the game's
injection points (embed / similarity / blur / ContentBackend).

This is the production counterpart of the test wiring in
tests/test_pipeline.py: one object owning the TPU state that the server
layer (server/app.py) plugs into the engine.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from cassmantle_tpu.config import FrameworkConfig
from cassmantle_tpu.ops.blur import device_blur
from cassmantle_tpu.ops.scorer import EmbeddingScorer
from cassmantle_tpu.serving import integrity
from cassmantle_tpu.serving.device_recovery import DeviceRecoveryManager
from cassmantle_tpu.serving.integrity import OutputInvalid
from cassmantle_tpu.serving.overload import (
    PRIORITY_BACKGROUND,
    make_admission,
    note_table_served,
)
from cassmantle_tpu.serving.pipeline import TPUContentBackend
from cassmantle_tpu.serving.queue import (
    BatchingQueue,
    DeadlineExceeded,
    DispatchTimeout,
    OverloadShed,
    QueueFull,
)
from cassmantle_tpu.serving.supervisor import ServingSupervisor
from cassmantle_tpu.utils.logging import get_logger

log = get_logger("service")


def default_serving_mesh(cfg: FrameworkConfig):
    """Batch-DP mesh over all local devices when more than one is
    visible (the v5e-8 serving layout); None on a single chip."""
    import jax

    if jax.local_device_count() <= 1:
        return None
    from cassmantle_tpu.config import MeshConfig
    from cassmantle_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(MeshConfig(dp=-1))
    log.info("serving mesh: dp=%d", mesh.shape["dp"])
    return mesh


class InferenceService:
    def __init__(self, cfg: FrameworkConfig,
                 weights_dir: Optional[str] = None,
                 mesh=None,
                 backend: Optional[TPUContentBackend] = None,
                 supervisor: Optional[ServingSupervisor] = None) -> None:
        if mesh is None:
            mesh = default_serving_mesh(cfg)
        self.cfg = cfg
        # shared with the Game in production (build_game) so breaker
        # trips here and in the engine fuse into one /readyz signal
        self.supervisor = supervisor or ServingSupervisor()
        self.scorer = EmbeddingScorer(
            cfg.models.minilm,
            weights_dir=weights_dir,
            batch_buckets=cfg.serving.score_batch_sizes,
        )
        self.backend = backend or TPUContentBackend(
            cfg, weights_dir=weights_dir, mesh=mesh)
        # stage-disaggregated serving (serving/stages.py): the image
        # pipeline's per-stage queues/watchdogs report into the SAME
        # supervisor as the score/prompt queues, so stage dispatch
        # health fuses into the one /readyz signal
        t2i = getattr(self.backend, "t2i", None)
        if t2i is not None and hasattr(t2i, "supervisor"):
            t2i.supervisor = self.supervisor
        # device-loss recovery (serving/device_recovery.py, ISSUE 17):
        # dispatch exceptions from either queue (and the image path in
        # generate_content) are classified where they surface; a
        # classified loss flips the supervisor to ``device_lost`` and
        # kicks off the single-flight rebuild below
        self._warm_count = 0
        self.recovery = DeviceRecoveryManager(
            supervisor=self.supervisor,
            rebuild=self.rebuild_device_state,
            warm=self._warm_after_recovery,
        )
        # published on the supervisor so the server layer (which wires
        # DeviceHealth after this constructor) can connect probe raises
        # to the same classifier
        self.supervisor.recovery = self.recovery
        dh = getattr(self.supervisor, "device_health", None)
        if dh is not None and hasattr(dh, "on_probe_error"):
            # a dispatch-quiet worker still detects runtime loss: probe
            # raises ride the same classifier as dispatch exceptions
            dh.on_probe_error = self.recovery.note_probe_exception
        self.score_queue: BatchingQueue = BatchingQueue(
            handler=self._score_batch,
            max_batch=max(cfg.serving.score_batch_sizes),
            max_delay_ms=cfg.serving.max_queue_delay_ms,
            max_pending=cfg.serving.max_pending,
            name="score",
            default_deadline_s=cfg.serving.submit_deadline_s,
            hang_timeout_s=cfg.serving.dispatch_hang_s,
            supervisor=self.supervisor,
            degraded_max_pending=cfg.serving.degraded_max_pending,
            admission=make_admission("score", cfg),
            background_every=cfg.serving.background_every_batches,
            on_dispatch_error=self.recovery.note_dispatch_exception,
        )
        # Concurrent round generations (double-buffering overlapping a
        # live promotion, or several Game instances sharing one service)
        # coalesce their LM decodes into one batched greedy_decode
        # dispatch (PromptGenerator.decode_ids_batch) instead of
        # serializing single-prompt scans on the dispatch thread.
        from cassmantle_tpu.serving.pipeline import PromptGenerator

        self.prompt_queue: BatchingQueue = BatchingQueue(
            handler=self._prompt_batch,
            max_batch=max(PromptGenerator.BATCH_BUCKETS),
            max_delay_ms=cfg.serving.max_queue_delay_ms,
            max_pending=cfg.serving.max_pending,
            name="prompt",
            default_deadline_s=cfg.serving.submit_deadline_s,
            hang_timeout_s=cfg.serving.dispatch_hang_s,
            supervisor=self.supervisor,
            degraded_max_pending=cfg.serving.degraded_max_pending,
            admission=make_admission("prompt", cfg),
            background_every=cfg.serving.background_every_batches,
            on_dispatch_error=self.recovery.note_dispatch_exception,
        )

    # handlers run on the dispatch thread
    def _score_batch(self, pairs: Sequence[Tuple[str, str]]):
        """Batch handler with per-pair integrity (ISSUE 17): the scorer
        marks rows whose device encode came back non-finite as NaN
        similarities (never cached); those pairs fail individually with
        a retriable OutputInvalid via the queue's per-member exception
        distribution, while valid neighbors in the same batch still
        resolve. Counting happened at the scorer (pipeline=scorer)."""
        sims = self.scorer.similarity(list(pairs))
        if integrity.integrity_disabled():
            return sims
        bad = ~np.isfinite(np.asarray(sims))
        if not bad.any():
            return sims
        return [OutputInvalid("scorer", "similarity", [i]) if bad[i]
                else sims[i] for i in range(len(sims))]

    def _prompt_batch(self, seeds: Sequence[str]):
        # rows the integrity sentinel rejected come back as
        # OutputInvalid instances; the queue's per-member distribution
        # fails those futures while healthy rows still serve
        return self.backend.prompt_gen.generate_batch(list(seeds))

    # -- engine injection points -----------------------------------------
    def embed(self, words) -> np.ndarray:
        return self.scorer.embed(list(words))

    def pin_answers(self, words) -> int:
        """RoundManager promotion hook (engine/rounds.py): embed the
        round's answers once and pin them into the scorer's int8 table,
        so every (in-vocabulary guess, answer) pair that follows is
        rung-0-servable with zero device dispatches."""
        return self.scorer.pin_answers(list(words))

    async def similarity(self, pairs) -> np.ndarray:
        """SimilarityFn, ladder rung 0: pairs fully covered by the
        armed int8 embed table complete right here as host dot products
        — no queue submit, no admission check, no breaker consult (the
        limiter's capacity estimates should only ever see true device
        work; ``overload.table_served`` counts what bypassed it). Pairs
        with any OOV side keep the entire queued ladder below."""
        pairs = list(pairs)
        table = self.scorer.table_scores(pairs)
        if table is not None:
            scores, served = table
            if served.all():
                note_table_served(len(pairs))
                return scores
            if served.any():
                rest_idx = [i for i, s in enumerate(served) if not s]
                note_table_served(len(pairs) - len(rest_idx))
                rest = await self._queued_similarity(
                    [pairs[i] for i in rest_idx])
                for j, i in enumerate(rest_idx):
                    scores[i] = rest[j]
                return scores
        return await self._queued_similarity(pairs)

    async def _queued_similarity(self, pairs) -> np.ndarray:
        """The queued ladder: each pair rides the continuous-batching
        queue, so concurrent guesses from many players coalesce into one
        device batch. The score breaker wraps the dispatch: while open,
        guesses degrade to floor scores instantly (no queue, no device
        dial) and the HTTP layer sheds with 503 + Retry-After;
        deadline/watchdog failures count toward tripping it."""
        import asyncio

        pairs = list(pairs)
        breaker = self.supervisor.score_breaker
        if not breaker.allow():
            log.warning("score breaker open; floor scores for %d pairs",
                        len(pairs))
            return np.zeros((len(pairs),), dtype=np.float32)
        try:
            results = await asyncio.gather(
                *(self.score_queue.submit(p) for p in pairs)
            )
        except OverloadShed:
            # adaptive admission shed this request with a computed
            # Retry-After: propagate so the HTTP layer answers 503 +
            # Retry-After in <50 ms (ISSUE 13 acceptance) instead of
            # silently serving floor scores. Not a breaker failure —
            # shedding IS the healthy overload response.
            raise
        except QueueFull:
            # hard backpressure (static bound / degraded bound):
            # degrade to the min score rather than failing the request
            # (skip-don't-crash). Backpressure is load, not a device
            # failure — it doesn't count against the breaker.
            log.warning("score queue full; returning zeros for %d pairs",
                        len(pairs))
            return np.zeros((len(pairs),), dtype=np.float32)
        except (DeadlineExceeded, DispatchTimeout) as exc:
            breaker.record_failure()
            log.warning("score dispatch failed (%s); floor scores for %d "
                        "pairs", type(exc).__name__, len(pairs))
            return np.zeros((len(pairs),), dtype=np.float32)
        except OutputInvalid as exc:
            # the device produced garbage for at least one pair
            # (integrity verdict, serving/integrity.py): degrade the
            # request to floor scores — an invalid score must never
            # reach a player as a real one — and count toward the
            # breaker (repeated invalid output = sick scorer)
            breaker.record_failure()
            log.warning("invalid scorer output (%s); floor scores for "
                        "%d pairs", exc, len(pairs))
            return np.zeros((len(pairs),), dtype=np.float32)
        except Exception as exc:
            breaker.record_failure()
            # a dead runtime surfaces here too (gather re-raises the
            # dispatch exception): classify before propagating
            self.recovery.note_dispatch_exception(exc)
            raise
        breaker.record_success()
        return np.asarray(results, dtype=np.float32)

    @staticmethod
    def blur(image: np.ndarray, radius: float) -> np.ndarray:
        return device_blur(image, radius)

    async def generate_content(self, seed: str, is_seed: bool):
        """ContentBackend-compatible generate whose text decode rides
        the prompt queue: N rounds generating concurrently become one
        (N<=8)-row decode batch. Image generation still runs per round
        in the executor. Queue overload degrades to the backend's own
        single-prompt decode (skip-don't-crash)."""
        text = None
        if hasattr(self.backend, "prompt_gen"):
            try:
                # round generation is BACKGROUND-tier work: interactive
                # scoring preempts it in dispatch order, and it is the
                # first shed under pressure (its fallback below keeps
                # rounds rotating — the starvation bound guarantees the
                # queue path itself also keeps progressing)
                text = await self.prompt_queue.submit(
                    seed, priority=PRIORITY_BACKGROUND)
            except (QueueFull, DeadlineExceeded, DispatchTimeout,
                    OutputInvalid) as exc:
                # any queue-path failure (backpressure, missed deadline,
                # wedged dispatch, invalid decode output) degrades to
                # the in-backend decode — the fallback exists precisely
                # for a sick queue path, and OutputInvalid is retriable
                # by design (a fresh dispatch usually succeeds)
                log.warning(
                    "prompt queue failed (%s); decoding %r in-backend",
                    type(exc).__name__, seed[:40])
        try:
            if text is not None:
                return await self.backend.generate(seed, is_seed,
                                                   text=text)
            # injected custom backends may not take a ``text`` kwarg
            return await self.backend.generate(seed, is_seed)
        except Exception as exc:
            # the image pipeline dispatches outside the queues, so its
            # exceptions classify here; rounds.py owns the retry ladder
            self.recovery.note_dispatch_exception(exc)
            raise

    @property
    def content_backend(self):
        """The ContentBackend the Game should own: same pipelines as
        ``self.backend``, but generate() coalesces concurrent LM decodes
        through the prompt queue. This is what server/app.py wires in —
        handing ``service.backend`` to the Game instead would silently
        bypass the batching."""
        return _QueuedContentBackend(self)

    # -- device-loss rebuild (serving/device_recovery.py) ------------------
    def rebuild_device_state(self) -> None:
        """ONE rebuild attempt, run on the recovery manager's thread:
        re-upload every pipeline's checkpoints through the
        fingerprint-verified load path (utils/checkpoint.py) and drop
        state that referenced the dead runtime (the staged slot server
        restarts lazily on the next generate). Raises on failure — the
        manager owns retries, backoff, and the retry budget."""
        for name in ("t2i", "sdxl", "prompt_gen"):
            pipe = getattr(self.backend, name, None)
            if pipe is not None and hasattr(pipe, "reload_params"):
                pipe.reload_params()
        if hasattr(self.scorer, "reload_params"):
            self.scorer.reload_params()
        dh = getattr(self.supervisor, "device_health", None)
        if dh is not None and hasattr(dh, "invalidate"):
            # the rebuilt runtime must be re-probed, not vouched for by
            # the dead one's cached verdict
            dh.invalidate()

    def _warm_after_recovery(self) -> None:
        """Post-rebuild warm: drive one real dispatch through the
        scorer inside a ``no_new_compiles`` window. Params re-enter the
        jits as ARGUMENTS (serving/pipeline.py __init__ note), so a
        rebuild must not recompile anything — if it does, the bucket
        key regressed and recovery fails loudly here instead of
        recompiling under live traffic. A fresh word each time keeps
        the scorer's host LRU from short-circuiting the device dial."""
        from cassmantle_tpu.utils import jit_sentinel

        self._warm_count += 1
        with jit_sentinel.no_new_compiles():
            self.scorer.embed([f"recovery warm {self._warm_count}"])

    async def stop(self) -> None:
        await self.score_queue.stop()
        await self.prompt_queue.stop()


class _QueuedContentBackend:
    """Thin ContentBackend adapter binding generate() to
    InferenceService.generate_content (prompt-queue-batched decode)."""

    def __init__(self, service: InferenceService) -> None:
        self._service = service
        # expose the underlying pipelines (tests and tools reach
        # backend.t2i / backend.prompt_gen through the Game)
        self.inner = service.backend

    def __getattr__(self, name):
        return getattr(self.inner, name)

    async def generate(self, seed: str, is_seed: bool):
        return await self._service.generate_content(seed, is_seed)
