"""TPU inference pipelines: text→image, prompt generation, content backend.

This is the local replacement for the reference's two Inference-API calls
(backend.py:240-295): CLIP encode → DDIM scan → VAE decode compile into one
XLA computation per (batch, resolution) bucket, and GPT-2 prefill+greedy
scan into one per prompt bucket. The game engine reaches all of it through
:class:`TPUContentBackend.generate` — the same seam the fake backend
implements for tests (engine/content.py).
"""

from __future__ import annotations

import asyncio
import contextvars
import os
import random
import threading
from functools import partial
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from cassmantle_tpu.chaos import fault_point
from cassmantle_tpu.config import FrameworkConfig
from cassmantle_tpu.engine.rounds import ContentBackend, RoundContent
from cassmantle_tpu.models.clip_text import ClipTextEncoder
from cassmantle_tpu.models.gpt2 import GPT2LM
from cassmantle_tpu.models.unet import UNet
from cassmantle_tpu.models.vae import VAEDecoder, postprocess_images
from cassmantle_tpu.models.weights import (
    convert_clip_text,
    convert_gpt2,
    convert_unet,
    convert_vae_decoder,
    init_params_cached,
    maybe_load,
)
from cassmantle_tpu.utils.compile_cache import (
    enable_compile_cache,
    param_cache_path,
)
from cassmantle_tpu.ops.ddim import (
    initial_latents,
    make_cfg_denoiser,
)
from cassmantle_tpu.ops.samplers import make_sampler
from cassmantle_tpu.ops.decode import greedy_decode
from cassmantle_tpu.serving import integrity
from cassmantle_tpu.utils.locks import OrderedLock
from cassmantle_tpu.utils.logging import get_logger, metrics
from cassmantle_tpu.utils.profiling import annotate, block_timer
from cassmantle_tpu.utils.tokenizers import load_tokenizer

log = get_logger("pipeline")


def dp_sharded_sampler(sample_impl, mesh):
    """Jit a ``(params, ids, uncond_ids, rng)`` sampler for the mesh.

    Returns ``(jitted_fn, dp)``: with a mesh, token ids arrive sharded
    over the required ``dp`` axis and params replicate (GSPMD inserts
    nothing in the forward — batch parallelism is collective-free);
    without one, a plain jit and dp=1. Shared by the SD1.5 and SDXL
    pipelines so the sharding/padding contract lives in one place.
    """
    if mesh is None:
        return jax.jit(sample_impl), 1
    from jax.sharding import NamedSharding, PartitionSpec as P

    batch = NamedSharding(mesh, P("dp"))
    repl = NamedSharding(mesh, P())
    fn = jax.jit(
        sample_impl,
        in_shardings=(repl, batch, batch, repl),
        out_shardings=batch,
    )
    return fn, int(mesh.shape["dp"])


def spatially_shard_latents(lat, mesh):
    """Latency scale-up for big latents (SURVEY §5.7's 1024²+ path,
    IN SERVING): constrain (B, H, W, C) latents to P("dp", "sp") so
    GSPMD spatially partitions the whole denoise over the mesh's sp
    axis — halo exchanges around every conv, resharding around the
    attention flattens, all compiler-inserted, riding ICI. A no-op
    without a mesh or with sp=1 (the batch-throughput layout). sp must
    divide the latent H."""
    if mesh is None or int(mesh.shape.get("sp", 1)) <= 1:
        return lat
    from jax.sharding import NamedSharding, PartitionSpec as P

    # lint: ignore[host-sync] — mesh.shape is static host metadata, not a device value
    assert lat.shape[1] % int(mesh.shape["sp"]) == 0, (
        f"latent H {lat.shape[1]} not divisible by sp={mesh.shape['sp']}")
    return jax.lax.with_sharding_constraint(
        lat, NamedSharding(mesh, P("dp", "sp")))


def share_compatible(models_a, models_b) -> bool:
    """True when two ModelZooConfigs can share Text2ImagePipeline param
    trees (same architectures + storage dtype; ``unet_int8`` MAY differ
    — the pipeline then derives/loads its own UNet). The single
    definition of the ``share_params_with`` contract: the pipeline's
    assert and callers picking anchors (tools/clip_report.py) both use
    this. UNet and VAE configs compare by ``arch()``: the fused-conv
    execution flags (fused_conv/conv_pad_to) change how convs run,
    never the param tree, so a fused A/B arm shares the donor's
    weights."""
    return (models_a.clip_text == models_b.clip_text
            and models_a.unet.arch() == models_b.unet.arch()
            and models_a.vae.arch() == models_b.vae.arch()
            and models_a.param_dtype == models_b.param_dtype)


def int8_unet_tools(models_cfg):
    """(loader transform, apply wrapper) for the weights-only int8 UNet
    option — the one place the int8 serving contract lives (shared by
    the SD1.5 and SDXL pipelines, like deepcache_schedule): quantize
    host-side before device placement, dequantize inside the jit."""
    if not models_cfg.unet_int8:
        return None, lambda apply: apply
    from cassmantle_tpu.ops.quant import quantize_tree_host, quantized_apply

    return (quantize_tree_host,
            lambda apply: quantized_apply(
                apply, jnp.dtype(models_cfg.param_dtype)))


def unet_w8a8_armed(models_cfg) -> bool:
    """True when the UNet actually serves through the int8 W8A8 kernels:
    the config knob AND the kill switch agree. The kill switch is read
    at pipeline BUILD time (never per dispatch): with it set the param
    tree is never quantized, so every module takes its plain fp branch
    and the revert is bit-exact against an unquantized build."""
    from cassmantle_tpu.ops.quant_matmul import w8a8_disabled

    return bool(models_cfg.unet_w8a8) and not w8a8_disabled()


def lm_w8a8_armed(models_cfg) -> bool:
    """LM twin of :func:`unet_w8a8_armed` (same build-time kill-switch
    contract; per-token activation scales, models/gpt2.py)."""
    from cassmantle_tpu.ops.quant_matmul import w8a8_disabled

    return bool(models_cfg.lm_w8a8) and not w8a8_disabled()


def w8a8_unet_tools(models_cfg):
    """Loader transform for the W8A8 UNet option, or None when off —
    the one place the image-side W8A8 serving contract lives (shared by
    the SD1.5 and SDXL pipelines, like int8_unet_tools): quantize
    weights host-side before device placement (per-output-channel int8
    scales), folding in static activation scales when the committed
    calibration artifact matches this model config's signature (else
    the kernels fall back to dynamic per-dispatch absmax). Unlike
    int8_unet_tools there is NO apply wrapper: the quantized leaves ride
    the tree into the unchanged ``unet.apply`` and each QDense /
    fused-conv site branches on its own leaf type."""
    if not unet_w8a8_armed(models_cfg):
        return None
    assert not models_cfg.unet_int8, (
        "unet_w8a8 and unet_int8 are mutually exclusive: both rewrite "
        "the same kernel leaves")
    assert models_cfg.unet.fused_conv, (
        "unet_w8a8 conv sites ride the fused GN+SiLU+conv path "
        "(ops/quant_matmul.py quantizes the fused activation); set "
        "models.unet.fused_conv=True")
    from cassmantle_tpu.ops.quant import (
        w8a8_default_predicate,
        w8a8_tree_host,
    )
    from cassmantle_tpu.parallel.calibrate import load_act_scales

    scales = load_act_scales(models_cfg)
    pred = partial(w8a8_default_predicate,
                   min_size=models_cfg.w8a8_min_size)
    return lambda params: w8a8_tree_host(
        params, act_scales=scales, predicate=pred)


def deepcache_schedule(sampler_cfg):
    """Validate a deepcache sampler config and build the matching
    schedule (shared by the SD1.5 and SDXL pipelines, like
    dp_sharded_sampler). Composes with ddim (even steps only) and
    dpmpp_2m (any step count; an odd final step runs unpaired-full)."""
    assert sampler_cfg.eta == 0.0, \
        "deepcache needs eta=0 (the paired loop is deterministic)"
    if sampler_cfg.kind == "ddim":
        from cassmantle_tpu.ops.ddim import DDIMSchedule

        assert sampler_cfg.num_steps % 2 == 0, \
            "ddim deepcache pairing needs an even step count"
        return DDIMSchedule.create(sampler_cfg.num_steps)
    if sampler_cfg.kind == "dpmpp_2m":
        from cassmantle_tpu.ops.samplers import DPMppSchedule

        return DPMppSchedule.create(sampler_cfg.num_steps)
    raise AssertionError(
        f"deepcache composes with ddim or dpmpp_2m, not "
        f"{sampler_cfg.kind!r}")


def encprop_plan(sampler_cfg):
    """Validate an encoder-propagation sampler config and return its
    ``(stride, dense_steps, key_count)`` key schedule (shared by the
    SD1.5 and SDXL pipelines, like deepcache_schedule). Composes with
    every deterministic sampler kind; eta>0 is rejected (propagated
    steps replay the decoder deterministically — there is no per-step
    noise chain to reuse), and the deepcache composition inherits
    deepcache's own sampler-kind constraint."""
    from cassmantle_tpu.ops.ddim import encprop_key_indices
    from cassmantle_tpu.ops.samplers import SAMPLER_KINDS

    assert sampler_cfg.eta == 0.0, \
        "encprop needs eta=0 (the propagated decoder loop is deterministic)"
    assert sampler_cfg.kind in SAMPLER_KINDS, \
        f"encprop composes with {SAMPLER_KINDS}, not {sampler_cfg.kind!r}"
    assert sampler_cfg.encprop_stride >= 1, \
        f"encprop stride must be >= 1, got {sampler_cfg.encprop_stride}"
    assert 0 <= sampler_cfg.encprop_dense_steps <= sampler_cfg.num_steps, \
        "encprop dense prefix outside the step count"
    if sampler_cfg.deepcache:
        assert sampler_cfg.kind in ("ddim", "dpmpp_2m"), \
            "deepcache composes with ddim or dpmpp_2m, not " \
            f"{sampler_cfg.kind!r}"
    keys = encprop_key_indices(
        sampler_cfg.num_steps, sampler_cfg.encprop_stride,
        sampler_cfg.encprop_dense_steps)
    return (sampler_cfg.encprop_stride, sampler_cfg.encprop_dense_steps,
            len(keys))


def consistency_plan(sampler_cfg) -> int:
    """Validate a few-step consistency sampler config and return its
    step count (shared by the SD1.5 and SDXL pipelines, like
    deepcache_schedule/encprop_plan). Consistency serving IS the
    few-step path — 1-8 direct x0 predictions — and does not compose
    with deepcache or encprop: the student is trained for direct
    few-step prediction, so there is no long solver loop to cache
    into. eta>0 is rejected (the re-noise ladder is deterministic by
    construction — what lets few-step requests ride the staged
    slot stepper)."""
    s = sampler_cfg
    assert s.eta == 0.0, \
        "consistency sampling is deterministic (eta=0)"
    assert 1 <= s.num_steps <= 8, (
        f"consistency serving is the few-step path (1-8 steps), got "
        f"{s.num_steps}; the teacher schedule lives in "
        f"consistency_teacher_steps")
    assert not s.deepcache, \
        "consistency does not compose with deepcache (no paired loop)"
    assert not s.encprop, \
        "consistency does not compose with encprop (no key schedule)"
    assert s.consistency_teacher_steps > s.num_steps, (
        f"consistency_teacher_steps ({s.consistency_teacher_steps}) must "
        f"exceed num_steps ({s.num_steps}): the student only ever trains "
        f"on the teacher discretization's query points "
        f"(ops/samplers.py::ConsistencySchedule), and the kill switch "
        f"reverts to this schedule")
    return s.num_steps


def effective_sampler_cfg(sampler_cfg):
    """The sampler config the pipeline is ACTUALLY dispatching: with
    consistency configured but KILLED (CASSMANTLE_NO_CONSISTENCY=1)
    serving reverts to the teacher path — the configured kind at
    ``consistency_teacher_steps``. Cost-model signatures must digest
    THIS config, not the nominal one: the lcm preset under the kill
    switch runs ~9x the student's FLOPs, and resolving the committed
    student entry would under-report mxu_utilization exactly during
    the quality incident the switch exists for."""
    import dataclasses as _dc

    from cassmantle_tpu.ops.samplers import consistency_disabled

    if sampler_cfg.consistency and consistency_disabled():
        return _dc.replace(sampler_cfg, consistency=False,
                           num_steps=sampler_cfg.consistency_teacher_steps)
    return sampler_cfg


def effective_sampler_steps(sampler_cfg) -> int:
    """The step count the pipeline's plain ``make_sampler`` schedule
    should use (the revert is bit-exact — the pinned contract,
    tests/test_samplers.py). Shared by both pipelines and the staged
    slot stepper so every dispatch path reverts identically."""
    return effective_sampler_cfg(sampler_cfg).num_steps


def note_consistency_counter(sampler_cfg, n_images: int) -> None:
    """Diagnosis counter for few-step serving (host-side, derived from
    the static schedule like note_encprop_counters): how many
    consistency UNet forwards the dispatch performed —
    ``pipeline.consistency_steps`` / images = UNet forwards per image,
    the number the `sd15_lcm` bench A/B attaches. Silent when the knob
    or the kill switch has consistency off, so A/B counter deltas
    separate the arms."""
    from cassmantle_tpu.ops.samplers import consistency_disabled

    if sampler_cfg.consistency and not consistency_disabled():
        metrics.inc("pipeline.consistency_steps",
                    sampler_cfg.num_steps * n_images)


def note_w8a8_counter(models_cfg, sampler_cfg, n_images: int) -> None:
    """Diagnosis counter for quantized serving (host-side, derived from
    the static schedule like note_consistency_counter): how many UNet
    forwards the dispatch ran through the int8 W8A8 kernel path —
    ``pipeline.w8a8_dispatches``. The `sd15_w8a8`/`sdxl_w8a8` bench A/B
    receipts attach this delta to prove the kernel path actually
    engaged (a CPU smoke that silently fell back to fp would otherwise
    look like a 1.0x win). Silent when the knob is off or the kill
    switch reverted the build, so A/B counter deltas separate the
    arms."""
    if unet_w8a8_armed(models_cfg):
        metrics.inc("pipeline.w8a8_dispatches",
                    effective_sampler_steps(sampler_cfg) * n_images)


def run_cfg_denoise(sampler_cfg, sample_latents, dc_schedule, unet_apply,
                    params, ctx, uncond_ctx, lat,
                    addition_embeds=None, uncond_addition_embeds=None):
    """The denoise stage both image pipelines share: few-step
    consistency sampling (the distilled-student path), plain CFG
    sampling, the deepcache full/shallow pairing, or encoder
    propagation (full forwards at key steps, batched decoder-only
    forwards in between — possibly composed with deepcache) when
    configured."""
    from cassmantle_tpu.ops.ddim import encprop_disabled
    from cassmantle_tpu.ops.samplers import consistency_disabled

    if sampler_cfg.consistency and not consistency_disabled():
        from cassmantle_tpu.ops.samplers import make_consistency_sampler

        denoise = make_cfg_denoiser(
            unet_apply, params, ctx, uncond_ctx,
            sampler_cfg.guidance_scale,
            addition_embeds=addition_embeds,
            uncond_addition_embeds=uncond_addition_embeds,
        )
        return make_consistency_sampler(
            sampler_cfg.num_steps,
            sampler_cfg.consistency_teacher_steps)(denoise, lat)
    if sampler_cfg.encprop and not encprop_disabled():
        from cassmantle_tpu.ops.ddim import make_cfg_denoiser_encprop
        from cassmantle_tpu.ops.samplers import make_encprop_sampler

        stride, dense, _ = encprop_plan(sampler_cfg)
        sample = make_encprop_sampler(
            sampler_cfg.kind, sampler_cfg.num_steps, stride, dense,
            deepcache=sampler_cfg.deepcache)
        dn_key, dn_prop, dn_shallow = make_cfg_denoiser_encprop(
            unet_apply, params, ctx, uncond_ctx,
            sampler_cfg.guidance_scale,
            addition_embeds=addition_embeds,
            uncond_addition_embeds=uncond_addition_embeds,
            deepcache=sampler_cfg.deepcache,
        )
        return sample(dn_key, dn_prop, lat, denoise_shallow=dn_shallow)
    if sampler_cfg.deepcache:
        from cassmantle_tpu.ops.ddim import (
            ddim_sample_deepcache,
            make_cfg_denoiser_pair,
        )

        dn_full, dn_shallow = make_cfg_denoiser_pair(
            unet_apply, params, ctx, uncond_ctx,
            sampler_cfg.guidance_scale,
            addition_embeds=addition_embeds,
            uncond_addition_embeds=uncond_addition_embeds,
        )
        if sampler_cfg.kind == "dpmpp_2m":
            from cassmantle_tpu.ops.samplers import (
                dpmpp_2m_sample_deepcache,
            )

            return dpmpp_2m_sample_deepcache(
                dn_full, dn_shallow, lat, dc_schedule)
        return ddim_sample_deepcache(dn_full, dn_shallow, lat, dc_schedule)
    denoise = make_cfg_denoiser(
        unet_apply, params, ctx, uncond_ctx, sampler_cfg.guidance_scale,
        addition_embeds=addition_embeds,
        uncond_addition_embeds=uncond_addition_embeds,
    )
    return sample_latents(denoise, lat)


def note_encprop_counters(counts, n_images: int) -> None:
    """Diagnosis counters for encoder propagation (host-side, derived
    from the static key schedule — the step loop itself is one XLA
    computation, so per-step device counters would cost a host sync):
    how many full-encoder, deepcache-shallow (composed loop only), and
    decoder-only UNet forwards the serving path dispatched. Shared by
    both image pipelines; silent when the config or the kill switch has
    encprop off, so bench A/B counter deltas separate the arms."""
    from cassmantle_tpu.ops.ddim import encprop_disabled

    if counts and not encprop_disabled():
        keys, shallow, props = counts
        metrics.inc("pipeline.encprop_key_steps", keys * n_images)
        if shallow:
            metrics.inc("pipeline.encprop_shallow_steps",
                        shallow * n_images)
        metrics.inc("pipeline.encprop_prop_steps", props * n_images)


def degraded_dispatch_variant(cache: dict, sampler_cfg, mesh,
                              build_impl, log_):
    """Shared brownout-variant machinery for BOTH image pipelines
    (serving/overload.py, ISSUE 13): resolve the active tier into a
    degraded SamplerConfig, build that delta's sampler + schedules +
    jitted dispatch ONCE (cached by the (steps, stride, size) key — a
    tier change never recompiles in steady state), and fall back to
    full quality on any build failure. ``build_impl(scfg, sampler,
    dc_schedule)`` returns the pipeline-specific sample impl; returns
    ``(sample_fn, scfg, encprop_counts)`` or None (tier 0 / no-op
    delta / unusable delta)."""
    from cassmantle_tpu.serving import overload

    tier = overload.quality_overrides()
    if tier is None:
        return None
    try:
        scfg = overload.degraded_sampler_cfg(sampler_cfg, tier)
        if scfg == sampler_cfg:
            return None
        key = (scfg.num_steps, scfg.encprop_stride, scfg.image_size,
               scfg.consistency)
        entry = cache.get(key)
        if entry is None:
            if scfg.consistency:
                consistency_plan(scfg)
            dc = deepcache_schedule(scfg) if scfg.deepcache else None
            counts = None
            if scfg.encprop:
                from cassmantle_tpu.ops.ddim import encprop_step_counts

                encprop_plan(scfg)
                counts = encprop_step_counts(
                    scfg.num_steps, scfg.encprop_stride,
                    scfg.encprop_dense_steps, scfg.deepcache)
            # consistency tiers dispatch their own sampler inside
            # run_cfg_denoise; a plain schedule here would be dead code
            sampler = (None if scfg.consistency
                       else make_sampler(scfg.kind, scfg.num_steps,
                                         eta=scfg.eta))
            fn, _ = dp_sharded_sampler(build_impl(scfg, sampler, dc),
                                       mesh)
            entry = (fn, scfg, counts)
            cache[key] = entry
        return entry
    except Exception:
        # counted: the ladder believes it engaged a cheaper tier, but
        # this config is quietly serving full quality — invisible in
        # the tier gauge, so the mismatch needs its own counter
        metrics.inc("pipeline.brownout_delta_unusable")
        log_.exception("brownout tier delta unusable for this config; "
                       "serving full quality")
        return None


def pad_prompts_to_dp(prompts: Sequence[str], dp: int):
    """Pad a prompt list to a multiple of the dp width (equal per-device
    shards); callers drop the pad rows from the output."""
    n = len(prompts)
    return list(prompts) + [""] * ((-n) % dp), n


def tokenize_clip_prompts(tokenizer, prompts: Sequence[str], pad_len: int,
                          vocab_size: int) -> np.ndarray:
    """Right-padded CLIP token ids: encode, trim, append EOS, pad.

    Shared by the SD1.5 and SDXL pipelines so both tokenize identically.
    """
    out = np.full((len(prompts), pad_len), tokenizer.pad_id, dtype=np.int32)
    for i, p in enumerate(prompts):
        toks = tokenizer.encode(p)[: pad_len - 1]
        toks = toks + [tokenizer.eos_id]
        # lint: ignore[host-sync] — toks is a host token list, not a device array
        out[i, : len(toks)] = np.asarray(toks) % vocab_size
    return out


class Text2ImagePipeline:
    """prompts -> uint8 images; whole sampler jitted per batch bucket.

    With ``mesh`` the batch shards over the ``dp`` axis (params
    replicated by GSPMD) — the v5e-8 batch-data-parallel serving layout;
    partial batches pad to the dp width and pad rows are dropped.
    """

    def __init__(self, cfg: FrameworkConfig,
                 weights_dir: Optional[str] = None,
                 mesh=None,
                 share_params_with: "Optional[Text2ImagePipeline]" = None,
                 ) -> None:
        """``share_params_with``: reuse another pipeline's already-loaded
        param trees (device buffers are shared, nothing is copied) when
        the model architectures match — presets that differ only in
        sampler (ddim50 vs dpmpp25 vs deepcache) then skip re-reading
        and re-converting the multi-GB checkpoints per variant. A donor
        that differs ONLY in ``unet_int8`` still shares CLIP/VAE, and an
        int8 pipeline derives its quantized UNet from the donor's
        in-memory fp tree instead of re-reading the checkpoint."""
        enable_compile_cache()
        m = cfg.models
        self.cfg = cfg
        self.mesh = mesh
        self._weights_dir = weights_dir
        self.clip = ClipTextEncoder(m.clip_text)
        self.unet = UNet(m.unet)
        self.vae = VAEDecoder(m.vae)
        if share_params_with is not None:
            assert share_compatible(share_params_with.cfg.models, m), (
                "share_params_with needs matching model architectures"
            )
        self.tokenizer = load_tokenizer(
            weights_dir, "clip", m.clip_text.vocab_size
        )
        self.pad_len = min(cfg.sampler.prompt_pad_len,
                           m.clip_text.max_positions)
        # pixels per latent: one 2x upsample per VAE level transition
        self.vae_scale = 2 ** (len(m.vae.channel_mults) - 1)
        unet_transform, wrap_unet_apply = int8_unet_tools(m)
        w8a8_transform = w8a8_unet_tools(m)
        if w8a8_transform is not None:
            # mutually exclusive with unet_int8 (asserted in
            # w8a8_unet_tools), so int8_unet_tools returned (None,
            # identity) and the slot is free
            unet_transform = w8a8_transform

        def load_unet(transform):
            """maybe_load-or-init for the UNet tree, shared by the
            fresh-load and fp-joins-int8-donor paths."""
            lat_hw = cfg.sampler.image_size // self.vae_scale
            loaded = maybe_load(
                weights_dir, "unet.safetensors",
                lambda t: convert_unet(t, m.unet), "unet",
                cast_to=m.param_dtype, transform=transform)
            if loaded is not None:
                return loaded, True
            # cache key on arch(): the fused-conv flags don't change the
            # tree, so both A/B arms reuse one cached init
            return init_params_cached(
                self.unet, 2,
                jnp.zeros((1, lat_hw, lat_hw, 4), jnp.float32),
                jnp.zeros((1,), jnp.int32),
                jnp.zeros((1, self.pad_len, m.unet.context_dim),
                          jnp.float32),
                cache_path=param_cache_path("unet", m.unet.arch()),
                cast_to=m.param_dtype, transform=transform), False

        def load_all_params() -> None:
            """Load/convert/share every stage tree and publish it on
            ``self``. Boot runs this once; a device-loss rebuild
            (serving/device_recovery.py, via :meth:`reload_params`)
            runs it again to re-upload the fingerprint-verified
            checkpoints onto the fresh runtime."""
            if share_params_with is not None:
                donor = share_params_with
                self.clip_params = donor.clip_params
                self.vae_params = donor.vae_params
                unet_was_loaded = True
                donor_m = donor.cfg.models
                donor_plain = (not donor_m.unet_int8
                               and not unet_w8a8_armed(donor_m))
                if (donor_m.unet_int8 == m.unet_int8
                        and unet_w8a8_armed(donor_m)
                        == unet_w8a8_armed(m)):
                    # same quantization mode (both fp, both int8, or
                    # both w8a8 with the same effective kill-switch
                    # state): share the device buffers outright
                    self.unet_params = donor.unet_params
                elif m.unet_int8 and donor_plain:
                    # int8 arm joining an fp donor: quantize the donor's
                    # in-memory tree (host-side) — no second checkpoint
                    # read
                    from cassmantle_tpu.ops.quant import (
                        quantize_tree_host,
                    )

                    self.unet_params = quantize_tree_host(
                        donor.unet_params)
                elif w8a8_transform is not None and donor_plain:
                    # w8a8 arm joining an fp donor: same derivation,
                    # through the w8a8 transform (static act scales and
                    # all)
                    self.unet_params = w8a8_transform(donor.unet_params)
                else:
                    # joining a donor quantized in a different mode:
                    # dequantization is lossy, so load this arm's own
                    # tree properly (through its own transform, if any)
                    self.unet_params, unet_was_loaded = load_unet(
                        unet_transform)
                # the donor's flag vouches only for tensors actually
                # taken from the donor; the fp-joins-int8-donor arm
                # re-loads its own UNet, and if the checkpoint vanished
                # between the two constructions that arm is random-init
                # and must say so
                self.loaded_real_weights = (
                    donor.loaded_real_weights and unet_was_loaded)
            else:
                ids = jnp.zeros((1, self.pad_len), dtype=jnp.int32)
                loaded_clip = maybe_load(
                    weights_dir, "clip_text.safetensors",
                    lambda t: convert_clip_text(
                        t, m.clip_text.num_layers),
                    "clip_text", cast_to=m.param_dtype)
                self.clip_params = (
                    loaded_clip if loaded_clip is not None
                    else init_params_cached(
                        self.clip, 1, ids,
                        cache_path=param_cache_path(
                            "clip_text", m.clip_text),
                        cast_to=m.param_dtype)
                )
                lat_hw = cfg.sampler.image_size // self.vae_scale
                lat = jnp.zeros((1, lat_hw, lat_hw, 4),
                                dtype=jnp.float32)
                self.unet_params, unet_was_loaded = load_unet(
                    unet_transform)
                loaded_vae = maybe_load(
                    weights_dir, "vae.safetensors",
                    lambda t: convert_vae_decoder(t, m.vae), "vae")
                self.vae_params = (
                    loaded_vae if loaded_vae is not None
                    else init_params_cached(
                        self.vae, 3, lat,
                        # cache key on arch(): fused_conv changes
                        # execution, not the tree (see UNet note above)
                        cache_path=param_cache_path(
                            f"vae{cfg.sampler.image_size}",
                            m.vae.arch()))
                )
                # True only when EVERY stage came from a checkpoint:
                # quality evals (tools/clip_report.py) refuse to call a
                # partially random-init pipeline a measurement
                self.loaded_real_weights = (
                    loaded_clip is not None
                    and unet_was_loaded
                    and loaded_vae is not None
                )

        self._param_loader = load_all_params
        load_all_params()
        self.unet_apply = wrap_unet_apply(self.unet.apply)
        from cassmantle_tpu.ops.fused_conv import describe as fc_describe

        if fc_describe(m.unet):
            log.info("%s", fc_describe(m.unet))
        if w8a8_transform is not None:
            from cassmantle_tpu.ops.quant import (
                w8a8_calibrated,
                w8a8_site_count,
            )
            from cassmantle_tpu.ops.quant_matmul import (
                describe as w8a8_describe,
            )

            log.info("%s", w8a8_describe(
                w8a8_calibrated(self.unet_params),
                w8a8_site_count(self.unet_params)))
        self._dc_schedule = (deepcache_schedule(cfg.sampler)
                             if cfg.sampler.deepcache else None)
        # fail fast on invalid encprop configs and precompute the
        # key/shallow/propagated accounting the diagnosis counters report
        self._encprop_counts = None
        if cfg.sampler.encprop:
            from cassmantle_tpu.ops.ddim import encprop_step_counts

            encprop_plan(cfg.sampler)
            self._encprop_counts = encprop_step_counts(
                cfg.sampler.num_steps, cfg.sampler.encprop_stride,
                cfg.sampler.encprop_dense_steps, cfg.sampler.deepcache)
        # fail fast on invalid few-step consistency configs; with the
        # kill switch set the plain schedule below IS the teacher path
        # (run_cfg_denoise falls through to it), so the revert is
        # bit-exact against a non-consistency teacher config. With
        # consistency ACTIVE there is no plain schedule at all —
        # run_cfg_denoise dispatches its own consistency sampler and
        # would silently ignore one built here
        if cfg.sampler.consistency:
            consistency_plan(cfg.sampler)
        self.sample_latents = (
            None if effective_sampler_cfg(cfg.sampler).consistency
            else make_sampler(
                cfg.sampler.kind, effective_sampler_steps(cfg.sampler),
                eta=cfg.sampler.eta))
        # Params enter the jit as ARGUMENTS (device buffers), never as
        # captured constants — capturing bakes ~4 GB of weights into the
        # HLO, blowing up compile payloads (fatal through a remote-compile
        # tunnel) and compile-cache keys.
        self._params = {"clip": self.clip_params, "unet": self.unet_params,
                        "vae": self.vae_params}
        self._sample, self.dp = dp_sharded_sampler(self._sample_impl, mesh)
        # brownout actuation (serving/overload.py, ISSUE 13): degraded
        # sampler variants keyed by their (steps, stride, size) delta —
        # each TIER compiles once on first engagement and is reused
        # (bucketed like every other serving variant), so steady-state
        # tier changes never recompile. Tier 0 uses self._sample
        # untouched: unloaded behavior is bit-for-bit the old path.
        self._tier_fns: dict = {}
        # roofline attribution (obs/costmodel.py, ISSUE 14): per-image
        # analytic FLOPs per dispatch variant, resolved lazily on first
        # dispatch (committed cost model for the production config,
        # trace-once otherwise; tier variants resolve on a background
        # thread — see _dispatch_flops)
        self._flops_cache: dict = {}
        self._flops_lock = threading.Lock()
        self._flops_pending: set = set()
        # One in-flight device batch per pipeline: concurrent round
        # buffering calls generate() from multiple executor threads, and
        # the device executes serially regardless — serializing dispatch
        # here costs nothing and removes a whole deadlock class
        # (concurrent executions of one compiled computation have
        # deadlocked the CPU backend under some jaxlib builds).
        # Outermost hierarchy tier (docs/STATIC_ANALYSIS.md): held for
        # whole device dispatches, so nothing coarser may nest inside.
        self._dispatch_lock = OrderedLock("pipeline.t2i_dispatch", rank=10)
        # stage-disaggregated serving (serving/stages.py): built lazily
        # on the first staged generate; the supervisor is wired by
        # InferenceService so per-stage watchdog health fuses into
        # /readyz like every other dispatch path
        self.supervisor = None
        self._staged = None
        # guards ONLY the lazy _staged construction (generate() is
        # called from multiple executor threads; two racing builders
        # would mean two denoise threads and duplicate jit graphs) —
        # rank 13, docs/STATIC_ANALYSIS.md
        self._staged_init_lock = OrderedLock("pipeline.staged_init",
                                             rank=13)

    def reload_params(self) -> None:
        """Device-loss rebuild (serving/device_recovery.py): re-run the
        boot load path — fingerprint-verified checkpoint reads
        (utils/checkpoint.py), donor sharing, int8 transform — and
        republish the tree onto the fresh runtime. Compiled executables
        take params as ARGUMENTS (see the __init__ note), so existing
        jitted fns stay valid; the recovery manager's warm pass
        verifies zero recompiles. The staged slot server held device
        state tied to the dead runtime: stop and drop it here — it
        rebuilds lazily on the next staged generate."""
        staged = self._staged
        if staged is not None:
            self._staged = None
            try:
                staged.stop()
            # lint: ignore[swallowed-error] — the staged server is dropped and rebuilt regardless; recovery's warm-pass counters cover the reload outcome
            except Exception:
                log.exception("staged server stop during reload failed")
        self._param_loader()
        self._params = {"clip": self.clip_params,
                        "unet": self.unet_params,
                        "vae": self.vae_params}
        if getattr(self, "vae_enc", None) is not None:
            # lazy img2img encoder state: drop it; _ensure_encoder
            # re-loads (fingerprint-verified) on the next img2img call
            self.vae_enc = None
            self.enc_params = None

    # -- stage-disaggregated serving (serving/stages.py) -------------------

    def _staged_enabled(self) -> bool:
        """Per-call routing decision: the ServingConfig knob, minus the
        runtime kill switch, minus configs the slot stepper cannot
        replay exactly — deepcache's paired steps, encprop's per-segment
        key/propagated structure (slots sit at arbitrary schedule
        positions; a slot admitted mid-segment has no cache), eta>0's
        per-step noise chain, non-stageable sampler kinds, and meshed
        (dp/sp) serving all keep the proven monolithic dispatch."""
        from cassmantle_tpu.serving.stages import (
            STAGEABLE_KINDS,
            staged_serving_disabled,
        )

        s = self.cfg.sampler
        return (self.cfg.serving.staged_serving
                and not staged_serving_disabled()
                and self.mesh is None
                and not s.deepcache
                and not s.encprop
                and s.eta == 0.0
                and s.kind in STAGEABLE_KINDS)

    def _encode_stage(self, params, ids, uncond_ids):
        """Encode-stage computation: exactly the conditioning block of
        ``_sample_impl`` (rows are batch-independent, so a staged row
        matches its monolithic counterpart bit for bit)."""
        return {
            "ctx": self.clip.apply(params["clip"], ids)["hidden"],
            "uctx": self.clip.apply(params["clip"], uncond_ids)["hidden"],
        }

    def _decode_stage(self, params, lat):
        """Decode-stage computation: exactly the VAE + uint8 tail of
        ``_sample_impl`` (the staged server's retirement verdict runs
        as its own dispatch on the latents — folding it in here would
        change fusion and break bit-parity with the monolith)."""
        decoded = self.vae.apply(params["vae"], lat)
        return postprocess_images(decoded)

    def _staged_server(self):
        if self._staged is None:
            with self._staged_init_lock:
                if self._staged is None:
                    from cassmantle_tpu.serving.stages import (
                        StagedImageServer,
                    )

                    self._staged = StagedImageServer(
                        self.cfg, self._params,
                        encode_fn=self._encode_stage,
                        decode_fn=self._decode_stage,
                        unet_apply=self.unet_apply,
                        tokenize=self._tokenize,
                        vae_scale=self.vae_scale,
                        supervisor=self.supervisor,
                    )
        return self._staged

    def _sample_impl(self, params, ids, uncond_ids, rng):
        with annotate("clip_encode"):
            ctx = self.clip.apply(params["clip"], ids)["hidden"]
            uncond = self.clip.apply(params["clip"], uncond_ids)["hidden"]
        lat = initial_latents(rng, ids.shape[0], self.cfg.sampler.image_size,
                              self.vae_scale)
        lat = spatially_shard_latents(lat, self.mesh)
        with annotate("denoise_scan"):
            final = run_cfg_denoise(
                self.cfg.sampler, self.sample_latents, self._dc_schedule,
                self.unet_apply, params["unet"], ctx, uncond, lat,
            )
        with annotate("vae_decode"):
            decoded = self.vae.apply(params["vae"], final)
        return postprocess_images(decoded)

    def _tokenize(self, prompts: Sequence[str]) -> np.ndarray:
        return tokenize_clip_prompts(
            self.tokenizer, prompts, self.pad_len,
            self.cfg.models.clip_text.vocab_size,
        )

    # -- brownout actuation (serving/overload.py, ISSUE 13) ----------------

    def _build_tier_impl(self, scfg, sampler, dc):
        """The SD1.5 sample impl bound to a degraded tier's config —
        ``_sample_impl`` with (steps, stride, size) swapped."""

        def impl(params, ids, uncond_ids, rng):
            with annotate("clip_encode"):
                ctx = self.clip.apply(params["clip"], ids)["hidden"]
                uncond = self.clip.apply(params["clip"],
                                         uncond_ids)["hidden"]
            lat = initial_latents(rng, ids.shape[0], scfg.image_size,
                                  self.vae_scale)
            lat = spatially_shard_latents(lat, self.mesh)
            with annotate("denoise_scan"):
                final = run_cfg_denoise(
                    scfg, sampler, dc, self.unet_apply,
                    params["unet"], ctx, uncond, lat,
                )
            with annotate("vae_decode"):
                decoded = self.vae.apply(params["vae"], final)
            return postprocess_images(decoded)

        return impl

    def _degraded_sampler(self):
        """(sample_fn, sampler_cfg, encprop_counts) for the active
        brownout tier, or None at full quality (see
        :func:`degraded_dispatch_variant`)."""
        return degraded_dispatch_variant(
            self._tier_fns, self.cfg.sampler, self.mesh,
            self._build_tier_impl, log)

    def _dispatch_flops(self, sample_fn, scfg, kind: str = "t2i",
                        signature=None):
        """Per-image analytic FLOPs for this dispatch variant (None =
        no attribution yet): the committed data/cost_model.json entry
        when the runtime signature matches the artifact, else a
        trace-once of the actual jitted ``sample_fn`` — exact for any
        variant (tiers, deepcache, encprop) because the jaxpr is the
        truth. Shared by the SDXL pipeline (same dispatch shape).

        Resolution is locked (racing executor threads pay one trace,
        not one each) and tiered by urgency: the pipeline's OWN config
        resolves inline — its cold dispatch is compile-dominated, so a
        trace is noise there — but a BROWNOUT-TIER variant engages
        exactly when the system is shedding latency, so its trace runs
        on a daemon thread and the first degraded dispatches simply
        carry no attribution until it lands."""
        from cassmantle_tpu.obs import costmodel

        # attribution follows what is DISPATCHED: under the consistency
        # kill switch the effective config is the teacher schedule
        eff = effective_sampler_cfg(scfg)
        key = (eff.num_steps, eff.image_size, eff.encprop,
               eff.encprop_stride, eff.deepcache, eff.consistency)
        if signature is None:
            signature = costmodel.t2i_signature(self.cfg, eff)

        def resolve():
            def trace() -> float:
                # minimal valid batch (the dp width with a mesh),
                # scaled back to per-image; tracing is abstract —
                # nothing runs on device
                ids = jax.ShapeDtypeStruct((self.dp, self.pad_len),
                                           jnp.int32)
                flops, _ = costmodel.trace_cost(
                    sample_fn, self._params, ids, ids,
                    jax.random.PRNGKey(0))
                return flops / self.dp

            return costmodel.flops_per_item(kind, signature,
                                            tracer=trace)

        with self._flops_lock:
            if key in self._flops_cache:
                return self._flops_cache[key]
            if scfg != self.cfg.sampler:
                if key not in self._flops_pending:
                    self._flops_pending.add(key)

                    def run_background():
                        value = resolve()
                        with self._flops_lock:
                            self._flops_cache[key] = value

                    threading.Thread(
                        target=run_background, daemon=True,
                        name="cassmantle-costtrace").start()
                return None
            per_image = resolve()
            self._flops_cache[key] = per_image
            return per_image

    def generate(self, prompts: Sequence[str], seed: int = 0,
                 deadline_s: Optional[float] = None) -> np.ndarray:
        """prompts -> (B, H, W, 3) uint8. One compiled graph per batch.

        With ``serving.staged_serving`` on (and the kill switch clear)
        the request rides the stage graph instead: encode/denoise/decode
        batch independently and the denoise loop admits at step
        granularity — same output bit for bit for a solo request.
        ``deadline_s`` is honored at step boundaries on the staged path
        (an expired request frees its denoise slot); the monolithic
        dispatch is all-or-nothing and ignores it."""
        # brownout tier first: a degraded delta routes to its own
        # monolithic variant (the staged slot stepper replays the FULL
        # schedule and cannot honor a tier's step/size delta)
        degraded = self._degraded_sampler()
        if degraded is None and self._staged_enabled():
            images = self._staged_server().generate(
                list(prompts), seed, deadline_s=deadline_s)
            metrics.inc("pipeline.images", len(prompts))
            note_consistency_counter(self.cfg.sampler, len(prompts))
            note_w8a8_counter(self.cfg.models, self.cfg.sampler,
                              len(prompts))
            return images
        sample_fn, scfg, ep_counts = (
            degraded if degraded is not None
            else (self._sample, self.cfg.sampler, self._encprop_counts))
        padded, n = pad_prompts_to_dp(prompts, self.dp)
        ids = jnp.asarray(self._tokenize(padded))
        uncond = jnp.asarray(self._tokenize(
            [scfg.negative_prompt] * len(padded)))
        rng = jax.random.PRNGKey(seed)
        per_image = self._dispatch_flops(sample_fn, scfg)
        # block_timer = metric + device-synchronized trace span (the
        # whole CLIP->denoise->VAE jit is ONE XLA computation; its
        # internal stages stay visible as profiler TraceAnnotations)
        # + roofline attribution: flops_est on the span, live
        # pipeline.mxu_utilization{pipeline="t2i"} vs the chip ceiling
        with self._dispatch_lock, block_timer(
                "pipeline.t2i_s",
                flops_est=(per_image * len(padded)) if per_image
                else None,
                pipeline="t2i"):
            fault_point("device.lost", peer="t2i")
            images = sample_fn(self._params, ids, uncond, rng)
            # the dispatch lock exists to serialize device work; blocking
            # on the result under it is the point
            # lint: ignore[lock-blocking-call] — intentional sync under dispatch lock
            images = jax.block_until_ready(images)
        out = integrity.poison(np.asarray(images[:n]), peer="t2i")
        # host-side sentinel on the already-transferred uint8 batch:
        # NaN/zeroed latents decode to constant frames, which the
        # degenerate-frame detector catches (the verdict stays OUT of
        # the sample jit to preserve staged-vs-monolithic bit-parity)
        integrity.enforce(np.ones(n, dtype=bool), pipeline="t2i",
                          stage="sample", images=out, n=n)
        metrics.inc("pipeline.images", n)
        if degraded is not None:
            metrics.inc("pipeline.brownout_images", n)
        note_encprop_counters(ep_counts, n)
        note_consistency_counter(scfg, n)
        note_w8a8_counter(self.cfg.models, scfg, n)
        return out

    # -- img2img ----------------------------------------------------------
    def _ensure_encoder(self) -> None:
        """Lazy VAE-encoder state: only img2img pays for it. The
        attribute checked by callers (``vae_enc``) is assigned LAST so a
        failed load leaves the pipeline retryable, not half-built."""
        if getattr(self, "vae_enc", None) is not None:
            return
        from cassmantle_tpu.models.vae import VAEEncoder
        from cassmantle_tpu.models.weights import convert_vae_encoder

        m = self.cfg.models
        encoder = VAEEncoder(m.vae)
        size = self.cfg.sampler.image_size
        img = jnp.zeros((1, size, size, 3), jnp.float32)
        self.enc_params = (
            maybe_load(self._weights_dir, "vae.safetensors",
                       lambda t: convert_vae_encoder(t, m.vae),
                       "vae_encoder")
            or init_params_cached(
                encoder, 4, img, jax.random.PRNGKey(0),
                cache_path=param_cache_path(f"vae_enc{size}", m.vae.arch()))
        )
        self._i2i_fns = {}
        self.vae_enc = encoder

    def _img2img_impl(self, k: int, params, ids, uncond_ids, images, rng):
        """Encode -> noise to the strength step -> run the schedule tail
        under the CONFIGURED sampler kind (same solver txt2img uses).
        ``k`` is static: one compiled graph per strength bucket."""
        from cassmantle_tpu.ops.samplers import make_img2img_sampler

        ctx = self.clip.apply(params["clip"], ids)["hidden"]
        uncond = self.clip.apply(params["clip"], uncond_ids)["hidden"]
        denoise = make_cfg_denoiser(
            self.unet_apply, params["unet"], ctx, uncond,
            self.cfg.sampler.guidance_scale,
        )
        rng_enc, rng_noise = jax.random.split(rng)
        # vae_enc is pure module structure (its params enter as the
        # ``params["vae_enc"]`` argument); reload_params nulls it only
        # so _ensure_encoder re-verifies the checkpoint and rebuilds an
        # architecturally identical module — the baked trace stays valid
        # lint: ignore[recompile-hazard] — structural capture, see above
        lat0 = self.vae_enc.apply(params["vae_enc"], images, rng_enc)
        s = self.cfg.sampler
        prepare, sample = make_img2img_sampler(
            s.kind, s.num_steps, s.num_steps - k, eta=s.eta
        )
        noise = jax.random.normal(rng_noise, lat0.shape, lat0.dtype)
        final = sample(denoise, prepare(lat0, noise))
        decoded = self.vae.apply(params["vae"], final)
        return postprocess_images(decoded)

    def generate_img2img(
        self,
        images: np.ndarray,          # (B, H, W, 3) uint8
        prompts: Sequence[str],
        strength: float = 0.6,
        seed: int = 0,
    ) -> np.ndarray:
        """Image-conditioned generation (DDIM tail from a noised VAE
        encoding — e.g. episode-to-episode visual continuity, an ability
        the reference's remote txt2img call could not offer). ``strength``
        in (0, 1]: fraction of the schedule re-run; higher = less of the
        input survives. Single-chip path (no dp sharding)."""
        assert 0.0 < strength <= 1.0
        if self.cfg.sampler.deepcache:
            raise NotImplementedError(
                "img2img does not support deepcache (schedule tails have "
                "arbitrary parity); use a non-deepcache config for "
                "image-conditioned generation"
            )
        if self.cfg.sampler.encprop:
            raise NotImplementedError(
                "img2img does not support encoder propagation (strength "
                "tails start mid-schedule, where the dense-prefix key "
                "accounting no longer holds); use a non-encprop config "
                "for image-conditioned generation"
            )
        if self.cfg.sampler.consistency:
            raise NotImplementedError(
                "img2img does not support the few-step consistency "
                "sampler (the student is trained to map noise states on "
                "the schedule, not arbitrary strength tails); use a "
                "non-consistency config for image-conditioned generation"
            )
        self._ensure_encoder()
        steps = self.cfg.sampler.num_steps
        k = max(1, min(steps, int(round(strength * steps))))
        if k not in self._i2i_fns:
            self._i2i_fns[k] = jax.jit(partial(self._img2img_impl, k))
        imgf = jnp.asarray(
            np.asarray(images, dtype=np.float32) / 127.5 - 1.0
        )
        ids = jnp.asarray(self._tokenize(list(prompts)))
        uncond = jnp.asarray(self._tokenize(
            [self.cfg.sampler.negative_prompt] * len(prompts)))
        params = dict(self._params, vae_enc=self.enc_params)
        with self._dispatch_lock, block_timer("pipeline.i2i_s"):
            out = self._i2i_fns[k](
                params, ids, uncond, imgf, jax.random.PRNGKey(seed)
            )
            # lint: ignore[lock-blocking-call] — intentional sync under dispatch lock
            out = jax.block_until_ready(out)
        out = np.asarray(out)
        # host-side degenerate-frame sentinel (see generate())
        integrity.enforce(np.ones(out.shape[0], dtype=bool),
                          pipeline="t2i", stage="img2img", images=out)
        metrics.inc("pipeline.images", len(prompts))
        return out


class PromptGenerator:
    """Story-episode text generation: greedy decode, bucketed.

    The LM family is config-selected: GPT-2 by default, or a
    Mistral-7B-class model (RoPE/GQA/sliding-window — the reference's
    actual prompt model, backend.py:25) when ``cfg.models.mistral`` is
    set. Both expose the same prefill/decode_step contract, so the scan
    in ops/decode.py drives either."""

    PROMPT_BUCKETS = (32, 64, 128, 256)

    def __init__(self, cfg: FrameworkConfig,
                 weights_dir: Optional[str] = None) -> None:
        from cassmantle_tpu.models.mistral import MistralLM
        from cassmantle_tpu.models.weights import convert_mistral

        enable_compile_cache()
        self.cfg = cfg
        self._decode_calls = 0  # auto-advancing sampling key (decode_ids)
        # one in-flight decode per generator (see Text2ImagePipeline's
        # dispatch lock; the prompt queue usually serializes decodes, but
        # direct generate() callers can race it)
        self._dispatch_lock = OrderedLock("pipeline.prompt_dispatch",
                                          rank=12)
        assert not (cfg.models.lm_int8 and cfg.models.lm_w8a8), (
            "lm_w8a8 and lm_int8 are mutually exclusive: both rewrite "
            "the same kernel leaves")
        if cfg.models.mistral is not None:
            m = cfg.models.mistral
            self.model = MistralLM(m)
            self.tokenizer = load_tokenizer(
                weights_dir, "mistral", m.vocab_size
            )
            loader = ("mistral.safetensors",
                      lambda t: convert_mistral(t, m.num_layers), "mistral")
        else:
            m = cfg.models.gpt2
            self.model = GPT2LM(m)
            self.tokenizer = load_tokenizer(weights_dir, "gpt2", m.vocab_size)
            loader = ("gpt2.safetensors",
                      lambda t: convert_gpt2(t, m.num_layers, m.hidden_size),
                      "gpt2")
        self.mcfg = m
        self._weights_dir = weights_dir
        self._int8_path = (
            os.path.join(weights_dir, f"{loader[2]}.int8.safetensors")
            if weights_dir else None)

        def load_params() -> None:
            """Load the LM tree and publish it on ``self``. Boot runs
            this once; a device-loss rebuild (reload_params) runs it
            again onto the fresh runtime."""
            ids = jnp.zeros((1, 8), dtype=jnp.int32)
            self.params = (
                self._load_int8_checkpoint(loader[2], weights_dir)
                if cfg.models.lm_int8 else None)
            if self.params is not None:
                # Pre-quantized checkpoint straight from disk.
                # Provenance: tools/quantize_weights.py falls back to
                # random init when no fp checkpoint exists, so the int8
                # file only counts as real weights if its fp source
                # (file or shards) is present (the staleness check
                # already ensures int8 is the newer).
                import glob as _glob

                stem = loader[0].rsplit(".", 1)[0]
                self.loaded_real_weights = bool(
                    os.path.exists(os.path.join(weights_dir, loader[0]))
                    or _glob.glob(os.path.join(
                        weights_dir, f"{stem}-*.safetensors")))
            else:
                transform = None
                if cfg.models.lm_int8:
                    # Quantize on HOST, before device placement: peak
                    # HBM stays at the int8 footprint (quantizing after
                    # would briefly hold the fp and int8 trees resident
                    # together — fatal for a 7B-class model on a 16 GB
                    # chip).
                    from cassmantle_tpu.ops.quant import (
                        quantize_tree_host,
                    )

                    transform = quantize_tree_host
                elif lm_w8a8_armed(cfg.models):
                    # W8A8 LM: same host-side quantize-before-placement
                    # rationale. No static act scales — the LM path
                    # quantizes activations per token (row absmax in
                    # graph, models/gpt2.py), so a calibration artifact
                    # has nothing to add here.
                    from cassmantle_tpu.ops.quant import (
                        w8a8_default_predicate,
                        w8a8_tree_host,
                    )

                    pred = partial(w8a8_default_predicate,
                                   min_size=cfg.models.w8a8_min_size)
                    transform = partial(w8a8_tree_host, predicate=pred)
                loaded = maybe_load(
                    weights_dir, loader[0], loader[1], loader[2],
                    cast_to=cfg.models.param_dtype, transform=transform)
                # measurement tools (tools/lm_int8_ab.py) refuse to
                # label a random-init decode a real-weights number
                self.loaded_real_weights = loaded is not None
                self.params = (
                    loaded if loaded is not None
                    else init_params_cached(
                        self.model, 5, ids,
                        cache_path=param_cache_path(loader[2], m),
                        cast_to=cfg.models.param_dtype,
                        transform=transform)
                )

        self._param_loader = load_params
        load_params()
        # params flow through greedy_decode as traced args (no captured
        # constants — see Text2ImagePipeline note)
        from cassmantle_tpu.ops.decode import make_apply_fns

        self._prefill, self._step, self._chunk = make_apply_fns(self.model)
        if cfg.models.lm_int8:
            from cassmantle_tpu.ops.quant import (
                quantized_apply,
                tree_nbytes,
            )

            dq_dtype = jnp.dtype(cfg.models.param_dtype)
            self._prefill = quantized_apply(self._prefill, dq_dtype)
            self._step = quantized_apply(self._step, dq_dtype)
            self._chunk = quantized_apply(self._chunk, dq_dtype)
            log.info("lm_int8: serving %.2f GB quantized param tree",
                     tree_nbytes(self.params) / 1e9)
        if lm_w8a8_armed(cfg.models):
            from cassmantle_tpu.ops.quant import (
                tree_nbytes,
                w8a8_site_count,
            )

            log.info(
                "lm_w8a8: int8 W8A8 matmuls at %d sites (per-token "
                "activation scales), %.2f GB param tree",
                w8a8_site_count(self.params),
                tree_nbytes(self.params) / 1e9)
        self._init_spec_decode(cfg, weights_dir)
        # roofline attribution (obs/costmodel.py): dense decode costs
        # 2·N(params) FLOPs per token processed; resolved lazily (the
        # committed cost model for the production LM, the same formula
        # over this tree otherwise) and accumulated per dispatch.
        # THREAD-LOCAL: concurrent generate_batch callers (two rooms
        # buffering rounds from separate executor threads) must each
        # read their OWN dispatch's total, and a decode that raises
        # attributes nothing (reset at decode entry) instead of the
        # previous successful dispatch's figure
        self._flops_per_token: Optional[float] = None
        self._decode_flops_tls = threading.local()
        # per-thread invalid-row indices from the LAST decode_ids_batch
        # on this thread (same ownership rationale as the flops TLS):
        # generate_batch reads it to fail exactly the poisoned rows
        self._decode_invalid_tls = threading.local()

    def _token_flops(self) -> float:
        """Analytic FLOPs per token processed (prefill or decode)."""
        if self._flops_per_token is None:
            from cassmantle_tpu.obs import costmodel

            self._flops_per_token = costmodel.flops_per_item(
                "prompt",
                costmodel.lm_signature(
                    self.mcfg, w8a8=lm_w8a8_armed(self.cfg.models)),
                tracer=lambda: 2.0 * costmodel.params_count(self.params),
            ) or 0.0
        return self._flops_per_token

    def _init_spec_decode(self, cfg: FrameworkConfig, weights_dir) -> None:
        """Build the draft source for speculative decoding
        (ops/decode.py). ``self._spec_draft`` is None when off; else a
        static NgramDraft/ModelDraft whose identity is stable for the
        life of the generator (it keys the jit cache). Stats of the
        most recent spec decode land in ``self.last_spec_stats``."""
        from cassmantle_tpu.ops.decode import ModelDraft, NgramDraft

        spec = cfg.spec_decode
        self._spec_draft = None
        self._spec_draft_params = None
        # re-runnable loader for a SEPARATE draft tree (reload_params);
        # the self-draft arm shares self.params and needs no loader
        self._spec_params_loader = None
        self.last_spec_stats = None
        if spec.mode == "off":
            return
        if spec.mode == "ngram":
            self._spec_draft = NgramDraft(ngram=spec.ngram)
            return
        assert spec.mode == "draft_model", \
            f"unknown spec_decode.mode {spec.mode!r}"
        d = spec.draft_model
        assert d is not None, "spec_decode.mode='draft_model' needs a " \
                              "draft_model config"
        assert d.vocab_size == self.mcfg.vocab_size, (
            "draft and target must share a tokenizer/vocab "
            f"({d.vocab_size} vs {self.mcfg.vocab_size}) — speculative "
            "acceptance compares token ids directly")
        if cfg.models.mistral is None and d == cfg.models.gpt2:
            # self-draft degenerate: reuse the target's (possibly
            # quantized) apply fns and params — no second tree
            self._spec_draft = ModelDraft(self._prefill, self._step)
            self._spec_draft_params = self.params
            return
        from cassmantle_tpu.models.weights import convert_gpt2
        from cassmantle_tpu.ops.decode import make_apply_fns

        draft_model = GPT2LM(d)

        def load_draft_params() -> None:
            loaded = maybe_load(
                weights_dir, "gpt2_draft.safetensors",
                lambda t: convert_gpt2(t, d.num_layers, d.hidden_size),
                "gpt2_draft", cast_to=cfg.models.param_dtype)
            self._spec_draft_params = (
                loaded if loaded is not None
                else init_params_cached(
                    draft_model, 6, jnp.zeros((1, 8), dtype=jnp.int32),
                    cache_path=param_cache_path("gpt2_draft", d),
                    cast_to=cfg.models.param_dtype))

        self._spec_params_loader = load_draft_params
        load_draft_params()
        d_prefill, d_step, _ = make_apply_fns(draft_model)
        self._spec_draft = ModelDraft(d_prefill, d_step)

    def reload_params(self) -> None:
        """Device-loss rebuild (serving/device_recovery.py): re-run the
        boot load path (fingerprint-verified reads, int8 transform) and
        republish the tree. The draft source object keeps its identity
        (it keys the jit cache — replacing it would recompile the spec
        graphs); only its PARAMS refresh: the self-draft arm re-shares
        the target tree, a separate draft tree re-loads."""
        shared_draft = self._spec_draft_params is self.params
        self._param_loader()
        if shared_draft:
            self._spec_draft_params = self.params
        elif self._spec_params_loader is not None:
            self._spec_params_loader()

    def _spec_enabled(self, bucket: int, max_new: int) -> bool:
        """Host-side, per bucket group: the spec path engages only for
        greedy decodes (temperature 0 — where acceptance is exact and
        output provably identical), only when the chunk scratch tail
        still fits the model's position table (the last chunk appends up
        to gamma past the budget), and only with the kill switch clear."""
        if self._spec_draft is None:
            return False
        if self.cfg.sampler.text_temperature > 0.0:
            return False
        if os.environ.get("CASSMANTLE_NO_SPEC_DECODE", "").lower() \
                not in ("", "0", "false", "no", "off"):
            return False
        gamma = self.cfg.spec_decode.gamma
        return bucket + max_new + gamma + 1 <= self.mcfg.max_positions

    def _load_int8_checkpoint(self, name: str, weights_dir):
        """Pre-quantized checkpoint (tools/quantize_weights.py): int8
        straight from disk — no fp pass, half the read bytes. Returns
        None (-> normal fp path) when the file is absent, STALE (the fp
        checkpoint is newer — an operator re-fetched weights without
        re-quantizing), or structurally unloadable (e.g. the model
        config changed since quantization)."""
        if not (self._int8_path and os.path.exists(self._int8_path)):
            return None
        fp_path = os.path.join(weights_dir, f"{name}.safetensors")
        if os.path.exists(fp_path) and \
                os.path.getmtime(fp_path) > os.path.getmtime(self._int8_path):
            log.warning(
                "%s is older than %s; re-quantizing from the fp "
                "checkpoint (run quantize-weights to refresh)",
                self._int8_path, fp_path)
            return None
        from cassmantle_tpu.ops.quant import load_quantized

        log.info("%s: loading quantized %s", name, self._int8_path)
        try:
            return jax.tree_util.tree_map(
                jnp.asarray, load_quantized(self._int8_path))
        # lint: ignore[swallowed-error] — load-time degrade: the fp fallback is the documented recovery, logged with the re-quantize instruction; serving correctness is unaffected
        except Exception:
            log.exception(
                "quantized checkpoint %s failed to load (model config "
                "changed since quantization?); falling back to the fp "
                "path", self._int8_path)
            return None

    def save_quantized(self, path: Optional[str] = None) -> str:
        """Persist the (quantized) param tree so later boots load int8
        straight from disk. Requires lm_int8; default path is the
        weights-dir convention the constructor checks."""
        assert self.cfg.models.lm_int8, "construct with lm_int8=True first"
        from cassmantle_tpu.ops.quant import save_quantized

        path = path or self._int8_path
        assert path, "no weights_dir: pass an explicit path"
        save_quantized(self.params, path)
        return path

    # Batch-size buckets: concurrent prompt requests coalesce into one
    # decode whose batch dim pads to the next bucket, so the jitted
    # greedy_decode graph is reused across calls instead of recompiling
    # per batch size (the image pipeline's bucket discipline applied to
    # text; reference issues one hosted LLM call per prompt,
    # backend.py:240-268, and cannot batch at all).
    BATCH_BUCKETS = (1, 2, 4, 8)

    def _bucket_for(self, n_tokens: int, max_new: int, limit: int) -> int:
        m = self.mcfg
        return next(
            (b for b in self.PROMPT_BUCKETS
             if n_tokens <= b and b + max_new <= m.max_positions),
            limit,
        )

    def decode_ids_batch(self, seed_texts: Sequence[str],
                         max_new_tokens: Optional[int] = None,
                         seed: Optional[int] = None):
        """Batched continuation at the token level: N seed texts ->
        one bucketed prefill + cached decode scan PER PROMPT BUCKET;
        returns (tokens (N, max_new), gen_len (N,)).

        Rows group by each prompt's OWN bucket — never the batch's
        longest — because all rows of a (B, P) decode share cache
        positions P+i: a short prompt co-batched into a longer prompt's
        bucket would decode at different position ids than it would
        alone, making round text depend on which requests happened to
        batch with it. Grouping by own bucket keeps batch output
        row-for-row IDENTICAL to single decodes (greedy; sampled rows
        draw per-row independent Gumbel noise) while still coalescing
        the common case — game seeds cluster in the same bucket. Each
        group's batch dim pads to the next BATCH_BUCKETS size with
        1-token dummy rows (decoded then dropped), keeping both shape
        axes static across calls.

        Decode mode comes from the config (text_temperature=0 -> greedy,
        the reference behavior; >0 -> top-k sampling keyed on ``seed``,
        auto-advanced per call so sampled stories vary round to round)."""
        assert len(seed_texts) > 0, "decode_ids_batch needs >=1 prompt"
        m = self.mcfg
        max_new = max_new_tokens or self.cfg.sampler.max_new_tokens
        limit = m.max_positions - max_new - 1
        rows = []
        for text in seed_texts:
            toks = self.tokenizer.encode(text)
            rows.append(toks[-limit:] if len(toks) > limit else toks)
        if seed is None:
            seed = self._decode_calls
            self._decode_calls += 1
        groups: dict = {}
        for i, toks in enumerate(rows):
            groups.setdefault(
                self._bucket_for(len(toks), max_new, limit), []
            ).append(i)
        out_tokens = np.zeros((len(rows), max_new), dtype=np.int32)
        out_len = np.zeros((len(rows),), dtype=np.int32)
        spec_stats = []
        dispatch_flops = 0.0
        self._decode_flops_tls.value = 0.0  # failed decodes attr nothing
        self._decode_invalid_tls.value = ()
        bad_members: set = set()
        for bucket, idxs in groups.items():
            n = len(idxs)
            fault_point("device.lost", peer="prompt")
            n_pad = next((b for b in self.BATCH_BUCKETS if n <= b), n)
            # roofline attribution: the dispatched shapes are fixed —
            # n_pad rows prefill `bucket` tokens then run max_new decode
            # steps regardless of eos (masked, not skipped), so the
            # device work is exactly these tokens (spec decode bounds
            # the same budget; greedy-equivalent estimate)
            dispatch_flops += self._token_flops() * n_pad * (
                bucket + max_new)
            # pad id normalized into the MODEL's vocab: the byte-fallback
            # tokenizer's pad (258) can exceed a small model vocab, and an
            # out-of-range id NaN-fills flax Embed's take — the NaN then
            # leaks through prefill into every decoded token
            ids = np.full((n_pad, bucket),
                          self.tokenizer.pad_id % m.vocab_size,
                          dtype=np.int32)
            lens = np.ones((n_pad,), dtype=np.int32)  # dummies: 1 pad token
            for row, src in enumerate(idxs):
                toks = rows[src]
                # lint: ignore[host-sync] — toks is a host token list
                ids[row, : len(toks)] = np.asarray(toks) % m.vocab_size
                lens[row] = max(1, len(toks))
            # an out-of-vocab eos (byte-fallback tokenizer vs a smaller
            # model vocab) can never be emitted: pass vocab_size as an
            # unreachable sentinel so early-stop is cleanly disabled — a
            # modulo here would ALIAS a real token as a phantom
            # terminator and silently truncate generations
            eos = (self.tokenizer.eos_id
                   if self.tokenizer.eos_id < m.vocab_size
                   else m.vocab_size)
            if self._spec_enabled(bucket, max_new):
                from cassmantle_tpu.ops.decode import speculative_decode

                with self._dispatch_lock, \
                        block_timer("decode.verify_s") as sink:
                    # draft + verify fuse into one device computation;
                    # the in-jit spec_draft/spec_verify TraceAnnotations
                    # split the two on the profiler path
                    tokens, gen_len, stats = speculative_decode(
                        (self._prefill, self._step, self._chunk),
                        self.params,
                        jnp.asarray(ids),
                        jnp.asarray(lens),
                        max_new,
                        eos,
                        self.cfg.spec_decode.gamma,
                        self._spec_draft,
                        self._spec_draft_params,
                        # dummy pad rows must not throttle the lockstep
                        # accept-min; their rows are dropped below anyway
                        jnp.asarray(np.arange(n_pad) < n),
                    )
                    sink.append(tokens)  # device-synchronized span
                spec_stats.append(stats)
            else:
                with self._dispatch_lock:
                    tokens, gen_len = greedy_decode(
                        (self._prefill, self._step),
                        self.params,
                        jnp.asarray(ids),
                        jnp.asarray(lens),
                        jax.random.PRNGKey(seed),
                        max_new,
                        eos,
                        self.cfg.sampler.text_temperature,
                        self.cfg.sampler.text_top_k,
                    )
            # one sync per DISPATCHED bucket group (not per row): each
            # group is a separate device computation whose result must
            # land before its rows scatter into the output
            toks_host = integrity.poison(
                # lint: ignore[host-sync] — per-dispatch sync, not per-item
                np.asarray(tokens[:n]), peer="prompt")
            if not integrity.integrity_disabled():
                # token-range validity on the just-transferred array —
                # no extra sync. Tokens are ints, so finiteness can't
                # carry the verdict here; range IS the sentinel: a dead
                # runtime hands back garbage buffers, and the chaos
                # poison fills -1 — both land outside [0, vocab).
                ok = ((toks_host >= 0)
                      & (toks_host < m.vocab_size)).all(axis=1)
                bad_members.update(
                    idxs[row] for row in np.nonzero(~ok)[0])
            out_tokens[idxs] = toks_host
            # lint: ignore[host-sync] — per-dispatch sync, not per-item
            out_len[idxs] = np.asarray(gen_len[:n])
            if lm_w8a8_armed(self.cfg.models):
                # one int8-kernel decode dispatch per bucket group (the
                # gpt2_w8a8 bench A/B's proof the path engaged)
                metrics.inc("pipeline.w8a8_dispatches")
        self._record_spec_stats(spec_stats)
        self._decode_flops_tls.value = dispatch_flops
        self._decode_invalid_tls.value = tuple(sorted(bad_members))
        return jnp.asarray(out_tokens), jnp.asarray(out_len)

    def _record_spec_stats(self, spec_stats) -> None:
        """ONE host transfer for the whole decode batch's spec counters
        (after the per-group dispatch loop — never per chunk):
        ``decode.spec_chunks`` counts verify forwards and
        ``decode.spec_accept_rate`` gauges accepted/drafted, the number
        that says whether the draft source is paying for itself."""
        if not spec_stats:
            return
        # stack the per-group device stats, then ONE transfer + sum
        chunks, drafted, accepted = np.asarray(
            jnp.stack(list(spec_stats))).sum(axis=0).tolist()
        self.last_spec_stats = {
            "chunks": chunks, "drafted": drafted, "accepted": accepted,
            "accept_rate": (accepted / drafted) if drafted else 0.0,
        }
        metrics.inc("decode.spec_chunks", chunks)
        if drafted:
            metrics.gauge("decode.spec_accept_rate", accepted / drafted)

    def decode_ids(self, seed_text: str,
                   max_new_tokens: Optional[int] = None,
                   seed: Optional[int] = None):
        """Single-prompt continuation: the B=1 case of
        :meth:`decode_ids_batch` (one code path, so the benchmark and
        the batched serving queue measure the same computation).
        Returns (tokens (1, max_new), gen_len (1,))."""
        return self.decode_ids_batch([seed_text], max_new_tokens, seed)

    def generate_batch(self, seed_texts: Sequence[str],
                       max_new_tokens: Optional[int] = None) -> List:
        """Batched greedy continuation: one device dispatch for N texts,
        each trimmed to its first two sentences (reference
        backend.py:253-265).

        Rows the integrity sentinel rejected come back as
        :class:`~cassmantle_tpu.serving.integrity.OutputInvalid`
        INSTANCES in their slots (not raised): the prompt queue's
        per-member distribution fails exactly those requests while the
        healthy rows of the same dispatch still serve."""
        # flops_est is a callable: the bucket grouping (and so the
        # dispatched token count) is only known after decode_ids_batch
        # runs; block_timer evaluates it at exit, on THIS thread (the
        # thread-local is written by the decode_ids_batch call below)
        with block_timer("pipeline.prompt_s",
                         flops_est=lambda: getattr(
                             self._decode_flops_tls, "value", 0.0),
                         pipeline="prompt") as sink:
            out_tokens, gen_len = self.decode_ids_batch(
                seed_texts, max_new_tokens)
            sink.append(out_tokens)
        # ONE device->host transfer for the whole batch: the per-row
        # int(gen_len[i]) / np.asarray(out_tokens[i]) this loop used to
        # do was a sync per text (the host-sync lint's serialization
        # hazard, tools/check_concurrency.py)
        out_tokens = np.asarray(out_tokens)
        lengths = np.asarray(gen_len).tolist()
        bad = frozenset(
            getattr(self._decode_invalid_tls, "value", ()) or ())
        if bad:
            integrity.note_invalid("prompt", "decode", sorted(bad))
        texts = []
        for i in range(len(seed_texts)):
            if i in bad:
                # never decode a rejected row — garbage/poisoned ids
                # must not reach the tokenizer, let alone a player
                texts.append(integrity.OutputInvalid(
                    "prompt", "decode", [i]))
                continue
            texts.append(two_sentences(
                self.tokenizer.decode(out_tokens[i, : lengths[i]].tolist())))
        return texts

    def generate(self, seed_text: str, max_new_tokens: Optional[int] = None
                 ) -> str:
        """Greedy continuation of ``seed_text`` (the reference decodes
        32-96 tokens then keeps the first two sentences,
        backend.py:253-265). Raises
        :class:`~cassmantle_tpu.serving.integrity.OutputInvalid` when
        the integrity sentinel rejects the row (retriable)."""
        out = self.generate_batch([seed_text], max_new_tokens)[0]
        if isinstance(out, Exception):
            raise out
        return out


def sanitize_text(text: str) -> str:
    """Strip non-printable characters from generated text."""
    return "".join(c for c in text if c.isprintable() or c == " ").strip()


def two_sentences(text: str) -> str:
    """Trim generated text to its first two sentences (reference
    backend.py:265 keeps ``'.'.join(parts[:2]) + '.'``)."""
    parts = [p.strip() for p in text.split(".")]
    keep = [p for p in parts[:2] if p]
    if not keep:
        return text.strip() or "An empty page waited."
    return ". ".join(keep) + "."


class TPUContentBackend(ContentBackend):
    """Production ContentBackend: GPT-2 episode text + diffusion image.

    Heavy device calls run in a thread-pool executor so the asyncio game
    loop (clock ticks, WS pushes) stays responsive while the DDIM scan is
    on device — the async-over-sync bridge (SURVEY.md §7 hard part (d)).
    """

    def __init__(
        self,
        cfg: FrameworkConfig,
        weights_dir: Optional[str] = None,
        styles: Optional[List[str]] = None,
        rng: Optional[random.Random] = None,
        mesh=None,
        t2i=None,
    ) -> None:
        from cassmantle_tpu.server.assets import load_styles

        self.cfg = cfg
        if t2i is not None:
            # caller-owned pipeline (e.g. one already compiled for this
            # mesh); skips a duplicate param init + jit compile
            self.t2i = t2i
        elif cfg.models.clip_text_2 is not None:
            # SDXL config (both text towers): serve rounds at SDXL-1024,
            # the reference's actual image model (backend.py:24).
            from cassmantle_tpu.serving.sdxl import SDXLPipeline

            self.t2i = SDXLPipeline(cfg, weights_dir, mesh=mesh)
        else:
            self.t2i = Text2ImagePipeline(cfg, weights_dir, mesh=mesh)
        self.prompt_gen = PromptGenerator(cfg, weights_dir)
        self.styles = styles or load_styles()
        self.rng = rng or random.Random(cfg.seed)
        self._round = 0

    def _style_prompt(self, prompt: str) -> str:
        style = self.rng.choice(self.styles)
        return f"A {style.lower()} style piece depicting: {prompt}"

    def generate_sync(self, seed: str, is_seed: bool,
                      text: Optional[str] = None) -> RoundContent:
        """``text`` lets a caller inject an already-decoded continuation
        (the InferenceService prompt queue batches decodes across
        concurrent round generations); None decodes here, single."""
        from cassmantle_tpu.engine.content import template_text
        from cassmantle_tpu.utils.text import is_wordlike, tokenize_words

        if text is None:
            text = self.prompt_gen.generate(seed)
        text = sanitize_text(text)
        wordy = sum(is_wordlike(t) for t in tokenize_words(text))
        if wordy < self.cfg.game.num_masked + 1:
            # degenerate LM output (e.g. random weights): keep the round
            # playable with deterministic template text.
            log.warning("degenerate generated text; using template fallback")
            metrics.inc("pipeline.text_fallbacks")
            text = template_text(seed)
        self._round += 1
        images = self.t2i.generate(
            [self._style_prompt(text)], seed=self._round
        )
        return RoundContent(prompt_text=text, image=images[0])

    async def generate(self, seed: str, is_seed: bool,
                       text: Optional[str] = None) -> RoundContent:
        loop = asyncio.get_running_loop()
        # run_in_executor does not carry contextvars: copy the context
        # so the round-generation trace follows onto the worker thread
        # (the pipeline's block_timer stage spans land in it)
        ctx = contextvars.copy_context()
        return await loop.run_in_executor(
            None, ctx.run, self.generate_sync, seed, is_seed, text
        )
