"""Drill scorer: hash similarity behind a REAL BatchingQueue.

The fake content backend's instant hash scorer can never be
overloaded, so a CPU drill against it would exercise nothing. This
module puts the same deterministic similarity behind a real
:class:`~cassmantle_tpu.serving.queue.BatchingQueue` whose handler
holds the dispatch thread a fixed ``ServingConfig.fake_score_batch_ms``
per batch — a device-cost stand-in with a known capacity
(``max(score_batch_sizes) / batch_s`` items/sec) that lets
``bench.py overload_drill`` ramp synthetic load past capacity through
the real fabric and the REAL admission / priority / computed-
Retry-After machinery (ISSUE 13).

Deliberately jax-free: drill workers are --fake spawns that must never
pay (or hang on) an accelerator backend import — the same contract as
the rooms_load harness (bench.py).
"""

from __future__ import annotations

import time

import numpy as np

from cassmantle_tpu.serving.overload import make_admission
from cassmantle_tpu.serving.queue import (
    BatchingQueue,
    DeadlineExceeded,
    OverloadShed,
    QueueFull,
)
from cassmantle_tpu.utils.logging import get_logger

log = get_logger("fake_scorer")


class FakeQueuedScorer:
    """Wired by ``server.app._serving_components`` when
    ``ServingConfig.fake_score_batch_ms`` > 0 on a --fake worker."""

    def __init__(self, cfg, supervisor=None) -> None:
        from cassmantle_tpu.engine.content import hash_embed

        batch_s = cfg.serving.fake_score_batch_ms / 1000.0
        max_batch = max(cfg.serving.score_batch_sizes)

        def handler(pairs):
            time.sleep(batch_s)      # the simulated device dispatch
            guesses = hash_embed([g for g, _ in pairs])
            answers = hash_embed([a for _, a in pairs])
            return np.sum(guesses * answers, axis=-1)

        self.queue: BatchingQueue = BatchingQueue(
            handler=handler,
            max_batch=max_batch,
            max_delay_ms=cfg.serving.max_queue_delay_ms,
            max_pending=cfg.serving.max_pending,
            name="score",
            default_deadline_s=cfg.serving.submit_deadline_s,
            hang_timeout_s=cfg.serving.dispatch_hang_s,
            supervisor=supervisor,
            degraded_max_pending=cfg.serving.degraded_max_pending,
            admission=make_admission("score", cfg),
            background_every=cfg.serving.background_every_batches,
        )

    def _retry_after_s(self) -> float:
        adm = self.queue.admission
        return (adm.retry_after_s(self.queue.depth())
                if adm is not None else 1.0)

    async def similarity(self, pairs) -> np.ndarray:
        import asyncio

        pairs = list(pairs)
        try:
            results = await asyncio.gather(
                *(self.queue.submit(p) for p in pairs))
        except OverloadShed:
            raise                    # HTTP answers 503 + Retry-After
        except DeadlineExceeded as exc:
            # a queued item that expired anyway IS overload: convert so
            # the player sees a computed Retry-After, not a 500
            raise OverloadShed("score", reason="deadline",
                               retry_after_s=self._retry_after_s()
                               ) from exc
        except QueueFull:
            return np.zeros((len(pairs),), dtype=np.float32)
        return np.asarray(results, dtype=np.float32)

    async def stop(self) -> None:
        await self.queue.stop()
