"""Output-integrity sentinels: no invalid output reaches a player.

The data-plane counterpart of the breaker/chaos control plane (ISSUE
17 rung 1). Every serving dispatch gets a per-batch-member validity
verdict, computed where parity constraints allow: the scorer encode
folds :func:`finite_verdict` into its own jit (no parity bar there);
the staged denoise/retirement paths run it as a SEPARATE tiny jitted
dispatch on the existing graph's output, because adding a consumer
inside the image-producing jits changes XLA fusion and breaks the
staged-vs-monolithic bit-parity bar (tests/test_stages.py); the
monolithic t2i/SDXL paths and the prompt decoder judge host-side on
the batch they already transferred (degenerate uint8 frames / token
range) — zero extra device work on those hot paths. At uint8
conversion the host-side detector (:func:`degenerate_frames`) catches
the all-black / stuck-constant frames a finite-but-dead device
produces.

An invalid member NEVER reaches the image cache, a round promotion, or
a player: the owning request fails :class:`OutputInvalid` (retriable —
round generation falls back down the existing reserve/replay ladder),
``pipeline.output_invalid{pipeline=,stage=}`` counts it, and the flight
recorder keeps the forensic trail. Per-member verdicts mean one
poisoned batch row fails one request, not the batch.

Kill switch: ``CASSMANTLE_NO_INTEGRITY_CHECKS`` (read per call) makes
every enforcement a no-op. Verdicts may still compute (they never
touch the image-producing graphs), so flipping the switch is a
bit-exact revert with zero recompiles.

Chaos: :func:`poison` is the ``device.poison`` fault point — it
corrupts one batch member of a dispatch result (NaN for float dtypes,
zeros for uint8) at the caller's representation, so the detectors
downstream must genuinely catch the bad data; detection never keys off
the injection site.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from cassmantle_tpu.chaos import ChaosInjected, fault_point
from cassmantle_tpu.obs.recorder import flight_recorder
from cassmantle_tpu.utils.logging import get_logger, metrics

log = get_logger("serving.integrity")


class OutputInvalid(RuntimeError):
    """A dispatch produced output the integrity sentinel rejected.

    Retriable: the device may be healthy again (or the poison transient)
    on the next attempt, so callers treat this like DispatchTimeout —
    retry/fallback ladders apply, breakers record the failure.
    """

    retriable = True

    def __init__(self, pipeline: str, stage: str,
                 members: Sequence[int] = ()):
        self.pipeline = pipeline
        self.stage = stage
        # lint: ignore[host-sync] — members are host-side np indices
        self.members = tuple(int(m) for m in members)
        detail = f" members={list(self.members)}" if self.members else ""
        super().__init__(
            f"invalid output from {pipeline}/{stage}{detail}")


def integrity_disabled() -> bool:
    """Kill switch, read per call (flip at runtime, no restart)."""
    return os.environ.get(
        "CASSMANTLE_NO_INTEGRITY_CHECKS", "").lower() \
        not in ("", "0", "false", "no", "off")


# -- device-side verdict -----------------------------------------------------

def finite_verdict(x: jnp.ndarray) -> jnp.ndarray:
    """Per-batch-member all-finite verdict. Fold it into a jit ONLY
    where no bit-parity bar constrains the graph (the scorer encode);
    paths under the staged-vs-monolithic parity bar dispatch it as its
    own tiny jit on the producing graph's output instead — an extra
    consumer inside those graphs changes XLA fusion and the rounding
    of the images themselves.

    ``(B, ...) -> (B,) bool``; integer outputs (token ids) are finite
    by construction so the verdict is constant-true for them (range
    checks are the caller's job — see PromptGenerator).
    """
    if not jnp.issubdtype(x.dtype, jnp.inexact):
        return jnp.ones(x.shape[:1] or (1,), dtype=bool)
    if x.ndim <= 1:
        return jnp.isfinite(x)
    axes = tuple(range(1, x.ndim))
    return jnp.isfinite(x).all(axis=axes)


# -- host-side detectors -----------------------------------------------------

def degenerate_frames(u8: np.ndarray) -> np.ndarray:
    """Constant-frame detector on a decoded uint8 batch ``(B, H, W, C)``
    → ``(B,)`` bool, True marking a degenerate (all-black / stuck)
    member. A frame every one of whose pixels is the same value is
    never a real generation — it is the signature of a dead VAE or a
    zeroed DMA buffer."""
    arr = np.asarray(u8)
    if arr.ndim <= 1 or arr.shape[0] == 0:
        return np.zeros(arr.shape[:1], dtype=bool)
    flat = arr.reshape(arr.shape[0], -1)
    return flat.max(axis=1) == flat.min(axis=1)


def invalid_members(verdict, *, images: Optional[np.ndarray] = None,
                    n: Optional[int] = None) -> np.ndarray:
    """Indices of invalid batch members: device verdict rows that are
    False, unioned with degenerate ``images`` frames when given. ``n``
    trims bucket-padding rows before judging. Returns an empty array
    when the kill switch is on."""
    if integrity_disabled():
        return np.empty(0, dtype=np.int64)
    ok = np.asarray(verdict).astype(bool).reshape(-1)
    if n is not None:
        ok = ok[:n]
    bad = ~ok
    if images is not None:
        deg = degenerate_frames(
            images if n is None else np.asarray(images)[:n])
        m = min(len(bad), len(deg))
        bad = bad[:m] | deg[:m]
    return np.nonzero(bad)[0]


def note_invalid(pipeline: str, stage: str,
                 members: Sequence[int]) -> None:
    """Count + flight-record invalid members (callers that handle the
    failure per-member instead of raising use this directly)."""
    # lint: ignore[host-sync] — members are host-side np indices
    members = [int(m) for m in members]
    metrics.inc("pipeline.output_invalid", float(len(members)),
                labels={"pipeline": pipeline, "stage": stage})
    flight_recorder.record("integrity.invalid", pipeline=pipeline,
                           stage=stage, members=members)
    log.warning("integrity: invalid output from %s/%s members=%s",
                pipeline, stage, members)


def enforce(verdict, *, pipeline: str, stage: str,
            images: Optional[np.ndarray] = None,
            n: Optional[int] = None) -> None:
    """Raise :class:`OutputInvalid` (after counting) when any batch
    member is invalid; no-op under the kill switch."""
    members = invalid_members(verdict, images=images, n=n)
    if members.size == 0:
        return
    note_invalid(pipeline, stage, members.tolist())
    raise OutputInvalid(pipeline, stage, members.tolist())


# -- chaos: the device.poison fault point ------------------------------------

def poison(arr, peer: str, member: int = 0):
    """``device.poison`` chaos hook: when the plan says so, corrupt one
    batch member of ``arr`` — NaN for floats, -1 for signed ints,
    zeros for uint8 — and return the corrupted array; otherwise ``arr``
    untouched. Host batches (numpy) get row ``member`` corrupted;
    device arrays (a single admitted slot row) are corrupted whole.

    Signed-integer fills are -1 (out of any vocab range) so the token
    range check downstream genuinely catches the poison; unsigned
    (uint8 frames) fill 0 so the degenerate-frame detector does.
    """
    try:
        fault_point("device.poison", peer=peer)
    except ChaosInjected:
        if isinstance(arr, np.ndarray):
            if arr.ndim == 0 or arr.shape[0] == 0:
                return arr
            arr = np.array(arr, copy=True)
            if np.issubdtype(arr.dtype, np.floating):
                arr[member % arr.shape[0]] = np.nan
            elif np.issubdtype(arr.dtype, np.signedinteger):
                arr[member % arr.shape[0]] = -1
            else:
                arr[member % arr.shape[0]] = 0
        else:
            if jnp.issubdtype(arr.dtype, jnp.inexact):
                fill = jnp.nan
            elif jnp.issubdtype(arr.dtype, jnp.signedinteger):
                fill = -1
            else:
                fill = 0
            arr = jnp.full_like(arr, fill)
        log.warning("chaos: device.poison corrupted %s output "
                    "(member %d)", peer, member)
    return arr
