"""Stage-disaggregated image serving: step-level continuous batching.

The monolithic image path (serving/pipeline.py, serving/sdxl.py) runs
CLIP encode → the full denoise scan → VAE decode as ONE device dispatch
under the pipeline dispatch lock — a request arriving one step after a
dispatch starts waits an entire image's latency for a slot. This module
splits the path into a **stage graph** (the SwiftDiffusion decoupled-
stages / LegoDiffusion micro-serving argument, PAPERS.md; ROADMAP open
item 1):

- **encode** — CLIP (or SDXL dual-tower) conditioning, its own
  :class:`~cassmantle_tpu.serving.queue.BatchingQueue` + bucket ladder
  and a dedicated dispatch worker;
- **denoise** — a persistent jitted STEP function over a fixed-capacity
  slot tensor (latents, per-slot step index, per-slot conditioning —
  no dynamic shapes; live slots gather into a power-of-two width
  bucket per step, so each bucket compiles exactly once and per-step
  compute tracks occupancy). A new request's encoded conditioning is
  admitted into a free slot at the next step boundary; a finished slot
  retires to the decode stage immediately; an expired deadline frees
  its slot at the next boundary instead of finishing the image;
- **decode** — VAE decode + uint8 postprocess, again a BatchingQueue +
  bucket ladder + dedicated dispatch worker (the blur pyramid stays in
  the game layer's per-fetch cache, ops/blur.py).

Parity bar: for a solo request the staged output is **bit-identical**
to the monolithic path (same seed → same image). The slot stepper
re-uses the monolithic schedule arrays and step arithmetic verbatim
(ops/samplers.py::make_slot_sampler, ops/ddim.py::make_slot_denoiser),
and every per-row computation in the UNet/CLIP/VAE is independent of
its batch neighbors — so admission at a step boundary cannot perturb
another slot (tests/test_stages.py pins both properties).

Control state (which slot is at which step, which are free) lives
entirely on the HOST as plain numpy mirrors maintained by the single
denoise thread: the step loop never reads device values back, so there
is **no host sync inside the step loop** (the static-analysis shape
pinned in tests/test_check_concurrency.py). The only device→host
transfers are the decode stage's one ``np.asarray`` per decoded batch.

``CASSMANTLE_NO_STAGED_SERVING=1`` is the runtime kill switch
(docs/DEPLOY.md §6): pipelines fall back to the monolithic dispatch.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import os
import queue as _thread_queue
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from cassmantle_tpu.chaos import fault_point
from cassmantle_tpu.obs.recorder import flight_recorder
from cassmantle_tpu.obs.trace import current_ctx, tracer
from cassmantle_tpu.ops.ddim import initial_latents, make_slot_denoiser
from cassmantle_tpu.ops.samplers import make_slot_sampler
from cassmantle_tpu.serving import integrity
from cassmantle_tpu.serving.integrity import OutputInvalid, finite_verdict
from cassmantle_tpu.serving.queue import (
    BatchingQueue,
    DeadlineExceeded,
    DispatchTimeout,
    QueueStopped,
    _DispatchWorker,
)
from cassmantle_tpu.utils.locks import OrderedLock
from cassmantle_tpu.utils.logging import get_logger, metrics

log = get_logger("stages")

#: sampler kinds whose per-step arithmetic the slot stepper replays
#: bit-exactly (deterministic, coefficient-gatherable — see
#: ops/samplers.py::make_slot_sampler)
STAGEABLE_KINDS = ("ddim", "euler", "dpmpp_2m")


def staged_serving_disabled() -> bool:
    """Runtime kill switch (same env parse as the other serving
    switches): CASSMANTLE_NO_STAGED_SERVING=1 routes every generate
    through the proven monolithic dispatch."""
    return os.environ.get("CASSMANTLE_NO_STAGED_SERVING", "").lower() \
        not in ("", "0", "false", "no", "off")


class _Unit:
    """One latent row flowing encode → denoise → decode. ``done`` is
    resolved by the denoise thread with the finished latent row (or the
    preemption error); everything else is bookkeeping."""

    __slots__ = ("ids", "uncond_ids", "lat", "aux", "cond", "done",
                 "deadline", "ctx", "slot", "admit_step",
                 "t_ready", "t_admit", "wall_ready")

    def __init__(self, ids, uncond_ids, lat, aux, deadline) -> None:
        self.ids = ids
        self.uncond_ids = uncond_ids
        self.lat = lat
        self.aux = aux
        self.cond: Optional[dict] = None
        self.done: concurrent.futures.Future = concurrent.futures.Future()
        self.deadline = deadline
        self.ctx = None
        self.slot: Optional[int] = None
        self.admit_step: Optional[int] = None
        self.t_ready = 0.0
        self.t_admit = 0.0
        self.wall_ready = 0.0

    def remaining(self) -> Optional[float]:
        if self.deadline is None:
            return None
        return max(0.01, self.deadline - time.monotonic())


class StagedImageServer:
    """The in-process stage scheduler one image pipeline owns when
    ``ServingConfig.staged_serving`` is on.

    The pipeline supplies its model-specific pieces as callables:

    - ``encode_fn(params, ids, uncond_ids) -> dict`` of conditioning
      arrays, each ``(B, ...)`` (SD1.5: ``ctx``/``uctx``; SDXL adds
      ``add``/``uadd``) — jitted here, one compile per encode bucket;
    - ``unet_apply`` + ``guidance_scale`` — wrapped by
      :func:`make_slot_denoiser` into the per-slot CFG step;
    - ``decode_fn(params, lat) -> uint8 images`` — jitted here, one
      compile per decode bucket;
    - ``tokenize(prompts) -> np.int32 ids`` — the pipeline's own
      tokenizer path, so staged and monolithic tokenize identically.

    ``generate`` keeps the monolithic call shape (sync, returns the
    stacked uint8 batch) so :class:`TPUContentBackend` and the bench
    drive either path unchanged.
    """

    def __init__(
        self,
        cfg,
        params,
        *,
        encode_fn: Callable,
        decode_fn: Callable,
        unet_apply: Callable,
        tokenize: Callable[[Sequence[str]], np.ndarray],
        vae_scale: int,
        supervisor=None,
    ) -> None:
        self.cfg = cfg
        self._params = params
        self._tokenize = tokenize
        self._vae_scale = vae_scale
        self._negative = cfg.sampler.negative_prompt
        self._supervisor = supervisor
        s = cfg.sampler
        assert s.kind in STAGEABLE_KINDS and not s.deepcache \
            and s.eta == 0.0, (
                "staged serving supports deterministic ddim/euler/dpmpp_2m "
                "without deepcache; the pipeline should have fallen back "
                f"to monolithic for {s.kind!r}")
        self.capacity = int(cfg.serving.denoise_slots)
        assert self.capacity >= 1
        # step-width bucket ladder: powers of two up to capacity (plus
        # capacity itself). The step gathers live slots into the
        # smallest bucket ≥ occupancy, so per-step UNet compute tracks
        # load instead of always paying the full slot width; each
        # bucket compiles once (tests pin the cache size).
        self._step_widths = []
        w = 1
        while w < self.capacity:
            self._step_widths.append(w)
            w *= 2
        self._step_widths.append(self.capacity)
        # few-step consistency serving rides the slot stepper through
        # its own make_slot_sampler variant (the deterministic re-noise
        # ladder folds each slot's OWN timestep, so mid-flight
        # admission replays exactly); with the kill switch set the
        # effective step count reverts to the teacher schedule, the
        # same bit-exact revert the monolithic path takes
        from cassmantle_tpu.ops.samplers import consistency_disabled
        from cassmantle_tpu.serving.pipeline import (
            effective_sampler_steps,
        )

        slot_kind = ("consistency"
                     if s.consistency and not consistency_disabled()
                     else s.kind)
        self._prepare, self._slot_step, self.num_steps = make_slot_sampler(
            slot_kind, effective_sampler_steps(s), eta=s.eta,
            teacher_steps=s.consistency_teacher_steps)
        self._denoise = make_slot_denoiser(unet_apply, s.guidance_scale)
        # jit surfaces — each compiles once per shape bucket and is the
        # ONLY dispatcher of its computation (one thread each), so no
        # compiled graph ever has two concurrent executions (the CPU-
        # backend deadlock the monolithic dispatch locks exist for)
        self._encode = jax.jit(encode_fn)
        self._decode = jax.jit(decode_fn)
        self._step = jax.jit(self._step_impl)
        self._admit = jax.jit(self._admit_impl)
        self._take = jax.jit(self._take_impl)
        self._fin_check = jax.jit(finite_verdict)
        self._fin_check_dec = jax.jit(finite_verdict)
        self._init = jax.jit(self._init_impl, static_argnums=0)
        # scheduler lifecycle only — never held across a device dispatch
        # or a cross-stage handoff (docs/STATIC_ANALYSIS.md rank 14)
        self._lock = OrderedLock("stage.scheduler", rank=14)
        self._started = False
        self._stop_evt = threading.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._loop_thread: Optional[threading.Thread] = None
        self._denoise_thread: Optional[threading.Thread] = None
        self._encode_q: Optional[BatchingQueue] = None
        self._decode_q: Optional[BatchingQueue] = None
        self._enc_buckets = tuple(cfg.serving.stage_encode_batch_sizes)
        self._dec_buckets = tuple(cfg.serving.stage_decode_batch_sizes)
        # denoise-thread-owned state: slot device arrays + host mirrors
        self._admit_q: _thread_queue.Queue = _thread_queue.Queue()
        self._pend: deque = deque()
        # in-flight generate() futures; stop() waits for them to unwind
        # before killing the loop their coroutines resume on (set ops
        # are GIL-atomic — no lock needed)
        self._inflight: set = set()
        self._lat = None
        self._aux = None
        self._cond: Optional[Dict[str, jax.Array]] = None
        # per-slot finiteness verdict (integrity rung 2): a SEPARATE
        # tiny jitted reduction over the slot tensor, dispatched after
        # each step and read back lazily. Kept OUT of the step jit on
        # purpose — an extra consumer inside that graph changes XLA
        # fusion decisions and breaks the staged-vs-monolithic
        # bit-parity bar (tests/test_stages.py).
        self._finite = None
        self._fin_probes: deque = deque()
        self._steps = np.zeros((self.capacity,), dtype=np.int32)
        self._alive = np.zeros((self.capacity,), dtype=bool)
        self._slots: List[Optional[_Unit]] = [None] * self.capacity
        self._free = list(range(self.capacity - 1, -1, -1))
        self._active_n = 0
        self._probe = None  # (wall, array) wedge-watchdog probe window
        # single-writer (denoise thread) counters; the bench derives
        # mean slot occupancy as slot_steps / (steps * capacity)
        self.stats = {"steps": 0, "slot_steps": 0, "admissions": 0,
                      "retirements": 0, "preemptions": 0,
                      "quarantines": 0}
        self._on_step = None  # test seam: called once per loop iteration
        # roofline attribution: per-image denoise FLOPs, traced on a
        # background thread kicked off at the first retirement (needs
        # the cond shapes to exist; must never stall the step loop)
        self._flops_img = None
        self._flops_trace_started = False

    # -- jitted pieces -----------------------------------------------------

    def _init_impl(self, batch: int, rng):
        """Per-request solver-space entry state — the same
        ``initial_latents`` call (same key, same shape) the monolithic
        jit traces, then the sampler's prepare (identity for DDIM/DPM++,
        the sigma-max scale for Euler)."""
        size = self.cfg.sampler.image_size
        return self._prepare(initial_latents(rng, batch, size,
                                             self._vae_scale))

    def _step_impl(self, params, lat, aux, cond, idx, slots):
        """One denoise step for the OCCUPIED slots only: ``slots`` is a
        width-``w`` int32 vector of slot indices (the smallest width
        bucket ≥ occupancy, padded by REPEATING the first live slot —
        duplicate rows compute bit-identical values, so the duplicate
        scatter writes are idempotent). Gather → step → scatter keeps
        the slot tensor fixed-shape while the UNet batch tracks
        occupancy: one compile per width bucket, never per admission,
        and a solo request pays the same per-step compute as the
        monolithic scan instead of a capacity-wide batch. Per-slot
        timesteps and schedule coefficients gather from ``idx``; rows
        are computation-independent, so neighbors cannot perturb each
        other."""
        lat_g = lat[slots]
        aux_g = aux[slots]
        cond_g = {k: v[slots] for k, v in cond.items()}
        idx_g = idx[slots]

        def dn(x, t):
            return self._denoise(params["unet"], x, t,
                                 cond_g["ctx"], cond_g["uctx"],
                                 cond_g.get("add"), cond_g.get("uadd"))

        new_lat, new_aux = self._slot_step(dn, lat_g, aux_g, idx_g)
        return lat.at[slots].set(new_lat), aux.at[slots].set(new_aux)

    @staticmethod
    def _admit_impl(lat, aux, cond, slot, lat_row, aux_row, cond_rows):
        """Write one request's rows into slot ``slot``. ``slot`` is a
        TRACED scalar, so admission into any slot reuses one compiled
        graph — no recompiles at admission/retirement. The quarantine
        scrub reuses this same graph with zero rows."""

        def put(dst, row):
            return jax.lax.dynamic_update_slice(
                dst, row, (slot,) + (0,) * (row.ndim - 1))

        return (put(lat, lat_row), put(aux, aux_row),
                {k: put(cond[k], cond_rows[k]) for k in cond})

    @staticmethod
    def _take_impl(lat, slot):
        return jax.lax.dynamic_slice_in_dim(lat, slot, 1, axis=0)

    # -- lifecycle ---------------------------------------------------------

    def _ensure_started(self) -> None:
        with self._lock:
            if self._started:
                return
            self._stop_evt.clear()
            self._loop = asyncio.new_event_loop()
            self._loop_thread = threading.Thread(
                target=self._loop.run_forever, daemon=True,
                name="cassmantle-stage-loop")
            self._loop_thread.start()
            self._denoise_thread = threading.Thread(
                target=self._denoise_loop, daemon=True,
                name="cassmantle-stage-denoise")
            self._denoise_thread.start()
            self._started = True

    def _ensure_queues(self) -> None:
        """Built lazily ON the stage event loop (single-threaded there,
        so no lock needed): each stage queue gets its OWN dispatch
        worker — encode/decode batches must not serialize behind the
        process-global worker's score/prompt dispatches."""
        if self._encode_q is not None:
            return
        serving = self.cfg.serving
        sup = self._supervisor
        # default_deadline_s stays None: the monolithic image path has
        # no deadline, and a cold-cache compile can take minutes — the
        # dispatch watchdog (hang_timeout_s) covers wedges, and request
        # deadlines apply only when the caller passes one.
        self._encode_q = BatchingQueue(
            handler=self._encode_batch,
            max_batch=max(self._enc_buckets),
            max_delay_ms=serving.stage_max_delay_ms,
            max_pending=serving.max_pending,
            name="stage.encode",
            hang_timeout_s=serving.dispatch_hang_s,
            supervisor=sup,
            degraded_max_pending=serving.degraded_max_pending,
            dispatcher=_DispatchWorker("stage.encode_dispatch", rank=21),
        )
        self._decode_q = BatchingQueue(
            handler=self._decode_batch,
            max_batch=max(self._dec_buckets),
            max_delay_ms=serving.stage_max_delay_ms,
            max_pending=serving.max_pending,
            name="stage.decode",
            hang_timeout_s=serving.dispatch_hang_s,
            supervisor=sup,
            degraded_max_pending=serving.degraded_max_pending,
            dispatcher=_DispatchWorker("stage.decode_dispatch", rank=22),
        )

    def stop(self) -> None:
        """Tear the stage graph down; pending/in-flight requests fail
        with :class:`QueueStopped` rather than dangling.

        Ordering is load-bearing: units are failed and the stage queues
        stopped WHILE the stage event loop still runs — their waiters
        resume via ``asyncio.wrap_future`` callbacks scheduled on that
        loop, so failing them after the loop stops would strand callers
        in ``generate``'s ``cf.result()`` forever. The loop is stopped
        only after every in-flight request future has completed."""
        with self._lock:
            started = self._started
            self._started = False
        if not started:
            return
        self._stop_evt.set()
        if self._denoise_thread is not None:
            self._denoise_thread.join(timeout=10.0)
        # the denoise thread is down: its structures are safe to drain
        leftovers = list(self._pend)
        self._pend.clear()
        while True:
            try:
                leftovers.append(self._admit_q.get_nowait())
            except _thread_queue.Empty:
                break
        for i, u in enumerate(self._slots):
            if u is not None:
                leftovers.append(u)
                self._slots[i] = None
        self._free = list(range(self.capacity - 1, -1, -1))
        self._alive[:] = False
        self._active_n = 0
        self._probe = None
        for u in leftovers:
            self._fail_unit(u, QueueStopped("stage.denoise"))

        async def _shutdown():
            if self._encode_q is not None:
                await self._encode_q.stop()
            if self._decode_q is not None:
                await self._decode_q.stop()

        asyncio.run_coroutine_threadsafe(
            _shutdown(), self._loop).result(timeout=10.0)
        # every request coroutine now has its failure/result scheduled;
        # wait for them to unwind before the loop they run on dies. A
        # request whose encode completed BEFORE the queue stop can
        # still race its admit-queue put past the drain above — keep
        # draining while we wait so such a unit is failed promptly
        # instead of stranding its caller.
        deadline = time.monotonic() + 10.0
        for cf in list(self._inflight):
            while not cf.done() and time.monotonic() < deadline:
                try:
                    self._fail_unit(self._admit_q.get_nowait(),
                                    QueueStopped("stage.denoise"))
                except _thread_queue.Empty:
                    time.sleep(0.005)
            if not cf.done():  # pragma: no cover
                log.error("stage request future did not unwind in 10s")
        self._loop.call_soon_threadsafe(self._loop.stop)
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=10.0)
        self._encode_q = None
        self._decode_q = None

    # -- request entry -----------------------------------------------------

    def generate(self, prompts: Sequence[str], seed: int = 0,
                 deadline_s: Optional[float] = None) -> np.ndarray:
        """Monolithic-compatible entry: prompts -> (B, H, W, 3) uint8.
        Runs the request through the stage graph; blocks the calling
        (executor) thread until every row decodes. ``deadline_s`` is
        honored at STEP granularity inside the denoise stage."""
        self._ensure_started()
        cf = asyncio.run_coroutine_threadsafe(
            self._request(list(prompts), int(seed), deadline_s),
            self._loop)
        self._inflight.add(cf)
        cf.add_done_callback(self._inflight.discard)
        return cf.result()

    async def _request(self, prompts: List[str], seed: int,
                       deadline_s: Optional[float]) -> np.ndarray:
        self._ensure_queues()
        # no implicit deadline: the monolithic generate() has none, and
        # a cold-cache first dispatch (encode + per-width step buckets +
        # decode compiles) can legitimately take minutes — deadlines
        # apply only when the caller passes one
        deadline = (time.monotonic() + deadline_s
                    if deadline_s is not None else None)
        ids = self._tokenize(prompts)
        uncond = self._tokenize([self._negative] * len(prompts))
        # one normal draw for the WHOLE request, exactly the monolithic
        # shape (row i of a B-row draw, not B separate draws)
        lat0, aux0 = self._init(len(prompts), jax.random.PRNGKey(seed))
        units = [
            _Unit(ids[i:i + 1], uncond[i:i + 1],
                  lat0[i:i + 1], aux0[i:i + 1], deadline)
            for i in range(len(prompts))
        ]
        images = await asyncio.gather(*(self._process(u) for u in units))
        return np.concatenate(images, axis=0)

    async def _process(self, u: _Unit) -> np.ndarray:
        sup = self._supervisor
        u.ctx = current_ctx()
        u.cond = await self._encode_q.submit(
            (u.ids, u.uncond_ids), deadline_s=u.remaining())
        if sup is not None:
            sup.note_stage_progress("encode")
        if self._stop_evt.is_set():
            # the denoise thread is (being) torn down: enqueueing now
            # would strand this caller on a queue nobody pops. stop()'s
            # drain-while-waiting loop catches the tiny window between
            # this check and put().
            raise QueueStopped("stage.denoise")
        u.t_ready = time.monotonic()
        u.wall_ready = time.time()
        self._admit_q.put(u)
        row = await asyncio.wrap_future(u.done)
        img = await self._decode_q.submit(row, deadline_s=u.remaining())
        if sup is not None:
            sup.note_stage_progress("decode")
        return img

    # -- encode / decode stage handlers (their dispatch threads) -----------

    def _encode_batch(self, items):
        n = len(items)
        bucket = next((b for b in self._enc_buckets if n <= b), n)
        pad_len = items[0][0].shape[1]
        ids = np.zeros((bucket, pad_len), dtype=np.int32)
        uncond = np.zeros((bucket, pad_len), dtype=np.int32)
        for i, (row, urow) in enumerate(items):
            ids[i] = row[0]
            uncond[i] = urow[0]
        cond = self._encode(self._params, jnp.asarray(ids),
                            jnp.asarray(uncond))
        # per-item device-side row views — no transfer here; rows ride
        # to the denoise admission queue as device arrays
        return [{k: v[i:i + 1] for k, v in cond.items()}
                for i in range(n)]

    def _decode_batch(self, rows):
        n = len(rows)
        bucket = next((b for b in self._dec_buckets if n <= b), n)
        if bucket > n:
            rows = list(rows) + [jnp.zeros_like(rows[0])] * (bucket - n)
        lat = jnp.concatenate(rows, axis=0)
        # retirement verdict on the LATENTS, a separate tiny dispatch
        # before decode (a verdict output folded into the decode jit
        # would change fusion and break the staged-vs-monolithic
        # bit-parity bar); its own jit instance so this thread never
        # shares an executable with the denoise thread's slot check
        verdict = self._fin_check_dec(lat)
        images = self._decode(self._params, lat)
        # the ONE device->host transfer of the whole stage graph:
        # collect-once per decoded batch (the verdict vector is tiny
        # and already in flight)
        images = np.asarray(images)
        bad = set(integrity.invalid_members(
            np.asarray(verdict), images=images, n=n).tolist())
        if bad:
            # per-member failure: one poisoned row (e.g. a quarantine
            # race that retired before its verdict landed) fails ITS
            # request; neighbors in this decode batch still get images
            integrity.note_invalid("staged", "decode", sorted(bad))
        return [OutputInvalid("staged", "decode", [i]) if i in bad
                else images[i:i + 1] for i in range(n)]

    # -- denoise stage (its own thread) ------------------------------------

    def _drain_admissions(self, block: bool) -> None:
        try:
            if block:
                self._pend.append(self._admit_q.get(timeout=0.05))
            while True:
                self._pend.append(self._admit_q.get_nowait())
        except _thread_queue.Empty:
            pass

    def _denoise_loop(self) -> None:
        """The step-level continuous-batching loop. All control state is
        host-side (numpy mirrors, single thread); the loop dispatches
        jitted work and NEVER reads device values back — retirement
        hands a device-side row to the decode stage, whose handler does
        the one sync per decoded batch."""
        while not self._stop_evt.is_set():
            try:
                self._denoise_tick()
            except Exception as exc:  # noqa: BLE001 — contained below
                # a step/trace failure must fail the waiting callers,
                # not silently kill this thread and hang their futures;
                # the loop keeps serving (a later admission re-traces)
                log.exception("stage.denoise loop error")
                metrics.inc("stage.denoise.loop_errors")
                self._fail_inflight(exc)

    def _fail_inflight(self, exc: Exception) -> None:
        """Fail every admitted/pending unit after a loop error and reset
        the slot state so the next admission starts clean."""
        for slot, u in enumerate(self._slots):
            if u is not None:
                self._fail_unit(u, exc)
                self._free_slot(slot)
        while self._pend:
            self._fail_unit(self._pend.popleft(), exc)
        self._lat = self._aux = self._cond = self._finite = None
        self._fin_probes.clear()
        self._probe = None

    def _denoise_tick(self) -> None:
        # the test seam runs FIRST so a hook that holds this boundary
        # until a submission lands observes that admission drained and
        # admitted at this same boundary, not the next one
        hook = self._on_step
        if hook is not None:
            hook(self)
        # staged-tick fault point (docs/CHAOS.md): a raise exercises the
        # loop-error containment below (in-flight callers failed, loop
        # survives); a wedge holds the denoise thread so the stage
        # progress watchdog path is the thing that notices
        fault_point("stage.denoise.tick")
        idle = self._active_n == 0 and not self._pend
        self._drain_admissions(block=idle)
        now = time.monotonic()
        self._admit_pending(now)
        self._preempt_expired(now)
        if self._active_n == 0:
            return
        width = next(w for w in self._step_widths
                     if w >= self._active_n)
        live = np.flatnonzero(self._alive).astype(np.int32)
        slots = np.full((width,), live[0], dtype=np.int32)
        slots[: len(live)] = live
        # .copy() on the steps mirror is load-bearing: the CPU backend
        # may zero-copy ALIAS a numpy buffer handed to jnp.asarray, and
        # the step dispatch is async — _note_step mutates the mirror in
        # place right after dispatch, so an aliased buffer lets an
        # in-flight step read NEXT tick's indices (wrong schedule
        # coefficients, silently wrong images). A private copy per
        # dispatch is immune; ``slots``/``live`` are fresh per tick.
        idx = jnp.asarray(self._steps.copy())
        self._lat, self._aux = self._step(
            self._params, self._lat, self._aux, self._cond, idx,
            jnp.asarray(slots))
        # per-slot finiteness verdict as a SEPARATE tiny dispatch on
        # the step's output (a consumer inside the step jit would
        # change fusion and break the bit-parity bar); stale rows in
        # freed slots may read non-finite, but the probe only judges
        # units that still own their slot
        self._finite = self._fin_check(self._lat)
        # snapshot (verdict array, slot→unit) for the lazy quarantine
        # probe: units are judged only while they still own their slot
        self._fin_probes.append((self._finite, tuple(self._slots)))
        self._note_step()
        self._check_quarantine()
        self._retire_finished()
        self._watchdog_check()

    def _ensure_state(self, u: _Unit) -> None:
        if self._lat is not None:
            return
        c = self.capacity

        def zeros(row):
            return jnp.zeros((c,) + row.shape[1:], row.dtype)

        self._lat = zeros(u.lat)
        self._aux = zeros(u.aux)
        self._cond = {k: zeros(v) for k, v in u.cond.items()}

    def _admit_pending(self, now: float) -> None:
        while self._pend and self._free:
            u = self._pend.popleft()
            if u.deadline is not None and now >= u.deadline:
                self._preempt(u, "expired_before_admission")
                continue
            slot = self._free.pop()
            self._ensure_state(u)
            # device.poison drill lever: corrupts THIS request's latent
            # row at admission — detection must come from the per-step
            # verdict + quarantine path, never from the injection site
            lat_row = integrity.poison(u.lat, peer="stage")
            self._lat, self._aux, self._cond = self._admit(
                self._lat, self._aux, self._cond,
                jnp.int32(slot), lat_row, u.aux, u.cond)
            # the slot tensor now owns copies; dropping the unit's row
            # references releases the views that would otherwise pin
            # the whole encode batch (and the request's init draw) in
            # device memory for the entire denoise
            u.cond = None
            u.lat = None
            u.aux = None
            self._steps[slot] = 0
            self._alive[slot] = True
            self._slots[slot] = u
            self._active_n += 1
            u.slot = slot
            u.admit_step = self.stats["steps"]
            u.t_admit = now
            self.stats["admissions"] += 1
            metrics.inc("stage.denoise.admissions")
            metrics.observe("stage.denoise.queue_wait_s",
                            now - u.t_ready)
            flight_recorder.record(
                "stage.admit", stage="denoise", slot=slot,
                step=self.stats["steps"],
                occupancy=self._active_n)

    def _preempt(self, u: _Unit, reason: str) -> None:
        self.stats["preemptions"] += 1
        metrics.inc("stage.denoise.preemptions")
        flight_recorder.record(
            "stage.preempt", stage="denoise", reason=reason,
            slot=u.slot, step=self.stats["steps"],
            steps_done=int(self._steps[u.slot]) if u.slot is not None
            else 0)
        self._fail_unit(u, DeadlineExceeded("stage.denoise"))

    def _preempt_expired(self, now: float) -> None:
        """Deadline honor at STEP granularity: an expired request frees
        its slot at this boundary instead of finishing the image; the
        freed slot's stale rows cannot perturb neighbors (rows are
        independent and a freed slot is excluded from the gathered
        step)."""
        for slot, u in enumerate(self._slots):
            if u is None or u.deadline is None or now < u.deadline:
                continue
            self._preempt(u, "deadline")
            self._free_slot(slot)

    def _free_slot(self, slot: int) -> None:
        self._slots[slot] = None
        self._alive[slot] = False
        self._steps[slot] = 0  # hygiene: freed slots never enter the
        self._free.append(slot)  # gathered step until re-admitted
        self._active_n -= 1

    def _note_step(self) -> None:
        self.stats["steps"] += 1
        self.stats["slot_steps"] += self._active_n
        for slot, u in enumerate(self._slots):
            if u is not None:
                self._steps[slot] += 1
        metrics.inc("stage.denoise.steps")
        metrics.gauge("stage.denoise.slot_occupancy",
                      self._active_n / self.capacity)

    # -- slot quarantine (integrity rung 2) --------------------------------

    def _check_quarantine(self) -> None:
        """Quarantine slots whose latents went non-finite mid-flight,
        detected from the per-step verdict dispatch with NO
        blocking sync: only READY verdict arrays are read (the same
        non-blocking ``is_ready`` discipline as the wedge watchdog), so
        detection lags dispatch by however long the device pipeline
        runs deep — bounded, because a poisoned slot's verdict stays
        False every subsequent step (NaN propagates) until scrubbed.
        A poisoned row that retires before its verdict lands is caught
        by the retirement verdict instead (never reaches a player).
        Under ``CASSMANTLE_NO_INTEGRITY_CHECKS`` (read per tick) ready
        probes drain unjudged — no quarantines, matching the global
        kill-switch contract.
        """
        probes = self._fin_probes
        disabled = integrity.integrity_disabled()
        while probes and self._array_ready(probes[0][0]):
            fin, units = probes.popleft()
            # ready ⇒ copy-out, not a device wait
            # lint: ignore[host-sync] — is_ready-gated read of a (capacity,) bool vector
            verdict = np.asarray(fin)
            for slot, u in enumerate(units):
                if disabled or u is None or verdict[slot]:
                    continue
                if self._slots[slot] is not u:
                    # already retired/preempted; admission re-writes
                    # the rows, so stale state cannot leak forward
                    continue
                self._quarantine(slot, u)
        # drop stale unread probes: detection does not depend on any
        # single probe (the per-slot verdict is persistent), and an
        # unready backlog must not grow without bound
        while len(probes) > 32:
            probes.popleft()

    def _quarantine(self, slot: int, u: _Unit) -> None:
        """Retire a poisoned slot with OutputInvalid and scrub its
        rows (zero-fill through the same compiled admission graph)
        before the slot can be reused; repeated quarantines trip the
        content breaker via the supervisor, so a sick device reads as
        sick, not as a run of unlucky requests."""
        steps_done = int(self._steps[slot])
        self.stats["quarantines"] += 1
        metrics.inc("stage.denoise.quarantines")
        integrity.note_invalid("staged", "denoise", [slot])
        flight_recorder.record(
            "stage.quarantine", stage="denoise", slot=slot,
            step=self.stats["steps"], steps_done=steps_done)
        log.error("stage.denoise slot %d latents non-finite after %d "
                  "steps: quarantined", slot, steps_done)
        zero_lat = jnp.zeros((1,) + self._lat.shape[1:], self._lat.dtype)
        zero_aux = jnp.zeros((1,) + self._aux.shape[1:], self._aux.dtype)
        zero_cond = {k: jnp.zeros((1,) + v.shape[1:], v.dtype)
                     for k, v in self._cond.items()}
        self._lat, self._aux, self._cond = self._admit(
            self._lat, self._aux, self._cond,
            jnp.int32(slot), zero_lat, zero_aux, zero_cond)
        self._fail_unit(u, OutputInvalid("staged", "denoise", [slot]))
        self._free_slot(slot)
        sup = self._supervisor
        if sup is not None:
            sup.content_breaker.record_failure()

    def _denoise_flops_per_image(self):
        """Analytic FLOPs of one request's full denoise residency (CFG
        denoiser × num_steps), traced once from the actual slot
        denoiser at width 1 (obs/costmodel.py — exact for this config).

        The jaxpr trace costs seconds for an SDXL-class UNet, and this
        is called from the single denoise-loop thread — tracing inline
        would stall EVERY co-resident slot's steps (and burn their
        step-granularity deadline budget) at the first retirement. So
        the first call only CAPTURES the shapes (cheap) and hands the
        trace to a daemon thread; retirements carry no attribution
        until it lands (None), then every later one uses the cached
        figure. 0.0 = tried and failed, permanently skipped."""
        if self._flops_img is not None:
            return self._flops_img or None
        if self._cond is None or self._lat is None \
                or self._flops_trace_started:
            return None
        self._flops_trace_started = True

        def one(a):
            return jax.ShapeDtypeStruct((1,) + a.shape[1:], a.dtype)

        lat1 = one(self._lat)
        cond1 = {k: one(v) for k, v in self._cond.items()}

        def run_trace():
            try:
                from cassmantle_tpu.obs import costmodel

                flops, _ = costmodel.trace_cost(
                    lambda p, x, t, c: self._denoise(
                        p, x, t, c["ctx"], c["uctx"],
                        c.get("add"), c.get("uadd")),
                    self._params["unet"], lat1,
                    jax.ShapeDtypeStruct((1,), jnp.int32), cond1)
                self._flops_img = flops * self.num_steps
            # lint: ignore[swallowed-error] — accounting-only degrade: retirements carry flops_est=0, which is itself visible in every stage.denoise.service span
            except Exception:
                log.exception("staged denoise cost trace failed; "
                              "retirements carry no FLOPs attribution")
                self._flops_img = 0.0

        threading.Thread(target=run_trace, daemon=True,
                         name="cassmantle-stage-costtrace").start()
        return None

    def _retire_finished(self) -> None:
        sup = self._supervisor
        for slot, u in enumerate(self._slots):
            if u is None or self._steps[slot] < self.num_steps:
                continue
            row = self._take(self._lat, jnp.int32(slot))
            self._free_slot(slot)
            self.stats["retirements"] += 1
            now = time.monotonic()
            metrics.observe("stage.denoise.service_s", now - u.t_admit)
            flight_recorder.record(
                "stage.retire", stage="denoise", slot=slot,
                step=self.stats["steps"], occupancy=self._active_n)
            # roofline attribution per retirement: the request's
            # denoise work is num_steps CFG forwards wherever its slot
            # sat. The mxu figure divides by residency (admit→retire),
            # a LOWER bound per unit — co-batched slots overlap, so the
            # per-pipeline gauge approaches truth as occupancy rises
            # (exactly the stage-serving occupancy argument,
            # docs/PERF_NOTES.md)
            unit_flops = self._denoise_flops_per_image()
            if unit_flops:
                from cassmantle_tpu.obs.costmodel import chip_peak_flops
                from cassmantle_tpu.obs.device import note_dispatch

                metrics.inc("request.device_flops", unit_flops,
                            labels={"pipeline": "staged_denoise"})
                service_s = now - u.t_admit
                if service_s > 0:
                    metrics.gauge(
                        "pipeline.mxu_utilization",
                        unit_flops / service_s / chip_peak_flops(),
                        labels={"pipeline": "staged_denoise"})
                note_dispatch("staged_denoise")
            if u.ctx is not None and u.ctx.sampled:
                wait_s = u.t_admit - u.t_ready
                tracer.record_span(
                    "stage.denoise.wait", tracer.child_ctx(u.ctx),
                    parent_id=u.ctx.span_id, start_wall=u.wall_ready,
                    duration_s=wait_s, attrs={"slot": slot})
                attrs = {"slot": slot, "steps": self.num_steps}
                if unit_flops:
                    attrs["flops_est"] = unit_flops
                tracer.record_span(
                    "stage.denoise.service", tracer.child_ctx(u.ctx),
                    parent_id=u.ctx.span_id,
                    start_wall=u.wall_ready + wait_s,
                    duration_s=now - u.t_admit,
                    attrs=attrs)
            if sup is not None:
                sup.note_stage_progress("denoise")
            # guarded: stop()/deadline/integrity can _fail_unit a slot
            # the denoise thread is concurrently retiring — the loser
            # of that race must not raise InvalidStateError here
            if not u.done.done():
                u.done.set_result(row)

    # -- wedge watchdog ----------------------------------------------------

    @staticmethod
    def _array_ready(arr) -> bool:
        ready = getattr(arr, "is_ready", None)
        return bool(ready()) if callable(ready) else True

    def _watchdog_check(self) -> None:
        """Per-stage dispatch health without a host sync: probe the
        NON-BLOCKING readiness of a recently dispatched state array. A
        probe still unready ``dispatch_hang_s`` after dispatch means the
        device wedged mid-denoise (the monolithic watchdog's condition,
        observed from outside the dispatch thread): flip the supervisor
        degraded and fail the in-flight slots — their callers must not
        hang on futures the device will never fill."""
        hang = self.cfg.serving.dispatch_hang_s
        if hang is None:
            return
        now = time.monotonic()
        if self._probe is None:
            self._probe = (now, self._lat)
            return
        t0, arr = self._probe
        if self._array_ready(arr):
            self._probe = None
            if self._supervisor is not None:
                self._supervisor.note_stage_progress("denoise")
            return
        if now - t0 <= hang:
            return
        log.error("stage.denoise step unready after %.1fs; failing %d "
                  "in-flight slots", hang, self._active_n)
        metrics.inc("stage.denoise.dispatch_hangs")
        flight_recorder.record("stage.dispatch_hang", stage="denoise",
                               hang_timeout_s=hang,
                               in_flight=self._active_n)
        if self._supervisor is not None:
            self._supervisor.note_dispatch_overrun("stage.denoise")
        exc = DispatchTimeout(
            f"stage.denoise step exceeded {hang}s")
        for slot, u in enumerate(self._slots):
            if u is not None:
                self._fail_unit(u, exc)
                self._free_slot(slot)
        self._probe = None

    @staticmethod
    def _fail_unit(u: _Unit, exc: Exception) -> None:
        if not u.done.done():
            u.done.set_exception(exc)
