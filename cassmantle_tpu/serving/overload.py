"""Overload control plane: adaptive admission + the SLO brownout ladder.

Before this module, every admission decision in the stack was a static
threshold: ``ServingConfig.max_pending`` 4096 (256 while degraded), a
constant ``Retry-After: 1`` on every 429/503, and a binary
healthy/degraded supervisor verdict. Under sustained overload the
system queued doomed work, burned its deadline budget, and collapsed
instead of plateauing at capacity (ISSUE 13). Two cooperating
mechanisms fix that:

- :class:`AdaptiveLimiter` — an AIMD concurrency limit per
  :class:`~cassmantle_tpu.serving.queue.BatchingQueue`, driven by the
  measured per-batch ``queue_wait_s + service_s`` against a latency
  target. While observed latency stays under the target the limit
  creeps up additively (probing for capacity); a breach decreases it
  multiplicatively (at most once per cooldown, so one slow batch never
  collapses the limit). Rejections carry a **computed Retry-After**
  from the predicted-wait estimator (queue depth × observed per-item
  service time), and a request whose predicted wait already exceeds
  its deadline is rejected at submit — in microseconds — instead of
  expiring in the queue after burning its whole budget. The
  ``server.loop_lag_s`` sleep-overshoot gauge (obs/process.py) feeds
  the same decision: a saturated event loop sheds background work
  BEFORE queues back up (the loop is upstream of every queue).
- :class:`BrownoutLadder` — a consumer of the SLO burn-rate engine
  (obs/slo.py): on sustained fast-window burn it steps through ordered
  quality tiers (diffusion step-count reduction → encprop stride
  increase → the few-step consistency student → resolution downshift →
  blur-ladder coarsening), each tier
  a config *delta* the pipelines compile once and reuse (bucketed like
  every other serving variant — a tier change never recompiles in
  steady state). The active tier is counted
  (``overload.brownout_tier``), stamped on responses
  (``X-Quality-Degraded``), surfaced in ``/readyz``, and recovered
  with hysteresis: stepping down waits for the engine's slow-window
  recovery plus a dwell, so a flapping burst cannot flap image quality
  with it. ``CASSMANTLE_NO_BROWNOUT=1`` pins tier 0.

Both halves are observable end to end (``overload.*`` metrics,
``overload.brownout`` flight-recorder events, the ``/readyz`` overload
block) and drillable: the ``server.admit`` fault point forces
mis-admission and ``overload.brownout`` forces tier flapping
(docs/CHAOS.md), exercised by ``bench.py overload_drill``.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, Dict, Optional, Sequence, Tuple

from cassmantle_tpu.chaos import ChaosInjected, fault_point
from cassmantle_tpu.obs.recorder import flight_recorder
from cassmantle_tpu.utils.locks import OrderedLock
from cassmantle_tpu.utils.logging import get_logger, metrics

log = get_logger("overload")

PRIORITY_INTERACTIVE = "interactive"
PRIORITY_BACKGROUND = "background"


def _env_flag(name: str) -> bool:
    return os.environ.get(name, "").lower() in ("1", "true", "yes", "on")


def adaptive_admission_disabled() -> bool:
    """CASSMANTLE_NO_ADAPTIVE_ADMISSION=1 reverts every queue to the
    static max_pending/degraded_max_pending pair (docs/DEPLOY.md §6).
    Read at service build like the other serving kill switches."""
    return _env_flag("CASSMANTLE_NO_ADAPTIVE_ADMISSION")


def brownout_disabled() -> bool:
    """CASSMANTLE_NO_BROWNOUT=1 pins the ladder at tier 0. Checked on
    every evaluation AND every override read, so setting it mid-flight
    drops quality degradation immediately (the pinned acceptance
    contract: with the flag set, unloaded traffic is bit-for-bit
    today's behavior)."""
    return _env_flag("CASSMANTLE_NO_BROWNOUT")


@dataclasses.dataclass(frozen=True)
class Rejection:
    """An admission verdict: why, and how long the client should wait
    (the computed Retry-After the HTTP layer serves)."""

    reason: str            # "overload" | "background" | "predicted_late"
                           # | "loop_lag" | "chaos"
    retry_after_s: float


class AdaptiveLimiter:
    """Gradient/AIMD concurrency limiter for one queue.

    The signal is the per-batch end-to-end latency (slowest member's
    queue wait + the batch's service time) against ``target_s``:

    - under target → additive increase (+``increase`` per batch, capped
      at ``max_limit``): the limit probes for capacity;
    - over target → multiplicative decrease (×``decrease``, floored at
      ``min_limit``), at most once per cooldown window (~one batch
      service time) so a single slow batch cannot collapse the limit
      to the floor before its successors report in.

    The same observations feed the predicted-wait estimator: an EWMA of
    per-item service time × current depth ≈ how long a new arrival will
    wait — the number behind every computed Retry-After and behind
    rejecting already-doomed work (predicted wait > deadline) at
    submit. Unloaded, the limit sits at ``max_limit`` and the estimator
    predicts ~0, so admission is exactly the old static bound.

    Thread contract: ``admit`` runs on the submitting event loop,
    ``observe_batch`` on the queue's collector; a queue owns its
    limiter, but /readyz reads snapshots cross-thread — state is
    guarded by an :class:`OrderedLock` (rank 54, docs/STATIC_ANALYSIS.md).
    """

    def __init__(
        self,
        name: str,
        *,
        target_s: float = 1.0,
        min_limit: int = 8,
        max_limit: int = 4096,
        decrease: float = 0.7,
        increase: float = 1.0,
        background_fraction: float = 0.5,
        loop_lag_shed_s: float = 0.25,
        ewma_alpha: float = 0.3,
        clock: Callable[[], float] = time.monotonic,
        registry=None,
        loop_lag_fn: Optional[Callable[[], float]] = None,
    ) -> None:
        self.name = name
        self.target_s = float(target_s)
        self.min_limit = max(1, int(min_limit))
        self.max_limit = max(self.min_limit, int(max_limit))
        self.decrease = float(decrease)
        self.increase = float(increase)
        self.background_fraction = float(background_fraction)
        self.loop_lag_shed_s = float(loop_lag_shed_s)
        self.ewma_alpha = float(ewma_alpha)
        self._clock = clock
        self._registry = registry if registry is not None else metrics
        # an injected loop_lag_fn (tests) is read live; the default
        # registry read — an O(all-gauges) scan under the process-wide
        # metrics lock — is cached ~250 ms so the admit fast path never
        # pays it per request at exactly the moment the system is hot
        self._loop_lag_fn = loop_lag_fn
        self._lag_cache: Tuple[float, float] = (-1e9, 0.0)
        self._lock = OrderedLock(f"overload.limiter.{name}", rank=54)
        self._limit = float(self.max_limit)
        # EWMA of per-ITEM service time (batch service / batch width):
        # depth × this = predicted wait. None until the first batch.
        self._item_service_s: Optional[float] = None
        self._last_decrease: Optional[float] = None
        self._last_latency_s = 0.0
        # NOT auto-registered: make_admission (the wiring site) calls
        # register_limiter, so transient constructions — config probes,
        # lock-rank tests — never become phantom /readyz queue rows

    # -- signals -----------------------------------------------------------
    def _loop_lag(self) -> float:
        if self._loop_lag_fn is not None:
            return self._loop_lag_fn()
        now = self._clock()
        cached_at, value = self._lag_cache
        if now - cached_at > 0.25:
            values = self._registry.gauge_values("server.loop_lag_s")
            value = max(values) if values else 0.0
            self._lag_cache = (now, value)
        return value

    def observe_batch(self, wait_s: float, service_s: float,
                      batch_size: int) -> None:
        """One completed batch: update the service-time estimator and
        run the AIMD step on the batch's end-to-end latency."""
        latency = float(wait_s) + float(service_s)
        per_item = float(service_s) / max(1, int(batch_size))
        now = self._clock()
        with self._lock:
            self._last_latency_s = latency
            if self._item_service_s is None:
                self._item_service_s = per_item
            else:
                a = self.ewma_alpha
                self._item_service_s = (
                    a * per_item + (1.0 - a) * self._item_service_s)
            if latency > self.target_s:
                # cooldown ≈ one batch service time (floor: the target):
                # every in-flight batch admitted before the decrease will
                # still report the old regime's latency
                cooldown = max(self.target_s, float(service_s))
                if self._last_decrease is None or \
                        now - self._last_decrease >= cooldown:
                    # gradient estimate: the depth this queue can hold
                    # and still meet the target is throughput × target
                    # (Little's law). Clamping the multiplicative step
                    # to it converges in ONE decrease from any height —
                    # a limit parked at max_pending must not take
                    # log-many cooldowns to reach a sane bound while
                    # admitted work burns its deadline budget.
                    est = (int(batch_size) / max(float(service_s), 1e-6)
                           ) * self.target_s
                    self._limit = max(
                        float(self.min_limit),
                        min(self._limit * self.decrease, est))
                    self._last_decrease = now
            else:
                self._limit = min(float(self.max_limit),
                                  self._limit + self.increase)
            limit = self._limit
        self._registry.gauge(f"{self.name}.admit_limit", limit)

    # -- estimator ---------------------------------------------------------
    def predicted_wait_s(self, depth: int) -> float:
        """Expected queue wait for an arrival behind ``depth`` pending
        items: depth × observed per-item service time. 0 before the
        first batch (never reject on a guess)."""
        with self._lock:
            per_item = self._item_service_s
        if per_item is None:
            return 0.0
        return max(0, int(depth)) * per_item

    def retry_after_s(self, depth: int) -> float:
        """The computed Retry-After for a rejection at ``depth``: the
        predicted wait for the backlog ahead (floor 1 s — the HTTP
        header is integral seconds and 0 invites an instant retry)."""
        return max(1.0, self.predicted_wait_s(depth))

    # -- admission ---------------------------------------------------------
    def limit(self) -> float:
        with self._lock:
            return self._limit

    def admit(self, depth: int, priority: str,
              deadline_s: Optional[float]) -> Optional[Rejection]:
        """None = admitted; a :class:`Rejection` otherwise. Background
        sheds first (at ``background_fraction`` of the limit, and on
        any event-loop lag); interactive sheds at the limit, or
        immediately when its predicted wait already exceeds its
        deadline (doomed work must fail in <50 ms, not at deadline)."""
        lag = self._loop_lag()
        background = priority == PRIORITY_BACKGROUND
        if lag > self.loop_lag_shed_s and \
                (background or lag > 4.0 * self.loop_lag_shed_s):
            # the event loop is the resource upstream of every queue:
            # shed before the queues themselves ever look deep
            metrics.inc("overload.loop_lag_sheds")
            return Rejection("loop_lag", max(1.0, lag))
        with self._lock:
            limit = self._limit
        bound = limit * self.background_fraction if background else limit
        if depth >= bound:
            return Rejection("background" if background else "overload",
                             self.retry_after_s(depth))
        predicted = self.predicted_wait_s(depth)
        if deadline_s is not None and predicted > deadline_s:
            return Rejection("predicted_late", self.retry_after_s(depth))
        return None

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "limit": round(self._limit, 1),
                "target_s": self.target_s,
                "item_service_s": (round(self._item_service_s, 6)
                                   if self._item_service_s is not None
                                   else None),
                "last_latency_s": round(self._last_latency_s, 4),
            }


# -- brownout ladder --------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class BrownoutTier:
    """One rung of quality degradation: a config delta the serving
    paths apply without recompiling in steady state (each distinct
    delta compiles once and is cached, like any other bucket)."""

    name: str
    # diffusion step-count multiplier (the dominant latency knob —
    # Efficient Diffusion Models survey, PAPERS.md)
    num_steps_scale: float = 1.0
    # added to SamplerConfig.encprop_stride when encprop is on (more
    # propagated decoder-only steps per full encoder forward)
    encprop_stride_add: int = 0
    # step INTO the few-step consistency student
    # (SamplerConfig.consistency, ops/samplers.py::consistency_sample)
    # at CONSISTENCY_BROWNOUT_STEPS — the biggest step-count lever in
    # the ladder, taken BEFORE any resolution downshift: a 4-forward
    # image at full resolution beats a half-resolution 30-forward one
    # on both latency and user-visible quality. Only engages when the
    # deployment declares a distilled student checkpoint
    # (SamplerConfig.consistency or .consistency_available — an
    # UNDISTILLED eps-net sampled 4-step is near-noise, worse than any
    # resolution downshift), and ignored while CASSMANTLE_NO_CONSISTENCY
    # pins the student off; otherwise the rung degrades like the
    # previous one and the ladder falls through to the resolution tier.
    consistency: bool = False
    # image resolution multiplier (quadratic compute lever)
    image_size_scale: float = 1.0
    # blur-ladder quantization in px: coarser buckets = fewer distinct
    # decode+blur+encode renders per round (engine/game.py)
    blur_bucket_px: float = 0.5


#: step count the few-step brownout tier serves (the lcm preset's 4)
CONSISTENCY_BROWNOUT_STEPS = 4

# Ordered mild → severe; tier 0 is full quality. Every tier includes
# the previous tiers' deltas so stepping up only ever removes compute.
DEFAULT_TIERS: Tuple[BrownoutTier, ...] = (
    BrownoutTier("full"),
    BrownoutTier("fewer-steps", num_steps_scale=0.6),
    BrownoutTier("stride", num_steps_scale=0.6, encprop_stride_add=2),
    BrownoutTier("few-step", num_steps_scale=0.6, encprop_stride_add=2,
                 consistency=True),
    BrownoutTier("low-res", num_steps_scale=0.6, encprop_stride_add=2,
                 consistency=True, image_size_scale=0.5),
    BrownoutTier("coarse-blur", num_steps_scale=0.6,
                 encprop_stride_add=2, consistency=True,
                 image_size_scale=0.5, blur_bucket_px=2.0),
)


def degraded_sampler_cfg(sampler_cfg, tier: BrownoutTier):
    """Apply a tier's deltas to a SamplerConfig, respecting the
    config's structural invariants (deepcache pairing needs even ddim
    step counts, encprop's dense prefix must fit the step count, the
    latent grid needs image_size on a /16 boundary, consistency does
    not compose with deepcache/encprop). Returns a config EQUAL to the
    input at tier 0 (callers skip the degraded path)."""
    from cassmantle_tpu.ops.samplers import consistency_disabled
    from cassmantle_tpu.serving.pipeline import effective_sampler_cfg

    # with the kill switch set serving already reverted to the teacher
    # path (kind @ consistency_teacher_steps); tiers degrade THAT — the
    # config the pipeline is actually dispatching (one shared revert,
    # so the brownout path can never diverge from the pinned bit-exact
    # teacher revert the pipeline/staged paths take)
    s = effective_sampler_cfg(sampler_cfg)
    steps = max(2, int(round(s.num_steps * tier.num_steps_scale)))
    if s.deepcache and s.kind == "ddim":
        steps += steps % 2
    stride = s.encprop_stride
    if s.encprop and tier.encprop_stride_add:
        stride = s.encprop_stride + int(tier.encprop_stride_add)
    size = s.image_size
    if tier.image_size_scale != 1.0:
        size = max(32, (int(s.image_size * tier.image_size_scale)
                        // 16) * 16)
    if (tier.consistency and not consistency_disabled()
            and (s.consistency or s.consistency_available)):
        # the few-step tier swaps the whole sampling loop for the
        # consistency student; deepcache/encprop don't compose with it
        # and eta is meaningless for the deterministic re-noise ladder,
        # so the delta clears all three — and touches NOTHING else, so
        # at the default geometry the delta's cost-model signature is
        # exactly the committed `t2i_lcm` entry's (no runtime jaxpr
        # trace while the system is shedding). A config ALREADY serving
        # the student keeps its (≤ CONSISTENCY_BROWNOUT_STEPS) step
        # count — there is no cheaper rung than the few-step path.
        few = (min(CONSISTENCY_BROWNOUT_STEPS, s.num_steps)
               if s.consistency else CONSISTENCY_BROWNOUT_STEPS)
        return dataclasses.replace(
            s, consistency=True, num_steps=few, deepcache=False,
            encprop=False, eta=0.0, image_size=size)
    dense = min(s.encprop_dense_steps, steps)
    return dataclasses.replace(
        s, num_steps=steps, encprop_stride=stride, image_size=size,
        encprop_dense_steps=dense)


class BrownoutLadder:
    """The ok↔burning consumer: steps the tier up while any watched
    objective reports ``burning`` (the engine's fast-window trip) for
    at least ``step_up_dwell_s``, and back down — one rung at a time —
    only after every watched objective has been ``ok`` (the engine's
    slow-window recovery) for ``step_down_dwell_s``. The asymmetric
    dwell pair IS the hysteresis: quality drops fast under real burn
    and recovers deliberately.

    The ``overload.brownout`` fault point lets a drill force a tier
    step regardless of SLO state (tier-flap exercises, docs/CHAOS.md).
    """

    def __init__(
        self,
        tiers: Sequence[BrownoutTier] = DEFAULT_TIERS,
        *,
        objectives: Sequence[str] = (),
        step_up_dwell_s: float = 10.0,
        step_down_dwell_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        registry=None,
        recorder=None,
    ) -> None:
        assert tiers, "the ladder needs at least tier 0"
        self.tiers = tuple(tiers)
        # empty = watch every objective the engine evaluates
        self.objectives = tuple(objectives)
        self.step_up_dwell_s = float(step_up_dwell_s)
        self.step_down_dwell_s = float(step_down_dwell_s)
        self._clock = clock
        self._registry = registry if registry is not None else metrics
        self._recorder = recorder if recorder is not None \
            else flight_recorder
        self._lock = OrderedLock("overload.brownout", rank=55)
        self._tier = 0
        self._burn_since: Optional[float] = None
        self._ok_since: Optional[float] = None
        self._registry.gauge("overload.brownout_tier", 0.0)

    # -- state -------------------------------------------------------------
    def tier(self) -> int:
        if brownout_disabled():
            return 0
        with self._lock:
            return self._tier

    def active_tier(self) -> Optional[BrownoutTier]:
        """The tier object when degraded, None at tier 0/disabled —
        what the pipelines consult per generate call."""
        t = self.tier()
        return self.tiers[t] if t else None

    def _step_to(self, new_tier: int, reason: str) -> None:
        """Caller holds the lock. Records the transition everywhere an
        operator could look for it."""
        old = self._tier
        self._tier = new_tier
        self._registry.gauge("overload.brownout_tier", float(new_tier))
        if new_tier > old:
            self._registry.inc("overload.brownout_trips")
        else:
            self._registry.inc("overload.brownout_recoveries")
        self._recorder.record(
            "overload.brownout", from_tier=old, to_tier=new_tier,
            tier_name=self.tiers[new_tier].name, reason=reason)
        log.warning("brownout tier %d -> %d (%s): %s", old, new_tier,
                    self.tiers[new_tier].name, reason)

    # -- the SLO-engine listener -------------------------------------------
    def on_slo_eval(self, verdicts: Dict[str, dict]) -> None:
        """Called by the SLO engine after every evaluation pass with
        the per-objective verdicts (obs/slo.py)."""
        if brownout_disabled():
            with self._lock:
                if self._tier:
                    self._step_to(0, "disabled")
                self._burn_since = self._ok_since = None
            return
        try:
            # drill lever: force a tier step independent of SLO state
            fault_point("overload.brownout")
        except ChaosInjected:
            with self._lock:
                if self._tier + 1 < len(self.tiers):
                    self._step_to(self._tier + 1, "chaos")
            return
        watched = {n: v for n, v in verdicts.items()
                   if not self.objectives or n in self.objectives}
        if not watched:
            return
        burning = any(v.get("state") == "burning"
                      for v in watched.values())
        now = self._clock()
        with self._lock:
            if burning:
                self._ok_since = None
                if self._burn_since is None:
                    self._burn_since = now
                elif now - self._burn_since >= self.step_up_dwell_s and \
                        self._tier + 1 < len(self.tiers):
                    self._step_to(self._tier + 1, "slo_burn")
                    # each further rung re-earns its own dwell
                    self._burn_since = now
            else:
                # the engine's own hysteresis already gated this: an
                # objective leaves "burning" only once the SLOW window
                # is back under budget
                self._burn_since = None
                if self._tier == 0:
                    self._ok_since = None
                elif self._ok_since is None:
                    self._ok_since = now
                elif now - self._ok_since >= self.step_down_dwell_s:
                    self._step_to(self._tier - 1, "slo_recovered")
                    self._ok_since = now

    def status(self) -> Dict[str, object]:
        disabled = brownout_disabled()
        with self._lock:
            tier = 0 if disabled else self._tier
            return {
                "tier": tier,
                "tier_name": self.tiers[tier].name,
                "tiers": len(self.tiers),
                "disabled": disabled,
            }


# -- process-global wiring --------------------------------------------------
#
# Like the chaos plan, the control plane is process-global: pipelines and
# the game engine read the active tier from worker threads without any
# app-object plumbing, and /readyz reads one status block. configure_*
# is idempotent per create_app.

_LADDER: Optional[BrownoutLadder] = None
_LIMITERS: Dict[str, AdaptiveLimiter] = {}
# last time any queue shed for overload: what the membership heartbeat
# advertises so peers stop hedging into us (server/app.py)
_LAST_SHED_T: Optional[float] = None
_SHED_ADVERT_S = 10.0


def register_limiter(limiter: AdaptiveLimiter) -> None:
    """Newest limiter wins its name: services are rebuilt per test/app
    and /readyz must describe the live one."""
    _LIMITERS[limiter.name] = limiter


def note_shed() -> None:
    """A queue rejected work for overload: remember when, so the
    membership heartbeat can advertise pressure to hedging peers."""
    global _LAST_SHED_T
    _LAST_SHED_T = time.monotonic()


def note_table_served(n: int) -> None:
    """Scoring work served from the host int8 embed table
    (ops/embed_table.py) never reached this module's limiter — by
    construction it costs no device time, so admitting it would only
    distort the limiter's wait/service estimates. Counted here
    (``overload.table_served``) so the interactive tier's capacity math
    can attribute traffic that bypassed admission entirely."""
    if n:
        metrics.inc("overload.table_served", n)


def shedding(within_s: float = _SHED_ADVERT_S) -> bool:
    return _LAST_SHED_T is not None and \
        time.monotonic() - _LAST_SHED_T < within_s


def peer_advert() -> Dict[str, object]:
    """The overload fields a worker's membership heartbeat carries:
    peers consult them before hedging scorer work here
    (``score.hedge_skipped_overloaded``, server/app.py)."""
    out: Dict[str, object] = {}
    if shedding():
        out["shed"] = 1
    tier = current_tier()
    if tier:
        out["btier"] = tier
    return out


def make_admission(name: str, cfg) -> Optional[AdaptiveLimiter]:
    """The per-queue adaptive limiter from a FrameworkConfig, or None
    with CASSMANTLE_NO_ADAPTIVE_ADMISSION=1 — reverting the queue to
    the static max_pending/degraded_max_pending pair exactly. Shared
    by the real InferenceService and the drill's FakeQueuedScorer."""
    if adaptive_admission_disabled():
        return None
    s = cfg.serving
    limiter = AdaptiveLimiter(
        name,
        target_s=s.queue_latency_target_s,
        min_limit=s.admission_min_pending,
        max_limit=s.max_pending,
        background_fraction=s.admission_background_fraction,
        loop_lag_shed_s=s.loop_lag_shed_s,
    )
    register_limiter(limiter)
    return limiter


def configure_brownout(cfg, slo_engine) -> Optional[BrownoutLadder]:
    """Build the ladder from ``cfg.serving`` and subscribe it to the
    SLO engine (create_app). Returns the ladder (None never — kept
    Optional-shaped for symmetry with chaos.configure)."""
    global _LADDER
    serving = cfg.serving
    _LADDER = BrownoutLadder(
        DEFAULT_TIERS,
        objectives=serving.brownout_objectives,
        step_up_dwell_s=serving.brownout_step_up_dwell_s,
        step_down_dwell_s=serving.brownout_step_down_dwell_s,
    )
    slo_engine.add_listener(_LADDER.on_slo_eval)
    return _LADDER


def ladder() -> Optional[BrownoutLadder]:
    return _LADDER


def current_tier() -> int:
    return _LADDER.tier() if _LADDER is not None else 0


def quality_overrides() -> Optional[BrownoutTier]:
    """The active degradation tier, None at full quality — the ONE
    read every actuation site (pipelines, fake backend, blur ladder)
    performs. Cheap: a global check, a flag read, a lock-guarded int."""
    return _LADDER.active_tier() if _LADDER is not None else None


def blur_bucket_px(default: float = 0.5) -> float:
    """The blur-ladder quantum the game should use right now
    (engine/game.py fetch_masked_image_b64)."""
    tier = quality_overrides()
    return tier.blur_bucket_px if tier is not None else default


def quantize_blur_radius(radius: float, default: float = 0.5) -> float:
    """Snap a reveal radius onto the active blur-bucket ladder. At the
    default quantum this is the legacy round-to-nearest (bit-for-bit
    the pre-brownout buckets); a COARSENED quantum rounds UP — quality
    degradation must only ever add blur, never serve a near-winner's
    almost-sharp radius as fully sharp (a tier-4 quantum of 2.0 with
    nearest-rounding would have revealed every radius < 1.0 px)."""
    import math

    quantum = blur_bucket_px(default)
    if quantum == default:
        return round(radius / quantum) * quantum
    return math.ceil(radius / quantum) * quantum


def status_block() -> Dict[str, object]:
    """The `/readyz` overload block: the brownout verdict plus every
    live queue limiter's state."""
    return {
        "brownout": (_LADDER.status() if _LADDER is not None
                     else {"tier": 0, "disabled": brownout_disabled(),
                           "configured": False}),
        "queues": {name: lim.snapshot()
                   for name, lim in sorted(_LIMITERS.items())},
        "shedding": shedding(),
        # lifetime count of scoring items the embed-table rung served
        # without ever reaching a queue limiter (zero device work)
        "table_served": int(metrics.counter_total(
            "overload.table_served")),
    }
