"""SDXL-base text→image pipeline: dual text towers + micro-conditioning,
data-parallel over the device mesh.

The reference's image generation IS a remote SDXL-base-1.0 call
(reference backend.py:24, 270-295); this is its local TPU replacement at
full 1024×1024 scale — the "SDXL-base 1024, batched prompts, data-parallel"
rung of the BASELINE.md workload ladder. SD1.5 serving lives in
serving/pipeline.py; this pipeline adds the SDXL-specific conditioning:

- TWO text towers (CLIP ViT-L + OpenCLIP bigG), each contributing its
  second-to-last hidden state, concatenated to the 2048-dim UNet context;
- pooled bigG embedding + sinusoidal size/crop "time ids" fed through the
  UNet's addition-embedding MLP (micro-conditioning);
- VAE with the 0.13025 SDXL scaling factor.

Parallelism is batch data-parallel over the mesh's ``dp`` axis via
``jax.jit`` in/out shardings: token ids arrive batch-sharded, params are
replicated by GSPMD, and each device denoises its shard of the batch —
collective-free in the forward pass, so throughput scales linearly over
ICI. The whole CLIP→DDIM→VAE trajectory is still ONE XLA computation.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from cassmantle_tpu.chaos import fault_point
from cassmantle_tpu.config import FrameworkConfig
from cassmantle_tpu.models.clip_text import ClipTextEncoder
from cassmantle_tpu.models.layers import timestep_embedding
from cassmantle_tpu.models.unet import UNet
from cassmantle_tpu.models.vae import VAEDecoder, postprocess_images
from cassmantle_tpu.models.weights import (
    convert_clip_text,
    convert_clip_text_projection,
    convert_tensors,
    convert_unet,
    convert_vae_decoder,
    init_params_cached,
    load_checkpoint_tensors,
    maybe_load,
)
from cassmantle_tpu.ops.ddim import initial_latents
from cassmantle_tpu.ops.samplers import make_sampler
from cassmantle_tpu.serving import integrity
from cassmantle_tpu.utils.compile_cache import (
    enable_compile_cache,
    param_cache_path,
)
from cassmantle_tpu.utils.logging import get_logger, metrics
from cassmantle_tpu.utils.profiling import annotate, block_timer
from cassmantle_tpu.utils.tokenizers import load_tokenizer

log = get_logger("sdxl")


class SDXLPipeline:
    """prompts -> (B, 1024, 1024, 3) uint8; batch-DP over ``mesh``'s dp axis.

    Build ``cfg`` with :func:`cassmantle_tpu.config.sdxl_config` (or the
    tiny :func:`test_sdxl_config` on CPU). With ``mesh=None`` it runs
    single-device, same as the SD1.5 pipeline.
    """

    def __init__(
        self,
        cfg: FrameworkConfig,
        weights_dir: Optional[str] = None,
        mesh: Optional[Mesh] = None,
        share_params_with: "Optional[SDXLPipeline]" = None,
    ) -> None:
        """``share_params_with``: reuse another SDXL pipeline's loaded
        param trees (device buffers shared, nothing copied) when the
        architectures match — the `sdxl_encprop` bench A/B arms then
        hold ONE set of the multi-GB SDXL weights in HBM instead of
        two. Stricter than the SD1.5 donor contract: both text towers
        and the int8 flag must match exactly (SDXL has no
        int8-asymmetry re-load path)."""
        enable_compile_cache()
        m = cfg.models
        assert m.clip_text_2 is not None, (
            "SDXL needs both text towers; use config.sdxl_config()"
        )
        assert m.unet.addition_embed_dim > 0, "SDXL UNet needs micro-conds"
        self.cfg = cfg
        self.mesh = mesh
        self.clip = ClipTextEncoder(m.clip_text)
        self.clip2 = ClipTextEncoder(m.clip_text_2)
        self.unet = UNet(m.unet)
        self.vae = VAEDecoder(m.vae)
        # Both towers share the CLIP BPE vocabulary.
        self.tokenizer = load_tokenizer(
            weights_dir, "clip", m.clip_text.vocab_size
        )
        self.pad_len = min(cfg.sampler.prompt_pad_len,
                           m.clip_text.max_positions,
                           m.clip_text_2.max_positions)
        self.vae_scale = 2 ** (len(m.vae.channel_mults) - 1)
        # addition vector = pooled bigG ++ 6 sinusoidal time-id embeddings
        self.time_id_dim = (
            m.unet.addition_embed_dim - m.clip_text_2.hidden_size
        ) // 6
        assert self.time_id_dim > 0, (
            "addition_embed_dim must exceed the bigG pooled width"
        )

        lat_hw = cfg.sampler.image_size // self.vae_scale
        lat = jnp.zeros((1, lat_hw, lat_hw, 4), dtype=jnp.float32)
        t0 = jnp.zeros((1,), dtype=jnp.int32)
        ctx = jnp.zeros((1, self.pad_len, m.unet.context_dim),
                        dtype=jnp.float32)
        add = jnp.zeros((1, m.unet.addition_embed_dim), dtype=jnp.float32)
        from cassmantle_tpu.serving.pipeline import (
            int8_unet_tools,
            w8a8_unet_tools,
        )

        unet_transform, wrap_unet_apply = int8_unet_tools(m)
        w8a8_transform = w8a8_unet_tools(m)
        if w8a8_transform is not None:
            # mutually exclusive with unet_int8 (asserted inside), so
            # the int8 slot is free (see Text2ImagePipeline)
            unet_transform = w8a8_transform

        def load_all_params() -> None:
            """Load/convert/share every stage tree and publish it on
            ``self``. Boot runs this once; a device-loss rebuild
            (serving/device_recovery.py, via :meth:`reload_params`)
            runs it again onto the fresh runtime."""
            if share_params_with is not None:
                from cassmantle_tpu.serving.pipeline import (
                    share_compatible,
                    unet_w8a8_armed,
                )

                donor = share_params_with
                dm = donor.cfg.models
                assert share_compatible(dm, m) \
                    and dm.clip_text_2 == m.clip_text_2 \
                    and dm.unet_int8 == m.unet_int8 \
                    and unet_w8a8_armed(dm) == unet_w8a8_armed(m), (
                        "share_params_with needs matching SDXL "
                        "architectures (incl. quantization mode)"
                    )
                self.clip_params = donor.clip_params
                self.clip2_params = donor.clip2_params
                self.clip2_proj = donor.clip2_proj
                self.unet_params = donor.unet_params
                self.vae_params = donor.vae_params
                return
            ids = jnp.zeros((1, self.pad_len), dtype=jnp.int32)
            self.clip_params = (
                maybe_load(weights_dir, "clip_text.safetensors",
                           lambda t: convert_clip_text(
                               t, m.clip_text.num_layers),
                           "clip_text", cast_to=m.param_dtype)
                or init_params_cached(
                    self.clip, 1, ids,
                    cache_path=param_cache_path("clip_text",
                                                m.clip_text),
                    cast_to=m.param_dtype)
            )
            # read once: the same file carries the tower AND its
            # text_projection (data/manifests/clip_bigg.json)
            t2 = load_checkpoint_tensors(
                weights_dir, "clip_text_2.safetensors", "clip_text_2")
            converted2 = convert_tensors(
                t2, lambda t: convert_clip_text(
                    t, m.clip_text_2.num_layers),
                "clip_text_2", cast_to=m.param_dtype)
            self.clip2_params = (
                converted2
                if converted2 is not None
                else init_params_cached(
                    self.clip2, 11, ids,
                    cache_path=param_cache_path("clip_text_2",
                                                m.clip_text_2),
                    cast_to=m.param_dtype)
            )
            # Real SDXL conditions on text_projection(pooled) — the
            # CLIPTextModelWithProjection text_embeds — not the raw
            # pooled state; skipping the (square, 1280x1280) projection
            # would silently divert from the published model the moment
            # real weights load. Random init keeps identity behavior.
            self.clip2_proj = None
            if converted2 is not None and t2 is not None \
                    and "text_projection.weight" in t2:
                self.clip2_proj = jnp.asarray(
                    convert_clip_text_projection(t2),
                    dtype=jnp.dtype(m.param_dtype))
            # cache key on arch(): the fused-conv execution flags
            # (UNetConfig.fused_conv / conv_pad_to) don't change the
            # tree, so A/B arms share one cached init (see
            # serving/pipeline.py)
            self.unet_params = (
                maybe_load(weights_dir, "unet_xl.safetensors",
                           lambda t: convert_unet(t, m.unet), "unet_xl",
                           cast_to=m.param_dtype,
                           transform=unet_transform)
                or init_params_cached(
                    self.unet, 2, lat, t0, ctx, add,
                    cache_path=param_cache_path("unet_xl",
                                                m.unet.arch()),
                    cast_to=m.param_dtype, transform=unet_transform)
            )
            self.vae_params = (
                maybe_load(weights_dir, "vae_xl.safetensors",
                           lambda t: convert_vae_decoder(t, m.vae),
                           "vae_xl")
                or init_params_cached(
                    self.vae, 3, lat,
                    cache_path=param_cache_path(
                        f"vae_xl{cfg.sampler.image_size}",
                        m.vae.arch()))
            )

        self._param_loader = load_all_params
        load_all_params()
        from cassmantle_tpu.serving.pipeline import (
            deepcache_schedule,
            encprop_plan,
        )

        self._dc_schedule = (deepcache_schedule(cfg.sampler)
                             if cfg.sampler.deepcache else None)
        # fail fast on invalid encprop configs + accounting for the
        # diagnosis counters (see Text2ImagePipeline)
        self._encprop_counts = None
        if cfg.sampler.encprop:
            from cassmantle_tpu.ops.ddim import encprop_step_counts

            encprop_plan(cfg.sampler)
            self._encprop_counts = encprop_step_counts(
                cfg.sampler.num_steps, cfg.sampler.encprop_stride,
                cfg.sampler.encprop_dense_steps, cfg.sampler.deepcache)
        self.unet_apply = wrap_unet_apply(self.unet.apply)
        from cassmantle_tpu.ops.fused_conv import describe as fc_describe

        if fc_describe(m.unet):
            log.info("%s", fc_describe(m.unet))
        if w8a8_transform is not None:
            from cassmantle_tpu.ops.quant import (
                w8a8_calibrated,
                w8a8_site_count,
            )
            from cassmantle_tpu.ops.quant_matmul import (
                describe as w8a8_describe,
            )

            log.info("%s", w8a8_describe(
                w8a8_calibrated(self.unet_params),
                w8a8_site_count(self.unet_params)))
        from cassmantle_tpu.serving.pipeline import (
            consistency_plan,
            effective_sampler_cfg,
            effective_sampler_steps,
        )

        # few-step consistency serving (see Text2ImagePipeline): fail
        # fast on invalid configs; the plain schedule below is the
        # teacher path the kill switch reverts to bit-exactly, and with
        # consistency ACTIVE run_cfg_denoise dispatches its own sampler
        # (no plain schedule to build)
        if cfg.sampler.consistency:
            consistency_plan(cfg.sampler)
        self.sample_latents = (
            None if effective_sampler_cfg(cfg.sampler).consistency
            else make_sampler(
                cfg.sampler.kind, effective_sampler_steps(cfg.sampler),
                eta=cfg.sampler.eta))
        # Params are jit ARGUMENTS (device buffers), not captured constants
        # (see Text2ImagePipeline note on compile payloads).
        self._params = {
            "clip": self.clip_params, "clip2": self.clip2_params,
            "clip2_proj": self.clip2_proj,  # None -> empty pytree leaf
            "unet": self.unet_params, "vae": self.vae_params,
        }

        from cassmantle_tpu.serving.pipeline import dp_sharded_sampler

        self._sample, self.dp = dp_sharded_sampler(self._sample_impl, mesh)
        # one in-flight device batch per pipeline (see Text2ImagePipeline:
        # concurrent executions of one compiled computation have
        # deadlocked the CPU backend under some jaxlib builds)
        from cassmantle_tpu.utils.locks import OrderedLock

        self._dispatch_lock = OrderedLock("pipeline.sdxl_dispatch", rank=11)
        # stage-disaggregated serving (serving/stages.py); supervisor is
        # wired by InferenceService, same as the SD1.5 pipeline
        self.supervisor = None
        self._staged = None
        self._staged_init_lock = OrderedLock("pipeline.staged_init",
                                             rank=13)
        # brownout tier variants (see Text2ImagePipeline._tier_fns)
        self._tier_fns: dict = {}
        # roofline attribution (see Text2ImagePipeline._flops_cache)
        self._flops_cache: dict = {}
        self._flops_lock = threading.Lock()
        self._flops_pending: set = set()

    def reload_params(self) -> None:
        """Device-loss rebuild (serving/device_recovery.py): re-run the
        boot load path and republish the tree (see
        Text2ImagePipeline.reload_params — same contract: params are
        jit ARGUMENTS, so nothing recompiles; the staged slot server is
        dropped and rebuilds lazily)."""
        staged = self._staged
        if staged is not None:
            self._staged = None
            try:
                staged.stop()
            # lint: ignore[swallowed-error] — the staged server is dropped and rebuilt regardless; recovery's warm-pass counters cover the reload outcome
            except Exception:
                log.exception("staged server stop during reload failed")
        self._param_loader()
        self._params = {
            "clip": self.clip_params, "clip2": self.clip2_params,
            "clip2_proj": self.clip2_proj,
            "unet": self.unet_params, "vae": self.vae_params,
        }

    # -- conditioning ------------------------------------------------------

    def _encode(self, params, ids: jax.Array) -> tuple:
        """ids -> (context (B,S,2048), pooled bigG (B,1280))."""
        out1 = self.clip.apply(params["clip"], ids)
        out2 = self.clip2.apply(params["clip2"], ids)
        context = jnp.concatenate(
            [out1["penultimate"], out2["penultimate"]], axis=-1
        )
        pooled = out2["pooled"]
        if self.clip2_proj is not None:  # static at trace time
            pooled = pooled @ params["clip2_proj"]
        return context, pooled

    def _time_ids(self, batch: int,
                  image_size: Optional[int] = None) -> jax.Array:
        """SDXL size/crop conditioning: (orig_h, orig_w, crop_t, crop_l,
        target_h, target_w), each sinusoidally embedded. ``image_size``
        overrides the configured resolution (brownout downshift)."""
        s = float(image_size if image_size is not None
                  else self.cfg.sampler.image_size)
        ids = jnp.asarray([s, s, 0.0, 0.0, s, s], dtype=jnp.float32)
        emb = timestep_embedding(ids, self.time_id_dim)  # (6, time_id_dim)
        flat = emb.reshape(-1)
        return jnp.broadcast_to(flat, (batch, flat.shape[0]))

    # -- sampling ----------------------------------------------------------

    def _sample_impl(self, params, ids, uncond_ids, rng):
        with annotate("sdxl_encode"):
            ctx, pooled = self._encode(params, ids)
            uncond_ctx, uncond_pooled = self._encode(params, uncond_ids)
        b = ids.shape[0]
        time_ids = self._time_ids(b)
        add = jnp.concatenate([pooled, time_ids], axis=-1)
        uncond_add = jnp.concatenate([uncond_pooled, time_ids], axis=-1)
        lat = initial_latents(rng, b, self.cfg.sampler.image_size,
                              self.vae_scale)
        from cassmantle_tpu.serving.pipeline import (
            run_cfg_denoise,
            spatially_shard_latents,
        )

        lat = spatially_shard_latents(lat, self.mesh)
        with annotate("sdxl_denoise_scan"):

            final = run_cfg_denoise(
                self.cfg.sampler, self.sample_latents, self._dc_schedule,
                self.unet_apply, params["unet"], ctx, uncond_ctx, lat,
                addition_embeds=add, uncond_addition_embeds=uncond_add,
            )
        with annotate("sdxl_vae_decode"):
            decoded = self.vae.apply(params["vae"], final)
        return postprocess_images(decoded)

    def _tokenize(self, prompts: Sequence[str]) -> np.ndarray:
        from cassmantle_tpu.serving.pipeline import tokenize_clip_prompts

        return tokenize_clip_prompts(
            self.tokenizer, prompts, self.pad_len,
            self.cfg.models.clip_text.vocab_size,
        )

    # -- stage-disaggregated serving (serving/stages.py) -------------------

    def _staged_enabled(self) -> bool:
        """Same routing decision as Text2ImagePipeline._staged_enabled
        (one seam, two pipelines)."""
        from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

        return Text2ImagePipeline._staged_enabled(self)

    def _encode_stage(self, params, ids, uncond_ids):
        """Encode-stage computation: exactly the dual-tower +
        micro-conditioning block of ``_sample_impl`` (rows are
        batch-independent, so staged rows match monolithic bit for
        bit)."""
        ctx, pooled = self._encode(params, ids)
        uctx, uncond_pooled = self._encode(params, uncond_ids)
        time_ids = self._time_ids(ids.shape[0])
        return {
            "ctx": ctx,
            "uctx": uctx,
            "add": jnp.concatenate([pooled, time_ids], axis=-1),
            "uadd": jnp.concatenate([uncond_pooled, time_ids], axis=-1),
        }

    def _decode_stage(self, params, lat):
        decoded = self.vae.apply(params["vae"], lat)
        return postprocess_images(decoded)

    def _staged_server(self):
        if self._staged is None:
            with self._staged_init_lock:
                if self._staged is None:
                    from cassmantle_tpu.serving.stages import (
                        StagedImageServer,
                    )

                    self._staged = StagedImageServer(
                        self.cfg, self._params,
                        encode_fn=self._encode_stage,
                        decode_fn=self._decode_stage,
                        unet_apply=self.unet_apply,
                        tokenize=self._tokenize,
                        vae_scale=self.vae_scale,
                        supervisor=self.supervisor,
                    )
        return self._staged

    def _build_tier_impl(self, scfg, sampler, dc):
        """The SDXL sample impl bound to a degraded tier's config —
        ``_sample_impl`` with (steps, stride, size) swapped, the
        micro-conditioning time_ids tracking the downshifted size."""
        from cassmantle_tpu.serving.pipeline import (
            run_cfg_denoise,
            spatially_shard_latents,
        )

        def impl(params, ids, uncond_ids, rng):
            with annotate("sdxl_encode"):
                ctx, pooled = self._encode(params, ids)
                uctx, upooled = self._encode(params, uncond_ids)
            b = ids.shape[0]
            time_ids = self._time_ids(b, scfg.image_size)
            add = jnp.concatenate([pooled, time_ids], axis=-1)
            uadd = jnp.concatenate([upooled, time_ids], axis=-1)
            lat = initial_latents(rng, b, scfg.image_size,
                                  self.vae_scale)
            lat = spatially_shard_latents(lat, self.mesh)
            with annotate("sdxl_denoise_scan"):
                final = run_cfg_denoise(
                    scfg, sampler, dc, self.unet_apply,
                    params["unet"], ctx, uctx, lat,
                    addition_embeds=add,
                    uncond_addition_embeds=uadd,
                )
            with annotate("sdxl_vae_decode"):
                decoded = self.vae.apply(params["vae"], final)
            return postprocess_images(decoded)

        return impl

    def _degraded_sampler(self):
        """Brownout actuation: the shared variant cache
        (`serving/pipeline.py::degraded_dispatch_variant`) with the
        SDXL impl builder."""
        from cassmantle_tpu.serving.pipeline import (
            degraded_dispatch_variant,
        )

        return degraded_dispatch_variant(
            self._tier_fns, self.cfg.sampler, self.mesh,
            self._build_tier_impl, log)

    def _dispatch_flops(self, sample_fn, scfg):
        """Per-image analytic FLOPs (obs/costmodel.py): the shared
        Text2ImagePipeline resolver with the SDXL artifact key and
        signature (dispatch call shape is identical)."""
        from cassmantle_tpu.obs import costmodel
        from cassmantle_tpu.serving.pipeline import (
            Text2ImagePipeline,
            effective_sampler_cfg,
        )

        # sign what is DISPATCHED: under the consistency kill switch
        # the effective config is the teacher schedule (same contract
        # as the shared resolver's t2i signature path)
        return Text2ImagePipeline._dispatch_flops(
            self, sample_fn, scfg, kind="sdxl",
            signature=costmodel.sdxl_signature(
                self.cfg, effective_sampler_cfg(scfg)))

    def generate(self, prompts: Sequence[str], seed: int = 0,
                 deadline_s: Optional[float] = None) -> np.ndarray:
        """prompts -> (B, H, W, 3) uint8. Batch is padded to a multiple of
        the dp axis so every device holds an equal shard; pad rows are
        dropped before returning. With ``serving.staged_serving`` on the
        request rides the stage graph (see Text2ImagePipeline.generate);
        meshed serving stays monolithic."""
        from cassmantle_tpu.serving.pipeline import (
            note_consistency_counter,
            note_w8a8_counter,
        )

        degraded = self._degraded_sampler()
        if degraded is None and self._staged_enabled():
            images = self._staged_server().generate(
                list(prompts), seed, deadline_s=deadline_s)
            metrics.inc("pipeline.sdxl_images", len(prompts))
            note_consistency_counter(self.cfg.sampler, len(prompts))
            note_w8a8_counter(self.cfg.models, self.cfg.sampler,
                              len(prompts))
            return images
        sample_fn, scfg, ep_counts = (
            degraded if degraded is not None
            else (self._sample, self.cfg.sampler, self._encprop_counts))
        from cassmantle_tpu.serving.pipeline import pad_prompts_to_dp

        padded, n = pad_prompts_to_dp(prompts, self.dp)
        ids = jnp.asarray(self._tokenize(padded))
        uncond = jnp.asarray(self._tokenize(
            [scfg.negative_prompt] * len(padded)))
        rng = jax.random.PRNGKey(seed)
        per_image = self._dispatch_flops(sample_fn, scfg)
        # metric + device-synchronized trace span in one, with roofline
        # attribution (flops_est attr + live mxu vs the chip ceiling)
        with self._dispatch_lock, block_timer(
                "pipeline.sdxl_s",
                flops_est=(per_image * len(padded)) if per_image
                else None,
                pipeline="sdxl"):
            fault_point("device.lost", peer="sdxl")
            images = sample_fn(self._params, ids, uncond, rng)
            # lint: ignore[lock-blocking-call] — intentional sync under dispatch lock
            images = jax.block_until_ready(images)
        out = integrity.poison(np.asarray(images[:n]), peer="sdxl")
        # host-side degenerate-frame sentinel on the transferred uint8
        # batch (the verdict stays OUT of the sample jit to preserve
        # staged-vs-monolithic bit-parity — see Text2ImagePipeline)
        integrity.enforce(np.ones(n, dtype=bool), pipeline="sdxl",
                          stage="sample", images=out, n=n)
        metrics.inc("pipeline.sdxl_images", n)
        if degraded is not None:
            metrics.inc("pipeline.brownout_images", n)
        from cassmantle_tpu.serving.pipeline import note_encprop_counters

        note_encprop_counters(ep_counts, n)
        note_consistency_counter(scfg, n)
        note_w8a8_counter(self.cfg.models, scfg, n)
        return out
