"""Continuous-batching coalescer: async requests -> fixed-shape device batches.

The reference scores each guess synchronously on the request path
(backend.py:303-317) and could not batch across players. Here concurrent
requests (guess scorings, image generations) land in an asyncio queue; a
collector drains up to the largest configured bucket or until
``max_delay_ms`` passes, then hands the batch to a single dispatch thread —
one thread per process so device dispatches serialize (one compiled graph
in flight per step) while the event loop stays free (SURVEY.md §7 stage 6,
hard part (d)). Bucketed batch sizes keep shapes static: a batch of 37
guesses pads to the 64 bucket, reusing the compiled graph.

Backpressure: a bounded queue; when full, ``submit`` fails fast and the
caller degrades (skip-don't-crash, reference error semantics §5.3).
"""

from __future__ import annotations

import asyncio
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Generic, List, Optional, Sequence, TypeVar

from cassmantle_tpu.utils.logging import get_logger, metrics

T = TypeVar("T")
R = TypeVar("R")

log = get_logger("queue")

# One dispatch thread per process: device work serializes here.
_dispatch_executor = ThreadPoolExecutor(
    max_workers=1, thread_name_prefix="cassmantle-dispatch"
)


class QueueFull(Exception):
    pass


class BatchingQueue(Generic[T, R]):
    """Coalesces ``submit`` calls into batched ``handler`` invocations.

    ``handler(items) -> results`` runs on the dispatch thread and must
    return one result per item (it pads internally to its bucket shapes).
    """

    def __init__(
        self,
        handler: Callable[[List[T]], Sequence[R]],
        max_batch: int = 1024,
        max_delay_ms: float = 25.0,
        max_pending: int = 4096,
        name: str = "queue",
    ) -> None:
        self.handler = handler
        self.max_batch = max_batch
        self.max_delay_s = max_delay_ms / 1000.0
        self.name = name
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=max_pending)
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.ensure_future(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def submit(self, item: T) -> R:
        self.start()
        loop = asyncio.get_event_loop()
        fut: asyncio.Future = loop.create_future()
        try:
            self._queue.put_nowait((item, fut))
        except asyncio.QueueFull:
            metrics.inc(f"{self.name}.rejected")
            raise QueueFull(self.name)
        metrics.gauge(f"{self.name}.depth", self._queue.qsize())
        return await fut

    async def _collect(self) -> List:
        """One entry (blocking) + everything arriving within the window."""
        first = await self._queue.get()
        batch = [first]
        loop = asyncio.get_event_loop()
        deadline = loop.time() + self.max_delay_s
        while len(batch) < self.max_batch:
            timeout = deadline - loop.time()
            if timeout <= 0:
                break
            try:
                batch.append(
                    await asyncio.wait_for(self._queue.get(), timeout)
                )
            except asyncio.TimeoutError:
                break
        return batch

    async def _run(self) -> None:
        loop = asyncio.get_event_loop()
        while True:
            batch = await self._collect()
            items = [item for item, _ in batch]
            futures = [fut for _, fut in batch]
            metrics.inc(f"{self.name}.batches")
            metrics.inc(f"{self.name}.items", len(items))
            try:
                with metrics.timer(f"{self.name}.batch_s"):
                    results = await loop.run_in_executor(
                        _dispatch_executor, self.handler, items
                    )
                if len(results) != len(items):
                    raise RuntimeError(
                        f"handler returned {len(results)} results for "
                        f"{len(items)} items"
                    )
                for fut, res in zip(futures, results):
                    if not fut.done():
                        fut.set_result(res)
            except Exception as exc:  # noqa: BLE001 — propagate per-item
                log.exception("%s batch failed", self.name)
                metrics.inc(f"{self.name}.failures")
                for fut in futures:
                    if not fut.done():
                        fut.set_exception(exc)
